#!/usr/bin/env python3
"""Runnable training example — one script, one JSON config per BASELINE
ladder rung (reference examples/ + docs/_tutorials/: a training script
driven by a ds_config JSON).

Every config under examples/configs/ works on the CPU mesh and on TPU
UNCHANGED — parallelism comes from the config (the engine builds the
device mesh from pipeline/tensor/expert/sequence_parallel_size), and
``--cpu`` only swaps the backend for an 8-device virtual CPU mesh.

    # smoke on any machine (no TPU needed)
    python examples/train.py --model gpt2-125m --cpu --steps 3 \
        --deepspeed_config examples/configs/gpt2_125m_zero0.json

    # the ladder rungs (drop --cpu on a TPU host)
    python examples/train.py --model gpt2-350m  --deepspeed_config examples/configs/gpt2_350m_zero1.json
    python examples/train.py --model gpt2-1.3b  --deepspeed_config examples/configs/gpt2_1p3b_zero3.json
    python examples/train.py --model gpt2-1.3b  --deepspeed_config examples/configs/gpt2_1p3b_zero2_offload.json
    python examples/train.py --model opt-125m   --deepspeed_config examples/configs/opt_pp4.json
    python examples/train.py --model gpt2-moe   --deepspeed_config examples/configs/moe_ep2.json

Data is the repo's own text, byte-tokenized (this environment has no
network egress); swap ``corpus_batches`` for your dataloader.
"""

import argparse
import dataclasses
import glob
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _force_cpu():
    # file-path load so the deepspeed_tpu package __init__ never runs
    # before the axon plugin is deregistered (outage-hermetic)
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_dstpu_hermetic",
        os.path.join(REPO, "deepspeed_tpu", "utils", "hermetic.py"))
    hermetic = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hermetic)
    hermetic.force_cpu(device_count=8)


def build_model(name: str, seq: int, layers=None, vocab=256):
    """Ladder-rung presets on a byte vocabulary (the example trains on
    byte-tokenized text; pass your tokenizer's vocab for real runs)."""
    from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2Model, GPT2_125M,
                                           GPT2_350M, GPT2_1_3B)
    if name.startswith("gpt2-moe"):
        from deepspeed_tpu.models.gpt2_moe import GPT2MoEConfig, GPT2MoEModel
        cfg = GPT2MoEConfig(vocab_size=vocab, n_positions=seq, n_embd=256,
                            n_layer=layers or 4, n_head=8,
                            pad_vocab_to_multiple=128, num_experts=4, top_k=2)
        return GPT2MoEModel(cfg)
    if name.startswith("opt"):
        from deepspeed_tpu.models.opt import OPTConfig, OPT_125M, OPTModel
        base = OPT_125M
        cfg = dataclasses.replace(base, vocab_size=vocab, n_positions=seq,
                                  pad_vocab_to_multiple=128,
                                  **({"n_layer": layers} if layers else {}))
        return OPTModel(cfg)
    base = {"gpt2-125m": GPT2_125M, "gpt2-350m": GPT2_350M,
            "gpt2-1.3b": GPT2_1_3B}[name]
    cfg = dataclasses.replace(base, vocab_size=vocab, n_positions=seq,
                              pad_vocab_to_multiple=128,
                              **({"n_layer": layers} if layers else {}))
    return GPT2Model(cfg)


def corpus_batches(gas, rows, seq, steps, seed=0):
    """Byte-tokenized batches from the repo's own text files."""
    import numpy as np
    chunks = []
    for pat in ("*.md", "docs/*.md", "deepspeed_tpu/**/*.py"):
        for path in sorted(glob.glob(os.path.join(REPO, pat),
                                     recursive=True))[:40]:
            try:
                with open(path, "rb") as f:
                    chunks.append(np.frombuffer(f.read(), np.uint8))
            except OSError:
                pass
    corpus = np.concatenate(chunks) if chunks else \
        np.random.default_rng(seed).integers(0, 256, 1 << 20).astype(np.uint8)
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        starts = rng.integers(0, len(corpus) - seq - 1, gas * rows)
        batch = np.stack([corpus[s:s + seq] for s in starts])
        yield {"input_ids": batch.reshape(gas, rows, seq).astype(np.int32)}


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="gpt2-125m",
                        choices=["gpt2-125m", "gpt2-350m", "gpt2-1.3b",
                                 "gpt2-moe", "opt-125m"])
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--seq", type=int, default=None,
                        help="sequence length (default: config hint or 1024)")
    parser.add_argument("--layers", type=int, default=None,
                        help="override layer count (cheap CI runs)")
    parser.add_argument("--cpu", action="store_true",
                        help="run on an 8-device virtual CPU mesh")
    parser.add_argument("--save", default=None,
                        help="checkpoint dir (saved at the end)")
    if "--cpu" in sys.argv:
        _force_cpu()
    import deepspeed_tpu
    deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()
    if not args.deepspeed_config:
        parser.error("--deepspeed_config is required (see examples/configs/)")

    seq = args.seq or (256 if args.cpu else 1024)
    model = build_model(args.model, seq, layers=args.layers)
    engine, _, _, _ = deepspeed_tpu.initialize(args=args, model=model)

    gas = engine.gradient_accumulation_steps
    rows = engine.train_micro_batch_size_per_gpu * engine.dp_world_size
    losses = []
    for step, batch in enumerate(
            corpus_batches(gas, rows, seq, args.steps)):
        loss = engine.train_batch(batch=batch)
        losses.append(float(loss))
        print(f"step {step:4d}  loss {losses[-1]:.4f}")
    if args.save:
        engine.save_checkpoint(args.save)
        print(f"checkpoint saved -> {args.save}")
    print(f"final loss {losses[-1]:.4f} (first {losses[0]:.4f})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
