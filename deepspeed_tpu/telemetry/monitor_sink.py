"""TelemetryMonitor — the fourth ``MonitorMaster`` sink.

Mirrors every monitor event into the telemetry counter gauges (so the
metrics snapshot and Prometheus dump see everything TensorBoard/W&B/CSV
see) and, when ``output_path`` is configured, maintains a Prometheus text
exposition file at ``{output_path}/{job_name}.prom`` — rewritten on every
``write_events`` batch (gauges are latest-value; the file is tiny) and on
``close()``.

Config block (training JSON and serving JSON alike)::

    "prometheus": {"enabled": true, "output_path": "./prom",
                   "job_name": "my_run"}
"""

import os

from ..utils.logging import logger
from .export import prometheus_dump
from .trace import get_tracer


class TelemetryMonitor:
    """Monitor-protocol sink feeding the telemetry pipeline (duck-typed to
    monitor/monitor.py's ``Monitor``: write_events/close/enabled)."""

    def __init__(self, config=None):
        self.enabled = bool(getattr(config, "enabled", False))
        self.output_path = getattr(config, "output_path", "") or ""
        self.job_name = getattr(config, "job_name", "DeepSpeedJobName")
        self._path = None
        if self.enabled and self.output_path:
            try:
                import jax
                if jax.process_index() != 0:
                    return
            except Exception:
                pass
            os.makedirs(self.output_path, exist_ok=True)
            self._path = os.path.join(self.output_path,
                                      f"{self.job_name}.prom")

    def write_events(self, event_list):
        if not self.enabled:
            return
        tracer = get_tracer()
        for tag, value, step in event_list:
            # gauge-only: emit() here would re-queue the event and feed the
            # pipeline back into itself on the next flush
            tracer.set_counter(tag, value, step)
        self._rewrite()

    def _rewrite(self):
        if self._path is None:
            return
        try:
            tmp = self._path + ".tmp"
            with open(tmp, "w") as f:
                f.write(prometheus_dump(get_tracer()))
            os.replace(tmp, self._path)
        except OSError as e:
            logger.warning(f"TelemetryMonitor: prometheus write failed: {e}")

    def close(self):
        self._rewrite()
