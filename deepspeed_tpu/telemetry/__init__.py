"""deepspeed_tpu.telemetry — unified structured tracing & metrics.

Usage::

    from deepspeed_tpu.telemetry import get_tracer
    tr = get_tracer()
    tr.configure(enabled=True)
    with tr.span("fwd") as sp:
        loss = step(...)
        sp.sync_on(loss)          # honest timing under async dispatch
    from deepspeed_tpu.telemetry.export import write_chrome_trace
    write_chrome_trace("trace.json")   # load in ui.perfetto.dev

Training runs enable it via the ``"telemetry"`` config block
(runtime/config.py); serving via ``ServingConfig.telemetry``. See
docs/observability.md.
"""

from .trace import (Span, Tracer, RecompileWatchdog, get_tracer,
                    configure_tracer)
from .export import (chrome_trace, write_chrome_trace, chrome_trace_slice,
                     metrics_snapshot, write_snapshot, prometheus_dump,
                     span_aggregates, comm_table)
from .monitor_sink import TelemetryMonitor
from .goodput import GoodputLedger, get_ledger, configure_ledger
from .statusz import StatuszServer
from .flight_recorder import FlightRecorder
from .hostagg import HostAggregator
from .compileplane import (CompileLedger, HBMLedger, fingerprint_args,
                           diff_fingerprints)
from .overlap import OverlapAnalyzer, interval_overlap, overlap_from_events
from .disttrace import (TraceContext, FleetAggregator, merge_chrome_traces,
                        split_events_by_replica, CRITICAL_PATH_STAGES)
from .scorecard import (SCORECARD_KIND, INVARIANTS, check_invariants,
                        fold_scorecard, diff_scorecards, write_scorecard)
from .perfplane import (ANATOMY_KIND, PerfPlane, anatomy_from_hlo,
                        measured_anatomy_from_trace, reconcile_anatomy,
                        diff_anatomy, check_anatomy_invariants,
                        write_anatomy)

__all__ = ["Span", "Tracer", "RecompileWatchdog", "get_tracer",
           "configure_tracer", "chrome_trace", "write_chrome_trace",
           "chrome_trace_slice", "metrics_snapshot", "write_snapshot",
           "prometheus_dump", "span_aggregates", "comm_table",
           "TelemetryMonitor", "GoodputLedger", "get_ledger",
           "configure_ledger", "StatuszServer", "FlightRecorder",
           "HostAggregator", "CompileLedger", "HBMLedger",
           "fingerprint_args", "diff_fingerprints", "OverlapAnalyzer",
           "interval_overlap", "overlap_from_events",
           "TraceContext", "FleetAggregator", "merge_chrome_traces",
           "split_events_by_replica", "CRITICAL_PATH_STAGES",
           "SCORECARD_KIND", "INVARIANTS", "check_invariants",
           "fold_scorecard", "diff_scorecards", "write_scorecard",
           "ANATOMY_KIND", "PerfPlane", "anatomy_from_hlo",
           "measured_anatomy_from_trace", "reconcile_anatomy",
           "diff_anatomy", "check_anatomy_invariants", "write_anatomy"]
