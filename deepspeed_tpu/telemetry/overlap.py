"""Collective-overlap analyzer — is communication hidden under compute?

ROADMAP item 2 (T3-style fine-grained overlap, arxiv 2401.16677;
DeepCompile schedule autotuning, arxiv 2504.09983) needs a before/after
instrument: the **overlap fraction** — of every second the interconnect
is busy moving collectives, how much runs concurrently with compute.
This module computes it from three sources, cheapest to deepest:

1. **HLO async start/done pairs** (static, CPU-runnable): what fraction
   of the compiled module's collectives are even *overlappable* —
   ``hlo_overlap_summary`` in telemetry/hlo_cost.py, re-exported here
   and captured per compile event by the compile ledger. This is the
   ``benchmarks/hlo_audit.py`` column.
2. **The span ring** (host-side, always on with the tracer): interval
   overlap between ``cat="comm"`` spans and compute spans. Honest for
   the explicit shard_map comm path and host-orchestrated work; under a
   single fused XLA step the host ring only sees dispatch, so the gauge
   is labelled by its source.
3. **A device trace** (``jax.profiler`` Perfetto file): per-op device
   wall time, collectives classified by op name — the ground truth on
   hardware, same file ``profiling/flops_profiler.py`` reads for wall
   fractions.

All three reduce through one pure function, ``interval_overlap``:
merge the compute intervals, clip each comm interval against the merged
set, ``overlap_fraction = overlapped_comm_time / comm_time`` ∈ [0, 1].

``OverlapAnalyzer`` is the engine-facing wrapper: throttled ring
analysis, the ``overlap/fraction`` gauge, and a statusz section.
"""

import gzip
import json
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .hlo_cost import hlo_overlap_summary  # noqa: F401  (re-export)
from .trace import get_tracer

__all__ = ["interval_overlap", "overlap_from_events", "overlap_from_tracer",
           "overlap_from_trace_file", "hlo_overlap_summary",
           "OverlapAnalyzer"]

#: device/trace op names that are communication (XLA op names, jax
#: primitive names, and this repo's comm-span op labels)
COMM_NAME_RE = re.compile(
    r"all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute|"
    r"all_reduce|all_gather|reduce_scatter|all_to_all|psum|ppermute|"
    r"send|recv-", re.IGNORECASE)

#: span categories never counted as compute
_NON_COMPUTE_CATS = ("comm", "warning", "async", "mem")


def interval_overlap(comm: Sequence[Tuple[float, float]],
                     compute: Sequence[Tuple[float, float]]) \
        -> Dict[str, float]:
    """Overlap of ``comm`` intervals against the union of ``compute``
    intervals (each a (start, end) pair, any consistent unit). Returns
    comm/compute busy time, the overlapped comm time, and
    ``overlap_fraction`` = overlapped / comm ∈ [0, 1] (0.0 when there is
    no communication at all)."""

    def merged(ivs):
        out = []
        for s, e in sorted((s, e) for s, e in ivs if e > s):
            if out and s <= out[-1][1]:
                out[-1][1] = max(out[-1][1], e)
            else:
                out.append([s, e])
        return out

    comp = merged(compute)
    comm_m = merged(comm)
    comm_t = sum(e - s for s, e in comm_m)
    comp_t = sum(e - s for s, e in comp)
    overlapped = 0.0
    ci = 0
    for s, e in comm_m:
        while ci < len(comp) and comp[ci][1] <= s:
            ci += 1
        j = ci
        while j < len(comp) and comp[j][0] < e:
            overlapped += min(e, comp[j][1]) - max(s, comp[j][0])
            j += 1
    return {
        "comm_s": comm_t,
        "compute_s": comp_t,
        "overlapped_s": overlapped,
        "overlap_fraction": round(overlapped / comm_t, 6) if comm_t else 0.0,
    }


def _default_is_comm(ev: Dict[str, Any]) -> bool:
    return "comm" in str(ev.get("cat", "")) or \
        bool(COMM_NAME_RE.search(str(ev.get("name", ""))))


def _default_is_compute(ev: Dict[str, Any]) -> bool:
    return str(ev.get("cat", "")) not in _NON_COMPUTE_CATS


def overlap_from_events(events: Sequence[Dict[str, Any]],
                        is_comm: Optional[Callable] = None,
                        is_compute: Optional[Callable] = None) \
        -> Dict[str, float]:
    """Overlap over Chrome trace-event dicts (ph="X" complete events,
    ``ts``/``dur`` in µs). Default classification: an event is comm when
    its category contains "comm" or its name matches a collective; every
    other complete event with positive duration is compute. Nested
    compute spans are handled by the interval union."""
    is_comm = is_comm or _default_is_comm
    is_compute = is_compute or _default_is_compute
    comm: List[Tuple[float, float]] = []
    compute: List[Tuple[float, float]] = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur = float(ev.get("dur", 0.0))
        if dur <= 0:
            continue
        ts = float(ev.get("ts", 0.0))
        if is_comm(ev):
            comm.append((ts, ts + dur))
        elif is_compute(ev):
            compute.append((ts, ts + dur))
    out = interval_overlap(comm, compute)
    return {k: (round(v / 1e6, 6) if k.endswith("_s") else v)
            for k, v in out.items()}


def overlap_from_tracer(tracer=None, last_ms: Optional[float] = None) \
        -> Dict[str, float]:
    """Overlap over the host span ring (comm spans vs everything else).
    ``last_ms`` restricts to the most recent window. Iterates the span
    records directly — no Chrome-event dicts are built, so this stays
    cheap enough for a per-N-steps gauge cadence on a full ring. Ring
    spans are classified by category alone (the comm layer always tags
    its spans ``cat="comm"``); the name regex is for foreign traces."""
    import time as _time
    tracer = tracer or get_tracer()
    cutoff = None if last_ms is None else \
        _time.perf_counter_ns() / 1e3 - float(last_ms) * 1e3
    comm: List[Tuple[float, float]] = []
    compute: List[Tuple[float, float]] = []
    stale = 0
    for sp in reversed(tracer.spans()):
        if sp.ph != "X" or sp.dur_us <= 0:
            continue
        end = sp.ts_us + sp.dur_us
        if cutoff is not None and end < cutoff:
            # the ring is (near-)ordered by end time: once a run of spans
            # falls before the window, the rest does too — stop scanning
            # instead of walking a full 65k-span ring every update
            stale += 1
            if stale > 32:
                break
            continue
        stale = 0
        if sp.cat == "comm":
            comm.append((sp.ts_us, end))
        elif sp.cat not in _NON_COMPUTE_CATS:
            compute.append((sp.ts_us, end))
    out = interval_overlap(comm, compute)
    return {k: (round(v / 1e6, 6) if k.endswith("_s") else v)
            for k, v in out.items()}


def overlap_from_trace_file(path: str) -> Dict[str, float]:
    """Overlap from a ``jax.profiler`` device trace (.trace.json or
    .trace.json.gz): device-op events only ("XLA Ops" threads), comm
    classified by op name — the measured half of ROADMAP item 2's
    success metric."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    tid_names = {(e.get("pid"), e.get("tid")): e["args"]["name"]
                 for e in events
                 if e.get("ph") == "M" and e.get("name") == "thread_name"}
    xla_ops = [e for e in events if e.get("ph") == "X" and
               tid_names.get((e.get("pid"), e.get("tid"))) == "XLA Ops"]
    if xla_ops:
        events = xla_ops
    return overlap_from_events(
        events,
        is_comm=lambda ev: bool(COMM_NAME_RE.search(
            str(ev.get("name", "")) + " " +
            " ".join(str(v) for v in (ev.get("args") or {}).values()))),
        is_compute=lambda ev: True)


class OverlapAnalyzer:
    """Engine-facing wrapper: recompute the ring overlap every
    ``interval_steps`` steps, keep the ``overlap/fraction`` gauge warm,
    and serve the statusz section. The compile ledger feeds the static
    HLO side through ``note_hlo``."""

    def __init__(self, tracer=None, owner: Any = None,
                 interval_steps: int = 16,
                 window_ms: float = 30_000.0,
                 floor: float = 0.0, recorder=None):
        self.tracer = tracer or get_tracer()
        self._owner = owner
        self.interval_steps = max(1, int(interval_steps))
        self.window_ms = float(window_ms)
        #: compile_plane.overlap_floor: a RECOMPILE whose program's
        #: static fraction falls below this fires an ``overlap_drop``
        #: flight-recorder bundle (0 = disabled)
        self.floor = float(floor)
        self.recorder = recorder
        self.floor_breaches = 0
        self.last: Optional[Dict[str, float]] = None
        self.last_hlo: Optional[Dict[str, Any]] = None

    def maybe_update(self, step: int) -> Optional[Dict[str, float]]:
        if step % self.interval_steps != 0:
            return None
        res = overlap_from_tracer(self.tracer, last_ms=self.window_ms)
        self.last = res
        if res["comm_s"] > 0:
            self.tracer.set_counter("overlap/fraction",
                                    res["overlap_fraction"],
                                    owner=self._owner)
        return res

    def note_hlo(self, summary: Dict[str, Any], kind: str = "compile",
                 label: str = "", step: Optional[int] = None):
        """Record the active executable's static overlap summary (the
        compile ledger captures it; the engine calls in on each compile
        event). ``kind="recompile"`` additionally runs the floor check:
        a recompiled program whose dependency-level static fraction
        dropped below ``floor`` fires an ``overlap_drop`` bundle — the
        "my schedule silently de-overlapped" postmortem."""
        self.last_hlo = summary
        self.tracer.set_counter("overlap/hlo_async_fraction",
                                summary.get("async_fraction", 0.0),
                                owner=self._owner)
        static = summary.get("static_overlap_fraction")
        if static is not None:
            self.tracer.set_counter("overlap/hlo_static_fraction",
                                    float(static), owner=self._owner)
        if (self.floor > 0.0 and kind == "recompile" and
                static is not None and float(static) < self.floor):
            self.floor_breaches += 1
            detail = (f"{label or 'step'}: static overlap "
                      f"{float(static):.3f} < floor {self.floor:.3f} "
                      f"after recompile "
                      f"({summary.get('overlappable', 0)}/"
                      f"{summary.get('collectives', 0)} collectives "
                      f"overlappable)")
            self.tracer.instant("overlap_drop", cat="warning",
                                args={"detail": detail})
            if self.recorder is not None:
                self.recorder.trigger("overlap_drop", detail, step=step)

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.last is not None:
            out["trace_overlap_fraction"] = self.last["overlap_fraction"]
            out["trace_comm_s"] = self.last["comm_s"]
            out["trace_overlapped_s"] = self.last["overlapped_s"]
        if self.last_hlo is not None:
            out["hlo_async_fraction"] = self.last_hlo["async_fraction"]
            out["hlo_static_fraction"] = self.last_hlo.get(
                "static_overlap_fraction", 0.0)
            out["hlo_collectives"] = self.last_hlo["collectives"]
            out["hlo_async"] = self.last_hlo["async"]
            out["hlo_overlappable"] = self.last_hlo.get("overlappable", 0)
        if self.floor > 0.0:
            out["overlap_floor"] = self.floor
            out["floor_breaches"] = self.floor_breaches
        if not out:
            out["status"] = "no overlap data yet"
        return out
