"""Telemetry exporters: Chrome trace JSON, metrics snapshot, Prometheus.

Three views over one ``Tracer``:

- ``chrome_trace`` / ``write_chrome_trace`` — trace-event JSON loadable in
  Perfetto (ui.perfetto.dev) or chrome://tracing. Complete spans become
  ``ph="X"`` events (nesting falls out of ts/dur on a shared tid); async
  request spans become ``ph="b"/"e"`` pairs keyed by request id.
- ``metrics_snapshot`` / ``write_snapshot`` — JSON aggregates: per-span
  count/total/mean/max, the counter gauges (MFU, recompiles, memory
  high-water, serving gauges), and a per-collective table with payload
  bytes and derived algorithm/bus bandwidth (comm/logging.py formulas).
- ``prometheus_dump`` — the same gauges in Prometheus text exposition
  format, for scrape-by-file or pushgateway-style export. Also what the
  ``TelemetryMonitor`` sink writes.
"""

import json
import re
import time
from typing import Any, Dict, List, Optional

from .trace import Tracer, get_tracer


def _calc_bw(op, nbytes, dur_s, n):
    # deferred: comm/comm.py imports telemetry.trace, so a module-level
    # import of comm.logging here would be order-sensitive
    from ..comm.logging import calc_bw_log
    return calc_bw_log(op, nbytes, dur_s, n)

__all__ = ["chrome_trace", "write_chrome_trace", "chrome_trace_slice",
           "span_aggregates", "comm_table", "metrics_snapshot",
           "write_snapshot", "prometheus_dump"]


def _pid() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def chrome_trace(tracer: Optional[Tracer] = None) -> Dict[str, Any]:
    """Trace-event JSON dict (Perfetto-loadable)."""
    tracer = tracer or get_tracer()
    pid = _pid()
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": f"deepspeed_tpu rank {pid}"},
    }]
    tids: List[int] = []
    for sp in tracer.spans():
        ev: Dict[str, Any] = {"name": sp.name, "cat": sp.cat, "ph": sp.ph,
                              "ts": sp.ts_us, "pid": pid, "tid": sp.tid}
        if sp.tid not in tids:
            tids.append(sp.tid)
        if sp.ph == "X":
            ev["dur"] = sp.dur_us
        if sp.ph in ("b", "e"):
            ev["id"] = format(sp.aid or 0, "x")
        if sp.ph == "i":
            ev["s"] = "t"      # thread-scoped instant
        args = dict(sp.args) if sp.args else {}
        if sp.cat == "comm" and sp.ph == "X":
            args.update(_bw_args(sp))
        if args:
            ev["args"] = args
        events.append(ev)
    # readable thread rows: raw thread idents are meaningless 15-digit
    # numbers in the Perfetto UI (the fleet-merged view re-labels lanes
    # per replica on top of this — telemetry/disttrace.py)
    for j, tid in enumerate(tids):
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": f"thread {j}"}})
        events.append({"name": "thread_sort_index", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"sort_index": j}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": tracer.dropped}}


def write_chrome_trace(path: str, tracer: Optional[Tracer] = None) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(tracer), f)
    return path


def chrome_trace_slice(tracer: Optional[Tracer] = None,
                       last_ms: Optional[float] = None) -> Dict[str, Any]:
    """Chrome trace JSON cut to the last ``last_ms`` milliseconds of span
    activity (span timestamps share the ``perf_counter_ns`` clock, so
    "now" is directly comparable). ``None`` = the full buffer. Shared by
    the statusz ``/trace`` endpoint and the flight-recorder bundles."""
    doc = chrome_trace(tracer)
    if last_ms is None:
        return doc
    cutoff = time.perf_counter_ns() / 1e3 - float(last_ms) * 1e3
    doc["traceEvents"] = [
        ev for ev in doc["traceEvents"]
        if ev["ph"] == "M" or
        ev.get("ts", 0) + ev.get("dur", 0) >= cutoff]
    return doc


def _bw_args(sp) -> Dict[str, float]:
    """Derived bandwidth for a comm span (GB/s, from measured duration —
    trace-time spans have ~0 duration and report 0). When the span carries
    ``wire_bytes`` (the dispatch's per-member link-byte model, compressed
    size when a codec ran) the bus bandwidth is wire_bytes ÷ duration
    directly; the analytic ring factors are only applied to legacy spans
    that lack it."""
    args = sp.args or {}
    nbytes = int(args.get("bytes", 0))
    n = int(args.get("participants", 0)) or 1
    dur_s = sp.dur_us / 1e6
    wire = args.get("wire_bytes")
    if wire is not None and dur_s > 0:
        return {"algbw_gbps": round(nbytes / dur_s / 1e9, 3),
                "busbw_gbps": round(int(wire) / dur_s / 1e9, 3)}
    algbw, busbw = _calc_bw(args.get("op", sp.name), nbytes, dur_s, n)
    return {"algbw_gbps": round(algbw, 3), "busbw_gbps": round(busbw, 3)}


def span_aggregates(tracer: Optional[Tracer] = None) -> Dict[str, Any]:
    """Per-name aggregates over complete spans: where did the time go."""
    tracer = tracer or get_tracer()
    out: Dict[str, Any] = {}
    for sp in tracer.spans():
        if sp.ph != "X":
            continue
        rec = out.setdefault(sp.name, {"count": 0, "total_ms": 0.0,
                                       "max_ms": 0.0})
        rec["count"] += 1
        rec["total_ms"] += sp.dur_us / 1e3
        rec["max_ms"] = max(rec["max_ms"], sp.dur_us / 1e3)
    for rec in out.values():
        rec["mean_ms"] = rec["total_ms"] / rec["count"]
        rec["total_ms"] = round(rec["total_ms"], 4)
        rec["mean_ms"] = round(rec["mean_ms"], 4)
        rec["max_ms"] = round(rec["max_ms"], 4)
    return out


def comm_table(tracer: Optional[Tracer] = None) -> Dict[str, Any]:
    """Per-collective totals: calls, payload bytes, derived bus bandwidth."""
    tracer = tracer or get_tracer()
    out: Dict[str, Any] = {}
    for sp in tracer.spans():
        if sp.cat != "comm" or sp.ph != "X":
            continue
        args = sp.args or {}
        op = args.get("op", sp.name)
        rec = out.setdefault(op, {"calls": 0, "bytes": 0, "wire_bytes": 0,
                                  "total_ms": 0.0,
                                  "participants": int(
                                      args.get("participants", 0))})
        rec["calls"] += 1
        rec["bytes"] += int(args.get("bytes", 0))
        rec["wire_bytes"] += int(args.get("wire_bytes", 0))
        pol = args.get("policy")
        if pol:
            rec["policy"] = pol
        rec["total_ms"] += sp.dur_us / 1e3
    for op, rec in out.items():
        dur_s = rec["total_ms"] / 1e3
        if rec["wire_bytes"] and dur_s > 0:
            # wire bytes come from the dispatch's link model (compressed
            # size when a codec ran): bus bw is wire ÷ time directly
            rec["algbw_gbps"] = round(rec["bytes"] / dur_s / 1e9, 3)
            rec["busbw_gbps"] = round(rec["wire_bytes"] / dur_s / 1e9, 3)
        else:
            algbw, busbw = _calc_bw(op, rec["bytes"], dur_s,
                                    max(rec["participants"], 1))
            rec["algbw_gbps"] = round(algbw, 3)
            rec["busbw_gbps"] = round(busbw, 3)
        rec["total_ms"] = round(rec["total_ms"], 4)
    return out


def metrics_snapshot(tracer: Optional[Tracer] = None,
                     extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One JSON document answering "where did this step's time go": span
    aggregates + gauges (MFU, recompiles, memory) + comm table."""
    tracer = tracer or get_tracer()
    counters = {tag: val for tag, (val, _step) in tracer.counters().items()}
    snap = {"spans": span_aggregates(tracer), "counters": counters,
            "comm": comm_table(tracer), "dropped_spans": tracer.dropped}
    from .goodput import get_ledger
    ledger = get_ledger()
    if ledger.enabled:
        snap["goodput"] = ledger.snapshot()
    if extra:
        snap.update(extra)
    return snap


def write_snapshot(path: str, tracer: Optional[Tracer] = None,
                   extra: Optional[Dict[str, Any]] = None) -> str:
    with open(path, "w") as f:
        json.dump(metrics_snapshot(tracer, extra=extra), f, indent=2,
                  default=str)
    return path


_PROM_NAME = re.compile(r"[^a-zA-Z0-9_:]")


def _prom(name: str) -> str:
    name = _PROM_NAME.sub("_", name)
    return name if not name[:1].isdigit() else "_" + name


def prometheus_dump(tracer: Optional[Tracer] = None,
                    prefix: str = "dstpu") -> str:
    """Prometheus text exposition of the gauges + span aggregates."""
    tracer = tracer or get_tracer()
    lines: List[str] = []
    host_lines: List[str] = []
    tenant_series: Dict[str, List[str]] = {}
    cost_series: Dict[str, List[str]] = {}
    anat_series: Dict[str, List[str]] = {}
    lines.append(f"# TYPE {prefix}_metric gauge")
    for tag, (val, _step) in sorted(tracer.counters().items()):
        try:
            fval = float(val)
        except (TypeError, ValueError):
            continue
        if tag.startswith("host/"):
            # per-host aggregates (telemetry/hostagg.py) get dedicated
            # series — dashboards alert on dstpu_host_step_time_spread
            # without label-matching through the generic gauge
            name = _prom(tag[len("host/"):])
            host_lines.append(f"# TYPE {prefix}_host_{name} gauge")
            host_lines.append(f"{prefix}_host_{name} {fval}")
            continue
        if tag.startswith("mem/"):
            # HBM role attribution (telemetry/compileplane.py HBMLedger):
            # dedicated dstpu_mem_* series so a dashboard stacks
            # params/grads/optimizer/activations/kv_slots directly
            name = _prom(tag[len("mem/"):])
            host_lines.append(f"# TYPE {prefix}_mem_{name} gauge")
            host_lines.append(f"{prefix}_mem_{name} {fval}")
            continue
        if tag.startswith("fleet/"):
            # fleet router gauges (serving/metrics.py FleetMetrics):
            # dstpu_fleet_ready_replicas / _failovers / _kv_handoffs /
            # _prefix_cache_hit_rate as first-class alerting series
            name = _prom(tag[len("fleet/"):])
            host_lines.append(f"# TYPE {prefix}_fleet_{name} gauge")
            host_lines.append(f"{prefix}_fleet_{name} {fval}")
            continue
        if tag.startswith("tenant/"):
            # per-tenant SLO gauges (serving/metrics.py tenant windows,
            # router throttle counts): tenant/<name>/<metric> becomes a
            # tenant=-labeled dstpu_tenant_<metric> series — dashboards
            # rank tenants by burn rate / share with one query instead of
            # label-matching through the generic gauge
            tname, _, metric = tag[len("tenant/"):].partition("/")
            if metric:
                name = _prom(metric)
                tenant_series.setdefault(name, []).append(
                    f'{prefix}_tenant_{name}{{tenant="{_prom(tname)}"}} '
                    f"{fval}")
                continue
        if tag.startswith("cost/"):
            # cost-plane attribution (serving/metrics.py update_cost,
            # folded at the router from telemetry/costplane.py ledgers):
            # cost/<tenant>/<metric> becomes a tenant=-labeled
            # dstpu_cost_<metric> series — chargeback dashboards rank
            # tenants by chip-milliseconds / HBM-GiB-seconds with one
            # query instead of label-matching through the generic gauge
            tname, _, metric = tag[len("cost/"):].partition("/")
            if metric:
                name = _prom(metric)
                cost_series.setdefault(name, []).append(
                    f'{prefix}_cost_{name}{{tenant="{_prom(tname)}"}} '
                    f"{fval}")
                continue
        if tag.startswith("elastic/"):
            # elasticity gauges (elasticity/coordinator.py on the
            # training side, FleetMetrics.update_autoscale on the serving
            # side): dedicated dstpu_elastic_world_size / _hosts_missing /
            # _resizes / _live_replicas / _scale_ups series — a fleet
            # changing size is an alerting event, not a label lookup
            name = _prom(tag[len("elastic/"):])
            host_lines.append(f"# TYPE {prefix}_elastic_{name} gauge")
            host_lines.append(f"{prefix}_elastic_{name} {fval}")
            continue
        if tag.startswith("moe/"):
            # expert-parallel telemetry (moe/sharded_moe.py MoeMetrics):
            # dedicated dstpu_moe_load_imbalance / _dropped_token_fraction
            # / _overflow_tokens series — capacity-factor overflow is an
            # alerting target (dropped tokens are silent quality loss),
            # not a label-matched lookup
            name = _prom(tag[len("moe/"):])
            host_lines.append(f"# TYPE {prefix}_moe_{name} gauge")
            host_lines.append(f"{prefix}_moe_{name} {fval}")
            continue
        if tag.startswith("spec/"):
            # speculative-decode gauges (serving/metrics.py): dedicated
            # dstpu_spec_acceptance_ema / _tokens_per_tick / _draft_ms /
            # _verify_ms series — the acceptance floor is an alerting
            # target, not a label-matched lookup
            name = _prom(tag[len("spec/"):])
            host_lines.append(f"# TYPE {prefix}_spec_{name} gauge")
            host_lines.append(f"{prefix}_spec_{name} {fval}")
            continue
        if tag.startswith("anat/"):
            # perf-plane anatomy gauges (telemetry/perfplane.py): one
            # program=-labeled dstpu_anat_<bucket>_ms family per bucket
            # so a dashboard stacks a step/tick's time decomposition
            # with one query; bare anat/<metric> (regressions counter)
            # exports unlabeled
            pname, _, metric = tag[len("anat/"):].partition("/")
            if metric:
                name = _prom(metric)
                anat_series.setdefault(name, []).append(
                    f'{prefix}_anat_{name}{{program="{_prom(pname)}"}} '
                    f"{fval}")
            else:
                name = _prom(pname)
                host_lines.append(f"# TYPE {prefix}_anat_{name} gauge")
                host_lines.append(f"{prefix}_anat_{name} {fval}")
            continue
        if tag.startswith("rollout/"):
            # rollout plane gauges (serving/metrics.py update_rollout):
            # dedicated dstpu_rollout_shift_fraction / _version_skew /
            # _rollbacks series — a rollback is a paging event and
            # nonzero steady-state skew is a stuck rollout, not a
            # label-matched lookup
            name = _prom(tag[len("rollout/"):])
            host_lines.append(f"# TYPE {prefix}_rollout_{name} gauge")
            host_lines.append(f"{prefix}_rollout_{name} {fval}")
            continue
        lines.append(f'{prefix}_metric{{tag="{_prom(tag)}"}} {fval}')
    lines.extend(host_lines)
    for name in sorted(tenant_series):
        # one TYPE header per family, samples contiguous per the
        # exposition format (tenants vary only by label)
        lines.append(f"# TYPE {prefix}_tenant_{name} gauge")
        lines.extend(tenant_series[name])
    for name in sorted(cost_series):
        lines.append(f"# TYPE {prefix}_cost_{name} gauge")
        lines.extend(cost_series[name])
    for name in sorted(anat_series):
        lines.append(f"# TYPE {prefix}_anat_{name} gauge")
        lines.extend(anat_series[name])
    aggs = span_aggregates(tracer)
    if aggs:
        lines.append(f"# TYPE {prefix}_span_ms_total counter")
        lines.append(f"# TYPE {prefix}_span_count counter")
        for name, rec in sorted(aggs.items()):
            lines.append(f'{prefix}_span_ms_total{{name="{_prom(name)}"}} '
                         f'{rec["total_ms"]}')
            lines.append(f'{prefix}_span_count{{name="{_prom(name)}"}} '
                         f'{rec["count"]}')
    from .goodput import get_ledger
    ledger = get_ledger()
    if ledger.enabled:
        snap = ledger.snapshot()
        lines.append(f"# TYPE {prefix}_goodput_seconds gauge")
        for bucket, secs in sorted(snap["buckets"].items()):
            lines.append(
                f'{prefix}_goodput_seconds{{bucket="{_prom(bucket)}"}} '
                f"{secs}")
        lines.append(f"# TYPE {prefix}_goodput_fraction gauge")
        lines.append(f"{prefix}_goodput_fraction "
                     f"{snap['goodput_fraction']}")
        lines.append(f"# TYPE {prefix}_wall_seconds gauge")
        lines.append(f"{prefix}_wall_seconds {snap['wall_s']}")
    lines.append(f"# TYPE {prefix}_dropped_spans gauge")
    lines.append(f"{prefix}_dropped_spans {tracer.dropped}")
    return "\n".join(lines) + "\n"
