"""Fleet soak scorecard — cross-subsystem invariants folded into ONE JSON.

PRs 8–15 each left a ledger behind: the goodput ledger (wall-clock
accounting), per-replica SLO windows, tenant counters, flight-recorder
bundles, and disttrace critical-path windows. Each is checked in its own
unit tests against its own subsystem; nothing checks them against *each
other* under sustained mixed load. The scorecard is that check: one
document folded at the end of a soak run (benchmarks/soak.py) with hard
cross-subsystem invariants evaluated at fold time:

- ``goodput_sums_to_wall``      — fleet goodput buckets (idle residual
  included) sum to measured wall-clock within ``goodput_wall_rel``, and
  serving work was actually attributed. An in-process fleet ticks its
  replicas sequentially on one thread against the process-global ledger,
  so attributed time sums to 1 x wall (``live_replica_seconds`` is
  recorded alongside for the multi-process reading of the same law);
  a hole means lost accounting, an overshoot means double-counting.
- ``exactly_once_streaming``    — zero dropped / duplicated / mismatched
  streamed tokens across failover and drain. The audit rides the PR-8
  dedup bookkeeping: every ``on_token`` delivery is recorded with its
  delivered position and compared against the request's final token
  list.
- ``slo_burn_recovers``         — after every chaos event the fleet burn
  rate returns to <= 1.0 within ``recovery_window_s``, and ends <= 1.0.
- ``autoscale_matches_load``    — the injected load shape's obligations
  were met: >= 1 scale-up per burst window, >= 1 failover per kill, and
  the live replica count respected the configured bounds.
- ``critical_path_decomposes``  — the aggregator's aligned stage-mean
  sum equals the mean e2e within ``critical_path_rel`` (per-request
  decomposition is exact by construction; the folded check guards the
  aggregation).
- ``bundle_retention_bounded``  — after minutes of sustained triggers,
  every member's bundle dir holds <= keep bundles and <= keep
  cross-replica postmortems (the unbounded-growth failure this PR
  fixed), with per-kind counts recorded.
- ``rollout_converges``         — when the trace injects a ``rollout``
  chaos event, the rolling weight update completed (no rollback for the
  soak's same-version rollout — its bitwise canary has a ground truth),
  version skew returned to zero within ``recovery_window_s`` and ends
  at zero, and the token audit stayed clean across the swap.
- ``cost_attribution_conserved`` — the cost plane's fold (per-tenant
  chip-seconds + the explicit overhead residual) sums to the fleet's
  serving wall-clock within ``cost_wall_rel``, tracks the goodput
  ledger's serving buckets within ``cost_goodput_rel``, and the radix
  cache's recorded savings never price a reused token above
  ``cost_savings_slack`` x the paid per-token prefill rate (savings
  must not overstate the cost they displaced). Lenient when the run
  folded no ``costs`` section — the plane is opt-in.

This module is stdlib-only on purpose: ``bin/ds_tpu_soakdiff`` loads it
by file path on machines with no jax/numpy, and ``check_invariants`` /
``diff_scorecards`` are pure functions over JSON-shaped dicts so the
rigged-input tests need no fleet.
"""

import json
import os
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["SCORECARD_KIND", "SCORECARD_VERSION", "INVARIANTS",
           "DEFAULT_TOLERANCES", "DIFF_TOLERANCES", "check_invariants",
           "fold_scorecard", "diff_scorecards", "format_diff",
           "write_scorecard"]

SCORECARD_KIND = "soak_scorecard"
SCORECARD_VERSION = 1

#: invariant names, in report order
INVARIANTS = ("goodput_sums_to_wall", "exactly_once_streaming",
              "slo_burn_recovers", "autoscale_matches_load",
              "critical_path_decomposes", "bundle_retention_bounded",
              "rollout_converges", "cost_attribution_conserved")

#: fold-time invariant tolerances (overridable per scorecard; the used
#: values are embedded in the document so a reader sees what was checked)
DEFAULT_TOLERANCES = {
    "goodput_wall_rel": 0.02,        # +/-2% fleet-wide, per the contract
    "recovery_window_s": 20.0,
    "critical_path_rel": 0.05,
    "critical_path_floor_ms": 0.5,
    "cost_wall_rel": 0.02,           # tenant chip + overhead == serving wall
    "cost_goodput_rel": 0.25,        # cost wall vs goodput serving buckets
    "cost_savings_slack": 2.0,       # savings rate vs paid prefill rate
}

#: soak-diff noise tolerances: metric path -> (mode, bound). ``min_ratio``
#: fails when candidate < bound x baseline; ``max_ratio`` when candidate >
#: bound x baseline; ``abs_band`` when |candidate - baseline| > bound;
#: ``max_abs`` when candidate > bound regardless of baseline. Latency on
#: a shared CPU host is noisy — the ratio bands are deliberately wide;
#: the *hard* signals (invariants, token audit) have no band at all.
DIFF_TOLERANCES: Dict[str, Tuple[str, float]] = {
    "goodput.goodput_fraction": ("min_ratio", 0.60),
    "fleet.completed": ("min_ratio", 0.90),
    "fleet.failovers": ("abs_band", 2),
    "fleet.scale_ups": ("abs_band", 3),
    "token_audit.dropped": ("max_abs", 0),
    "token_audit.duplicated": ("max_abs", 0),
    "token_audit.mismatched": ("max_abs", 0),
    "token_audit.failed_requests": ("max_abs", 0),
    "rollout.rollbacks": ("max_abs", 0),
    "rollout.rollouts": ("abs_band", 0),
    "latency.ttft_ms_p99": ("max_ratio", 3.0),
    "latency.e2e_ms_p95": ("max_ratio", 3.0),
    "critical_path.e2e_ms_mean": ("max_ratio", 3.0),
    "wall_s": ("max_ratio", 2.0),
    "costs.serving_wall_s": ("max_ratio", 2.0),
    "costs.overhead_s": ("max_ratio", 3.0),
}


def _get(doc: Dict[str, Any], path: str, default=None):
    cur: Any = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


# ---------------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------------

def _inv_goodput(doc, tol) -> Tuple[bool, str]:
    g = doc.get("goodput")
    if not g:
        return False, "no goodput window in scorecard"
    wall = float(g.get("wall_s") or 0.0)
    if wall <= 0:
        return False, "goodput wall_s is zero"
    buckets = g.get("buckets") or {}
    total = sum(float(v) for v in buckets.values())
    serving = float(buckets.get("serving_step", 0.0)) \
        + float(buckets.get("serving_drain", 0.0))
    rel = tol["goodput_wall_rel"]
    if serving <= 0:
        return False, "no serving_step/serving_drain time attributed"
    if abs(total - wall) > rel * wall:
        kind = "hole (lost accounting)" if total < wall \
            else "overshoot (double-counted interval)"
        return False, (f"buckets sum {total:.3f}s vs wall {wall:.3f}s "
                       f"({kind}, tol {rel:.0%})")
    return True, (f"buckets sum {total:.3f}s == wall {wall:.3f}s "
                  f"(+/-{rel:.0%}); serving {serving:.3f}s")


def _inv_streaming(doc, tol) -> Tuple[bool, str]:
    ta = doc.get("token_audit")
    if not ta:
        return False, "no token audit in scorecard"
    if int(ta.get("audited") or 0) <= 0:
        return False, "token audit saw zero requests"
    bad = {k: int(ta.get(k) or 0)
           for k in ("dropped", "duplicated", "mismatched",
                     "failed_requests")}
    if any(bad.values()):
        return False, ("exactly-once violated: " +
                       ", ".join(f"{k}={v}" for k, v in bad.items()
                                 if v))
    return True, (f"{ta.get('streamed_tokens', 0)} tokens over "
                  f"{ta.get('audited', 0)} requests, 0 dropped / "
                  f"0 duplicated (failovers={_get(doc, 'fleet.failovers', 0)})")


def _inv_burn(doc, tol) -> Tuple[bool, str]:
    series = _get(doc, "slo.burn_series") or []
    chaos = doc.get("chaos") or []
    if not series:
        return False, "no burn samples recorded"
    window = tol["recovery_window_s"]
    final = float(series[-1][1])
    if final > 1.0:
        return False, f"final burn {final:.2f} > 1.0"
    recoveries = []
    for ev in chaos:
        t0 = float(ev.get("t_s") or 0.0)
        rec_at = next((float(t) for t, b in series
                       if t >= t0 and float(b) <= 1.0), None)
        if rec_at is None or rec_at - t0 > window:
            return False, (f"burn after {ev.get('kind')}@{t0:.1f}s did "
                           f"not recover within {window:g}s")
        recoveries.append(f"{ev.get('kind')}@{t0:.1f}s: "
                          f"{rec_at - t0:.1f}s")
    return True, ("recovered <= 1.0 after every chaos event ("
                  + "; ".join(recoveries) + ")" if recoveries
                  else f"final burn {final:.2f} <= 1.0 (no chaos)")


def _inv_autoscale(doc, tol) -> Tuple[bool, str]:
    exp = doc.get("expected") or {}
    ups = int(_get(doc, "fleet.scale_ups", 0) or 0)
    fails = int(_get(doc, "fleet.failovers", 0) or 0)
    need_ups = int(exp.get("scale_ups_min") or 0)
    need_fails = int(exp.get("failovers_min") or 0)
    if ups < need_ups:
        return False, (f"{ups} scale-up(s) vs >= {need_ups} demanded by "
                       f"the injected burst(s)")
    if fails < need_fails:
        return False, (f"{fails} failover(s) vs >= {need_fails} demanded "
                       f"by the injected kill(s)")
    live = _get(doc, "autoscale.live_replicas")
    lo = _get(doc, "autoscale.min_replicas")
    hi = _get(doc, "autoscale.max_replicas")
    if live is not None and lo is not None and hi is not None and \
            not (int(lo) <= int(live) <= int(hi)):
        return False, (f"live replicas {live} outside autoscale bounds "
                       f"[{lo}, {hi}]")
    return True, (f"scale_ups={ups} (>= {need_ups}), failovers={fails} "
                  f"(>= {need_fails}), live={live} in [{lo}, {hi}]")


def _inv_critical_path(doc, tol) -> Tuple[bool, str]:
    cp = doc.get("critical_path")
    if not cp:
        return False, "no critical-path summary in scorecard"
    if int(cp.get("requests") or 0) <= 0:
        return False, "critical path observed zero requests"
    e2e = float(cp.get("e2e_ms_mean") or 0.0)
    ssum = float(cp.get("stage_sum_ms_mean") or 0.0)
    slack = max(tol["critical_path_floor_ms"],
                tol["critical_path_rel"] * e2e)
    if abs(ssum - e2e) > slack:
        return False, (f"stage sum {ssum:.2f}ms != e2e mean {e2e:.2f}ms "
                       f"(slack {slack:.2f}ms)")
    return True, (f"stage sum {ssum:.2f}ms == e2e mean {e2e:.2f}ms over "
                  f"{cp['requests']} request(s)")


def _inv_bundles(doc, tol) -> Tuple[bool, str]:
    members = _get(doc, "flight_recorder.members")
    if not members:
        return False, "no flight-recorder members in scorecard"
    total = 0
    for name, m in members.items():
        keep = int(m.get("keep") or 0)
        bundles = int(m.get("bundles") or 0)
        crossrep = int(m.get("crossrep") or 0)
        total += bundles
        if keep and bundles > keep:
            return False, (f"{name}: {bundles} bundles on disk > "
                           f"keep={keep} (retention leak)")
        if keep and crossrep > keep:
            return False, (f"{name}: {crossrep} crossrep docs on disk > "
                           f"keep={keep} (retention leak)")
    return True, (f"{total} bundle(s) across {len(members)} member(s), "
                  f"all within keep")


def _inv_rollout(doc, tol) -> Tuple[bool, str]:
    exp = doc.get("expected") or {}
    need = int(exp.get("rollouts") or 0)
    if need <= 0:
        return True, "no rollout injected"
    ro = doc.get("rollout") or {}
    if not ro:
        return False, "rollout injected but no rollout section folded"
    done = int(ro.get("rollouts") or 0)
    rollbacks = int(ro.get("rollbacks") or 0)
    if done < need:
        return False, (f"{done} rollout(s) completed vs >= {need} "
                       f"injected by the trace")
    if rollbacks:
        return False, (f"{rollbacks} rollback(s) — the soak's "
                       f"same-version rollout must pass its bitwise "
                       f"canary")
    window = tol["recovery_window_s"]
    series = ro.get("skew_series") or []
    final = int(series[-1][1]) if series \
        else int(ro.get("version_skew") or 0)
    if final != 0:
        return False, f"final version skew {final} != 0"
    last_bad = None
    for t, s in series:
        if int(s) != 0:
            last_bad = float(t)
    if last_bad is not None:
        rec_at = next((float(t) for t, s in series
                       if float(t) > last_bad and int(s) == 0), None)
        if rec_at is None or rec_at - last_bad > window:
            return False, (f"version skew did not return to 0 within "
                           f"{window:g}s of its last excursion")
    ta = doc.get("token_audit") or {}
    bad = sum(int(ta.get(k) or 0)
              for k in ("dropped", "duplicated", "mismatched"))
    if bad:
        return False, "token stream integrity violated across the swap"
    return True, (f"{done} rollout(s), 0 rollbacks, version skew 0 "
                  f"(canary {ro.get('canary_verdict')})")


def _inv_cost(doc, tol) -> Tuple[bool, str]:
    costs = doc.get("costs")
    if not costs:
        # the plane is opt-in: a run without it has nothing to conserve
        return True, "no costs section (cost plane off)"
    wall = float(costs.get("serving_wall_s") or 0.0)
    if wall <= 0:
        return False, "cost plane enabled but serving_wall_s is zero"
    tenants = costs.get("tenants") or {}
    chip_s = sum(float(r.get("chip_ms") or 0.0)
                 for r in tenants.values()) / 1e3
    overhead = float(costs.get("overhead_s") or 0.0)
    total = chip_s + overhead
    rel = tol["cost_wall_rel"]
    if abs(total - wall) > rel * wall:
        kind = "hole (unattributed serving time)" if total < wall \
            else "overshoot (double-charged request)"
        return False, (f"tenant chip {chip_s:.3f}s + overhead "
                       f"{overhead:.3f}s = {total:.3f}s vs serving wall "
                       f"{wall:.3f}s ({kind}, tol {rel:.0%})")
    buckets = _get(doc, "goodput.buckets") or {}
    serving = float(buckets.get("serving_step", 0.0)) \
        + float(buckets.get("serving_drain", 0.0))
    grel = tol["cost_goodput_rel"]
    if serving > 0 and abs(wall - serving) > grel * max(serving, wall):
        return False, (f"cost serving wall {wall:.3f}s vs goodput "
                       f"serving buckets {serving:.3f}s (tol {grel:.0%})"
                       f" — the two ledgers disagree")
    saved_tok = sum(int(r.get("cache_saved_tokens") or 0)
                    for r in tenants.values())
    savings_ms = sum(float(r.get("cache_savings_ms") or 0.0)
                     for r in tenants.values())
    prefill_ms = sum(float(r.get("prefill_ms") or 0.0)
                     for r in tenants.values())
    prompt_tok = sum(int(r.get("prompt_tokens") or 0)
                     for r in tenants.values())
    if saved_tok > 0 and prefill_ms > 0:
        paid_rate = prefill_ms / max(1, prompt_tok - saved_tok)
        slack = tol["cost_savings_slack"]
        if savings_ms / saved_tok > slack * paid_rate:
            return False, (f"cache savings {savings_ms / saved_tok:.3f}"
                           f"ms/token > {slack:g}x the paid prefill rate "
                           f"{paid_rate:.3f}ms/token — savings overstate "
                           f"the displaced cost")
    return True, (f"tenant chip {chip_s:.3f}s + overhead {overhead:.3f}s"
                  f" == serving wall {wall:.3f}s (+/-{rel:.0%}); savings "
                  f"{savings_ms:.1f}ms over {saved_tok} reused token(s)")


_CHECKS = {
    "goodput_sums_to_wall": _inv_goodput,
    "exactly_once_streaming": _inv_streaming,
    "slo_burn_recovers": _inv_burn,
    "autoscale_matches_load": _inv_autoscale,
    "critical_path_decomposes": _inv_critical_path,
    "bundle_retention_bounded": _inv_bundles,
    "rollout_converges": _inv_rollout,
    "cost_attribution_conserved": _inv_cost,
}


def check_invariants(doc: Dict[str, Any],
                     tolerances: Optional[Dict[str, float]] = None
                     ) -> Dict[str, Dict[str, Any]]:
    """Evaluate every invariant against a scorecard-shaped dict. Pure:
    no fleet required, so rigged inputs (an injected dropped token, a
    goodput hole, an unrecovered burn) test each named invariant in
    isolation."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(doc.get("tolerances") or {})
    tol.update(tolerances or {})
    out: Dict[str, Dict[str, Any]] = {}
    for name in INVARIANTS:
        try:
            ok, detail = _CHECKS[name](doc, tol)
        except Exception as e:       # a malformed section is a failure,
            ok, detail = False, f"check error: {e}"   # not a crash
        out[name] = {"ok": bool(ok), "detail": detail}
    return out


# ---------------------------------------------------------------------------
# folding
# ---------------------------------------------------------------------------

def _crossrep_count(bundle_dir: str) -> int:
    try:
        return sum(1 for n in os.listdir(bundle_dir)
                   if n.startswith("crossrep-") and n.endswith(".json"))
    except OSError:
        return 0


def _recorder_member(rec) -> Dict[str, Any]:
    by_kind: Dict[str, int] = {}
    entries = rec.bundles()
    for b in entries:
        by_kind[b["kind"]] = by_kind.get(b["kind"], 0) + 1
    return {"keep": int(rec.keep), "bundles": len(entries),
            "by_kind": by_kind, "crossrep": _crossrep_count(rec.dir),
            "triggers": dict(rec.trigger_counts),
            "suppressed": int(rec.suppressed)}


def fold_scorecard(router, *, wall_s: float,
                   goodput: Optional[Dict[str, Any]] = None,
                   token_audit: Optional[Dict[str, Any]] = None,
                   burn_series: Optional[List[List[float]]] = None,
                   chaos: Optional[List[Dict[str, Any]]] = None,
                   expected: Optional[Dict[str, Any]] = None,
                   live_replica_seconds: Optional[float] = None,
                   latency: Optional[Dict[str, float]] = None,
                   trace_summary: Optional[Dict[str, Any]] = None,
                   tolerances: Optional[Dict[str, float]] = None,
                   skew_series: Optional[List[List[float]]] = None,
                   ) -> Dict[str, Any]:
    """Fold one finished soak run into the scorecard document. The
    harness supplies what only it can know (wall clock, the streamed-
    token audit, the burn/chaos timelines, the injected-load
    expectations); everything else is read off the router: fleet
    counters, autoscale + tenant summaries, comm stats, the disttrace
    critical-path summary, and every member's flight-recorder state.
    Invariants are evaluated at fold time and embedded."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    m = router.metrics
    doc: Dict[str, Any] = {
        "kind": SCORECARD_KIND,
        "version": SCORECARD_VERSION,
        "wall_s": round(float(wall_s), 3),
        "tolerances": tol,
        "fleet": {
            "submitted": m.submitted, "completed": m.completed,
            "failovers": m.failovers, "requeued": m.requeued,
            "handoffs": m.handoffs, "throttled": m.throttled,
            "scale_ups": m.scale_ups, "scale_downs": m.scale_downs,
            "tenant_throttled": dict(m.tenant_throttled),
            "replicas": len(router.replicas),
        },
        "autoscale": router.autoscale_summary(),
        "tenants": router.tenant_summary(),
    }
    if live_replica_seconds is not None:
        doc["fleet"]["live_replica_seconds"] = round(
            float(live_replica_seconds), 3)
    if goodput is not None:
        doc["goodput"] = goodput
    if token_audit is not None:
        doc["token_audit"] = token_audit
    doc["slo"] = {"burn_series": [[round(float(t), 3),
                                   round(float(b), 4)]
                                  for t, b in (burn_series or [])]}
    doc["chaos"] = list(chaos or [])
    if expected is not None:
        doc["expected"] = expected
    if latency is not None:
        doc["latency"] = latency
    if trace_summary is not None:
        doc["load"] = trace_summary
    ro = {"rollouts": int(getattr(m, "rollouts", 0)),
          "rollbacks": int(getattr(m, "rollbacks", 0)),
          "canary_failures": int(getattr(m, "canary_failures", 0))}
    if hasattr(router, "version_skew"):
        ro["version_skew"] = router.version_skew()["skew"]
    ctl = getattr(router, "rollout", None)
    if ctl is not None:
        ro["phase"] = ctl.phase
        ro["canary_verdict"] = ctl.canary_verdict
        ro["target_version"] = ctl.target_version
    if skew_series is not None:
        ro["skew_series"] = [[round(float(t), 3), int(s)]
                             for t, s in skew_series]
    doc["rollout"] = ro
    agg = getattr(router, "aggregator", None)
    if agg is not None:
        doc["critical_path"] = agg.critical_path_summary()
    if hasattr(router, "cost_summary"):
        costs = router.cost_summary()
        if costs.get("enabled"):
            doc["costs"] = costs
    try:
        from ..comm.comm import comm_stats
        doc["comm"] = comm_stats()
    except Exception:
        pass
    members: Dict[str, Any] = {}
    rec = getattr(router, "recorder", None)
    if rec is not None:
        members["router"] = _recorder_member(rec)
    for name, handle in router.replicas.items():
        eng_rec = getattr(handle.engine, "_recorder", None)
        if eng_rec is not None:
            members[name] = _recorder_member(eng_rec)
    if members:
        doc["flight_recorder"] = {"members": members}
    doc["invariants"] = check_invariants(doc)
    doc["ok"] = all(v["ok"] for v in doc["invariants"].values())
    return doc


def write_scorecard(doc: Dict[str, Any], path: str) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# diffing (the regression gate)
# ---------------------------------------------------------------------------

def diff_scorecards(base: Dict[str, Any], cand: Dict[str, Any],
                    tolerances: Optional[Dict[str, Tuple[str, float]]]
                    = None) -> Tuple[List[Dict[str, Any]], bool]:
    """Compare a candidate scorecard against a baseline with per-metric
    noise tolerances. Returns ``(rows, ok)``. Hard gates first: the
    candidate must be a scorecard, and every embedded invariant must
    hold — a run whose own invariants fail cannot pass the diff no
    matter how its metrics compare."""
    rows: List[Dict[str, Any]] = []

    def row(metric, b, c, tol, ok, note=""):
        rows.append({"metric": metric, "baseline": b, "candidate": c,
                     "tolerance": tol, "ok": bool(ok), "note": note})

    if cand.get("kind") != SCORECARD_KIND:
        row("kind", base.get("kind"), cand.get("kind"),
            SCORECARD_KIND, False, "candidate is not a soak scorecard")
        return rows, False
    for name in INVARIANTS:
        inv = (cand.get("invariants") or {}).get(name) or {}
        row(f"invariant:{name}",
            (( base.get("invariants") or {}).get(name) or {}).get("ok"),
            inv.get("ok"), "must hold", bool(inv.get("ok")),
            "" if inv.get("ok") else str(inv.get("detail")))

    for path, (mode, bound) in (tolerances or DIFF_TOLERANCES).items():
        b, c = _get(base, path), _get(cand, path)
        if c is None:
            if b is None:            # optional section (e.g. rollout) ran
                row(path, None, None, f"{mode} {bound:g}", True,
                    "absent in both")
                continue             # in neither run: nothing to compare
            row(path, b, None, f"{mode} {bound:g}", False,
                "missing in candidate")
            continue
        b_f, c_f = float(b if b is not None else 0.0), float(c)
        if mode == "max_abs":
            ok, tol_s = c_f <= bound, f"<= {bound:g}"
        elif mode == "abs_band":
            ok = b is None or abs(c_f - b_f) <= bound
            tol_s = f"+/-{bound:g}"
        elif mode == "min_ratio":
            ok = b is None or b_f <= 0 or c_f >= bound * b_f
            tol_s = f">= {bound:g}x base"
        else:                                   # max_ratio
            ok = b is None or b_f <= 0 or c_f <= bound * b_f
            tol_s = f"<= {bound:g}x base"
        row(path, b, c, tol_s, ok)
    return rows, all(r["ok"] for r in rows)


def format_diff(rows: List[Dict[str, Any]]) -> str:
    """The pass/fail regression table ds_tpu_soakdiff prints."""
    def fmt(v):
        if isinstance(v, float):
            return f"{v:.4g}"
        return "-" if v is None else str(v)

    header = ("metric", "baseline", "candidate", "tolerance", "verdict")
    table = [header]
    for r in rows:
        verdict = "ok" if r["ok"] else "FAIL"
        if r["note"]:
            verdict += f"  ({r['note']})"
        table.append((r["metric"], fmt(r["baseline"]),
                      fmt(r["candidate"]), str(r["tolerance"]), verdict))
    widths = [max(len(row[i]) for row in table)
              for i in range(len(header) - 1)]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(cell.ljust(widths[j]) if j < len(widths)
                               else cell
                               for j, cell in enumerate(row)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths) + "  " +
                         "-" * 7)
    return "\n".join(lines)
