"""Compile/memory plane — compile ledger with recompile diffs + HBM ledger.

The recompile watchdog (telemetry/trace.py) counts recompiles; this
answers **why**. The ``CompileLedger`` records every compile event of a
watched jitted function — the argument fingerprint (per-leaf
shape/dtype/sharding, donation flags), the wall time of the step that
paid the compile, the XLA ``cost_analysis()`` FLOPs/bytes summary, and
the ``memory_analysis()`` argument/output/temp breakdown — and on a
recompile emits a **diff against the previous fingerprint of the same
function**::

    arg 3 (batch)['input_ids']: s32[1,8,16] -> s32[1,8,8]

The diff lands on ``/statusz`` (the ``compile_plane`` section and a
banner), in flight-recorder recompile bundles
(``FlightRecorder.attach_compile_plane``), and in the ``ds_tpu_top``
screen — the operator reads *which argument changed shape* instead of a
bare recompile count.

Event detection is fingerprint-driven: a changed signature IS the
recompile cause the diff names, and the jit cache size is sampled as a
backstop for recompiles with an unchanged signature (static-argument or
weak-type changes). ``observe()`` runs before the call on the training
path (the train step donates its inputs, so fingerprints must be taken
while the arrays are alive; ``fn.lower`` only reads avals and never
consumes donated buffers) and works equally after the call on the
serving path (slot programs don't donate).

Analysis capture: ``cost_analysis`` comes from the *lowered* stage (no
backend compile — global-program FLOPs). ``memory_analysis`` needs a
compiled executable, so when ``compile_plane.memory_analysis`` is on the
ledger AOT-compiles the lowered module once per compile *event* — that
measures the isolated XLA compile wall time and yields the per-device
memory breakdown plus the optimized HLO (collectives + async-overlap
summary via telemetry/hlo_cost.py), at the cost of a second compile of
that event's program. Compile events are rare by construction; steady
state pays only the per-call fingerprint.

The ``HBMLedger`` is the memory half: live per-device bytes attributed
by role — params / grads / optimizer state / activations (executable
temps) / KV slot pool — from pytree accounting over each array's
addressable shards, exported as ``dstpu_mem_*`` gauges, a ``memory``
statusz section, and a Perfetto counter-track waterline in the span ring
(``Tracer.counter_track``).

Off ⇒ allocates nothing: no ``compile_plane`` config block means no
ledger object, no per-call fingerprints, no gauges (the PR 4/5 pattern).
"""

import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .hlo_cost import (collect_collectives, cost_summary,
                       hlo_overlap_summary, memory_summary)
from .trace import get_tracer

__all__ = ["CompileLedger", "HBMLedger", "fingerprint_args",
           "diff_fingerprints", "HBM_ROLES"]

#: jnp dtype name -> the short HLO spelling used in fingerprints/diffs
_DTYPE_SHORT = {"float32": "f32", "bfloat16": "bf16", "float16": "f16",
                "float64": "f64", "int64": "s64", "int32": "s32",
                "int16": "s16", "int8": "s8", "uint64": "u64",
                "uint32": "u32", "uint16": "u16", "uint8": "u8",
                "bool": "pred", "float8_e4m3fn": "f8e4m3",
                "float8_e5m2": "f8e5m2"}


def _leaf_desc(x, donated: bool = False) -> str:
    """One leaf's fingerprint: ``f32[8,512]@(dp,-)`` — dtype, shape, and
    the NamedSharding partition spec when the array carries one (single-
    device arrays have no spec and print bare). Non-array leaves (static
    python scalars, None) fingerprint by repr."""
    shape = getattr(x, "shape", None)
    if shape is None:
        if x is None:
            return "None"
        if isinstance(x, (bool, int, float, complex)):
            # python scalars enter jit as weak-typed arrays: a VALUE
            # change doesn't recompile, so fingerprint the type only
            return f"py_{type(x).__name__}"
        return repr(x)
    dtype = str(getattr(x, "dtype", "?"))
    desc = (_DTYPE_SHORT.get(dtype, dtype) +
            "[" + ",".join(str(d) for d in shape) + "]")
    spec = getattr(getattr(x, "sharding", None), "spec", None)
    if spec is not None:
        desc += "@(" + ",".join(
            "-" if p is None else
            ("+".join(p) if isinstance(p, (tuple, list)) else str(p))
            for p in spec) + ")"
    if donated:
        desc += " donated"
    return desc


def fingerprint_args(args: Sequence[Any],
                     names: Optional[Sequence[str]] = None,
                     donated: Sequence[int] = ()) -> List[Tuple[str, str]]:
    """Fingerprint one call's arguments as ordered (key, descriptor)
    pairs, one per pytree leaf: key = ``arg 3 (batch)['input_ids']``,
    descriptor = ``s32[1,8,16]``. Pure host-side shape inspection — no
    device work, safe on donated buffers *before* the call."""
    import jax

    donated = set(donated)
    out: List[Tuple[str, str]] = []
    for i, arg in enumerate(args):
        label = f"arg {i}"
        if names is not None and i < len(names):
            label += f" ({names[i]})"
        leaves = jax.tree_util.tree_flatten_with_path(arg)[0]
        if not leaves:                    # None / empty subtree
            out.append((label, _leaf_desc(arg, i in donated)))
            continue
        for path, leaf in leaves:
            key = label + jax.tree_util.keystr(path)
            out.append((key, _leaf_desc(leaf, i in donated)))
    return out


def diff_fingerprints(old: Sequence[Tuple[str, str]],
                      new: Sequence[Tuple[str, str]]) -> List[str]:
    """Human-readable diff between two fingerprints: one line per
    changed/added/removed leaf, e.g. ``arg 3 (batch)['input_ids']:
    s32[1,8,16] -> s32[1,8,8]``."""
    old_map = dict(old)
    new_map = dict(new)
    out = []
    for key, desc in new:
        prev = old_map.get(key)
        if prev is None:
            out.append(f"{key}: added {desc}")
        elif prev != desc:
            out.append(f"{key}: {prev} -> {desc}")
    for key, desc in old:
        if key not in new_map:
            out.append(f"{key}: removed {desc}")
    return out


class CompileLedger:
    """Per-engine compile-event recorder: fingerprints, diffs, cost and
    memory analysis, bounded history."""

    def __init__(self, config=None, tracer=None, owner: Any = None):
        def g(key, default):
            return getattr(config, key, default) if config is not None \
                else default

        self.tracer = tracer or get_tracer()
        self._owner = owner
        self.memory_analysis = bool(g("memory_analysis", True))
        self._events: "deque" = deque(maxlen=int(g("history", 32)))
        #: label -> {"fn", "fp", "size"}; holds fn refs so identity is
        #: stable (the RecompileWatchdog pattern)
        self._state: Dict[str, Dict[str, Any]] = {}
        self.compiles = 0
        self.recompiles = 0
        self.analysis_compile_ms = 0.0   # total AOT-analysis compile time
        self.last_recompile: Optional[Dict[str, Any]] = None
        self._next_id = 1
        #: optional telemetry.perfplane.PerfPlane; when attached, every
        #: analyzed event's HLO gets an anatomy (attach_perf_plane)
        self._perf_plane = None

    def attach_perf_plane(self, perf_plane):
        self._perf_plane = perf_plane

    # ------------------------------------------------------------ observing
    @staticmethod
    def _cache_size(fn) -> int:
        size_of = getattr(fn, "_cache_size", None)
        if size_of is None:
            return 0
        try:
            return int(size_of())
        except Exception:
            return 0

    @staticmethod
    def _quick_sig(args) -> tuple:
        """Cheap structural signature: (shape, dtype, sharding) per leaf
        via one C-level flatten — the steady-state fast path. The full
        labeled fingerprint (string keys, donation flags) is only built
        when this differs, i.e. on compile events."""
        import jax
        return tuple(
            (getattr(leaf, "shape", None), getattr(leaf, "dtype", None),
             getattr(leaf, "sharding", None))
            if hasattr(leaf, "shape") else (None, None, type(leaf))
            for leaf in jax.tree.leaves(args))

    def observe(self, label: str, fn, args: Sequence[Any] = (),
                names: Optional[Sequence[str]] = None,
                donated: Sequence[int] = (), step: Optional[int] = None,
                mesh=None) -> Optional[Dict[str, Any]]:
        """Record a compile/recompile event for one call of ``fn`` under
        ``label``. Returns the event dict when this call's signature is
        new (first sight = ``compile``; changed fingerprint or jit-cache
        growth on the same fn = ``recompile``; a *different* fn object
        under the same label — e.g. a new static-argument bucket — is a
        fresh ``compile`` whose diff still names what changed), else
        None. Steady state costs one flatten + one tuple compare."""
        size = self._cache_size(fn)
        st = self._state.get(label)
        quick = self._quick_sig(args)
        if st is not None and st["fn"] is fn and quick == st["quick"] and \
                size <= st["size"]:
            return None                   # steady state: no strings built
        fp = fingerprint_args(args, names=names, donated=donated)
        # expected cache size after this call: observations run BEFORE the
        # call (donated inputs must be fingerprinted alive), so an event's
        # compile hasn't grown the cache yet — store size+1 so the next
        # steady-state call doesn't read its growth as a second recompile
        if st is None:
            ev = self._event("compile", label, fn, args, fp, None, step,
                             mesh)
            expected = size + 1
        elif st["fn"] is not fn:
            # a new jit wrapper under this label: a distinct program (new
            # ltd bucket, new prefill bucket) — first compile of that
            # program, with the cross-program diff attached
            diff = diff_fingerprints(st["fp"], fp) or \
                ["same argument signature (static-argument change "
                 "compiled a new program)"]
            ev = self._event("compile", label, fn, args, fp, diff, step,
                             mesh)
            expected = size + 1
        elif fp != st["fp"]:
            ev = self._event("recompile", label, fn, args, fp,
                             diff_fingerprints(st["fp"], fp), step, mesh)
            expected = size + 1
        elif size > st["size"]:
            # backstop, one call late by construction: the cache grew with
            # no signature change (static argument / weak-type / context)
            ev = self._event(
                "recompile", label, fn, args, fp,
                ["no argument signature change (static context or "
                 "weak-type change grew the jit cache)"], step, mesh)
            expected = size
        else:
            # quick-sig churn with an identical full fingerprint (fresh
            # but equal sharding objects): refresh the cheap key
            st["quick"] = quick
            st["size"] = max(st["size"], size)
            return None
        self._state[label] = {"fn": fn, "fp": fp, "quick": quick,
                              "size": expected}
        return ev

    def _event(self, kind: str, label: str, fn, args, fp, diff,
               step: Optional[int], mesh) -> Dict[str, Any]:
        ev: Dict[str, Any] = {
            "id": self._next_id,
            "kind": kind,
            "label": label,
            "step": step,
            "time": time.time(),
            "wall_ms": None,             # set by finish(): the step that
                                         # paid this compile
            "fingerprint": [f"{k}: {d}" for k, d in fp],
        }
        self._next_id += 1
        if diff:
            ev["diff"] = list(diff)
        self._analyze(ev, fn, args, mesh)
        self._events.append(ev)
        if kind == "recompile":
            self.recompiles += 1
            self.last_recompile = {
                "label": label, "step": step, "time": ev["time"],
                "diff": ev.get("diff", []),
            }
        else:
            self.compiles += 1
        tr = self.tracer
        tr.set_counter("compileplane/compiles", float(self.compiles),
                       owner=self._owner)
        tr.set_counter("compileplane/recompiles", float(self.recompiles),
                       owner=self._owner)
        tr.instant(f"compile_plane:{kind}", cat="warning",
                   args={"label": label,
                         "diff": "; ".join(ev.get("diff", []))[:512]})
        return ev

    def _analyze(self, ev: Dict[str, Any], fn, args, mesh):
        """Attach XLA's own accounting of the program this event compiled:
        cost_analysis from the lowered stage (global FLOPs/bytes, no
        backend compile), and — when ``memory_analysis`` is on — one AOT
        compile for the per-device memory breakdown, the measured compile
        wall time, and the optimized HLO's collective/overlap summary. A
        failed analysis annotates the event instead of losing it."""
        if not hasattr(fn, "lower"):
            return
        try:
            if mesh is not None:
                with mesh:
                    lowered = fn.lower(*args)
            else:
                lowered = fn.lower(*args)
            ev["cost"] = cost_summary(lowered.cost_analysis())
        except Exception as e:
            ev["analysis_error"] = str(e)
            return
        if not self.memory_analysis:
            return
        try:
            t0 = time.perf_counter()
            compiled = lowered.compile()
            compile_ms = (time.perf_counter() - t0) * 1e3
            ev["compile_ms"] = round(compile_ms, 3)
            self.analysis_compile_ms += compile_ms
            self.tracer.set_counter("compileplane/last_compile_ms",
                                    round(compile_ms, 3),
                                    owner=self._owner)
            mem = memory_summary(compiled.memory_analysis())
            if mem:
                ev["memory"] = mem
            hlo = compiled.as_text()
            ev["collectives"] = collect_collectives(hlo)
            ev["overlap"] = hlo_overlap_summary(hlo)
            self.tracer.set_counter("overlap/hlo_async_fraction",
                                    ev["overlap"]["async_fraction"],
                                    owner=self._owner)
            self.tracer.set_counter(
                "overlap/hlo_static_fraction",
                ev["overlap"].get("static_overlap_fraction", 0.0),
                owner=self._owner)
            if self._perf_plane is not None:
                # perf plane: bucket anatomy of this exact program,
                # attached to the event (postmortem bundles embed it)
                # and gauged; a banded recompile shift fires
                # perf_regression from inside observe_program
                self._perf_plane.observe_program(
                    ev["label"], hlo, kind=ev["kind"], step=ev["step"],
                    event=ev)
        except Exception as e:
            ev["analysis_error"] = str(e)

    def finish(self, ev: Dict[str, Any], wall_ms: float):
        """Record the wall time of the step that paid this compile (the
        jit call's own compile+run, distinct from ``compile_ms``, the
        isolated AOT-analysis compile)."""
        ev["wall_ms"] = round(float(wall_ms), 3)

    # -------------------------------------------------------------- reading
    def events(self) -> List[Dict[str, Any]]:
        return list(self._events)

    def events_since(self, event_id: int) -> List[Dict[str, Any]]:
        """Events with id strictly greater than ``event_id`` — the
        measured-trial read API (autotuning/measure.py): a trial driver
        remembers the last id after warmup, and any event returned here
        during the measured window is a steady-state recompile (a hard
        disqualification). Pass 0 for the full history."""
        return [ev for ev in self._events if ev["id"] > event_id]

    @property
    def last_event_id(self) -> int:
        """Highest event id issued so far (0 before the first event)."""
        return self._next_id - 1

    def last_event(self, label: Optional[str] = None) \
            -> Optional[Dict[str, Any]]:
        for ev in reversed(self._events):
            if label is None or ev["label"] == label:
                return ev
        return None

    def step_flops(self, label: str, fn=None) -> float:
        """Global-program FLOPs of the executable currently active under
        ``label``, from the captured ``cost_analysis`` — the MFU-gauge
        fallback when the flops profiler is off. 0 when unknown or when
        ``fn`` is no longer the executable the cost was captured for."""
        st = self._state.get(label)
        if st is None or (fn is not None and st["fn"] is not fn):
            return 0.0
        ev = self.last_event(label)
        if ev is None:
            return 0.0
        return float((ev.get("cost") or {}).get("flops", 0.0))

    def summary(self) -> Dict[str, Any]:
        """The statusz section / ds_tpu_top view."""
        out: Dict[str, Any] = {
            "compiles": self.compiles,
            "recompiles": self.recompiles,
            "functions": len(self._state),
            "events_kept": len(self._events),
        }
        if self.analysis_compile_ms:
            out["analysis_compile_ms"] = round(self.analysis_compile_ms, 1)
        last = self.last_event()
        if last is not None and last.get("cost"):
            flops = last["cost"].get("flops")
            if flops:
                out["last_step_gflops"] = round(flops / 1e9, 3)
        if last is not None and last.get("overlap"):
            out["hlo_async_fraction"] = last["overlap"]["async_fraction"]
            out["hlo_static_fraction"] = last["overlap"].get(
                "static_overlap_fraction", 0.0)
        lr = self.last_recompile
        if lr is not None:
            out["last_recompile"] = (
                f"{lr['label']} step {lr['step']}: " +
                "; ".join(lr["diff"][:4]) +
                (" …" if len(lr["diff"]) > 4 else ""))
            out["last_recompile_age_s"] = round(
                max(0.0, time.time() - lr["time"]), 1)
        return out

    def bundle_section(self) -> Dict[str, Any]:
        """What a flight-recorder bundle embeds: the summary plus the full
        event history (fingerprints, diffs, cost/memory summaries)."""
        return {"summary": self.summary(), "events": self.events()}


# ---------------------------------------------------------------- HBM ledger

#: the role vocabulary (stable schema for dashboards; ``other`` catches
#: caller-defined roles)
HBM_ROLES = ("params", "grads", "optimizer_state", "activations",
             "kv_slots", "other")


class HBMLedger:
    """Live per-device bytes by role. The peak-HBM gauge says *how much*;
    this says *what it is* — pytree accounting over addressable shards
    for the live trees (params/grads/optimizer state/KV slots), plus the
    active executable's temp allocation (``memory_analysis``) for
    activations. Gauges are ``mem/<role>_gib`` (Prometheus:
    ``dstpu_mem_<role>_gib``); every update also drops one Perfetto
    counter-track sample into the span ring — the waterline timeline."""

    def __init__(self, tracer=None, owner: Any = None):
        self.tracer = tracer or get_tracer()
        self._owner = owner
        self._bytes: Dict[str, int] = {}
        self.updates = 0

    def device_bytes(self, tree) -> int:
        """Per-device live bytes of a pytree: per-shard size from each
        array's sharding metadata (``shard_shape`` — replicated arrays
        count full size, that's what they occupy per device). Metadata
        only: no shard objects are materialized, so this is cheap enough
        for a per-N-steps cadence."""
        import math

        import jax

        total = 0
        for leaf in jax.tree.leaves(tree):
            shape = getattr(leaf, "shape", None)
            if shape is None:
                continue
            itemsize = leaf.dtype.itemsize
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None:
                try:
                    shape = sharding.shard_shape(tuple(shape))
                except Exception:
                    pass
            total += math.prod(shape) * itemsize
        return total

    def update(self, roles: Dict[str, float],
               peak_bytes: Optional[int] = None) -> Dict[str, Any]:
        """Set the current role attribution (bytes). Roles not in this
        update keep their last value; pass 0 to clear one. Mirrors the
        ``mem/*_gib`` gauges and emits the waterline counter-track
        sample. ``peak_bytes`` (the allocator high-water, when the
        backend reports one) yields the coverage ratio — how much of the
        high-water the roles explain."""
        self._bytes.update({k: int(v) for k, v in roles.items()})
        self.updates += 1
        tr = self.tracer
        gib = {}
        total = 0
        for role, nbytes in self._bytes.items():
            total += nbytes
            gib[role] = round(nbytes / 2**30, 6)
            tr.set_counter(f"mem/{role}_gib", gib[role], owner=self._owner)
        tr.set_counter("mem/total_gib", round(total / 2**30, 6),
                       owner=self._owner)
        out: Dict[str, Any] = {"total_bytes": total, "roles": dict(self._bytes)}
        if peak_bytes:
            out["peak_bytes"] = int(peak_bytes)
            out["coverage"] = round(total / peak_bytes, 4)
            tr.set_counter("mem/coverage", out["coverage"],
                           owner=self._owner)
        tr.counter_track("hbm_gib", gib)
        return out

    def summary(self) -> Dict[str, Any]:
        """The ``memory`` statusz section: role GiB plus the allocator's
        own numbers when the backend reports them (the CPU test backend
        does not)."""
        out: Dict[str, Any] = {}
        total = 0
        for role in HBM_ROLES:
            nbytes = self._bytes.get(role)
            if nbytes is not None:
                out[f"{role}_gib"] = round(nbytes / 2**30, 6)
                total += nbytes
        for role, nbytes in self._bytes.items():
            if role not in HBM_ROLES:
                out[f"{role}_gib"] = round(nbytes / 2**30, 6)
                total += nbytes
        out["total_gib"] = round(total / 2**30, 6)
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
        except Exception:
            stats = {}
        if stats.get("bytes_in_use"):
            out["in_use_gib"] = round(stats["bytes_in_use"] / 2**30, 6)
        if stats.get("peak_bytes_in_use"):
            out["peak_gib"] = round(stats["peak_bytes_in_use"] / 2**30, 6)
            if total:
                out["coverage"] = round(total / stats["peak_bytes_in_use"],
                                        4)
        return out
