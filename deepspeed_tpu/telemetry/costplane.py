"""Cost plane — per-request / per-tenant chip-second & HBM attribution.

The goodput ledger (telemetry/goodput.py) answers "where did the wall
clock go" with exclusive buckets that sum to wall time by construction.
This module applies the same accounting discipline *per request*: every
second of serving wall-clock is split across the requests occupying it,
and whatever no request can claim lands in an explicit overhead
residual — so per-replica request costs + overhead **sum to serving
wall-clock by construction**, the invariant the soak scorecard checks.

Attribution rules (the contract ``tests/unit/test_costplane.py`` rigs):

- **Decode ticks** are divided over the active slots weighted by tokens
  emitted that tick. On the non-speculative path every slot emits one
  token, so the split is equal; on the speculative path accepted draft
  tokens credit their request and the draft/verify overhead is split
  pro-rata (one weighted split of the whole tick wall by emitted
  tokens achieves both).
- **Prefill** (inline, suffix after a radix hit, chunked, lane-copy,
  handoff insert) is charged whole to the owning request — prefill is
  never shared work.
- **Radix-cache hits** record *avoided* prefill cost as explicit
  savings: reused tokens x the EMA of observed per-token prefill cost.
  Savings are what the fleet did NOT pay, kept separate from chip_ms so
  costs still sum to wall; the scorecard cross-checks that the implied
  per-token savings rate never exceeds the paid rate by more than a
  small slack.
- **HBM byte-seconds** accrue per slot from the pool footprint
  (int8-aware: a quantized pool's q+scales bytes are what the device
  holds, the same bytes the PR-7 HBM ledger's ``kv_slots`` role counts)
  x residency, sampled every tick for every occupied slot (decoding or
  mid-chunked-prefill).
- **Overhead** is the tick residual: tick wall minus everything
  attributed. Idle ticks (no occupants) are pure overhead.

A per-request :class:`CostRecord` rides the request's ``TraceContext``
(``telemetry/disttrace.py``), so it crosses KV handoffs inside the frame
header and survives failover — a survivor replica's charges accumulate
into the SAME record, attributed by attempt number. Per-tenant totals
accumulate at charge time in each replica's :class:`CostLedger` and are
folded fleet-wide by the ``FleetRouter`` (``cost_summary``), which is
where the ``dstpu_cost_*`` Prometheus family, the ``/statusz`` costs
table, and the scorecard section come from.

Disabled (the default) allocates nothing: the scheduler holds ``None``
and every hook is a single ``is None`` test.
"""

import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["CostRecord", "CostLedger", "tree_nbytes", "merge_cost_totals",
           "capacity_report"]

_GIB = 1024.0 ** 3

#: the per-tenant metrics a fold carries — the dstpu_cost_* family plus
#: the denominators the capacity report divides by
TENANT_COST_METRICS = ("chip_ms", "decode_ms", "prefill_ms", "hbm_gib_s",
                       "tokens", "prompt_tokens", "cache_savings_ms",
                       "cache_saved_tokens", "requests")


def tree_nbytes(tree) -> int:
    """Host-side logical bytes of an array pytree (no device sync):
    ``sum(leaf.size * leaf.dtype.itemsize)``. A quantized pool's int8 q
    + f32 scales leaves count at their real widths, so the figure is
    int8-aware by construction."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is None or dtype is None:
            continue
        total += int(size) * int(dtype.itemsize)
    return total


@dataclasses.dataclass
class CostRecord:
    """One request's accumulated cost, fleet-wide. Travels on the
    request's TraceContext: serialized into the KVHandoff frame header
    by ``to_dict`` and revived by ``from_dict`` on the decode side, and
    carried through failover by the router's persistent context — every
    attempt charges into the same record, keyed by attempt number."""
    request_id: Optional[int] = None
    tenant: str = "default"
    decode_ms: float = 0.0
    prefill_ms: float = 0.0
    hbm_gib_s: float = 0.0
    tokens: int = 0
    prompt_tokens: int = 0
    cache_savings_ms: float = 0.0
    cache_saved_tokens: int = 0
    #: chip_ms per attempt (0 = first): a failed-over request shows
    #: exactly what each attempt cost, including the abandoned one
    by_attempt: Dict[int, float] = dataclasses.field(default_factory=dict)
    #: the live attempt number (trace.replays), refreshed on every fetch
    attempt: int = 0

    @property
    def chip_ms(self) -> float:
        return self.decode_ms + self.prefill_ms

    def charge(self, ms: float, *, decode: bool):
        if decode:
            self.decode_ms += ms
        else:
            self.prefill_ms += ms
        self.by_attempt[self.attempt] = \
            self.by_attempt.get(self.attempt, 0.0) + ms

    def to_dict(self) -> dict:
        return {"request_id": self.request_id, "tenant": self.tenant,
                "decode_ms": self.decode_ms, "prefill_ms": self.prefill_ms,
                "hbm_gib_s": self.hbm_gib_s, "tokens": self.tokens,
                "prompt_tokens": self.prompt_tokens,
                "cache_savings_ms": self.cache_savings_ms,
                "cache_saved_tokens": self.cache_saved_tokens,
                "by_attempt": {str(k): v for k, v in self.by_attempt.items()},
                "attempt": self.attempt}

    @classmethod
    def from_dict(cls, d: dict) -> "CostRecord":
        rec = cls(request_id=d.get("request_id"),
                  tenant=d.get("tenant") or "default",
                  decode_ms=float(d.get("decode_ms", 0.0)),
                  prefill_ms=float(d.get("prefill_ms", 0.0)),
                  hbm_gib_s=float(d.get("hbm_gib_s", 0.0)),
                  tokens=int(d.get("tokens", 0)),
                  prompt_tokens=int(d.get("prompt_tokens", 0)),
                  cache_savings_ms=float(d.get("cache_savings_ms", 0.0)),
                  cache_saved_tokens=int(d.get("cache_saved_tokens", 0)),
                  attempt=int(d.get("attempt", 0)))
        rec.by_attempt = {int(k): float(v)
                          for k, v in (d.get("by_attempt") or {}).items()}
        return rec

    def summary(self) -> dict:
        out = self.to_dict()
        out["chip_ms"] = round(self.chip_ms, 3)
        return out


class _TenantCost:
    """One tenant's accumulated totals on one replica's ledger."""

    __slots__ = TENANT_COST_METRICS

    def __init__(self):
        self.chip_ms = 0.0
        self.decode_ms = 0.0
        self.prefill_ms = 0.0
        self.hbm_gib_s = 0.0
        self.tokens = 0
        self.prompt_tokens = 0
        self.cache_savings_ms = 0.0
        self.cache_saved_tokens = 0
        self.requests = 0

    def row(self) -> dict:
        return {"chip_ms": round(self.chip_ms, 3),
                "decode_ms": round(self.decode_ms, 3),
                "prefill_ms": round(self.prefill_ms, 3),
                "hbm_gib_s": round(self.hbm_gib_s, 9),
                "tokens": self.tokens,
                "prompt_tokens": self.prompt_tokens,
                "cache_savings_ms": round(self.cache_savings_ms, 3),
                "cache_saved_tokens": self.cache_saved_tokens,
                "requests": self.requests}


class CostLedger:
    """Per-replica cost accounting. The scheduler charges spans into it
    during every tick; ``end_tick`` closes the tick's books — HBM
    residency for the occupants, the overhead residual, the wall total.
    All charges use the scheduler's injected clock, so rigged tests can
    engineer exact splits."""

    def __init__(self, config=None, clock=None, slot_bytes: int = 0):
        self.enabled = bool(getattr(config, "enabled", True))
        self.clock = clock or time.monotonic
        self.ema_alpha = float(getattr(config, "ema_alpha", 0.25) or 0.25)
        self.track_hbm = bool(getattr(config, "hbm", True))
        self._tenant_cap = int(getattr(config, "max_tracked", 64) or 64)
        #: bytes one slot pins in HBM (pool + draft pool share, int8-
        #: aware) — set by the scheduler once the pools exist
        self.slot_bytes = int(slot_bytes)
        self._tenants: Dict[str, _TenantCost] = {}
        #: EMA of observed per-token prefill cost (ms/token): what a
        #: radix hit's avoided cost is priced at. None until the first
        #: real prefill — a hit before any paid prefill saves "0" (there
        #: is nothing honest to price it with).
        self.prefill_ms_per_token: Optional[float] = None
        self._max_ms_per_token = 0.0
        self.serving_wall_s = 0.0
        self.overhead_s = 0.0
        self.idle_ticks = 0
        self.ticks = 0
        self.spec_draft_ms = 0.0
        self.spec_verify_ms = 0.0
        self._tick_attr_s = 0.0     # seconds attributed this tick

    # ------------------------------------------------------------- records
    def record_for(self, req) -> CostRecord:
        """The request's CostRecord, minted on first touch and attached
        to its TraceContext (the carrier that survives handoff and
        failover). Requests without a trace keep the record on the
        Request object itself — replica-local, but never lost."""
        ctx = getattr(req, "trace", None)
        carrier = ctx if ctx is not None else req
        rec = getattr(carrier, "cost", None)
        if rec is None:
            rec = CostRecord(request_id=getattr(req, "request_id", None),
                             tenant=getattr(req, "tenant", None)
                             or "default",
                             prompt_tokens=int(
                                 getattr(req.prompt, "size", 0)))
            self._tenant(rec.tenant).requests += 1
            self._tenant(rec.tenant).prompt_tokens += rec.prompt_tokens
            carrier.cost = rec
        if ctx is not None:
            rec.attempt = int(getattr(ctx, "replays", 0) or 0)
        return rec

    def _tenant(self, name: str) -> _TenantCost:
        name = name or "default"
        t = self._tenants.get(name)
        if t is None:
            if len(self._tenants) >= self._tenant_cap and \
                    name != "__other__":
                return self._tenant("__other__")
            t = self._tenants[name] = _TenantCost()
        return t

    # ------------------------------------------------------------- charging
    def charge_decode(self, dt_s: float,
                      weighted: List[Tuple[CostRecord, int]]):
        """Split one decode tick's wall over its records, weighted by
        tokens emitted (equal on the non-speculative path, where every
        weight is 1)."""
        total_w = sum(max(0, w) for _r, w in weighted)
        if total_w <= 0 or dt_s <= 0:
            return
        self._tick_attr_s += dt_s
        for rec, w in weighted:
            if w <= 0:
                continue
            ms = dt_s * 1e3 * w / total_w
            rec.charge(ms, decode=True)
            rec.tokens += w
            t = self._tenant(rec.tenant)
            t.decode_ms += ms
            t.chip_ms += ms
            t.tokens += w

    def charge_spec(self, dt_s: float, draft_s: float, verify_s: float,
                    weighted: List[Tuple[CostRecord, int]]):
        """One speculative tick: the whole tick wall (draft + verify +
        bookkeeping) splits over the emitted tokens, so accepted drafts
        credit their request and the draft/verify overhead lands
        pro-rata. The aggregate draft/verify walls are kept for the
        statusz table."""
        self.spec_draft_ms += draft_s * 1e3
        self.spec_verify_ms += verify_s * 1e3
        self.charge_decode(dt_s, weighted)

    def charge_prefill(self, rec: CostRecord, dt_s: float, tokens: int,
                       *, update_rate: bool = True):
        """Charge one prefill span (inline, suffix, chunk, lane-copy, or
        handoff insert) whole to its owning request. ``update_rate``
        feeds the per-token EMA that prices radix savings — lane copies
        and handoff inserts don't (their per-token cost is not prefill
        compute)."""
        if dt_s <= 0:
            return
        ms = dt_s * 1e3
        self._tick_attr_s += dt_s
        rec.charge(ms, decode=False)
        t = self._tenant(rec.tenant)
        t.prefill_ms += ms
        t.chip_ms += ms
        if update_rate and tokens > 0:
            rate = ms / tokens
            if self.prefill_ms_per_token is None:
                self.prefill_ms_per_token = rate
            else:
                self.prefill_ms_per_token += self.ema_alpha * (
                    rate - self.prefill_ms_per_token)
            self._max_ms_per_token = max(self._max_ms_per_token, rate)

    def note_cache_savings(self, rec: CostRecord, reused_tokens: int):
        """A radix hit avoided prefilling ``reused_tokens`` — record the
        avoided cost at the EMA per-token rate. Priced, never charged:
        savings are what the fleet did not pay."""
        if reused_tokens <= 0 or self.prefill_ms_per_token is None:
            return
        saved = reused_tokens * self.prefill_ms_per_token
        rec.cache_savings_ms += saved
        rec.cache_saved_tokens += reused_tokens
        t = self._tenant(rec.tenant)
        t.cache_savings_ms += saved
        t.cache_saved_tokens += reused_tokens

    # ----------------------------------------------------------------- tick
    def end_tick(self, wall_s: float, occupants: List[CostRecord]):
        """Close one tick: HBM residency for every occupied slot
        (footprint x tick wall), the overhead residual (wall minus
        attributed), and the wall total — conservation by construction."""
        if wall_s < 0:
            wall_s = 0.0
        self.ticks += 1
        self.serving_wall_s += wall_s
        self.overhead_s += max(0.0, wall_s - self._tick_attr_s)
        self._tick_attr_s = 0.0
        if not occupants:
            self.idle_ticks += 1
        elif self.track_hbm and self.slot_bytes > 0:
            gib_s = self.slot_bytes * wall_s / _GIB
            for rec in occupants:
                rec.hbm_gib_s += gib_s
                self._tenant(rec.tenant).hbm_gib_s += gib_s

    # -------------------------------------------------------------- folding
    def tenant_totals(self) -> Dict[str, dict]:
        return {name: t.row() for name, t in self._tenants.items()}

    def snapshot(self) -> dict:
        attributed_ms = sum(t.chip_ms for t in self._tenants.values())
        return {"enabled": self.enabled,
                "serving_wall_s": round(self.serving_wall_s, 6),
                "overhead_s": round(self.overhead_s, 6),
                "attributed_ms": round(attributed_ms, 3),
                "ticks": self.ticks,
                "idle_ticks": self.idle_ticks,
                "slot_bytes": self.slot_bytes,
                "prefill_ms_per_token":
                    None if self.prefill_ms_per_token is None
                    else round(self.prefill_ms_per_token, 6),
                "spec_draft_ms": round(self.spec_draft_ms, 3),
                "spec_verify_ms": round(self.spec_verify_ms, 3),
                "tenants": self.tenant_totals()}

    def reset(self):
        """Zero the fold state (tenant totals, wall, overhead) — the
        soak harness resets after warmup so the scorecard's conservation
        window matches the goodput window. Per-request records are
        untouched; in-flight requests re-register on their next charge."""
        self._tenants = {}
        self.serving_wall_s = 0.0
        self.overhead_s = 0.0
        self.idle_ticks = 0
        self.ticks = 0
        self.spec_draft_ms = 0.0
        self.spec_verify_ms = 0.0
        self._tick_attr_s = 0.0


def merge_cost_totals(into: Dict[str, Any], snap: dict):
    """Fold one replica's ``CostLedger.snapshot()`` into a fleet
    accumulator (the router's cost_summary, which also folds snapshots
    retained from failed/drained replicas)."""
    into["serving_wall_s"] = into.get("serving_wall_s", 0.0) + \
        float(snap.get("serving_wall_s", 0.0))
    into["overhead_s"] = into.get("overhead_s", 0.0) + \
        float(snap.get("overhead_s", 0.0))
    into["ticks"] = into.get("ticks", 0) + int(snap.get("ticks", 0))
    into["idle_ticks"] = into.get("idle_ticks", 0) + \
        int(snap.get("idle_ticks", 0))
    tenants = into.setdefault("tenants", {})
    for name, row in (snap.get("tenants") or {}).items():
        acc = tenants.setdefault(name, {m: 0 for m in TENANT_COST_METRICS})
        for metric in TENANT_COST_METRICS:
            acc[metric] = acc.get(metric, 0) + row.get(metric, 0)


def capacity_report(costs: dict, *, target_tokens_per_s: float = 0.0,
                    replicas: int = 0) -> dict:
    """Turn a cost fold into the capacity answer: tokens per chip-second
    per tenant, the fleet-effective rate (overhead included), and —
    given a target aggregate token rate for the SAME traffic mix — the
    projected replica count. ``replicas`` scales per-replica serving
    wall out of the fold's total chip-seconds; 0 derives nothing."""
    import math
    tenants = costs.get("tenants") or {}
    wall_s = float(costs.get("serving_wall_s", 0.0))
    total_tokens = sum(int(r.get("tokens", 0)) for r in tenants.values())
    rows = {}
    for name, r in sorted(tenants.items()):
        chip_s = float(r.get("chip_ms", 0.0)) / 1e3
        toks = int(r.get("tokens", 0))
        rows[name] = {
            "tokens": toks,
            "chip_s": round(chip_s, 6),
            "tokens_per_chip_s":
                round(toks / chip_s, 3) if chip_s > 0 else None,
            "hbm_gib_s": round(float(r.get("hbm_gib_s", 0.0)), 6),
            "cache_savings_ms":
                round(float(r.get("cache_savings_ms", 0.0)), 3),
            "cost_share": round(chip_s / wall_s, 4) if wall_s > 0 else None,
        }
    effective = total_tokens / wall_s if wall_s > 0 else 0.0
    out = {"tenants": rows,
           "total_tokens": total_tokens,
           "serving_wall_s": round(wall_s, 6),
           "overhead_s": round(float(costs.get("overhead_s", 0.0)), 6),
           "effective_tokens_per_chip_s": round(effective, 3)}
    if target_tokens_per_s > 0 and effective > 0:
        # chip-seconds demanded per wall second at the same mix; each
        # replica supplies ~1 chip-second per second of serving wall
        chips = target_tokens_per_s / effective
        out["target_tokens_per_s"] = target_tokens_per_s
        out["projected_replicas"] = max(1, math.ceil(chips))
        if replicas > 0:
            out["current_replicas"] = replicas
    return out
