"""Shared HLO cost core — one parser for every XLA-cost consumer.

Three consumers used to carry private copies of this logic:
``benchmarks/hlo_audit.py`` (the collective-schedule regression gate),
the flight recorder's "XLA cost summary" capture, and the compile ledger
(telemetry/compileplane.py). They now all read from here, so a change to
the HLO text format (an XLA upgrade renaming an op, a new async form) is
fixed in exactly one place — and the future schedule autotuner (ROADMAP
item 2/5) scores candidate plans with the same numbers the gate enforces.

Contents:

- ``collect_collectives(hlo_text)`` — {op: {count, bytes}} over a
  compiled module's *synchronous* collectives (single-result and
  tuple-result forms), payload bytes from the printed result shapes.
- ``collect_async(hlo_text)`` — per-op counts of collectives emitted in
  async start/done form (``all-gather-start`` … ``all-gather-done``, or
  the generic ``async-start`` wrapper) — the ops XLA's latency-hiding
  scheduler *can* overlap with compute.
- ``hlo_overlap_summary(hlo_text)`` — sync vs async collective counts
  and the ``async_fraction`` in [0, 1]: the static half of the
  collective-overlap instrument (telemetry/overlap.py layers the
  trace-measured half on top).
- ``collect_schedule_overlap(hlo_text)`` — the dependency-level overlap
  instrument for backends that never emit async start/done pairs (the
  CPU lowering): per collective, is there compute a latency-hiding
  executor could legally run between the collective's issue point and
  its first real consumer? Computed from ASAP dataflow levels, so it is
  robust to the printed schedule order — this is the number the bucketed
  ZeRO exchange (runtime/zero/overlap_schedule.py) exists to raise and
  the schedule autotuner (autotuning/schedule.py) scores.
- ``collect_replica_groups(hlo_text)`` — parsed ``replica_groups`` per
  collective instruction (explicit ``{{0,1},{2,3}}`` lists, the iota
  ``[G,S]<=[dims]T(perm)`` form, and the empty all-devices form), one
  record per op with the expanded group membership. The collective-
  safety auditors (analysis/hlo_audit_rules.py) consume this instead of
  re-regexing HLO text.
- ``module_num_partitions(hlo_text)`` — the module's declared partition
  count (``num_partitions=N`` header field), 0 when absent.
- ``cost_summary(raw)`` — normalize a ``cost_analysis()`` result
  (dict, or the list/tuple wrapping older jax returns) to a flat dict
  of floats with python-identifier keys.
- ``memory_summary(stats)`` — normalize a ``memory_analysis()``
  ``CompiledMemoryStats`` to a plain dict of the ``*_in_bytes`` fields.

This module is deliberately standalone — stdlib-only, no package
imports — so ``benchmarks/hlo_audit.py`` can load it by file path before
the deepspeed_tpu package (and its backend-touching ``__init__`` chain)
is imported, the same way it loads ``utils/hermetic.py``.
"""

import math
import re
from typing import Any, Dict, Optional

__all__ = ["DTYPE_BYTES", "COLLECTIVES", "collect_collectives",
           "collect_async", "collect_schedule_overlap",
           "collect_replica_groups", "module_num_partitions",
           "hlo_overlap_summary", "cost_summary", "memory_summary"]

#: HLO shape-prefix dtype -> bytes per element (unknown dtypes assume 4)
DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
               "s8": 1, "u8": 1, "pred": 1, "f64": 8, "s64": 8, "u64": 8,
               "s16": 2, "u16": 2, "f8e4m3fn": 1, "f8e5m2": 1}

#: the collective-op vocabulary the audit and the overlap analyzer track
COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
               "collective-permute")

_PAT_SINGLE = re.compile(
    r"=\s*(\w+)\[([\d,]*)\]\S*\s+(" + "|".join(COLLECTIVES) + r")\(")
_PAT_TUPLE = re.compile(
    r"=\s*\(([^)]+)\)\s+(" + "|".join(COLLECTIVES) + r")\(")
_PAT_SHAPE = re.compile(r"(\w+)\[([\d,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    numel = math.prod([int(d) for d in dims.split(",") if d] or [1])
    return numel * DTYPE_BYTES.get(dtype, 4)


def collect_collectives(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """{op: {count, bytes}} over the compiled module (fusion-internal
    shapes included via the op's result shape). Synchronous forms only —
    async start/done pairs are ``collect_async``'s domain."""
    out: Dict[str, Dict[str, int]] = {}
    # single-result form only ('= f32[...] all-reduce('); tuple results
    # ('= (f32[...], ...) all-reduce(') are handled by _PAT_TUPLE below —
    # anchoring at '= <dtype>[' keeps the two disjoint
    for m in _PAT_SINGLE.finditer(hlo_text):
        dtype, dims, op = m.group(1), m.group(2), m.group(3)
        rec = out.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += _shape_bytes(dtype, dims)
    # tuple-result collectives (all-reduce of N tensors) print as
    # `(f32[...], f32[...]) all-reduce(` — catch those too
    for m in _PAT_TUPLE.finditer(hlo_text):
        shapes, op = m.group(1), m.group(2)
        rec = out.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        for sm in _PAT_SHAPE.finditer(shapes):
            rec["bytes"] += _shape_bytes(sm.group(1), sm.group(2))
    return out


def collect_async(hlo_text: str) -> Dict[str, int]:
    """Per-op counts of collectives in async start/done form. XLA prints
    dedicated pairs for some ops (``all-gather-start(``) and wraps the
    rest in generic ``async-start`` instructions whose line names the
    wrapped op; both count."""
    out: Dict[str, int] = {}
    for op in COLLECTIVES:
        n = len(re.findall(rf"\b{op}-start\(", hlo_text))
        n += len(re.findall(rf"\basync-start[^\n]*\b{op}\b", hlo_text))
        if n:
            out[op] = n
    return out


_NUM_PARTITIONS_RE = re.compile(r"\bnum_partitions=(\d+)")
#: replica_groups in either printed form: explicit nested brace lists
#: ('{{0,1},{2,3}}', '{}' = all devices) or the iota shorthand
#: ('[G,S]<=[d0,d1,...]' with an optional 'T(perm)' transpose)
_RG_RE = re.compile(
    r"replica_groups=(\{(?:\{[\d,\s]*\}(?:,\s*)?)*\}|"
    r"\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)")
_RG_LINE_RE = re.compile(
    r"(%?[\w.\-]+)\s*=\s*(?:\([^=]*?\)|\S+)\s+([\w\-]+)\(")
_IOTA_RE = re.compile(
    r"\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")


def _expand_iota_groups(shape, dims, perm):
    """Expand HLO's iota replica-group shorthand ``[G,S]<=[dims]T(perm)``:
    device ids are ``transpose(arange(prod(dims)).reshape(dims), perm)``
    flattened, then chunked into G groups of S."""
    total = math.prod(dims)
    # row-major strides of the ORIGINAL dims layout
    strides = [1] * len(dims)
    for i in range(len(dims) - 2, -1, -1):
        strides[i] = strides[i + 1] * dims[i + 1]
    tdims = [dims[p] for p in perm]
    tstrides = [strides[p] for p in perm]
    flat = []
    for i in range(total):
        rem, off = i, 0
        for d, s in zip(reversed(tdims), reversed(tstrides)):
            off += (rem % d) * s
            rem //= d
        flat.append(off)
    group_size = shape[-1] if shape else total
    n_groups = max(1, total // max(1, group_size))
    return [flat[g * group_size:(g + 1) * group_size]
            for g in range(n_groups)]


def module_num_partitions(hlo_text: str) -> int:
    """The compiled module's declared partition count (0 when the header
    does not carry one)."""
    m = _NUM_PARTITIONS_RE.search(hlo_text)
    return int(m.group(1)) if m else 0


def collect_replica_groups(hlo_text: str):
    """One record per instruction carrying a ``replica_groups=`` field:
    ``{"name", "op", "groups", "form", "line"}``. ``groups`` is the
    expanded ``[[device ids], ...]`` membership — ``None`` for the empty
    form (``replica_groups={}``: every device in one group). ``form`` is
    ``"explicit"``, ``"iota"`` or ``"all"``. Shared by the HLO
    collective-safety auditors and the overlap analyzer so nobody
    re-regexes the module text."""
    out = []
    for lineno, line in enumerate(hlo_text.split("\n"), start=1):
        if "replica_groups=" not in line:
            continue
        rg = _RG_RE.search(line)
        if not rg:
            continue
        m = _RG_LINE_RE.search(line)
        name = m.group(1).lstrip("%") if m else f"line{lineno}"
        op = m.group(2) if m else ""
        body = rg.group(1)
        if body.startswith("["):
            im = _IOTA_RE.match(body)
            shape = [int(x) for x in im.group(1).split(",")]
            dims = [int(x) for x in im.group(2).split(",")]
            perm = ([int(x) for x in im.group(3).split(",")]
                    if im.group(3) else list(range(len(dims))))
            groups = _expand_iota_groups(shape, dims, perm)
            form = "iota"
        elif body == "{}":
            groups, form = None, "all"
        else:
            groups = [[int(x) for x in g.split(",") if x.strip()]
                      for g in re.findall(r"\{([\d,\s]*)\}", body[1:-1])]
            form = "explicit"
        out.append({"name": name, "op": op, "groups": groups,
                    "form": form, "line": lineno})
    return out


#: ops with matmul/reduction-class work — the compute a latency-hiding
#: executor can run under an in-flight collective. Elementwise and
#: data-movement ops are deliberately absent: they are memory-bound
#: epilogues that attach to their producers (a dequantize multiply or a
#: tanh fusion hides nothing by itself). A ``fusion`` counts only when
#: its fused computation body contains one of these.
_HEAVY_RE = re.compile(
    r"^(dot|convolution|custom-call|reduce|reduce-window|sort|while|"
    r"scatter|select-and-scatter|rng|rng-bit-generator|cholesky|"
    r"triangular-solve|fft)(\.|$)")

_INSTR_RE = re.compile(
    r"^\s*(ROOT\s+)?(%?[\w.\-]+)\s*=\s*(?:\([^=]*?\)|\S+)\s+([\w\-]+)"
    r"\(([^)]*)\)")
_NAME_TOKEN_RE = re.compile(r"%[\w.\-]+")
_CALLS_RE = re.compile(r"calls=(%?[\w.\-]+)")
_COMP_HEADER_RE = re.compile(r"^(?:ENTRY\s+)?(%?[\w.\-]+)\s*(?:\(|\s)")


def _parse_computations(hlo_text: str) -> Dict[str, list]:
    """{computation name: [instruction lines]} for every computation in
    an HLO module dump (ENTRY, while/cond bodies, fusion bodies)."""
    out: Dict[str, list] = {}
    block: list = []
    name = None
    depth = 0
    for line in hlo_text.split("\n"):
        stripped = line.strip()
        if depth == 0:
            if stripped.endswith("{") and "(" in stripped:
                m = _COMP_HEADER_RE.match(stripped)
                name = m.group(1) if m else f"_anon{len(out)}"
                depth = 1
                block = []
            continue
        depth += stripped.count("{") - stripped.count("}")
        if depth <= 0:
            if block:
                out[name] = block
            depth = 0
            continue
        if "=" in stripped:
            block.append(stripped)
    return out


def _instr_op(line: str) -> str:
    m = _INSTR_RE.match(line)
    return m.group(3) if m else ""


def _is_collective(op: str) -> bool:
    return any(op == c or op.startswith(f"{c}.") for c in COLLECTIVES)


def _count_between(sorted_levels, lo: int, hi: int) -> int:
    """Heavy ops with level strictly inside (lo, hi)."""
    import bisect
    if hi <= lo:
        return 0
    return bisect.bisect_left(sorted_levels, hi) - \
        bisect.bisect_right(sorted_levels, lo)


def collect_schedule_overlap(hlo_text: str) -> Dict[str, Any]:
    """Dependency-level static overlap of a compiled module's collectives.

    ASAP levels count the heavy ops (matmul/reduction class, see
    ``_HEAVY_RE``; fusions classified by their fused body) on each
    value's critical path. For each synchronous collective C the window
    runs from C's ready level to the minimum level of its first *real*
    consumer — a heavy op or another collective, traced through
    elementwise/movement ops (a dequantize epilogue does not end the
    window; the matmul that needs the data does). C is **overlappable**
    when a heavy op's level falls strictly inside that window: compute
    that is independent of C by construction (ancestors sit below the
    window, descendants at or above its end) which an async executor
    could run while C is on the wire. Collectives already emitted in
    async start/done form count as overlappable outright.

    A single fused whole-tree exchange scores 0 (every heavy op either
    feeds it or waits on it); a bucketed exchange issued in layer order
    scores (nb-1)/nb-ish — the metric ``benchmarks/overlap.py`` records
    as CPU evidence and the schedule autotuner scores."""
    comps = _parse_computations(hlo_text)
    # a fusion is heavy iff its fused body does real work
    heavy_fusion: Dict[str, bool] = {}
    for cname, block in comps.items():
        heavy_fusion[cname.lstrip("%")] = any(
            _HEAVY_RE.match(_instr_op(line)) for line in block)

    def is_heavy(op: str, line: str) -> bool:
        if _HEAVY_RE.match(op):
            return True
        if op == "fusion" or op.startswith("fusion."):
            m = _CALLS_RE.search(line)
            return bool(m) and heavy_fusion.get(m.group(1).lstrip("%"),
                                                False)
        return False

    total = 0
    overlappable = 0
    async_n = 0
    windows = []
    for cname, block in comps.items():
        if not any(_is_collective(_instr_op(l)) or "-start" in _instr_op(l)
                   for l in block):
            continue                     # no collectives: nothing to score
        names: list = []
        ops: list = []
        heavy: list = []
        operand_lists: list = []
        index: Dict[str, int] = {}
        for line in block:
            m = _INSTR_RE.match(line)
            if not m:
                names.append(None)
                ops.append("")
                heavy.append(False)
                operand_lists.append([])
                continue
            name, op, operands = m.group(2), m.group(3), m.group(4)
            names.append(name)
            ops.append(op)
            heavy.append(is_heavy(op, line))
            operand_lists.append(_NAME_TOKEN_RE.findall(operands))
            index[name] = len(names) - 1
        if not names:
            continue
        # ASAP heavy-op levels + a users index (producer idx -> consumers)
        asap = [0] * len(names)
        users: Dict[int, list] = {}
        for i, operands in enumerate(operand_lists):
            lvl = 0
            for tok in operands:
                j = index.get(tok)
                if j is None:
                    continue
                lvl = max(lvl, asap[j])
                users.setdefault(j, []).append(i)
            asap[i] = lvl + 1 if heavy[i] else lvl
        heavy_levels = sorted(asap[i] for i in range(len(names))
                              if heavy[i])
        max_level = max(asap) if asap else 0
        for i, op in enumerate(ops):
            is_async = any(op.startswith(f"{c}-start") for c in COLLECTIVES)
            if not is_async and not _is_collective(op):
                continue
            total += 1
            if is_async:
                async_n += 1
                overlappable += 1
                continue
            # first real consumer level, traced through light ops
            frontier = [i]
            seen = {i}
            consumer_lvl = None
            while frontier:
                j = frontier.pop()
                for k in users.get(j, ()):
                    if k in seen:
                        continue
                    seen.add(k)
                    if heavy[k] or _is_collective(ops[k]):
                        lvl = asap[k] if heavy[k] else asap[k] + 1
                        if consumer_lvl is None or lvl < consumer_lvl:
                            consumer_lvl = lvl
                    else:
                        frontier.append(k)
            if consumer_lvl is None:
                consumer_lvl = max_level + 1     # consumed by the output
            lo, hi = asap[i], consumer_lvl
            n_hidden = _count_between(heavy_levels, lo, hi)
            if n_hidden > 0:
                overlappable += 1
            windows.append({"op": op, "ready_level": lo,
                            "consumer_level": hi,
                            "compute_in_window": n_hidden})
    return {
        "collectives": total,
        "overlappable": overlappable,
        "async": async_n,
        "static_overlap_fraction":
            round(overlappable / total, 6) if total else 0.0,
        "windows": windows[:256],
    }


def hlo_overlap_summary(hlo_text: str) -> Dict[str, Any]:
    """The static overlap instrument: how much of the module's collective
    schedule is even *overlappable*. ``async_fraction`` is async ops over
    all collective ops, in [0, 1] — 0 on a fully synchronous schedule
    (the CPU backend), 1 when every collective has a start/done pair the
    latency-hiding scheduler can move compute between. The wall-clock
    half (did the overlap actually happen) comes from a device trace via
    telemetry/overlap.py."""
    sync = collect_collectives(hlo_text)
    async_ = collect_async(hlo_text)
    sched = collect_schedule_overlap(hlo_text)
    n_sync = sum(v["count"] for v in sync.values())
    n_async = sum(async_.values())
    total = n_sync + n_async
    return {
        "collectives": total,
        "sync": n_sync,
        "async": n_async,
        "async_fraction": round(n_async / total, 6) if total else 0.0,
        # the dependency-level instrument (collect_schedule_overlap):
        # collectives with hideable compute in their issue window — the
        # CPU-measurable half of the overlap story, and what the bucketed
        # ZeRO schedule raises on a backend with no async HLO forms
        "overlappable": sched["overlappable"],
        "static_overlap_fraction": sched["static_overlap_fraction"],
        "sync_bytes": sum(v["bytes"] for v in sync.values()),
        "per_op_sync": {op: v["count"] for op, v in sorted(sync.items())},
        "per_op_async": dict(sorted(async_.items())),
    }


def cost_summary(raw: Any) -> Dict[str, float]:
    """Normalize a ``cost_analysis()`` result to {identifier: float}.
    Handles the list/tuple wrapping of older jax versions, drops
    non-numeric values, and rewrites keys like ``"bytes accessed"`` to
    ``bytes_accessed`` (the per-operand ``bytes accessed0{}`` entries are
    dropped — consumers want module totals)."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not raw:
        return {}
    out: Dict[str, float] = {}
    for key, val in dict(raw).items():
        try:
            fval = float(val)
        except (TypeError, ValueError):
            continue
        name = re.sub(r"[^0-9a-zA-Z]+", "_", str(key)).strip("_")
        if re.search(r"\d", name):      # per-operand entries: skip
            continue
        out[name] = fval
    return out


def memory_summary(stats: Any) -> Optional[Dict[str, int]]:
    """``memory_analysis()`` CompiledMemoryStats -> plain dict of the
    per-device ``*_in_bytes`` fields (argument/output/temp/alias/
    generated_code, plus the host-memory variants when non-zero).
    Returns None when the backend reports nothing."""
    if stats is None:
        return None
    out: Dict[str, int] = {}
    for attr in dir(stats):
        if not attr.endswith("_size_in_bytes"):
            continue
        try:
            val = int(getattr(stats, attr))
        except (TypeError, ValueError):
            continue
        if attr.startswith("host_") and val == 0:
            continue                     # host fields are usually all-zero
        out[attr[:-len("_size_in_bytes")]] = val
    return out or None
