"""Shared HLO cost core — one parser for every XLA-cost consumer.

Three consumers used to carry private copies of this logic:
``benchmarks/hlo_audit.py`` (the collective-schedule regression gate),
the flight recorder's "XLA cost summary" capture, and the compile ledger
(telemetry/compileplane.py). They now all read from here, so a change to
the HLO text format (an XLA upgrade renaming an op, a new async form) is
fixed in exactly one place — and the future schedule autotuner (ROADMAP
item 2/5) scores candidate plans with the same numbers the gate enforces.

Contents:

- ``collect_collectives(hlo_text)`` — {op: {count, bytes}} over a
  compiled module's *synchronous* collectives (single-result and
  tuple-result forms), payload bytes from the printed result shapes.
- ``collect_async(hlo_text)`` — per-op counts of collectives emitted in
  async start/done form (``all-gather-start`` … ``all-gather-done``, or
  the generic ``async-start`` wrapper) — the ops XLA's latency-hiding
  scheduler *can* overlap with compute.
- ``hlo_overlap_summary(hlo_text)`` — sync vs async collective counts
  and the ``async_fraction`` in [0, 1]: the static half of the
  collective-overlap instrument (telemetry/overlap.py layers the
  trace-measured half on top).
- ``cost_summary(raw)`` — normalize a ``cost_analysis()`` result
  (dict, or the list/tuple wrapping older jax returns) to a flat dict
  of floats with python-identifier keys.
- ``memory_summary(stats)`` — normalize a ``memory_analysis()``
  ``CompiledMemoryStats`` to a plain dict of the ``*_in_bytes`` fields.

This module is deliberately standalone — stdlib-only, no package
imports — so ``benchmarks/hlo_audit.py`` can load it by file path before
the deepspeed_tpu package (and its backend-touching ``__init__`` chain)
is imported, the same way it loads ``utils/hermetic.py``.
"""

import math
import re
from typing import Any, Dict, Optional

__all__ = ["DTYPE_BYTES", "COLLECTIVES", "collect_collectives",
           "collect_async", "hlo_overlap_summary", "cost_summary",
           "memory_summary"]

#: HLO shape-prefix dtype -> bytes per element (unknown dtypes assume 4)
DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
               "s8": 1, "u8": 1, "pred": 1, "f64": 8, "s64": 8, "u64": 8,
               "s16": 2, "u16": 2, "f8e4m3fn": 1, "f8e5m2": 1}

#: the collective-op vocabulary the audit and the overlap analyzer track
COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
               "collective-permute")

_PAT_SINGLE = re.compile(
    r"=\s*(\w+)\[([\d,]*)\]\S*\s+(" + "|".join(COLLECTIVES) + r")\(")
_PAT_TUPLE = re.compile(
    r"=\s*\(([^)]+)\)\s+(" + "|".join(COLLECTIVES) + r")\(")
_PAT_SHAPE = re.compile(r"(\w+)\[([\d,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    numel = math.prod([int(d) for d in dims.split(",") if d] or [1])
    return numel * DTYPE_BYTES.get(dtype, 4)


def collect_collectives(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """{op: {count, bytes}} over the compiled module (fusion-internal
    shapes included via the op's result shape). Synchronous forms only —
    async start/done pairs are ``collect_async``'s domain."""
    out: Dict[str, Dict[str, int]] = {}
    # single-result form only ('= f32[...] all-reduce('); tuple results
    # ('= (f32[...], ...) all-reduce(') are handled by _PAT_TUPLE below —
    # anchoring at '= <dtype>[' keeps the two disjoint
    for m in _PAT_SINGLE.finditer(hlo_text):
        dtype, dims, op = m.group(1), m.group(2), m.group(3)
        rec = out.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += _shape_bytes(dtype, dims)
    # tuple-result collectives (all-reduce of N tensors) print as
    # `(f32[...], f32[...]) all-reduce(` — catch those too
    for m in _PAT_TUPLE.finditer(hlo_text):
        shapes, op = m.group(1), m.group(2)
        rec = out.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        for sm in _PAT_SHAPE.finditer(shapes):
            rec["bytes"] += _shape_bytes(sm.group(1), sm.group(2))
    return out


def collect_async(hlo_text: str) -> Dict[str, int]:
    """Per-op counts of collectives in async start/done form. XLA prints
    dedicated pairs for some ops (``all-gather-start(``) and wraps the
    rest in generic ``async-start`` instructions whose line names the
    wrapped op; both count."""
    out: Dict[str, int] = {}
    for op in COLLECTIVES:
        n = len(re.findall(rf"\b{op}-start\(", hlo_text))
        n += len(re.findall(rf"\basync-start[^\n]*\b{op}\b", hlo_text))
        if n:
            out[op] = n
    return out


def hlo_overlap_summary(hlo_text: str) -> Dict[str, Any]:
    """The static overlap instrument: how much of the module's collective
    schedule is even *overlappable*. ``async_fraction`` is async ops over
    all collective ops, in [0, 1] — 0 on a fully synchronous schedule
    (the CPU backend), 1 when every collective has a start/done pair the
    latency-hiding scheduler can move compute between. The wall-clock
    half (did the overlap actually happen) comes from a device trace via
    telemetry/overlap.py."""
    sync = collect_collectives(hlo_text)
    async_ = collect_async(hlo_text)
    n_sync = sum(v["count"] for v in sync.values())
    n_async = sum(async_.values())
    total = n_sync + n_async
    return {
        "collectives": total,
        "sync": n_sync,
        "async": n_async,
        "async_fraction": round(n_async / total, 6) if total else 0.0,
        "sync_bytes": sum(v["bytes"] for v in sync.values()),
        "per_op_sync": {op: v["count"] for op, v in sorted(sync.items())},
        "per_op_async": dict(sorted(async_.items())),
    }


def cost_summary(raw: Any) -> Dict[str, float]:
    """Normalize a ``cost_analysis()`` result to {identifier: float}.
    Handles the list/tuple wrapping of older jax versions, drops
    non-numeric values, and rewrites keys like ``"bytes accessed"`` to
    ``bytes_accessed`` (the per-operand ``bytes accessed0{}`` entries are
    dropped — consumers want module totals)."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not raw:
        return {}
    out: Dict[str, float] = {}
    for key, val in dict(raw).items():
        try:
            fval = float(val)
        except (TypeError, ValueError):
            continue
        name = re.sub(r"[^0-9a-zA-Z]+", "_", str(key)).strip("_")
        if re.search(r"\d", name):      # per-operand entries: skip
            continue
        out[name] = fval
    return out


def memory_summary(stats: Any) -> Optional[Dict[str, int]]:
    """``memory_analysis()`` CompiledMemoryStats -> plain dict of the
    per-device ``*_in_bytes`` fields (argument/output/temp/alias/
    generated_code, plus the host-memory variants when non-zero).
    Returns None when the backend reports nothing."""
    if stats is None:
        return None
    out: Dict[str, int] = {}
    for attr in dir(stats):
        if not attr.endswith("_size_in_bytes"):
            continue
        try:
            val = int(getattr(stats, attr))
        except (TypeError, ValueError):
            continue
        if attr.startswith("host_") and val == 0:
            continue                     # host fields are usually all-zero
        out[attr[:-len("_size_in_bytes")]] = val
    return out or None
