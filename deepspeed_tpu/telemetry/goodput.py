"""Goodput ledger — classify every second of wall-clock into named buckets.

On preemptible TPU fleets the operator question is not "what is the step
time" but "what fraction of wall-clock was productive training". The
ledger answers it by accounting wall time into exclusive buckets:

- ``productive_step``  — synced train-step time (goodput)
- ``serving_step``     — serving scheduler ticks (goodput for replicas)
- ``compile``          — steps that paid the *initial* XLA compile
- ``recompile``        — steps the RecompileWatchdog flagged (jit-cache
  growth: the silent-recompile perf cliff, made a first-class cost)
- ``checkpoint_save`` / ``checkpoint_load`` — checkpoint IO
- ``sentinel``         — steps whose update the sentinel skipped, plus
  rollback restores (work that had to be thrown away)
- ``preemption``       — preemption handling (emergency checkpoint, drain)
- ``data_wait``        — blocking on the input pipeline
- ``serving_drain``    — serving drain (no new admissions)
- ``idle``             — the residual: wall-clock not attributed above

Buckets are *exclusive* and sum to measured wall-clock by construction:
``idle`` is computed as the residual at snapshot time, and nested
``track()`` intervals follow an **outermost-wins** rule — an interval
opened while another is active on the same ledger contributes to the
outer interval's bucket (so a checkpoint load performed *inside* a
sentinel rollback lands in ``sentinel``, not split across two buckets).

Intervals support late reclassification: the engine opens a step interval
as ``productive_step`` and, once the recompile watchdog has spoken, moves
it to ``compile``/``recompile``/``sentinel`` — time transfers between
buckets, never double-counts.

Disabled (the default) costs nothing: ``track()`` returns a shared no-op
interval, no object is allocated, no clock is read. Enable through the
``telemetry`` config block (``{"telemetry": {"enabled": true}}`` enables
the ledger alongside the tracer; ``"goodput": false`` opts out) or
``configure_ledger(enabled=True)``.

Every interval close mirrors the bucket totals and the goodput fraction
into the process-global tracer gauges (``goodput/*``), so
``metrics_snapshot()``, ``prometheus_dump()``, the monitor sinks, and the
``/statusz`` page all see the ledger live.
"""

import threading
import time
from typing import Dict, Optional

__all__ = ["GoodputLedger", "get_ledger", "configure_ledger",
           "BUCKETS", "PRODUCTIVE_BUCKETS"]

#: the full bucket vocabulary (snapshot always reports every name, so
#: downstream dashboards get a stable schema)
BUCKETS = ("productive_step", "serving_step", "compile", "recompile",
           "checkpoint_save", "checkpoint_load", "sentinel", "preemption",
           "data_wait", "serving_drain", "idle")

#: buckets counted as goodput in the fraction's numerator
PRODUCTIVE_BUCKETS = ("productive_step", "serving_step")


class _Interval:
    """One tracked wall-clock interval. Context manager; one allocation
    per *outermost* track() call on an enabled ledger."""

    __slots__ = ("bucket", "seconds", "_ledger", "_t0", "_closed")

    def __init__(self, ledger: "GoodputLedger", bucket: str):
        self.bucket = bucket
        self.seconds = 0.0
        self._ledger = ledger
        self._t0 = 0.0
        self._closed = False

    def __enter__(self):
        self._t0 = self._ledger._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.seconds = self._ledger._clock() - self._t0
        self._closed = True
        self._ledger._commit(self)
        return False

    def reclassify(self, bucket: str):
        """Move this interval's time to another bucket — the engine opens
        a step as ``productive_step`` and renames it once it knows whether
        the step compiled, recompiled, or was sentinel-skipped."""
        if bucket == self.bucket:
            return
        if self._closed:
            self._ledger._move(self.bucket, bucket, self.seconds)
        self.bucket = bucket


class _NullInterval:
    """Shared no-op interval: what a disabled ledger (or a nested track()
    under outermost-wins) hands out. No allocation, no clock read."""

    __slots__ = ()
    bucket = None
    seconds = 0.0

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def reclassify(self, bucket):
        pass


_NULL_INTERVAL = _NullInterval()


class GoodputLedger:
    """Wall-clock accountant: exclusive buckets + residual idle."""

    def __init__(self, enabled: bool = False, clock=time.monotonic):
        self.enabled = enabled
        self._clock = clock
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._buckets: Dict[str, float] = {}
        self._t0: Optional[float] = None
        self._last_export = 0.0
        #: min seconds between gauge-mirror refreshes (the snapshot and
        #: prometheus_dump read the ledger directly and are always live;
        #: only the redundant goodput/* gauge mirror is throttled)
        self.export_interval_s = 0.2

    # ------------------------------------------------------------ configure
    def configure(self, enabled: Optional[bool] = None) -> "GoodputLedger":
        if enabled is not None:
            was = self.enabled
            self.enabled = bool(enabled)
            if self.enabled and not was:
                self.reset()
            if was and not self.enabled:
                # gauge lifecycle: a disabled ledger's goodput/* mirror
                # must not read as live in prometheus_dump()//metrics
                from .trace import get_tracer
                get_tracer().release_counters(self)
        return self

    def reset(self):
        """Restart the wall-clock epoch and zero every bucket."""
        with self._lock:
            self._buckets = {}
            self._t0 = self._clock()
        self._last_export = 0.0

    # ------------------------------------------------------------- tracking
    def track(self, bucket: str):
        """Open an exclusive interval attributed to ``bucket``. Nested
        calls on the same thread return the shared no-op interval (the
        outer interval keeps the time — outermost wins). Disabled ledger:
        the same no-op, zero cost."""
        if not self.enabled:
            return _NULL_INTERVAL
        if getattr(self._tls, "active", False):
            return _NULL_INTERVAL
        self._tls.active = True
        if self._t0 is None:
            self.reset()
        return _Interval(self, bucket)

    def record(self, bucket: str, seconds: float):
        """Attribute ``seconds`` of already-measured time to ``bucket``
        (for callers that timed the work themselves)."""
        if not self.enabled or seconds <= 0:
            return
        with self._lock:
            self._buckets[bucket] = self._buckets.get(bucket, 0.0) + seconds
        self._export()

    def _commit(self, interval: _Interval):
        self._tls.active = False
        with self._lock:
            self._buckets[interval.bucket] = \
                self._buckets.get(interval.bucket, 0.0) + interval.seconds
        self._export()

    def _move(self, src: str, dst: str, seconds: float):
        with self._lock:
            self._buckets[src] = self._buckets.get(src, 0.0) - seconds
            if abs(self._buckets[src]) < 1e-12:
                self._buckets[src] = 0.0
            self._buckets[dst] = self._buckets.get(dst, 0.0) + seconds
        self._export()

    # -------------------------------------------------------------- reading
    def totals(self) -> Dict[str, float]:
        """Raw bucket totals (no idle residual, no rounding) — what the
        flight recorder diffs per step record; cheaper than snapshot()."""
        with self._lock:
            return dict(self._buckets)

    def window(self, before: Dict[str, float],
               wall_s: float) -> Dict[str, object]:
        """Bucket deltas since ``before`` (a prior ``totals()`` snapshot)
        as a self-contained windowed accounting over ``wall_s`` seconds of
        wall-clock: per-bucket seconds, the ``idle`` residual, productive
        seconds, and the window's goodput fraction. The measured-trial
        read API (autotuning/measure.py): a trial driver snapshots
        ``totals()`` after warmup and scores only the steady-state window,
        so compile time never pollutes a trial's productive fraction.
        Buckets (idle included) sum to ``wall_s`` by construction."""
        wall = max(0.0, float(wall_s))
        totals = self.totals()
        buckets = {}
        for name, secs in totals.items():
            delta = secs - before.get(name, 0.0)
            if delta > 1e-9:
                buckets[name] = round(delta, 6)
        attributed = sum(buckets.values())
        buckets["idle"] = round(max(0.0, wall - attributed), 6)
        productive = sum(buckets.get(b, 0.0) for b in PRODUCTIVE_BUCKETS)
        return {
            "wall_s": round(wall, 6),
            "buckets": buckets,
            "productive_s": round(productive, 6),
            "goodput_fraction": round(productive / wall, 6) if wall else 0.0,
        }

    def wall_seconds(self) -> float:
        if self._t0 is None:
            return 0.0
        return max(0.0, self._clock() - self._t0)

    def snapshot(self) -> Dict[str, object]:
        """The ledger as one JSON-able dict. Buckets (including the
        computed ``idle`` residual) sum to ``wall_s`` by construction."""
        wall = self.wall_seconds()
        with self._lock:
            buckets = {name: round(self._buckets.get(name, 0.0), 6)
                       for name in BUCKETS if name != "idle"}
            for name, secs in self._buckets.items():
                if name not in buckets:          # caller-defined bucket
                    buckets[name] = round(secs, 6)
        attributed = sum(buckets.values())
        buckets["idle"] = round(max(0.0, wall - attributed), 6)
        productive = sum(buckets.get(b, 0.0) for b in PRODUCTIVE_BUCKETS)
        badput = {name: secs for name, secs in buckets.items()
                  if name not in PRODUCTIVE_BUCKETS and name != "idle"
                  and secs > 0}
        return {
            "wall_s": round(wall, 6),
            "buckets": buckets,
            "goodput_fraction": round(productive / wall, 6) if wall else 0.0,
            "badput": badput,
        }

    # ------------------------------------------------------------- mirroring
    def _export(self):
        """Mirror bucket totals + goodput fraction into the tracer gauges
        so every existing exporter (snapshot, Prometheus, monitor sinks,
        /statusz) sees the ledger without new plumbing. Rate-limited to
        ``export_interval_s`` — per-step gauge rewrites would be pure
        overhead (the ledger itself is always read live)."""
        wall = self.wall_seconds()
        now = self._clock()
        if self._last_export and \
                now - self._last_export < self.export_interval_s:
            return
        self._last_export = now
        from .trace import get_tracer
        tracer = get_tracer()
        with self._lock:
            items = list(self._buckets.items())
        productive = 0.0
        for name, secs in items:
            tracer.set_counter(f"goodput/{name}_s", round(secs, 6),
                               owner=self)
            if name in PRODUCTIVE_BUCKETS:
                productive += secs
        if wall > 0:
            tracer.set_counter("goodput/wall_s", round(wall, 6),
                               owner=self)
            tracer.set_counter("goodput/fraction",
                               round(productive / wall, 6), owner=self)


_LEDGER: Optional[GoodputLedger] = None


def get_ledger() -> GoodputLedger:
    """The process-global goodput ledger (created disabled)."""
    global _LEDGER
    if _LEDGER is None:
        _LEDGER = GoodputLedger()
    return _LEDGER


def configure_ledger(enabled: Optional[bool] = None) -> GoodputLedger:
    return get_ledger().configure(enabled=enabled)
