"""Flight recorder — anomaly-triggered postmortem bundles.

The tracer, goodput ledger, and statusz server can tell you *that* a step
was slow; by the time a human looks, the span ring has wrapped and the
moment is gone. The flight recorder is the capture layer: an always-on,
bounded in-memory ring of recent **step records** (step wall time,
goodput-bucket deltas, collective op/byte deltas, serving queue/SLO
state) plus a set of **trigger rules** that, when an anomaly fires, write
a self-contained **postmortem bundle** to disk while the evidence is
still in memory:

- ``slow_step``   — step wall time exceeded ``slow_step_factor`` × the
  EMA of recent steps (or the absolute ``slow_step_ms`` threshold).
  Compile/recompile steps are excluded from both the check and the EMA —
  they are separately attributed and would poison the baseline.
- ``recompile``   — the RecompileWatchdog saw jit-cache growth.
- ``sentinel``    — the training sentinel flagged a NaN loss / grad-norm
  spike (resilience/sentinel.py calls in).
- ``slo_burn``    — a serving replica's error-budget burn rate crossed
  ``slo_burn_threshold`` (edge-triggered by serving/engine.py).
- ``preemption``  — a preemption signal latched (always bypasses
  debounce: there may be no second chance to capture).
- ``straggler``   — the host aggregator (telemetry/hostagg.py) attributed
  the step time to one slow host.
- ``overlap_drop`` — a recompile produced a step program whose HLO
  static overlap fraction fell below ``compile_plane.overlap_floor``
  (telemetry/overlap.py: a schedule that silently de-overlapped).
- ``acceptance_drop`` — a serving replica's speculative-decode
  acceptance EMA fell below ``speculative.acceptance_floor``
  (edge-triggered by serving/engine.py after warmup: speculation that
  stopped paying for itself — draft drift, workload shift).
- ``manual``      — an explicit ``/debug/capture`` request.

A bundle is ONE JSON file (atomic tmp+rename write) containing the
last-N step records, the Perfetto trace slice around the trigger
(``trace_ms`` window), the goodput snapshot, the registered status
sections (config fingerprint, counters, checkpoint/rollback history),
the live tracer counters, and the XLA cost-analysis summary of the
active compiled executable. Retention is keep-last-``keep`` bundles, and
triggers are **debounced per kind** (``debounce_s``) so a pathological
run cannot fill the disk or capture in a loop — while one slow step, one
recompile, and one NaN arriving together still yield one bundle each.

Fully off by default: a disabled config means no recorder object, no
thread (the recorder never starts one — bundles are written inline at
trigger time, which is rare by construction), no directory, no files.
"""

import json
import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .goodput import get_ledger
from .trace import get_tracer

__all__ = ["FlightRecorder", "TRIGGER_KINDS"]

#: the trigger-rule vocabulary (bundle filenames carry the kind).
#: ``trial_best`` / ``trial_worst`` are fired once per measured autotuning
#: sweep (autotuning/measure.py) with the winning and losing trial's
#: goodput table, compile events, and score breakdown embedded — every
#: tuning decision stays auditable post-hoc. ``perf_regression`` is the
#: perf twin of ``overlap_drop``: a recompile whose step/tick anatomy
#: shifts a bucket beyond the perf plane's configured band
#: (telemetry/perfplane.py), edge-triggered with the shifted bucket
#: names in the detail.
TRIGGER_KINDS = ("slow_step", "recompile", "sentinel", "slo_burn",
                 "preemption", "straggler", "failover", "overlap_drop",
                 "acceptance_drop", "resize", "rollout_failed",
                 "trial_best", "trial_worst", "perf_regression", "manual")


class FlightRecorder:
    """Bounded step-record ring + trigger rules + bundle writer."""

    def __init__(self, config=None, tracer=None, ledger=None,
                 clock=time.monotonic):
        def g(key, default):
            return getattr(config, key, default) if config is not None \
                else default

        self.tracer = tracer or get_tracer()
        self._ledger = ledger or get_ledger()
        self._clock = clock
        self.dir = str(g("dir", "flight_bundles"))
        self.keep = int(g("keep", 8))
        self.debounce_s = float(g("debounce_s", 30.0))
        self.slow_step_factor = float(g("slow_step_factor", 3.0))
        self.slow_step_ms = float(g("slow_step_ms", 0.0))
        self.warmup_steps = int(g("warmup_steps", 5))
        self.ema_alpha = float(g("ema_alpha", 0.2))
        self.trace_ms = float(g("trace_ms", 10_000.0))
        self.slo_burn_threshold = float(g("slo_burn_threshold", 2.0))
        self._records: "deque" = deque(maxlen=int(g("ring", 256)))
        #: name -> callable() -> dict; one bundle "status" section each
        self._providers: Dict[str, Callable[[], dict]] = {}
        self._cost_provider: Optional[Callable[[], dict]] = None
        self._compile_plane = None       # CompileLedger (attach_compile_plane)
        #: callable() -> [trace_id, ...]: the distributed-trace ids in
        #: flight on this member at capture time — what lets a router
        #: correlate same-trace bundles across replica bundle dirs
        self._trace_provider: Optional[Callable[[], list]] = None
        self._closed = False
        self.ema_ms = 0.0
        self._baseline_steps = 0       # records feeding the EMA
        self._last_goodput: Dict[str, float] = {}
        self._last_comm: Optional[Dict[str, int]] = None
        self._last_fire_at: Dict[str, float] = {}   # per-kind debounce
        self.trigger_counts: Dict[str, int] = {}
        self.suppressed = 0            # debounced (counted, not captured)
        self.last_fire: Optional[Dict[str, Any]] = None
        self._next_id = 1

    # ------------------------------------------------------------- registry
    def add_provider(self, name: str, provider: Callable[[], dict]):
        """Add a bundle status section (same shape as a statusz section:
        config fingerprint, counters, checkpoint history, ...)."""
        self._providers[name] = provider
        return self

    def set_cost_provider(self, provider: Callable[[], dict]):
        """Callable returning the XLA cost-analysis summary of the active
        compiled executable (the engine captures it when the MFU profiler
        traces the step fn)."""
        self._cost_provider = provider
        return self

    def attach_compile_plane(self, ledger):
        """Embed the compile ledger (telemetry/compileplane.py) in every
        bundle: fingerprints, recompile diffs, and per-event cost/memory
        summaries — a recompile bundle then names the exact argument
        whose shape changed instead of just counting the recompile."""
        self._compile_plane = ledger
        return self

    def set_trace_provider(self, provider: Callable[[], list]):
        """Callable returning the distributed trace ids currently in
        flight on this member (telemetry/disttrace.py); every bundle
        embeds them as ``in_flight_traces`` so cross-replica postmortems
        join on the request, not on wall-clock proximity."""
        self._trace_provider = provider
        return self

    # ------------------------------------------------------------ recording
    def record_step(self, step: int, dur_ms: float, compile: bool = False,
                    recompile: bool = False, slow_check: bool = True,
                    extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Append one finished step/tick to the ring and run the slow-step
        rule. Returns the bundle path if the rule fired, else None."""
        record: Dict[str, Any] = {"step": int(step), "t": time.time(),
                                  "dur_ms": round(float(dur_ms), 3)}
        if compile:
            record["compile"] = True
        if recompile:
            record["recompile"] = True
        if self._ledger.enabled:
            totals = self._ledger.totals()
            deltas = {name: round(secs - self._last_goodput.get(name, 0.0), 6)
                      for name, secs in totals.items()
                      if secs - self._last_goodput.get(name, 0.0) > 1e-9}
            self._last_goodput = totals
            if deltas:
                record["goodput"] = deltas
        stats = self._comm_stats()
        if stats is not None:
            # diff every counter comm_stats exposes (ops + wire/logical/
            # inter-host/intra-host bytes) so the record shows the step's
            # actual link traffic, compressed size included
            prev = self._last_comm or {}
            self._last_comm = stats
            record["comm"] = {k: v - prev.get(k, 0)
                              for k, v in stats.items()}
        if extra:
            record.update(extra)
        self._records.append(record)

        baseline = not (compile or recompile)
        fired = None
        if slow_check and baseline and \
                self._baseline_steps >= self.warmup_steps and self.ema_ms > 0:
            slow = dur_ms > self.slow_step_factor * self.ema_ms or \
                (self.slow_step_ms > 0 and dur_ms > self.slow_step_ms)
            if slow:
                record["slow"] = True
                fired = self.trigger(
                    "slow_step",
                    f"step {step}: {dur_ms:.1f}ms vs EMA "
                    f"{self.ema_ms:.1f}ms "
                    f"(trigger {self.slow_step_factor:g}x)", step=step)
        if baseline:
            # the anomalous step still feeds the EMA (alpha-damped), so a
            # genuine regime change stops triggering after a few steps
            self.ema_ms = dur_ms if self._baseline_steps == 0 else \
                (1 - self.ema_alpha) * self.ema_ms + self.ema_alpha * dur_ms
            self._baseline_steps += 1
        return fired

    @staticmethod
    def _comm_stats() -> Optional[Dict[str, int]]:
        # deferred: comm.comm imports telemetry.trace; importing it here at
        # module level would be order-sensitive
        try:
            from ..comm.comm import comm_stats
            return comm_stats()
        except Exception:
            return None

    # ------------------------------------------------------------- triggers
    def trigger(self, kind: str, detail: str = "",
                step: Optional[int] = None,
                force: bool = False) -> Optional[str]:
        """Fire one trigger rule. Writes a bundle unless the per-kind
        debounce suppresses it (``force`` bypasses — preemption and
        explicit captures must not be dropped). Returns the bundle path
        or None when debounced."""
        self.trigger_counts[kind] = self.trigger_counts.get(kind, 0) + 1
        now = self._clock()
        last = self._last_fire_at.get(kind)
        if not force and last is not None and \
                now - last < self.debounce_s:
            self.suppressed += 1
            return None
        self._last_fire_at[kind] = now
        return self._write_bundle(kind, detail, step)

    # --------------------------------------------------------------- bundles
    def _write_bundle(self, kind: str, detail: str,
                      step: Optional[int]) -> str:
        from .export import chrome_trace_slice
        bid = self._next_id
        self._next_id += 1
        doc: Dict[str, Any] = {
            "id": bid,
            "kind": kind,
            "detail": detail,
            "step": step,
            "time": time.time(),
            "trigger_counts": dict(self.trigger_counts),
            "records": list(self._records),
            "trace": chrome_trace_slice(self.tracer, last_ms=self.trace_ms),
            "counters": {tag: val for tag, (val, _s)
                         in self.tracer.counters().items()},
            "status": {},
        }
        if self._trace_provider is not None:
            try:
                doc["in_flight_traces"] = list(self._trace_provider())
            except Exception as e:
                doc["in_flight_traces"] = []
                doc["trace_provider_error"] = str(e)
        if self._ledger.enabled:
            doc["goodput"] = self._ledger.snapshot()
        for name, provider in list(self._providers.items()):
            try:
                doc["status"][name] = provider()
            except Exception as e:   # a broken provider must not lose the
                doc["status"][name] = {"error": str(e)}   # whole bundle
        if self._cost_provider is not None:
            try:
                doc["cost"] = self._cost_provider()
            except Exception as e:
                doc["cost"] = {"error": str(e)}
        if self._compile_plane is not None:
            try:
                doc["compile_plane"] = self._compile_plane.bundle_section()
            except Exception as e:
                doc["compile_plane"] = {"error": str(e)}
        os.makedirs(self.dir, exist_ok=True)
        fname = f"bundle-{bid:06d}-{kind}.json"
        path = os.path.join(self.dir, fname)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)          # a reader never sees a torn bundle
        self._retain()
        self.last_fire = {"id": bid, "kind": kind, "detail": detail,
                          "step": step, "time": doc["time"], "path": path}
        self.tracer.set_counter("recorder/bundles",
                                float(sum(self.trigger_counts.values())
                                      - self.suppressed), owner=self)
        self.tracer.instant(f"flight_recorder:{kind}", cat="warning",
                            args={"detail": detail, "bundle": fname})
        return path

    def _bundle_files(self) -> List[str]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        return sorted(n for n in names
                      if n.startswith("bundle-") and n.endswith(".json"))

    def _retain(self):
        files = self._bundle_files()
        for name in files[:max(0, len(files) - self.keep)]:
            try:
                os.remove(os.path.join(self.dir, name))
            except OSError:
                pass
        # cross-replica postmortems (crossrep-NNNN.json, written into
        # this dir by FleetAggregator.cross_replica_postmortem) obey the
        # same keep — a soak with a failover every few seconds must not
        # grow the bundle dir without bound
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("crossrep-")
                           and n.endswith(".json"))
        except OSError:
            return
        for name in names[:max(0, len(names) - self.keep)]:
            try:
                os.remove(os.path.join(self.dir, name))
            except OSError:
                pass

    def bundles(self) -> List[Dict[str, Any]]:
        """On-disk bundle index (newest last): id, kind, file, bytes."""
        out = []
        for name in self._bundle_files():
            parts = name[len("bundle-"):-len(".json")].split("-", 1)
            try:
                bid = int(parts[0])
            except ValueError:
                continue
            path = os.path.join(self.dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            out.append({"id": bid, "kind": parts[1] if len(parts) > 1
                        else "?", "file": name, "bytes": size})
        return out

    def read_bundle(self, bid: int) -> Optional[str]:
        """Raw JSON text of bundle ``bid`` (the /debug/bundle download)."""
        for entry in self.bundles():
            if entry["id"] == bid:
                try:
                    with open(os.path.join(self.dir, entry["file"])) as f:
                        return f.read()
                except OSError:
                    return None
        return None

    # ------------------------------------------------------------ lifecycle
    def close(self):
        """Retract this recorder's gauges from the shared counter space
        (the owning engine/router's shutdown path) — a closed member's
        bundle count must not linger in /metrics as if it were live.
        Bundles on disk are untouched. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.tracer.release_counters(self)

    # -------------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        """The statusz/ds_tpu_top view: bundle count, last fire + age."""
        out: Dict[str, Any] = {
            "bundles": len(self._bundle_files()),
            "dir": self.dir,
            "triggers": dict(self.trigger_counts),
            "suppressed": self.suppressed,
            "ema_ms": round(self.ema_ms, 3),
            "records": len(self._records),
        }
        if self.last_fire is not None:
            last = dict(self.last_fire)
            last["age_s"] = round(max(0.0, time.time() - last["time"]), 1)
            out["last"] = last
        return out
