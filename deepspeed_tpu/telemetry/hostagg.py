"""Cross-host aggregation — straggler attribution + heartbeat gaps.

On a multi-host slice every collective is a barrier: the whole pod runs at
the speed of its slowest host, and per-host telemetry alone cannot say
*which* host that is. The aggregator piggybacks a tiny per-host metrics
vector — ``[host_id, step_time_ms, data_wait_ms, heartbeat_seqno]`` — on a
low-frequency all-gather (``multihost_utils.process_allgather`` every
``interval`` steps; a few doubles per host, noise next to a train step)
and computes:

- **per-host spread**: min / median / max step time across hosts, and the
  ``spread`` ratio max/median;
- **slowest-host attribution**: when spread exceeds
  ``straggler_factor`` the slowest host is flagged as the straggler —
  an *edge* on the flag is a flight-recorder trigger
  (telemetry/flight_recorder.py), so the postmortem bundle names the
  host while the evidence is fresh;
- **heartbeat gaps**: a host whose seqno stops advancing for
  ``heartbeat_misses`` consecutive aggregations is reported missing
  (its step loop is stuck even though it still answers the collective),
  which flips ``/healthz`` through the registered health check.

Results are exported as ``host/*`` tracer gauges, which
``prometheus_dump`` emits as dedicated ``dstpu_host_*`` series, and as
the ``hosts`` document on ``/statusz`` (the straggler table
``bin/ds_tpu_top`` renders).

Single-process runs (and tests) inject ``gather_fn`` to simulate
per-host feeds; the default gather degrades to a one-host list outside a
multi-process runtime.
"""

from typing import Any, Callable, Dict, List, Optional

from .trace import get_tracer

__all__ = ["HostAggregator"]


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def _default_gather(vec: List[float]) -> List[List[float]]:
    """All-gather one metrics vector across hosts; identity when the
    runtime is single-process."""
    try:
        import jax
        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils
            rows = multihost_utils.process_allgather(
                np.asarray(vec, np.float64))
            return [list(map(float, row))
                    for row in np.asarray(rows).reshape(-1, len(vec))]
    except Exception:
        pass
    return [list(vec)]


class HostAggregator:
    """Per-host metrics vector exchange + straggler/heartbeat analysis."""

    def __init__(self, config=None, tracer=None,
                 gather_fn: Optional[Callable] = None,
                 host_id: Optional[int] = None, owner: Any = None):
        def g(key, default):
            return getattr(config, key, default) if config is not None \
                else default

        self.tracer = tracer or get_tracer()
        self.interval = max(1, int(g("interval", 10)))
        self.straggler_factor = float(g("straggler_factor", 1.5))
        self.heartbeat_misses = max(1, int(g("heartbeat_misses", 3)))
        self._gather = gather_fn or _default_gather
        self._host_id = host_id if host_id is not None else _process_index()
        self._owner = owner
        self._seqno = 0
        self._step_ms = 0.0
        self._data_wait_ms = 0.0
        self._rounds = 0
        #: host -> [last seqno, rounds since it advanced]
        self._seen: Dict[int, List[int]] = {}
        self._prev_straggler: Optional[int] = None
        self.last: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------ local feed
    def update_local(self, step_time_ms: float, data_wait_ms: float = 0.0):
        """Record this host's latest step. The seqno is the heartbeat: a
        stuck step loop stops advancing it even if the host still answers
        the gather."""
        self._seqno += 1
        self._step_ms = float(step_time_ms)
        self._data_wait_ms = float(data_wait_ms)

    # ------------------------------------------------------------- aggregate
    def maybe_aggregate(self, steps_done: int) -> Optional[Dict[str, Any]]:
        """Aggregate on the configured step cadence; None off-cadence.
        Every host must call this at the same steps — the gather is a
        collective."""
        if steps_done % self.interval != 0:
            return None
        return self.aggregate()

    def aggregate(self) -> Dict[str, Any]:
        vec = [float(self._host_id), self._step_ms, self._data_wait_ms,
               float(self._seqno)]
        rows = self._gather(vec)
        self._rounds += 1
        hosts: Dict[int, Dict[str, Any]] = {}
        for row in rows:
            hosts[int(row[0])] = {"step_time_ms": round(float(row[1]), 3),
                                  "data_wait_ms": round(float(row[2]), 3),
                                  "seqno": int(row[3])}
        missing = []
        for hid in sorted(hosts):
            state = self._seen.get(hid)
            seq = hosts[hid]["seqno"]
            if state is None or seq > state[0]:
                self._seen[hid] = [seq, 0]
            else:
                state[1] += 1
                if state[1] >= self.heartbeat_misses:
                    missing.append(hid)
            # heartbeat age in aggregation rounds (0 = advanced this
            # round) — the per-host column ds_tpu_top renders
            hosts[hid]["beats_behind"] = self._seen[hid][1]

        by_time = sorted((h["step_time_ms"], hid)
                         for hid, h in hosts.items())
        times = [t for t, _ in by_time]
        n = len(times)
        # true median (mean of the middle two for even n): the upper-middle
        # shortcut would make a 2-host straggler mathematically invisible
        # (median == max ⇒ spread pinned at 1.0)
        median = times[n // 2] if n % 2 else \
            0.5 * (times[n // 2 - 1] + times[n // 2])
        max_ms, slowest = by_time[-1]
        spread = max_ms / max(median, 1e-9)
        straggler = slowest if (len(hosts) > 1 and
                                spread > self.straggler_factor) else None
        res: Dict[str, Any] = {
            "round": self._rounds,
            "n_hosts": len(hosts),
            "min_ms": round(by_time[0][0], 3),
            "median_ms": round(median, 3),
            "max_ms": round(max_ms, 3),
            "spread": round(spread, 3),
            "straggler": straggler,
            # edge detection: the flight-recorder trigger fires when a
            # straggler APPEARS (or moves), not on every cadence it persists
            "new_straggler": straggler is not None and
            straggler != self._prev_straggler,
            "missing": missing,
            "hosts": hosts,
        }
        self._prev_straggler = straggler
        self.last = res
        self._export(res)
        return res

    def _export(self, res: Dict[str, Any]):
        """Mirror the aggregate into ``host/*`` gauges (prometheus_dump
        emits these as dedicated ``dstpu_host_*`` series)."""
        tr = self.tracer
        o = self._owner

        def gauge(name, value):
            tr.set_counter(f"host/{name}", float(value), owner=o)

        gauge("n_hosts", res["n_hosts"])
        gauge("step_time_min_ms", res["min_ms"])
        gauge("step_time_median_ms", res["median_ms"])
        gauge("step_time_max_ms", res["max_ms"])
        gauge("step_time_spread", res["spread"])
        gauge("straggler", -1 if res["straggler"] is None
              else res["straggler"])
        gauge("missing_heartbeats", len(res["missing"]))

    # --------------------------------------------------------------- health
    def health(self):
        """/healthz check: a host with a heartbeat gap is a pod problem."""
        if self.last is not None and self.last["missing"]:
            return False, (f"missing heartbeat from host(s) "
                           f"{self.last['missing']}")
        n = self.last["n_hosts"] if self.last is not None else 0
        return True, f"{n} host(s) reporting"

    # -------------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        """The ``hosts`` document on /statusz (what ds_tpu_top renders)."""
        if self.last is None:
            return {"n_hosts": 0, "rounds": 0}
        out = dict(self.last)
        out.pop("new_straggler", None)
        # JSON object keys are strings; normalize so consumers don't care
        out["hosts"] = {str(hid): h for hid, h in out["hosts"].items()}
        return out
