"""Fleet-wide distributed tracing — request trace context + aggregation.

PR 8 made serving a fleet; every instrument before this file was
per-process. A request that crosses three replicas (router admission →
prefill replica → KV handoff → decode replica, possibly replayed after a
failover) used to leave three disconnected span fragments and no answer
to "which stage ate the TTFT budget". This module is the cross-process
layer:

- ``TraceContext`` — the request-scoped identity minted by
  ``FleetRouter.submit`` (or lazily by a standalone scheduler): a fleet-
  unique ``trace_id``, the current span id (the live Request's id on its
  current replica), the replay lineage after failovers (the replayed
  attempt is a *child span* of the original attempt, never a new trace),
  the replicas visited, and an ordered list of **marks** — wall-clock
  waypoints stamped at every propagation point (router submit, scheduler
  enqueue, slot admission, first token, handoff serialize / transfer /
  insert, decode completion, finish). Marks are consecutive intervals,
  so the per-request critical path sums to the request's end-to-end time
  *by construction*.
- ``to_header()`` / ``from_header()`` — the JSON-able context that rides
  the ``KVHandoff`` frame header across a real interconnect (marks are
  ``perf_counter`` timestamps and stay process-local; identity, lineage,
  and hop history cross the wire).
- ``merge_chrome_traces`` — N replica chrome-trace slices into ONE
  Perfetto document with a stable pid lane per replica and explicit
  ``process_name`` / ``thread_name`` metadata events, fixing the
  co-resident-engine pid collision (every in-process replica used to
  land on ``jax.process_index()``'s lane and interleave).
- ``FleetAggregator`` — the router-side consumer: merged fleet timeline
  (in-process replicas partition the shared span ring by the ``replica``
  span arg; url replicas are fetched over ``/trace``), per-request
  critical-path windows exported as ``dstpu_fleet_path_*`` gauges and a
  router ``/statusz`` section, in-flight trace ids for flight-recorder
  bundles, and cross-replica postmortem correlation: bundles in the
  router's and every replica's bundle dir that share a trace id are
  merged into one document — the postmortem for a request, not for a
  process.
"""

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["TraceContext", "FleetAggregator", "merge_chrome_traces",
           "split_events_by_replica", "CRITICAL_PATH_STAGES"]

#: canonical stage order for critical-path reports (queue / route+probe /
#: prefill / handoff serialize+transfer+insert / decode / stream, plus
#: the failover re-enqueue gap when a replay happened)
CRITICAL_PATH_STAGES = ("route", "queue", "prefill_chunk", "prefill",
                        "handoff_serialize", "handoff_transfer",
                        "handoff_insert", "decode", "spec_verify", "stream",
                        "failover")

_MINT_LOCK = threading.Lock()
_MINT_SEQ = itertools.count()
_MINT_SALT = os.urandom(4).hex()


def _now_us() -> float:
    return time.perf_counter_ns() / 1e3


def _stage_of(prev: Optional[str], end: str) -> Optional[str]:
    """Stage bucket for the interval ENDING at mark ``end``. A few ends
    are disambiguated by what preceded them: ``finished`` directly after
    ``queued`` is a queue-expiry (timeout), not decode."""
    if end == "queued":
        return "route"
    if end == "admitted":
        return "queue"
    if end == "first_token":
        return "prefill"
    # chunked prefill marks once per chunk: admitted -> prefill_chunk and
    # chunk -> chunk intervals accumulate into the prefill_chunk stage
    # (the waiting BETWEEN chunks — interleaved decode ticks — included:
    # that wait is exactly the latency chunking trades for bounded TPOT);
    # the last chunk ends at first_token and buckets as plain prefill, so
    # stage sums still equal e2e exactly
    if end == "prefill_chunk":
        return "prefill_chunk"
    if end == "handoff_out":
        return "handoff_serialize"
    if end == "handoff_queued":
        return "handoff_transfer"
    if end == "handoff_inserted":
        return "handoff_insert"
    if end == "decode_done":
        return "decode"
    # speculative decode brackets the verify forward with a mark pair
    # every tick: prev -> spec_verify_start is draft + scheduling time
    # (the decode bucket), spec_verify_start -> spec_verify is the
    # verify forward itself — repeated pairs accumulate, so stage sums
    # still equal e2e exactly
    if end == "spec_verify_start":
        return "decode"
    if end == "spec_verify":
        return "spec_verify"
    if end == "requeued":
        return "failover"
    if end == "finished":
        if prev == "decode_done":
            return "stream"
        if prev in ("queued", "handoff_queued", "requeued", "submit"):
            return "queue"
        return "decode"
    return None


class TraceContext:
    """One request's identity and timeline across the fleet."""

    __slots__ = ("trace_id", "origin", "span_ids", "replays",
                 "replay_parent", "hops", "marks", "sampling", "tenant",
                 "weights_version", "cost")

    def __init__(self, trace_id: str, origin: str,
                 span_ids: Optional[List[int]] = None, replays: int = 0,
                 replay_parent: Optional[int] = None,
                 hops: Optional[List[str]] = None,
                 sampling: Optional[Dict[str, Any]] = None,
                 tenant: Optional[str] = None,
                 weights_version: Optional[int] = None):
        self.trace_id = trace_id
        self.origin = origin
        self.span_ids = list(span_ids or [])
        self.replays = int(replays)
        self.replay_parent = replay_parent
        self.hops = list(hops or [])
        self.marks: List[tuple] = []        # (label, t_us), process-local
        #: the stream's replay law ({temperature, top_k, top_p, seed}):
        #: a failover survivor replays the IDENTICAL sampled stream from
        #: these, so the delivered-position dedup stays exact — and a
        #: postmortem can name the seed a disputed stream ran under
        self.sampling = sampling
        #: the tenant this request bills to — stamped on every span the
        #: request touches and carried across handoffs and failovers, so
        #: ds_tpu_top and postmortem bundles can NAME the tenant that ate
        #: the TTFT budget instead of pointing at anonymous traffic
        self.tenant = tenant
        #: the weights_version of the replica that last ran this request
        #: (stamped at prefill/handoff time): a decode replica refuses a
        #: KV handoff whose version differs from its own — mixing KV
        #: from two models would be silent garbage, not a crash
        self.weights_version = weights_version
        #: the request's CostRecord (telemetry/costplane.py), attached
        #: lazily by the cost plane when enabled — riding the context is
        #: what makes cost attribution survive KV handoffs (frame
        #: header) and failover (the router's persistent context):
        #: survivor attempts accumulate into the SAME record
        self.cost = None

    # ------------------------------------------------------------- minting
    @classmethod
    def mint(cls, origin: str,
             tenant: Optional[str] = None) -> "TraceContext":
        """A fleet-unique context. The id mixes pid + a per-process random
        salt + a counter, so co-resident routers and separate hosts can
        mint concurrently without coordination."""
        with _MINT_LOCK:
            seq = next(_MINT_SEQ)
        return cls(trace_id=f"{os.getpid():x}-{_MINT_SALT}-{seq:x}",
                   origin=origin, tenant=tenant)

    # ---------------------------------------------------------- propagation
    @property
    def span_id(self) -> Optional[int]:
        """The live attempt's span id (its Request id on its replica)."""
        return self.span_ids[-1] if self.span_ids else None

    def bind_span(self, request_id: int):
        """A replica admitted this request under ``request_id`` — the
        id becomes the current span of the trace."""
        if not self.span_ids or self.span_ids[-1] != request_id:
            self.span_ids.append(int(request_id))

    def hop(self, replica: str):
        """Record a replica boundary crossing (dedup consecutive)."""
        if not self.hops or self.hops[-1] != replica:
            self.hops.append(replica)

    def replay(self):
        """The current attempt died (failover): the NEXT bound span is a
        child of the attempt that just failed — same trace, linked
        parent — never a fresh trace."""
        self.replays += 1
        self.replay_parent = self.span_id
        self.mark("requeued")

    def mark(self, label: str):
        self.marks.append((label, _now_us()))

    def span_args(self) -> Dict[str, Any]:
        """The args every span touching this request carries — what the
        aggregator (and a human in Perfetto) joins on."""
        out: Dict[str, Any] = {"trace_id": self.trace_id,
                               "origin": self.origin}
        if self.tenant:
            out["tenant"] = self.tenant
        if self.span_ids:
            out["span_id"] = self.span_ids[-1]
        if self.replays:
            out["attempt"] = self.replays
            out["replay_of"] = self.replay_parent
        return out

    # -------------------------------------------------------------- framing
    def to_header(self) -> Dict[str, Any]:
        """JSON-able identity for the KVHandoff frame header. Marks stay
        behind: they are ``perf_counter`` timestamps, meaningless in
        another process's clock domain."""
        out = {"trace_id": self.trace_id, "origin": self.origin,
               "span_ids": list(self.span_ids), "replays": self.replays,
               "replay_parent": self.replay_parent,
               "hops": list(self.hops),
               "sampling": self.sampling,
               "tenant": self.tenant,
               "weights_version": self.weights_version}
        if self.cost is not None:
            out["cost"] = self.cost.to_dict()
        return out

    @classmethod
    def from_header(cls, header: Dict[str, Any]) -> "TraceContext":
        ctx = cls(trace_id=str(header["trace_id"]),
                  origin=str(header.get("origin", "?")),
                  span_ids=header.get("span_ids"),
                  replays=header.get("replays", 0),
                  replay_parent=header.get("replay_parent"),
                  hops=header.get("hops"),
                  sampling=header.get("sampling"),
                  tenant=header.get("tenant"),
                  weights_version=header.get("weights_version"))
        if header.get("cost") is not None:
            from .costplane import CostRecord
            ctx.cost = CostRecord.from_dict(header["cost"])
        return ctx

    # -------------------------------------------------------- critical path
    def total_ms(self) -> float:
        """First mark to last mark — the trace-clock end-to-end time."""
        if len(self.marks) < 2:
            return 0.0
        return (self.marks[-1][1] - self.marks[0][1]) / 1e3

    def critical_path(self) -> Dict[str, float]:
        """Per-stage milliseconds. Stages are consecutive mark intervals,
        so ``sum(critical_path().values()) == total_ms()`` exactly (a
        replayed request accumulates its second pass into the same
        buckets, plus a ``failover`` stage for the re-enqueue gap)."""
        out: Dict[str, float] = {}
        prev_label: Optional[str] = None
        prev_t: Optional[float] = None
        for label, t in self.marks:
            if prev_t is not None:
                stage = _stage_of(prev_label, label)
                if stage is not None:
                    out[stage] = out.get(stage, 0.0) + (t - prev_t) / 1e3
                else:
                    out["other"] = out.get("other", 0.0) + (t - prev_t) / 1e3
            prev_label, prev_t = label, t
        return out


# --------------------------------------------------------------------------
# chrome-trace merging (the pid/tid collision fix)
# --------------------------------------------------------------------------

def split_events_by_replica(events: List[Dict[str, Any]],
                            default_lane: str = "router"
                            ) -> Dict[str, List[Dict[str, Any]]]:
    """Partition one process's trace events by the ``replica`` span arg.
    Co-resident replicas share the process-global span ring; the arg is
    the only thing that says whose lane an event belongs to. Events
    without one (router spans, training spans) go to ``default_lane``."""
    lanes: Dict[str, List[Dict[str, Any]]] = {}
    for ev in events:
        if ev.get("ph") == "M":
            continue                       # lane metadata is re-emitted
        rep = (ev.get("args") or {}).get("replica", default_lane)
        lanes.setdefault(str(rep), []).append(ev)
    return lanes


def merge_chrome_traces(slices: Dict[str, Dict[str, Any]],
                        labels: Optional[Dict[str, str]] = None
                        ) -> Dict[str, Any]:
    """N chrome-trace documents (one per lane) -> ONE Perfetto-loadable
    document with a stable pid per lane and explicit ``process_name`` /
    ``thread_name`` metadata, so merged views never interleave unrelated
    replicas on one process row. Lane order is deterministic: ``router``
    first, then sorted replica names."""
    labels = labels or {}
    order = sorted(slices, key=lambda n: (n != "router", n))
    events: List[Dict[str, Any]] = []
    dropped = 0
    for pid, lane in enumerate(order):
        doc = slices[lane] or {}
        lane_events = [ev for ev in doc.get("traceEvents", [])
                       if ev.get("ph") != "M"]
        dropped += int((doc.get("otherData") or {}).get("dropped_spans", 0))
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": labels.get(lane, lane)}})
        events.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"sort_index": pid}})
        tids = []
        for ev in lane_events:
            ev = dict(ev)
            ev["pid"] = pid
            tid = ev.get("tid", 0)
            if tid not in tids:
                tids.append(tid)
            events.append(ev)
        for j, tid in enumerate(tids):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid,
                           "args": {"name": f"{lane}/t{j}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"lanes": {lane: i for i, lane
                                    in enumerate(order)},
                          "dropped_spans": dropped}}


# --------------------------------------------------------------------------
# router-side aggregation
# --------------------------------------------------------------------------

class FleetAggregator:
    """Merged fleet timeline + SLO critical-path attribution, owned by
    the router. Built only when ``fleet.disttrace`` is on."""

    def __init__(self, router, tracer=None, window: int = 512):
        self.router = router
        self.tracer = tracer if tracer is not None else router.tracer
        self._stage_windows: Dict[str, deque] = {}
        self._e2e_window: deque = deque(maxlen=window)
        self._window = int(window)
        self.observed = 0

    # --------------------------------------------------------- merged trace
    def merged_trace(self, last_ms: Optional[float] = None
                     ) -> Dict[str, Any]:
        """ONE Perfetto document for the whole fleet. In-process replicas
        share the router's span ring and partition by the ``replica``
        span arg; url-only replicas are polled over their ``/trace``
        endpoint (best effort — an unreachable replica simply contributes
        no lane)."""
        from .export import chrome_trace_slice
        doc = chrome_trace_slice(self.tracer, last_ms=last_ms)
        slices = {lane: {"traceEvents": evs}
                  for lane, evs in split_events_by_replica(
                      doc["traceEvents"]).items()}
        labels = {"router": "fleet router"}
        for name, handle in self.router.replicas.items():
            labels[name] = f"replica {name} [{handle.role}]"
            if name in slices or handle.engine is not None:
                continue
            remote = self._fetch_remote_trace(handle, last_ms)
            if remote is not None:
                slices[name] = remote
        merged = merge_chrome_traces(slices, labels=labels)
        merged["otherData"]["dropped_spans"] = doc.get(
            "otherData", {}).get("dropped_spans", 0)
        return merged

    def _fetch_remote_trace(self, handle, last_ms):
        if not getattr(handle, "url", None):
            return None
        import urllib.request
        url = handle.url + "/trace"
        if last_ms is not None:
            url += f"?last_ms={float(last_ms):g}"
        try:
            with urllib.request.urlopen(
                    url, timeout=float(handle._p("probe_timeout_s",
                                                 1.0))) as r:
                return json.load(r)
        except Exception:
            return None

    # -------------------------------------------------------- critical path
    def observe(self, freq):
        """Fold one COMPLETED fleet request's critical path into the
        sliding stage windows (the router calls this exactly once per
        request, at harvest time). Every known stage window gets a sample
        per request (0.0 when the request skipped the stage), so the
        windows stay ALIGNED: the sum of stage means equals the mean e2e
        by linearity — the decomposition check is not vacuous."""
        ctx = getattr(freq, "trace", None)
        if ctx is None or len(ctx.marks) < 2:
            return
        path = ctx.critical_path()
        for stage in set(CRITICAL_PATH_STAGES) | set(path) | \
                set(self._stage_windows):
            self._stage_windows.setdefault(
                stage, deque(maxlen=self._window)).append(
                    path.get(stage, 0.0))
        self._e2e_window.append(ctx.total_ms())
        self.observed += 1

    @staticmethod
    def _p50(window) -> float:
        vals = sorted(window)
        return vals[min(len(vals) - 1, len(vals) // 2)] if vals else 0.0

    def critical_path_summary(self) -> Dict[str, Any]:
        """Per-stage p50/mean over the recent window, in canonical stage
        order. ``stage_sum_ms_mean`` (sum of aligned stage means) matches
        ``e2e_ms_mean`` by construction — the sum-to-e2e contract a
        consumer can verify. Stage *p50s* are reported per stage and do
        NOT sum to the e2e p50 under skew (quantiles are not linear);
        per-request decomposition is always exact."""
        stages: Dict[str, Any] = {}
        names = [s for s in CRITICAL_PATH_STAGES
                 if s in self._stage_windows]
        names += [s for s in self._stage_windows if s not in names]
        for name in names:
            w = self._stage_windows[name]
            if w and max(w) <= 0:
                continue                  # stage never exercised
            stages[name] = {
                "p50_ms": round(self._p50(w), 3),
                "mean_ms": round(sum(w) / len(w), 3) if w else 0.0,
                "n": len(w),
            }
        e2e = self._e2e_window
        return {"requests": self.observed,
                "e2e_ms_p50": round(self._p50(e2e), 3),
                "e2e_ms_mean": round(sum(e2e) / len(e2e), 3)
                if e2e else 0.0,
                "stage_sum_ms_mean": round(
                    sum(s["mean_ms"] for s in stages.values()), 3),
                "stages": stages}

    def export_gauges(self):
        """Mirror the stage p50s into ``fleet/path_*`` gauges — the
        dedicated ``dstpu_fleet_path_<stage>_ms_p50`` Prometheus series.
        Owned by the router's FleetMetrics so shutdown retracts them."""
        owner = self.router.metrics
        for stage, w in self._stage_windows.items():
            self.tracer.set_counter(f"fleet/path_{stage}_ms_p50",
                                    round(self._p50(w), 3), owner=owner)
        if self._e2e_window:
            self.tracer.set_counter("fleet/path_e2e_ms_p50",
                                    round(self._p50(self._e2e_window), 3),
                                    owner=owner)

    def statusz_section(self) -> Dict[str, Any]:
        """The router /statusz ``critical_path`` section: one flat row
        per stage (tables render flat dicts)."""
        summary = self.critical_path_summary()
        out: Dict[str, Any] = {
            "requests": summary["requests"],
            "e2e_ms_p50": summary["e2e_ms_p50"],
            "e2e_ms_mean": summary["e2e_ms_mean"],
            "stage_sum_ms_mean": summary["stage_sum_ms_mean"],
        }
        for stage, rec in summary["stages"].items():
            out[f"{stage}_ms_p50"] = rec["p50_ms"]
        return out

    # ----------------------------------------------------------- recorders
    def in_flight_trace_ids(self) -> List[str]:
        """Trace ids with work still moving through the fleet — what a
        flight-recorder bundle embeds so postmortems correlate."""
        ids = []
        for freq in self.router._fleet_requests.values():
            ctx = getattr(freq, "trace", None)
            if ctx is not None and not freq.done:
                ids.append(ctx.trace_id)
        return sorted(set(ids))

    def _bundle_dirs(self) -> Dict[str, str]:
        dirs: Dict[str, str] = {}
        rec = getattr(self.router, "recorder", None)
        if rec is not None:
            dirs["router"] = rec.dir
        for name, handle in self.router.replicas.items():
            eng_rec = getattr(handle.engine, "_recorder", None)
            if eng_rec is not None:
                dirs[name] = eng_rec.dir
        return dirs

    def correlate_bundles(self) -> Dict[str, List[Dict[str, Any]]]:
        """Scan the router's and every replica's bundle dirs and group
        bundles by the trace ids they embedded: trace_id -> [bundle ref].
        A trace that appears in bundles from two different members is the
        cross-replica incident this module exists to stitch together."""
        by_trace: Dict[str, List[Dict[str, Any]]] = {}
        for member, bdir in self._bundle_dirs().items():
            try:
                names = sorted(os.listdir(bdir))
            except OSError:
                continue
            for name in names:
                if not (name.startswith("bundle-") and
                        name.endswith(".json")):
                    continue
                path = os.path.join(bdir, name)
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    continue
                ref = {"member": member, "file": name, "path": path,
                       "kind": doc.get("kind"), "detail": doc.get("detail"),
                       "time": doc.get("time")}
                for tid in doc.get("in_flight_traces", []) or []:
                    by_trace.setdefault(str(tid), []).append(ref)
        return by_trace

    def cross_replica_postmortem(self, trace_ids: Optional[List[str]]
                                 = None, write: bool = True
                                 ) -> Optional[Dict[str, Any]]:
        """One document correlating same-trace bundles across every
        member's bundle dir. ``trace_ids=None`` keeps traces that appear
        in bundles from >= 2 distinct members (plus everything in the
        router's newest bundle). Returns None when there is nothing to
        correlate; otherwise writes ``crossrep-NNNN.json`` next to the
        router's bundles (when ``write``) and returns the document."""
        rec = getattr(self.router, "recorder", None)
        by_trace = self.correlate_bundles()
        if trace_ids is None:
            keep = {tid for tid, refs in by_trace.items()
                    if len({r["member"] for r in refs}) >= 2}
            last = getattr(rec, "last_fire", None) if rec is not None \
                else None
            if last is not None:
                for tid, refs in by_trace.items():
                    if any(r["member"] == "router" and
                           r["file"] == os.path.basename(last["path"])
                           for r in refs):
                        keep.add(tid)
            trace_ids = sorted(keep)
        traces = {tid: by_trace.get(tid, []) for tid in trace_ids
                  if by_trace.get(tid)}
        if not traces:
            return None
        doc = {"kind": "cross_replica_postmortem",
               "time": time.time(),
               "members": sorted(self._bundle_dirs()),
               "traces": traces}
        if write and rec is not None:
            try:
                os.makedirs(rec.dir, exist_ok=True)
                # next sequence = max existing + 1, NOT count + 1: once
                # retention prunes old crossrep docs, a count-derived
                # name would collide with (and silently overwrite) a
                # surviving newer one
                seqs = [0]
                for n in os.listdir(rec.dir):
                    if n.startswith("crossrep-") and n.endswith(".json"):
                        try:
                            seqs.append(int(n[len("crossrep-"):-len(
                                ".json")]))
                        except ValueError:
                            pass
                seq = max(seqs) + 1
                path = os.path.join(rec.dir, f"crossrep-{seq:04d}.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(doc, f, default=str)
                os.replace(tmp, path)
                doc["path"] = path
                rec._retain()          # crossrep docs share keep-last-N
            except OSError:
                pass
        return doc
