"""Perf plane — step/tick anatomy, roofline attribution, regression gate.

The compile plane says *what* compiled and *what it holds* (HBM roles,
collective counts); the goodput ledger says *how much* wall-clock was
productive; this module says **where a compiled program spends its
time**: every step/tick decomposes into named buckets — ``attn``,
``mlp`` (weight streaming rides the MLP/attn matmuls on a dense model),
``kv_read`` / ``kv_write`` (the KV-pool traffic ROADMAP item 2's paged
pool must beat), ``sample`` / ``verify`` (the decode tail), ``embed`` /
``head``, ``moe``, one ``coll_<op>`` bucket per collective kind, and
``other`` — with **two backends**:

- the **static path** (:func:`anatomy_from_hlo`): a stdlib-only per-op
  walk of the compiled HLO text. Each instruction is classified by the
  ``jax.named_scope`` tokens XLA preserves in its ``op_name`` metadata
  (the same scopes the flops profiler reads from jaxprs), priced under
  an alpha-beta device model (compute = max(flops/peak, bytes/hbm_bw);
  collectives = bytes/link_bw + latency, discounted by the module's
  dependency-level ``static_overlap_fraction`` — so *de-overlapping a
  schedule inflates the exposed ``coll_*`` ms even on CPU*). Runs in
  tier-1 with no backend.
- the **measured path** (:func:`measured_anatomy_from_trace`): the same
  bucket taxonomy over a ``jax.profiler`` device trace ("XLA Ops" lane
  durations), plus the ``host_gap`` bucket (wall window minus device
  busy) the static path cannot see.

:func:`reconcile_anatomy` joins the two into a roofline report: per
bucket arithmetic intensity, memory-bound flag against the device
ridge, and predicted-vs-measured skew — the number STANDING CHIP DEBT
says to calibrate on hardware (ROADMAP item 5).

Sums are exact **by construction**: a program's ``total_ms`` is
*defined* as the float sum of its bucket ms values in sorted bucket
order, so the decomposition can never drift from its total (tested ±0
in tests/unit/test_perfplane.py).

The runtime half (:class:`PerfPlane`) hangs off the compile ledger:
every compile/recompile event with HLO text gets its anatomy attached,
``dstpu_anat_*`` gauges updated (owner lifecycle), a ``/statusz``
"anatomy" section, and — when a *recompile* shifts any bucket beyond
the configured band — an edge-triggered ``perf_regression`` flight
bundle, the perf twin of ``overlap_drop``.

The offline half is the regression gate: ``benchmarks/anatomy.py``
emits ``anatomy.json`` and ``bin/ds_tpu_perfdiff`` diffs it against the
checked-in baseline via :func:`diff_anatomy` (per-bucket noise bands,
hard gates, embedded invariants). Everything the CLI needs is importable
with zero third-party deps — ``hlo_cost.py`` is pulled in by file path
when the package is not importable, the ``ds_tpu_soakdiff`` pattern.
"""

import glob
import gzip
import json
import math
import os
import re
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

try:
    from .hlo_cost import (COLLECTIVES, DTYPE_BYTES, _INSTR_RE, _PAT_SHAPE,
                           _parse_computations, collect_schedule_overlap)
except ImportError:      # file-path load (bin/ds_tpu_perfdiff, stdlib-only)
    import importlib.util as _ilu
    _spec = _ilu.spec_from_file_location(
        "_dstpu_hlo_cost",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "hlo_cost.py"))
    _hc = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_hc)
    COLLECTIVES, DTYPE_BYTES = _hc.COLLECTIVES, _hc.DTYPE_BYTES
    _INSTR_RE, _PAT_SHAPE = _hc._INSTR_RE, _hc._PAT_SHAPE
    _parse_computations = _hc._parse_computations
    collect_schedule_overlap = _hc.collect_schedule_overlap

__all__ = ["ANATOMY_KIND", "PHASE_BUCKETS", "DEVICE_MODEL",
           "anatomy_from_hlo", "measured_anatomy_from_trace",
           "reconcile_anatomy", "diff_anatomy", "format_diff",
           "check_anatomy_invariants", "write_anatomy", "PerfPlane"]

#: document kind pinned into anatomy.json (ds_tpu_perfdiff refuses to
#: baseline anything else)
ANATOMY_KIND = "dstpu_anatomy"

#: named-scope buckets in PRECEDENCE order: the first token found in an
#: op's scope stack wins, so ``.../attn/kv_write/...`` classifies as
#: kv_write (the inner, more specific scope), not attn. ``moe`` outranks
#: mlp because expert blocks nest a gate inside the mlp scope.
PHASE_BUCKETS = ("kv_write", "kv_read", "sample", "verify", "moe", "attn",
                 "mlp", "embed", "head")

#: token-boundary matchers (the flops profiler's `_PHASE_RE` trick:
#: "attn" must not match inside "attntmp")
_PHASE_RES = {p: re.compile(rf"(?<![A-Za-z0-9_]){p}(?![A-Za-z0-9_])")
              for p in PHASE_BUCKETS}

#: alpha-beta device model defaults — the same constants the PR-15
#: schedule cost model ships (autotuning/cost_model.ScheduleCostModel)
#: plus an HBM bandwidth term for the roofline ridge. All overridable
#: via ``perf_plane.device_model`` (and re-calibrated on chip with
#: ``calibrate_cost_model``: STANDING CHIP DEBT, ROADMAP item 5).
DEVICE_MODEL = {
    "peak_flops": 100e12,        # FLOP/s
    "hbm_bandwidth": 800e9,      # bytes/s
    "link_bandwidth": 40e9,      # bytes/s per link (collectives)
    "op_latency_s": 2e-6,        # per-collective dispatch latency
    "overlap_efficiency": 0.9,   # fraction of overlappable wire time the
                                 # latency-hiding executor actually hides
}

#: bookkeeping ops that move no HBM bytes of their own (or are priced
#: elsewhere): parameters/constants/tuple plumbing are free; ``while``
#: and ``conditional`` call-sites are priced through their bodies;
#: ``*-done``/``async-done`` halves carry the same payload their start
#: already counted.
_SKIP_OPS = frozenset((
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "while", "conditional", "call", "after-all", "partition-id",
    "replica-id", "async-done", "async-update", "copy-start", "copy-done",
))

_ENTRY_RE = re.compile(r"^ENTRY\s+(%?[\w.\-]+)", re.M)
#: XLA annotates wide tuples with /*index=N*/ comments whose '=' breaks
#: _INSTR_RE's tuple-result alternative — strip before matching
_COMMENT_RE = re.compile(r"/\*.*?\*/")
_RESULT_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\([^=]*?\)|\S+)\s+[\w\-]+\(")
_CALLS_RE = re.compile(r"calls=(%?[\w.\-]+)")
_BODY_RE = re.compile(r"body=(%?[\w.\-]+)")
_COND_RE = re.compile(r"condition=(%?[\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_TRUEFALSE_RE = re.compile(r"(?:true|false)_computation=(%?[\w.\-]+)")
_TO_APPLY_RE = re.compile(r"to_apply=(%?[\w.\-]+)")
_TRIP_RE = re.compile(r'"known_trip_count":\{"n":"(\d+)"\}')
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_LHS_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")


def _shape_numel_bytes(dtype: str, dims: str) -> Tuple[int, int]:
    numel = math.prod([int(d) for d in dims.split(",") if d] or [1])
    return numel, numel * DTYPE_BYTES.get(dtype, 4)


def _line_bytes(text: str) -> int:
    return sum(_shape_numel_bytes(m.group(1), m.group(2))[1]
               for m in _PAT_SHAPE.finditer(text))


def _classify_scope(op_name: str) -> Optional[str]:
    """Highest-precedence phase token in a metadata scope stack."""
    for phase in PHASE_BUCKETS:
        if _PHASE_RES[phase].search(op_name):
            return phase
    return None


def _collective_base(op: str) -> Optional[str]:
    """'all-gather-start' / 'all-gather.3' / 'all-gather' -> 'all-gather'
    (None for non-collectives)."""
    for c in COLLECTIVES:
        if op == c or op.startswith(f"{c}-start") or \
                op.startswith(f"{c}."):
            return c
    if op.startswith("async-start"):
        return None     # handled by the caller via the line text
    return None


def _dot_flops(line: str, operands: str, result_numel: int) -> float:
    """2 * numel(result) * prod(lhs contracting dim sizes) — the shared
    contraction depth parsed from the printed ``lhs_contracting_dims``
    against the first (lhs) operand shape."""
    m = _LHS_CONTRACT_RE.search(line)
    lhs = _PAT_SHAPE.search(operands)
    if not m or not lhs:
        return 2.0 * result_numel
    dims = [int(d) for d in lhs.group(2).split(",") if d]
    depth = 1
    for idx in (int(i) for i in m.group(1).split(",") if i):
        if 0 <= idx < len(dims):
            depth *= dims[idx]
    return 2.0 * result_numel * depth


def _computation_multipliers(hlo_text: str,
                             comps: Dict[str, list]) -> Dict[str, float]:
    """Walk the call graph from ENTRY assigning each computation an
    execution multiplier: while bodies/conditions inherit the parent's
    multiplier times the printed ``known_trip_count`` (XLA prints it for
    rolled ``lax.scan`` loops; 1 when absent), conditional branches and
    ``call`` targets inherit it unchanged, fusion bodies stay at 0 —
    they are priced at their call site, where operand/result shapes
    approximate the fusion's real HBM traffic."""
    entry_m = _ENTRY_RE.search(hlo_text)
    entry = entry_m.group(1).lstrip("%") if entry_m else None
    mult: Dict[str, float] = {}
    names = {name.lstrip("%"): name for name in comps}
    if entry is None or entry not in names:
        # headerless fragment: treat every computation as entry-level
        return {name: 1.0 for name in comps}
    frontier = [(entry, 1.0)]
    while frontier:
        cname, m = frontier.pop()
        if mult.get(cname, 0.0) >= m:
            continue
        mult[cname] = m
        for line in comps.get(names.get(cname, cname), ()):
            if "/*" in line:
                line = _COMMENT_RE.sub("", line)
            bm = _BODY_RE.search(line)
            if bm:
                trip = _TRIP_RE.search(line)
                n = float(trip.group(1)) if trip else 1.0
                frontier.append((bm.group(1).lstrip("%"), m * n))
                cm = _COND_RE.search(line)
                if cm:
                    frontier.append((cm.group(1).lstrip("%"), m * n))
                continue
            br = _BRANCHES_RE.search(line)
            if br:
                for tok in re.findall(r"%?[\w.\-]+", br.group(1)):
                    frontier.append((tok.lstrip("%"), m))
                continue
            for tm in _TRUEFALSE_RE.finditer(line):
                frontier.append((tm.group(1).lstrip("%"), m))
            op_m = _INSTR_RE.match(line)
            if op_m and op_m.group(3) == "call":
                ta = _TO_APPLY_RE.search(line)
                if ta:
                    frontier.append((ta.group(1).lstrip("%"), m))
    return {name: mult.get(name.lstrip("%"), 0.0) for name in comps}


def _fusion_info(comps: Dict[str, list]) -> Dict[str, Dict[str, Any]]:
    """Per fusion body: the highest-precedence phase among its fused
    instructions' scope metadata, and the dot flops buried inside it
    (fusion call-site shapes carry the bytes; the body carries the
    math)."""
    out: Dict[str, Dict[str, Any]] = {}
    for cname, block in comps.items():
        best: Optional[str] = None
        flops = 0.0
        for line in block:
            if "/*" in line:
                line = _COMMENT_RE.sub("", line)
            om = _OP_NAME_RE.search(line)
            if om:
                phase = _classify_scope(om.group(1))
                if phase is not None and (
                        best is None or PHASE_BUCKETS.index(phase) <
                        PHASE_BUCKETS.index(best)):
                    best = phase
            im = _INSTR_RE.match(line)
            if im and im.group(3) == "dot":
                rm = _RESULT_RE.match(line)
                numel = 0
                if rm:
                    numel = sum(
                        _shape_numel_bytes(s.group(1), s.group(2))[0]
                        for s in _PAT_SHAPE.finditer(rm.group(1)))
                flops += _dot_flops(line, im.group(4), numel)
        out[cname.lstrip("%")] = {"phase": best, "dot_flops": flops}
    return out


def anatomy_from_hlo(hlo_text: str,
                     device_model: Optional[Dict[str, float]] = None
                     ) -> Dict[str, Any]:
    """Static anatomy of one compiled HLO module.

    Returns ``{"buckets": {name: {ms, flops, bytes, ops}}, "total_ms",
    "flops", "bytes", "static_overlap_fraction",
    "memory_bound_fraction", "device_model"}``. ``total_ms`` is the
    float sum of bucket ms in sorted bucket order — the decomposition
    sums to it exactly, by construction. ``host_gap`` is present at 0.0
    (only the measured path can see host time).
    """
    dm = dict(DEVICE_MODEL)
    dm.update(device_model or {})
    comps = _parse_computations(hlo_text)
    mults = _computation_multipliers(hlo_text, comps)
    fusions = _fusion_info(comps)
    overlap = collect_schedule_overlap(hlo_text)
    static_frac = float(overlap.get("static_overlap_fraction", 0.0))
    # exposed fraction of collective wire time after the latency-hiding
    # executor hides what the schedule makes hideable — the knob the
    # bucketed ZeRO exchange raises and a de-overlap regression drops
    exposed = 1.0 - dm["overlap_efficiency"] * static_frac

    buckets: Dict[str, Dict[str, float]] = {}

    def acc(name: str, ms: float, flops: float, nbytes: float,
            membound: bool):
        b = buckets.setdefault(name, {"ms": 0.0, "flops": 0.0,
                                      "bytes": 0.0, "ops": 0,
                                      "membound_ms": 0.0})
        b["ms"] += ms
        b["flops"] += flops
        b["bytes"] += nbytes
        b["ops"] += 1
        if membound:
            b["membound_ms"] += ms

    for cname, block in comps.items():
        mult = mults.get(cname, 0.0)
        if mult <= 0.0:
            continue
        for line in block:
            if "/*" in line:
                line = _COMMENT_RE.sub("", line)
            im = _INSTR_RE.match(line)
            if not im:
                continue
            op, operands = im.group(3), im.group(4)
            if op in _SKIP_OPS or op.split(".")[0] in _SKIP_OPS:
                continue
            rm = _RESULT_RE.match(line)
            result_text = rm.group(1) if rm else ""
            result_numel = sum(
                _shape_numel_bytes(s.group(1), s.group(2))[0]
                for s in _PAT_SHAPE.finditer(result_text))
            result_bytes = _line_bytes(result_text)
            operand_bytes = _line_bytes(operands)
            coll = _collective_base(op)
            if coll is None and op.startswith("async-start"):
                for c in COLLECTIVES:
                    if re.search(rf"\b{c}\b", line):
                        coll = c
                        break
            if coll is not None:
                if op.endswith("-done") or ".done" in op:
                    continue
                wire = max(result_bytes, operand_bytes)
                raw_ms = (wire / dm["link_bandwidth"] +
                          dm["op_latency_s"]) * 1e3
                acc(f"coll_{coll.replace('-', '_')}",
                    raw_ms * exposed * mult, 0.0, float(wire) * mult,
                    True)
                continue
            if op == "fusion" or op.startswith("fusion."):
                cm = _CALLS_RE.search(line)
                info = fusions.get(cm.group(1).lstrip("%"), {}) if cm \
                    else {}
                phase = info.get("phase")
                flops = float(info.get("dot_flops") or result_numel)
                if phase is None:
                    om = _OP_NAME_RE.search(line)
                    phase = _classify_scope(om.group(1)) if om else None
            else:
                om = _OP_NAME_RE.search(line)
                phase = _classify_scope(om.group(1)) if om else None
                if op == "dot":
                    flops = _dot_flops(line, operands, result_numel)
                elif op.startswith("reduce"):
                    flops = float(
                        sum(_shape_numel_bytes(s.group(1),
                                               s.group(2))[0]
                            for s in _PAT_SHAPE.finditer(operands)))
                else:
                    flops = float(result_numel)
            nbytes = float(operand_bytes + result_bytes)
            compute_ms = flops / dm["peak_flops"] * 1e3
            mem_ms = nbytes / dm["hbm_bandwidth"] * 1e3
            acc(phase or "other", max(compute_ms, mem_ms) * mult,
                flops * mult, nbytes * mult, mem_ms >= compute_ms)

    buckets.setdefault("host_gap", {"ms": 0.0, "flops": 0.0, "bytes": 0.0,
                                    "ops": 0, "membound_ms": 0.0})
    for b in buckets.values():
        b["ms"] = float(b["ms"])
        b["flops"] = float(b["flops"])
        b["bytes"] = float(b["bytes"])
    # THE sum-by-construction contract: total is DEFINED as the sorted
    # bucket sum, so `sum(buckets) == total` holds to the last ulp
    total_ms = float(sum(buckets[name]["ms"] for name in sorted(buckets)))
    membound = float(sum(b["membound_ms"] for b in buckets.values()))
    for b in buckets.values():
        del b["membound_ms"]
    return {
        "buckets": buckets,
        "total_ms": total_ms,
        "flops": float(sum(b["flops"] for b in buckets.values())),
        "bytes": float(sum(b["bytes"] for b in buckets.values())),
        "static_overlap_fraction": static_frac,
        "memory_bound_fraction":
            round(membound / total_ms, 6) if total_ms > 0 else 0.0,
        "device_model": dm,
    }


# ---------------------------------------------------------------------------
# measured path (jax.profiler device traces)
# ---------------------------------------------------------------------------

def measured_anatomy_from_trace(trace_dir: str) -> Optional[Dict[str, Any]]:
    """Bucket the device time of a ``jax.profiler`` trace directory with
    the SAME taxonomy as the static path, plus ``host_gap`` = wall
    window minus device-busy time. Returns None when no trace files are
    found. Multi-phase events (a fusion whose name carries two scopes)
    go to the highest-precedence phase — consistent with the static
    fusion rule."""
    files = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not files:
        return None
    buckets: Dict[str, float] = {}
    t_min, t_max, busy = None, None, 0.0
    for path in sorted(files):
        with gzip.open(path, "rt") as f:
            doc = json.load(f)
        events = doc.get("traceEvents", [])
        xla_tids = set()
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "thread_name" and \
                    "XLA Ops" in str((e.get("args") or {}).get("name", "")):
                xla_tids.add((e.get("pid"), e.get("tid")))
        for e in events:
            if e.get("ph") != "X" or \
                    (e.get("pid"), e.get("tid")) not in xla_tids:
                continue
            dur = float(e.get("dur", 0.0))
            ts = float(e.get("ts", 0.0))
            t_min = ts if t_min is None else min(t_min, ts)
            t_max = ts + dur if t_max is None else max(t_max, ts + dur)
            busy += dur
            text = str(e.get("name", "")) + " " + " ".join(
                str(v) for v in (e.get("args") or {}).values())
            coll = next((c for c in COLLECTIVES if c in text), None)
            if coll is not None:
                name = f"coll_{coll.replace('-', '_')}"
            else:
                name = _classify_scope(text) or "other"
            buckets[name] = buckets.get(name, 0.0) + dur
    wall = (t_max - t_min) if t_min is not None else 0.0
    out = {name: round(us / 1e3, 6) for name, us in buckets.items()}
    out["host_gap"] = round(max(0.0, wall - busy) / 1e3, 6)
    total = float(sum(out[name] for name in sorted(out)))
    return {"buckets_ms": out, "total_ms": total,
            "wall_ms": round(wall / 1e3, 6)}


def reconcile_anatomy(static: Dict[str, Any],
                      measured: Optional[Dict[str, Any]] = None
                      ) -> List[Dict[str, Any]]:
    """The roofline report: one row per bucket with arithmetic
    intensity (flops/byte), the memory-bound verdict against the device
    ridge (peak_flops / hbm_bandwidth), predicted ms, and — when a
    measured anatomy is supplied — measured ms and the
    predicted/measured skew the chip calibration pass pins down."""
    dm = static.get("device_model", DEVICE_MODEL)
    ridge = dm["peak_flops"] / dm["hbm_bandwidth"]
    meas = (measured or {}).get("buckets_ms", {})
    rows = []
    for name in sorted(static.get("buckets", {})):
        b = static["buckets"][name]
        intensity = (b["flops"] / b["bytes"]) if b["bytes"] else 0.0
        row = {
            "bucket": name,
            "flops": b["flops"],
            "bytes": b["bytes"],
            "arithmetic_intensity": round(intensity, 4),
            "memory_bound": intensity < ridge,
            "predicted_ms": round(b["ms"], 6),
        }
        if measured is not None:
            m_ms = float(meas.get(name, 0.0))
            row["measured_ms"] = m_ms
            row["skew"] = round(b["ms"] / m_ms, 4) if m_ms > 0 else None
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# the regression gate (stdlib-pure: bin/ds_tpu_perfdiff loads this file)
# ---------------------------------------------------------------------------

#: per-bucket noise bands. Static predictions are deterministic on an
#: unchanged tree, so the bands only absorb benign drift (an XLA fusion
#: decision moving ops between buckets) — a real regression (a
#: de-overlapped collective, bloated decode bytes) blows well past
#: them. Floors keep sub-ulp buckets from tripping ratio math.
DIFF_TOLERANCES = {
    "ms_ratio": 1.25,        # per-bucket ms <= 1.25x baseline
    "ms_floor": 0.01,        # ... ignoring buckets under 0.01 ms (the
                             # tiny-size pin keeps collective buckets in
                             # the tens of microseconds — the floor only
                             # mutes sub-noise epilogue buckets)
    "bytes_ratio": 1.10,     # per-bucket bytes <= 1.10x baseline
    "bytes_floor": 64 << 10,  # ... ignoring buckets under 64 KiB
    "total_ratio": 1.15,     # program total_ms <= 1.15x baseline
    "membound_band": 0.15,   # |memory_bound_fraction delta| <= 0.15
}


def check_anatomy_invariants(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Fold-time invariants embedded in every anatomy.json: each
    program's bucket decomposition re-sums to its recorded total
    EXACTLY (the by-construction contract — any drift means the doc was
    hand-edited or the writer broke), and the decode KV-scaling
    evidence holds when both decode flavors are present."""
    out: Dict[str, Any] = {}
    bad = []
    for name, prog in sorted((doc.get("programs") or {}).items()):
        buckets = prog.get("buckets") or {}
        resum = float(sum(buckets[b]["ms"] for b in sorted(buckets)))
        if resum != float(prog.get("total_ms", -1.0)):
            bad.append(f"{name}: sum(buckets)={resum!r} != "
                       f"total_ms={prog.get('total_ms')!r}")
    out["sum_to_total"] = {"ok": not bad, "detail": "; ".join(bad) or
                           "every program re-sums exactly"}
    d1 = (doc.get("programs") or {}).get("decode_tick")
    d2 = (doc.get("programs") or {}).get("decode_tick_x2")
    if d1 and d2:
        b1 = float((d1.get("extras") or {}).get("kv_read_bytes_per_tick",
                                                0.0))
        b2 = float((d2.get("extras") or {}).get("kv_read_bytes_per_tick",
                                                0.0))
        ratio = (b2 / b1) if b1 > 0 else 0.0
        ok = 1.8 <= ratio <= 2.2
        out["kv_read_scales_with_max_len"] = {
            "ok": ok, "ratio": round(ratio, 4),
            "detail": f"dense-pool KV read bytes at 2x max_len: "
                      f"{ratio:.3f}x (expect ~2x — the number the paged "
                      f"pool must beat, ROADMAP item 2)"}
    return out


def diff_anatomy(base: Dict[str, Any], cand: Dict[str, Any],
                 tolerances: Optional[Dict[str, float]] = None
                 ) -> Tuple[List[Dict[str, Any]], bool]:
    """Compare a candidate anatomy.json against a baseline. Returns
    ``(rows, ok)``. Hard gates first: candidate kind, candidate's own
    embedded invariants (re-checked here — a doc whose buckets don't
    re-sum cannot pass), no baseline program missing from the
    candidate. Then per-program noise-banded comparisons that FAIL BY
    BUCKET NAME — the line a future PR reads when it silently
    de-overlaps a collective or bloats decode bytes."""
    tol = dict(DIFF_TOLERANCES)
    tol.update(tolerances or {})
    rows: List[Dict[str, Any]] = []

    def row(metric, b, c, t, ok, note=""):
        rows.append({"metric": metric, "baseline": b, "candidate": c,
                     "tolerance": t, "ok": bool(ok), "note": note})

    if cand.get("kind") != ANATOMY_KIND:
        row("kind", base.get("kind"), cand.get("kind"), ANATOMY_KIND,
            False, "candidate is not an anatomy doc")
        return rows, False
    for name, inv in sorted(check_anatomy_invariants(cand).items()):
        row(f"invariant:{name}", True, inv["ok"], "must hold", inv["ok"],
            "" if inv["ok"] else str(inv.get("detail")))

    base_progs = base.get("programs") or {}
    cand_progs = cand.get("programs") or {}
    for pname in sorted(base_progs):
        bp, cp = base_progs[pname], cand_progs.get(pname)
        if cp is None:
            row(f"{pname}", "present", None, "program must exist", False,
                "missing in candidate")
            continue
        bb = bp.get("buckets") or {}
        cb = cp.get("buckets") or {}
        for bucket in sorted(set(bb) | set(cb)):
            b_ms = float((bb.get(bucket) or {}).get("ms", 0.0))
            c_ms = float((cb.get(bucket) or {}).get("ms", 0.0))
            if max(b_ms, c_ms) < tol["ms_floor"]:
                continue                       # noise floor: skip row
            ok = c_ms <= max(b_ms * tol["ms_ratio"], tol["ms_floor"])
            row(f"{pname}.{bucket}.ms", round(b_ms, 4), round(c_ms, 4),
                f"<= {tol['ms_ratio']:g}x base", ok,
                "" if ok else "bucket regressed")
            b_by = float((bb.get(bucket) or {}).get("bytes", 0.0))
            c_by = float((cb.get(bucket) or {}).get("bytes", 0.0))
            if max(b_by, c_by) >= tol["bytes_floor"]:
                ok_b = c_by <= max(b_by * tol["bytes_ratio"],
                                   tol["bytes_floor"])
                row(f"{pname}.{bucket}.bytes", b_by, c_by,
                    f"<= {tol['bytes_ratio']:g}x base", ok_b,
                    "" if ok_b else "bucket bytes regressed")
        b_t = float(bp.get("total_ms", 0.0))
        c_t = float(cp.get("total_ms", 0.0))
        ok_t = b_t <= 0 or c_t <= b_t * tol["total_ratio"]
        row(f"{pname}.total_ms", round(b_t, 4), round(c_t, 4),
            f"<= {tol['total_ratio']:g}x base", ok_t)
        b_f = float(bp.get("memory_bound_fraction", 0.0))
        c_f = float(cp.get("memory_bound_fraction", 0.0))
        ok_f = abs(c_f - b_f) <= tol["membound_band"]
        row(f"{pname}.memory_bound_fraction", b_f, c_f,
            f"+/-{tol['membound_band']:g}", ok_f)
    return rows, all(r["ok"] for r in rows)


def format_diff(rows: List[Dict[str, Any]]) -> str:
    """The pass/fail table ds_tpu_perfdiff prints (soakdiff's format)."""
    def fmt(v):
        if isinstance(v, float):
            return f"{v:.4g}"
        return "-" if v is None else str(v)

    header = ("metric", "baseline", "candidate", "tolerance", "verdict")
    table = [header]
    for r in rows:
        verdict = "ok" if r["ok"] else "FAIL"
        if r["note"]:
            verdict += f"  ({r['note']})"
        table.append((r["metric"], fmt(r["baseline"]),
                      fmt(r["candidate"]), str(r["tolerance"]), verdict))
    widths = [max(len(row[i]) for row in table)
              for i in range(len(header) - 1)]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(cell.ljust(widths[j]) if j < len(widths)
                               else cell
                               for j, cell in enumerate(row)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths) + "  " +
                         "-" * 7)
    return "\n".join(lines)


def write_anatomy(doc: Dict[str, Any], path: str) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# runtime integration (rides the compile ledger)
# ---------------------------------------------------------------------------

class PerfPlane:
    """Per-engine anatomy engine: computes a static anatomy for every
    compile-ledger event that carries HLO text, exports ``anat/*``
    gauges (-> ``dstpu_anat_*``), serves the ``/statusz`` "anatomy"
    section + flight-bundle provider, and edge-triggers
    ``perf_regression`` when a *recompile* shifts any bucket beyond the
    configured band (first sight of a label never fires — the
    ``overlap_drop`` pattern)."""

    def __init__(self, config=None, tracer=None, owner: Any = None,
                 recorder=None):
        def g(key, default):
            return getattr(config, key, default) if config is not None \
                else default

        from .trace import get_tracer
        self.tracer = tracer or get_tracer()
        self._owner = owner if owner is not None else self
        self._recorder = recorder
        self.band = float(g("band", 0.25))
        self.band_floor_ms = float(g("band_floor_ms", 0.05))
        self.device_model = dict(DEVICE_MODEL)
        dm = g("device_model", None)
        if isinstance(dm, dict):
            self.device_model.update(dm)
        self._anatomies: Dict[str, Dict[str, Any]] = {}
        self._history: "deque" = deque(maxlen=int(g("history", 32)))
        self.programs_observed = 0
        self.regressions = 0
        self.last_regression: Optional[Dict[str, Any]] = None

    # ---------------------------------------------------------- observing
    def observe_program(self, label: str, hlo_text: str,
                        kind: str = "compile", step: Optional[int] = None,
                        event: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, Any]:
        """Anatomize one compiled program. Attaches the anatomy to the
        ledger event (postmortem bundles embed it via
        ``attach_compile_plane``), refreshes the per-label gauges, and
        fires the ``perf_regression`` trigger on a banded bucket shift
        during a recompile."""
        anat = anatomy_from_hlo(hlo_text, self.device_model)
        if event is not None:
            event["anatomy"] = {
                "buckets": {name: round(b["ms"], 6)
                            for name, b in anat["buckets"].items()},
                "total_ms": anat["total_ms"],
                "memory_bound_fraction": anat["memory_bound_fraction"],
            }
        self.programs_observed += 1
        prev = self._anatomies.get(label)
        self._anatomies[label] = anat
        self._history.append({"label": label, "kind": kind, "step": step,
                              "time": time.time(),
                              "total_ms": anat["total_ms"]})
        tr = self.tracer
        tr.set_counter(f"anat/{label}/total_ms",
                       round(anat["total_ms"], 6), owner=self._owner)
        tr.set_counter(f"anat/{label}/memory_bound_fraction",
                       anat["memory_bound_fraction"], owner=self._owner)
        for name, b in anat["buckets"].items():
            if b["ms"] >= self.band_floor_ms or name.startswith("coll_"):
                tr.set_counter(f"anat/{label}/{name}_ms",
                               round(b["ms"], 6), owner=self._owner)
        if prev is not None and kind == "recompile":
            shifted = self._shifted_buckets(prev, anat)
            if shifted:
                self.regressions += 1
                detail = "; ".join(
                    f"{name}: {p:.3f}ms -> {c:.3f}ms" for name, p, c in
                    shifted[:6])
                self.last_regression = {"label": label, "step": step,
                                        "buckets": [s[0] for s in shifted],
                                        "detail": detail}
                tr.set_counter("anat/regressions",
                               float(self.regressions), owner=self._owner)
                tr.instant("perf_plane:regression", cat="warning",
                           args={"label": label, "detail": detail[:512]})
                if self._recorder is not None:
                    self._recorder.trigger(
                        "perf_regression",
                        f"recompile of {label} shifted bucket(s) beyond "
                        f"the {self.band:.0%} band: {detail}", step=step)
        return anat

    def _shifted_buckets(self, prev: Dict[str, Any], cur: Dict[str, Any]
                         ) -> List[Tuple[str, float, float]]:
        out = []
        names = set(prev["buckets"]) | set(cur["buckets"])
        for name in sorted(names):
            p = float((prev["buckets"].get(name) or {}).get("ms", 0.0))
            c = float((cur["buckets"].get(name) or {}).get("ms", 0.0))
            if abs(c - p) > max(self.band * p, self.band_floor_ms):
                out.append((name, p, c))
        return out

    # ------------------------------------------------------------ reading
    def anatomy(self, label: str) -> Optional[Dict[str, Any]]:
        return self._anatomies.get(label)

    def roofline(self, label: str,
                 measured: Optional[Dict[str, Any]] = None
                 ) -> Optional[List[Dict[str, Any]]]:
        anat = self._anatomies.get(label)
        return None if anat is None else reconcile_anatomy(anat, measured)

    def summary(self) -> Dict[str, Any]:
        """The /statusz "anatomy" section (ds_tpu_top renders the
        per-bucket bars from ``programs``)."""
        programs: Dict[str, Any] = {}
        for label, anat in self._anatomies.items():
            programs[label] = {
                "total_ms": round(anat["total_ms"], 4),
                "memory_bound_fraction": anat["memory_bound_fraction"],
                "buckets_ms": {
                    name: round(b["ms"], 4)
                    for name, b in sorted(anat["buckets"].items())
                    if b["ms"] > 0.0},
            }
        out: Dict[str, Any] = {
            "programs_observed": self.programs_observed,
            "regressions": self.regressions,
            "band": self.band,
            "programs": programs,
        }
        if self.last_regression is not None:
            out["last_regression"] = dict(self.last_regression)
        return out

    def bundle_section(self) -> Dict[str, Any]:
        """Flight-bundle provider: the full anatomy table at capture
        time (roofline rows included — a postmortem should not need a
        second run to see where time went)."""
        return {
            "summary": self.summary(),
            "rooflines": {label: reconcile_anatomy(anat)
                          for label, anat in self._anatomies.items()},
        }

    def close(self):
        """Retract every ``anat/*`` gauge. Standalone use only — when an
        engine owns the plane, ``engine.close()``'s counter release
        covers these (the owner is the engine, not this object)."""
        if self._owner is self:
            self.tracer.release_counters(self)
