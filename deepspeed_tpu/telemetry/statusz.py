"""Live introspection server — /healthz, /metrics, /statusz, /trace.

The file exporters (trace JSON, snapshot JSON, .prom rewrite) answer
"what happened"; this answers "what is happening *right now*" over plain
HTTP, so a load balancer, a Prometheus scraper, `bin/ds_tpu_top`, and a
human with a browser all read the same live state:

- ``/healthz``        — liveness/readiness. 200 while every registered
  health check passes; 503 (with the failing reasons) once any fails —
  a serving replica registers its drain/preemption state here, so the
  balancer stops routing to a draining replica *before* it disappears.
- ``/metrics``        — the Prometheus text exposition, live (the same
  bytes the ``prometheus`` monitor sink writes to its ``.prom`` file).
- ``/statusz``        — human-readable HTML: process info + config
  fingerprint, the goodput table, every registered section (training
  counters, serving queue/slots/SLO), recent spans.
- ``/statusz?format=json`` (alias ``/statusz.json``) — the same data as
  one JSON document (what ``bin/ds_tpu_top`` polls).
- ``/trace?last_ms=N`` — Chrome trace-event JSON of the last N ms of the
  span ring buffer (load in ui.perfetto.dev); no param = full buffer.
- ``/debug/bundles`` / ``/debug/bundle?id=N`` / ``/debug/capture`` — the
  flight-recorder surface (telemetry/flight_recorder.py) when one is
  attached: list the on-disk postmortem bundles, download one, or force
  an explicit capture (a trigger rule in its own right).
- ``/fleet/trace?last_ms=N`` — the MERGED fleet timeline (one Perfetto
  document, one stable pid lane per replica) when a FleetAggregator
  (telemetry/disttrace.py) is attached — the router's statusz carries
  this; a plain replica answers 404.

Malformed query parameters (``/trace?last_ms=-5``, ``?last_ms=abc``, an
unknown ``?format=``) answer HTTP 400 with a one-line message — a typo'd
dashboard URL must not surface a 500 traceback.

Opt-in and off by default: no thread is started and no port is bound
unless the ``statusz`` config block enables it. The server is a stdlib
``ThreadingHTTPServer`` on a daemon thread bound to ``host`` (default
loopback); ``port: 0`` binds an ephemeral port (read it back from
``server.port``). ``close()`` shuts the listener down and joins the
thread — engines own their server and close it on shutdown.
"""

import html
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..utils.logging import logger
from .trace import get_tracer

__all__ = ["StatuszServer"]

_LINT_FP: Optional[str] = None


def _lint_fingerprint() -> str:
    """Cached one-liner from analysis/findings.py (rules version + rule
    count + checked-in waiver count). Computed once per process — it
    only changes with the tree — and never allowed to fail a status
    render."""
    global _LINT_FP
    if _LINT_FP is None:
        try:
            from ..analysis.findings import lint_fingerprint
            _LINT_FP = lint_fingerprint()
        except Exception:
            _LINT_FP = "unavailable"
    return _LINT_FP


class StatuszServer:
    """One engine's introspection endpoint. Providers and health checks
    are registered by name; the handler composes them per request."""

    def __init__(self, config=None, tracer=None, host: Optional[str] = None,
                 port: Optional[int] = None):
        self.tracer = tracer or get_tracer()
        host = host if host is not None else \
            getattr(config, "host", "127.0.0.1")
        port = port if port is not None else int(getattr(config, "port", 0))
        self.max_spans = int(getattr(config, "spans", 50) or 50)
        #: name -> callable() -> dict (one /statusz section each)
        self._providers: Dict[str, Callable[[], dict]] = {}
        #: name -> callable() -> (healthy: bool, detail: str)
        self._health: Dict[str, Callable[[], Tuple[bool, str]]] = {}
        self._recorder = None     # FlightRecorder (the /debug/* surface)
        self._hostagg = None      # HostAggregator (the straggler table)
        self._aggregator = None   # FleetAggregator (/fleet/trace)
        self._t_start = time.time()
        self._httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dstpu-statusz",
            daemon=True)
        self._thread.start()
        self._closed = False
        logger.info(f"statusz server listening on http://{self.host}:"
                    f"{self.port}")

    # ------------------------------------------------------------- registry
    def register(self, name: str, provider: Callable[[], dict]):
        """Add a /statusz section; ``provider()`` returns a flat dict."""
        self._providers[name] = provider
        return self

    def register_health(self, name: str,
                        check: Callable[[], Tuple[bool, str]]):
        """Add a /healthz check; ``check()`` returns (healthy, detail)."""
        self._health[name] = check
        return self

    def unregister(self, name: str):
        self._providers.pop(name, None)
        self._health.pop(name, None)

    def attach_recorder(self, recorder):
        """Expose a FlightRecorder: /debug/bundles, /debug/bundle?id=N,
        /debug/capture, and the fired-recently banner on /statusz."""
        self._recorder = recorder
        return self

    def attach_hostagg(self, hostagg):
        """Expose a HostAggregator: the ``hosts`` document in the statusz
        JSON and the straggler table on the HTML page."""
        self._hostagg = hostagg
        return self

    def attach_aggregator(self, aggregator):
        """Expose a FleetAggregator (telemetry/disttrace.py): the
        ``/fleet/trace`` merged-timeline endpoint on the router's
        statusz."""
        self._aggregator = aggregator
        return self

    # ------------------------------------------------------------ lifecycle
    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        """The bound port (resolves ``port: 0`` ephemeral binds)."""
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self):
        """Stop serving, release the port, join the thread. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    # ------------------------------------------------------------- payloads
    def health(self) -> Tuple[bool, str]:
        problems = []
        for name, check in list(self._health.items()):
            try:
                ok, detail = check()
            except Exception as e:   # a broken check is an unhealthy check
                ok, detail = False, f"health check error: {e}"
            if not ok:
                problems.append(f"{name}: {detail}")
        if problems:
            return False, "; ".join(problems)
        return True, "ok"

    def status(self) -> dict:
        """Everything /statusz shows, as one JSON-able document."""
        from .goodput import get_ledger
        healthy, detail = self.health()
        counters = {tag: val for tag, (val, _s)
                    in self.tracer.counters().items()}
        doc = {
            "process": {
                "pid": os.getpid(),
                "uptime_s": round(time.time() - self._t_start, 1),
                "healthy": healthy,
                "health_detail": detail,
                # which lint vintage this build was checked against —
                # flight-recorder bundles embed the status sections, so
                # every postmortem records it
                "lint": _lint_fingerprint(),
            },
            "counters": counters,
            "sections": {},
            "spans": self._recent_spans(),
        }
        ledger = get_ledger()
        if ledger.enabled:
            doc["goodput"] = ledger.snapshot()
        if self._recorder is not None:
            doc["flight_recorder"] = self._recorder.summary()
        if self._hostagg is not None:
            doc["hosts"] = self._hostagg.summary()
        for name, provider in list(self._providers.items()):
            try:
                doc["sections"][name] = provider()
            except Exception as e:
                doc["sections"][name] = {"error": str(e)}
        return doc

    def _recent_spans(self):
        spans = [s for s in self.tracer.spans() if s.ph == "X"]
        out = []
        for s in spans[-self.max_spans:]:
            out.append({"name": s.name, "cat": s.cat,
                        "dur_ms": round(s.dur_us / 1e3, 3)})
        return out

    def trace_slice(self, last_ms: Optional[float] = None) -> dict:
        """Chrome trace JSON, optionally cut to the last ``last_ms``
        milliseconds of span activity (the flight recorder writes the
        same slice into its bundles — telemetry/export.py owns it)."""
        from .export import chrome_trace_slice
        return chrome_trace_slice(self.tracer, last_ms=last_ms)

    # ---------------------------------------------------------------- html
    def status_html(self) -> str:
        doc = self.status()
        esc = html.escape
        parts = ["<!doctype html><html><head><title>deepspeed_tpu statusz"
                 "</title><style>body{font-family:monospace;margin:2em}"
                 "table{border-collapse:collapse;margin:0.5em 0}"
                 "td,th{border:1px solid #999;padding:2px 8px;"
                 "text-align:left}h2{margin-top:1.2em}"
                 ".bad{color:#b00}.good{color:#080}</style></head><body>",
                 "<h1>deepspeed_tpu /statusz</h1>"]
        proc = doc["process"]
        cls = "good" if proc["healthy"] else "bad"
        parts.append(
            f"<p>pid {proc['pid']} · uptime {proc['uptime_s']}s · health "
            f"<span class='{cls}'>{esc(proc['health_detail'])}</span> · "
            f"{esc(proc.get('lint', ''))}</p>")

        def table(rows):
            body = "".join(f"<tr><td>{esc(str(k))}</td>"
                           f"<td>{esc(str(v))}</td></tr>"
                           for k, v in rows)
            return f"<table>{body}</table>"

        fr = doc.get("flight_recorder")
        if fr and fr.get("last"):
            last = fr["last"]
            parts.append(
                f"<p class='bad'><b>flight recorder fired "
                f"{last.get('age_s', '?')}s ago</b>: "
                f"{esc(str(last.get('kind')))} — "
                f"{esc(str(last.get('detail', '')))} "
                f"(<a href='/debug/bundles'>{fr.get('bundles', 0)} "
                f"bundle(s)</a>)</p>")
        # compile-plane recompile banner: the diff names WHICH argument
        # changed shape — the single most actionable line on this page
        # when a job silently recompiles (telemetry/compileplane.py)
        cp = (doc.get("sections") or {}).get("compile_plane") or {}
        if cp.get("last_recompile"):
            parts.append(
                f"<p class='bad'><b>recompile "
                f"{cp.get('last_recompile_age_s', '?')}s ago</b>: "
                f"{esc(str(cp['last_recompile']))}</p>")
        if "goodput" in doc:
            g = doc["goodput"]
            parts.append("<h2>goodput</h2>")
            parts.append(f"<p>wall {g['wall_s']}s · goodput fraction "
                         f"<b>{g['goodput_fraction']}</b></p>")
            rows = sorted(g["buckets"].items(), key=lambda kv: -kv[1])
            parts.append(table([(k, f"{v}s") for k, v in rows if v > 0]))
        hosts = doc.get("hosts")
        if hosts and hosts.get("hosts"):
            parts.append("<h2>hosts</h2>")
            strag = hosts.get("straggler")
            strag_txt = (f"<span class='bad'>host {strag}</span>"
                         if strag is not None else "none")
            parts.append(
                f"<p>step time min/median/max "
                f"{hosts.get('min_ms')} / {hosts.get('median_ms')} / "
                f"{hosts.get('max_ms')} ms · spread "
                f"{hosts.get('spread')}x · straggler {strag_txt}</p>")
            rows = []
            for hid, h in sorted(hosts["hosts"].items(),
                                 key=lambda kv: str(kv[0])):
                mark = ""
                if strag is not None and str(hid) == str(strag):
                    mark = " (straggler)"
                if str(hid) in {str(m) for m in hosts.get("missing", [])}:
                    mark = " (MISSING HEARTBEAT)"
                rows.append((f"host {hid}{mark}",
                             f"{h['step_time_ms']}ms · data-wait "
                             f"{h['data_wait_ms']}ms · seq {h['seqno']}"))
            parts.append(table(rows))
        for name, section in doc["sections"].items():
            parts.append(f"<h2>{esc(name)}</h2>")
            parts.append(table(sorted(section.items())))
        parts.append("<h2>counters</h2>")
        parts.append(table(sorted(doc["counters"].items())))
        if doc["spans"]:
            parts.append(f"<h2>last {len(doc['spans'])} spans</h2>")
            parts.append(table([(f"{s['cat']}/{s['name']}",
                                 f"{s['dur_ms']}ms") for s in doc["spans"]]))
        parts.append("<p><a href='/metrics'>/metrics</a> · "
                     "<a href='/healthz'>/healthz</a> · "
                     "<a href='/trace'>/trace</a> · "
                     "<a href='/statusz?format=json'>json</a></p>")
        parts.append("</body></html>")
        return "".join(parts)


def _make_handler(server: StatuszServer):
    """Handler class closed over the StatuszServer (BaseHTTPRequestHandler
    instantiates per request; state lives on ``server``)."""

    class Handler(BaseHTTPRequestHandler):

        def log_message(self, fmt, *args):   # keep stdout clean
            pass

        def _send(self, code: int, body: str, ctype: str):
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _bad(self, msg: str):
            """HTTP 400 with a one-line message: a malformed query param
            is the CALLER's bug, never a 500 traceback."""
            self._send(400, msg.splitlines()[0] + "\n",
                       "text/plain; charset=utf-8")

        @staticmethod
        def _parse_last_ms(qs):
            """(error_message, value): shared ``last_ms=`` validation for
            /trace and /fleet/trace."""
            raw = qs.get("last_ms", [None])[0]
            if raw is None:
                return None, None
            try:
                last_ms = float(raw)
            except ValueError:
                return (f"bad last_ms={raw!r}: want a number of "
                        f"milliseconds"), None
            if not (last_ms >= 0) or last_ms != last_ms or \
                    last_ms == float("inf"):
                return (f"bad last_ms={raw!r}: want a finite "
                        f"number >= 0"), None
            return None, last_ms

        def do_GET(self):
            try:
                url = urlparse(self.path)
                path = url.path.rstrip("/") or "/statusz"
                qs = parse_qs(url.query)
                if path == "/healthz":
                    healthy, detail = server.health()
                    self._send(200 if healthy else 503, detail + "\n",
                               "text/plain; charset=utf-8")
                elif path == "/metrics":
                    from .export import prometheus_dump
                    self._send(200, prometheus_dump(server.tracer),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path in ("/statusz", "/statusz.json", "/varz"):
                    fmt = qs.get("format", [""])[0]
                    if fmt not in ("", "json", "html"):
                        return self._bad(
                            f"unknown format={fmt!r}: want json or html")
                    as_json = path == "/statusz.json" or fmt == "json"
                    if as_json:
                        self._send(200, json.dumps(server.status(),
                                                   default=str),
                                   "application/json")
                    else:
                        self._send(200, server.status_html(),
                                   "text/html; charset=utf-8")
                elif path == "/trace":
                    err, last_ms = self._parse_last_ms(qs)
                    if err is not None:
                        return self._bad(err)
                    doc = server.trace_slice(last_ms)
                    self._send(200, json.dumps(doc), "application/json")
                elif path == "/fleet/trace":
                    agg = server._aggregator
                    if agg is None:
                        return self._send(
                            404, "no fleet aggregator attached (this is "
                            "not a router statusz, or fleet.disttrace "
                            "is off)\n", "text/plain; charset=utf-8")
                    err, last_ms = self._parse_last_ms(qs)
                    if err is not None:
                        return self._bad(err)
                    doc = agg.merged_trace(last_ms=last_ms)
                    self._send(200, json.dumps(doc), "application/json")
                elif path == "/debug/bundles":
                    rec = server._recorder
                    if rec is None:
                        return self._send(
                            404, "no flight recorder attached (enable the "
                            "flight_recorder config block)\n",
                            "text/plain; charset=utf-8")
                    self._send(200,
                               json.dumps({"bundles": rec.bundles(),
                                           "dir": rec.dir}),
                               "application/json")
                elif path == "/debug/bundle":
                    rec = server._recorder
                    if rec is None:
                        return self._send(
                            404, "no flight recorder attached\n",
                            "text/plain; charset=utf-8")
                    raw = qs.get("id", [None])[0]
                    if raw is None or not raw.isdigit():
                        return self._bad(
                            f"bad id={raw!r}: want /debug/bundle?id=N "
                            f"(see /debug/bundles)")
                    body = rec.read_bundle(int(raw))
                    if body is None:
                        return self._send(
                            404, f"no bundle with id {raw}\n",
                            "text/plain; charset=utf-8")
                    self._send(200, body, "application/json")
                elif path == "/debug/capture":
                    rec = server._recorder
                    if rec is None:
                        return self._send(
                            404, "no flight recorder attached\n",
                            "text/plain; charset=utf-8")
                    bundle = rec.trigger(
                        "manual", detail="explicit /debug/capture",
                        force=True)
                    self._send(200, json.dumps({"bundle": bundle}),
                               "application/json")
                else:
                    self._send(404, "not found: try /healthz /metrics "
                               "/statusz /trace /fleet/trace "
                               "/debug/bundles\n",
                               "text/plain; charset=utf-8")
            except BrokenPipeError:      # client went away mid-response
                pass
            except Exception as e:
                try:
                    self._send(500, f"statusz error: {e}\n",
                               "text/plain; charset=utf-8")
                except OSError:
                    pass

    return Handler
