"""Structured tracer — the substrate of deepspeed_tpu observability.

One per-process ``Tracer`` owns a fixed-capacity ring buffer of ``Span``
records plus a lightweight counter pipeline. Spans are host-side wall-time
intervals opened with ``tracer.span("fwd")`` context managers; under XLA's
async dispatch a raw host interval only measures *dispatch*, so spans carry
explicit sync points: ``sp.sync_on(outputs)`` blocks on the step's outputs
before the end timestamp is taken (the CUDA-event analogue of
utils/timer.py's ``stop(sync=True)``).

Three record kinds:

- complete spans (``ph='X'``): nested host intervals — fwd/bwd/step,
  dispatch, prefill, decode ticks. Nesting is depth-tracked per thread.
- async spans (``ph='b'``/``'e'``): intervals that outlive any one stack
  frame — a serving request's queue→prefill→decode→complete lifecycle,
  keyed by request id.
- counters: latest-value metrics (MFU, recompiles, queue depth, ...) in
  one process-wide gauge space — everything the training engine and the
  serving stack record lands here, so the metrics snapshot and Prometheus
  dump see it all. Monitor-EVENT fan-out stays per-producer: the engine
  and ``ServingMetrics`` buffer their own ``(tag, value, step)`` batches
  for ``MonitorMaster.write_events`` (a shared event queue would let two
  engines in one process drain each other's events); ``emit()`` +
  ``drain_events()`` remain as a single-consumer pipeline for scripts.

Disabled is the default and costs nothing: ``span()`` returns a shared
no-op singleton — no ``Span`` object is ever allocated (asserted by
tests/unit/test_telemetry.py). Counters stay live regardless, since the
monitor pipeline must work without tracing.

Exporters (Chrome trace JSON for Perfetto, metrics snapshot, Prometheus
text) live in telemetry/export.py; the ``MonitorMaster`` sink in
telemetry/monitor_sink.py.
"""

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["Span", "Tracer", "RecompileWatchdog", "get_tracer",
           "configure_tracer"]

_NOSYNC = object()


def _default_sync():
    """Best-effort full-device sync for ``sync=True`` spans without an
    output to block on (accurate spans should prefer ``sync_on(value)``)."""
    try:
        import jax
        jax.effects_barrier()
    except Exception:
        pass


def _block_on(value):
    try:
        import jax
        jax.block_until_ready(value)
    except Exception:
        pass


class Span:
    """One record in the ring buffer. Also its own context manager, so an
    enabled ``tracer.span(...)`` costs exactly one allocation."""

    __slots__ = ("name", "cat", "ts_us", "dur_us", "depth", "tid", "args",
                 "ph", "aid", "_tracer", "_sync", "_sync_val")

    def __init__(self, tracer, name: str, cat: str = "host",
                 args: Optional[Dict[str, Any]] = None, sync: bool = False,
                 ph: str = "X", aid: Optional[int] = None):
        self.name = name
        self.cat = cat
        self.args = args
        self.ph = ph
        self.aid = aid
        self.ts_us = 0.0
        self.dur_us = 0.0
        self.depth = 0
        self.tid = threading.get_ident()
        self._tracer = tracer
        self._sync = sync
        self._sync_val = _NOSYNC

    def sync_on(self, value):
        """Block on ``value`` (any pytree of jax arrays) at span exit before
        the end timestamp — the honest duration under async dispatch."""
        self._sync_val = value
        return value

    def set(self, **kwargs):
        """Attach/update args on an open span."""
        if self.args is None:
            self.args = {}
        self.args.update(kwargs)

    def __enter__(self):
        tr = self._tracer
        self.depth = tr._enter_depth()
        self.ts_us = time.perf_counter_ns() / 1e3
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._sync_val is not _NOSYNC:
            _block_on(self._sync_val)
        elif self._sync:
            _default_sync()
        self.dur_us = time.perf_counter_ns() / 1e3 - self.ts_us
        tr = self._tracer
        tr._exit_depth()
        # drop the references a retained record doesn't need
        self._sync_val = _NOSYNC
        tr._record(self)
        return False


class _NullSpan:
    """Shared no-op span: what a disabled tracer hands out. A singleton —
    the zero-cost-when-disabled contract is that no object is allocated."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def sync_on(self, value):
        return value

    def set(self, **kwargs):
        pass


_NULL_SPAN = _NullSpan()


class Tracer:
    """Per-process structured tracer: span ring buffer + counter pipeline."""

    def __init__(self, buffer_size: int = 65536, enabled: bool = False):
        self.enabled = enabled
        self.sync_spans = True
        self._cap = max(16, int(buffer_size))
        self._ring: List[Optional[Span]] = [None] * self._cap
        self._head = 0          # next write index
        self._total = 0         # spans ever recorded (wraparound detector)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._counters: Dict[str, Any] = {}
        # gauge ownership: tag -> id(owner) for gauges registered by a
        # closable producer (an engine); release_counters(owner) drops the
        # tags that owner still holds, so /metrics and prometheus_dump
        # never report stale values from a closed engine. Last writer
        # wins: a tag two co-resident engines both write belongs to
        # whichever wrote it last, and only that one's close() removes it.
        self._counter_owners: Dict[str, int] = {}
        self._pending: "deque" = deque(maxlen=8192)

    # ------------------------------------------------------------ configure
    def configure(self, config=None, **overrides):
        """Apply a ``TelemetryConfig`` (or kwargs): enabled, buffer_size,
        sync_spans. Resizing the buffer clears recorded spans."""
        kv = {}
        if config is not None:
            for k in ("enabled", "buffer_size", "sync_spans"):
                if hasattr(config, k):
                    kv[k] = getattr(config, k)
        kv.update(overrides)
        if "buffer_size" in kv and int(kv["buffer_size"]) != self._cap:
            with self._lock:
                self._cap = max(16, int(kv["buffer_size"]))
                self._ring = [None] * self._cap
                self._head = 0
                self._total = 0
        if "sync_spans" in kv:
            self.sync_spans = bool(kv["sync_spans"])
        if "enabled" in kv:
            self.enabled = bool(kv["enabled"])
        return self

    # ----------------------------------------------------------------- spans
    def span(self, name: str, cat: str = "host",
             args: Optional[Dict[str, Any]] = None, sync: bool = False):
        """Open a nested wall-time span. ``sync=True`` fences the device at
        exit; for accuracy prefer ``sp.sync_on(step_outputs)``. Disabled
        tracer: returns the shared no-op singleton (no allocation)."""
        if not self.enabled:
            return _NULL_SPAN
        return Span(self, name, cat=cat, args=args,
                    sync=sync and self.sync_spans)

    def instant(self, name: str, cat: str = "host",
                args: Optional[Dict[str, Any]] = None):
        """Zero-duration marker event."""
        if not self.enabled:
            return
        sp = Span(self, name, cat=cat, args=args, ph="i")
        sp.ts_us = time.perf_counter_ns() / 1e3
        self._record(sp)

    def async_begin(self, name: str, aid: int, cat: str = "async",
                    args: Optional[Dict[str, Any]] = None):
        """Open one side of an async span (an interval that outlives the
        current stack frame, e.g. a serving request). Pair with
        ``async_end`` on the same (name, aid)."""
        if not self.enabled:
            return
        sp = Span(self, name, cat=cat, args=args, ph="b", aid=aid)
        sp.ts_us = time.perf_counter_ns() / 1e3
        self._record(sp)

    def async_end(self, name: str, aid: int, cat: str = "async",
                  args: Optional[Dict[str, Any]] = None):
        if not self.enabled:
            return
        sp = Span(self, name, cat=cat, args=args, ph="e", aid=aid)
        sp.ts_us = time.perf_counter_ns() / 1e3
        self._record(sp)

    def counter_track(self, name: str, values: Dict[str, float],
                      cat: str = "mem"):
        """Record a Chrome counter sample (``ph='C'``): Perfetto renders
        successive samples of the same ``name`` as a stacked counter
        track — the HBM ledger's waterline timeline rides this."""
        if not self.enabled:
            return
        sp = Span(self, name, cat=cat, args=dict(values), ph="C")
        sp.ts_us = time.perf_counter_ns() / 1e3
        self._record(sp)

    def _record(self, span: Span):
        with self._lock:
            self._ring[self._head] = span
            self._head = (self._head + 1) % self._cap
            self._total += 1

    def _enter_depth(self) -> int:
        d = getattr(self._tls, "depth", 0)
        self._tls.depth = d + 1
        return d

    def _exit_depth(self):
        self._tls.depth = max(0, getattr(self._tls, "depth", 1) - 1)

    def spans(self) -> List[Span]:
        """Recorded spans, oldest first (at most ``buffer_size``; older
        records are overwritten — the ring never grows)."""
        with self._lock:
            if self._total < self._cap:
                return [s for s in self._ring[:self._head] if s is not None]
            return ([s for s in self._ring[self._head:] if s is not None] +
                    [s for s in self._ring[:self._head] if s is not None])

    @property
    def dropped(self) -> int:
        """Spans overwritten by ring wraparound."""
        return max(0, self._total - self._cap)

    # -------------------------------------------------------------- counters
    def emit(self, tag: str, value: float, step: Optional[int] = None):
        """Update the gauge AND queue a monitor event on the process-global
        pipeline — a convenience for scripts with ONE drain_events()
        consumer. Library producers (the engine, ServingMetrics) use
        ``set_counter`` plus their own event buffers instead, so
        co-resident producers can't steal each other's events. Works with
        tracing disabled (gauges must not depend on span recording)."""
        self._counters[tag] = (value, step)
        self._pending.append((tag, value, 0 if step is None else step))

    def set_counter(self, tag: str, value: float, step: Optional[int] = None,
                    owner: Any = None):
        """Gauge-only update (no queued monitor event) — what the engines
        and the TelemetryMonitor sink use (the sink re-queueing events
        would loop the pipeline back into itself). ``owner`` ties the tag
        to a closable producer for ``release_counters``."""
        self._counters[tag] = (value, step)
        if owner is not None:
            self._counter_owners[tag] = id(owner)
        # owner=None leaves any existing ownership standing: the
        # TelemetryMonitor sink mirrors an engine's own events back into
        # the gauge space ownerless, and that mirror must not strip the
        # engine's right to retract its tags at close()

    def release_counters(self, owner: Any):
        """Drop every gauge still owned by ``owner`` (engine close path):
        a closed engine's queue depth / step time must not linger in
        prometheus_dump() or /metrics as if it were live."""
        oid = id(owner)
        for tag in [t for t, o in self._counter_owners.items() if o == oid]:
            del self._counter_owners[tag]
            self._counters.pop(tag, None)

    def counters(self) -> Dict[str, Any]:
        return dict(self._counters)

    def counter_value(self, tag: str, default=None):
        """Latest value of one gauge (without its step), or ``default`` —
        the cheap single-tag read for per-tick consumers that must not pay
        for a full counters() copy."""
        val = self._counters.get(tag)
        return val[0] if val is not None else default

    def drain_events(self):
        """Take all pending (tag, value, step) monitor events."""
        out = list(self._pending)
        self._pending.clear()
        return out

    # ------------------------------------------------------------------ misc
    def clear(self):
        with self._lock:
            self._ring = [None] * self._cap
            self._head = 0
            self._total = 0
        self._counters.clear()
        self._counter_owners.clear()
        self._pending.clear()


class RecompileWatchdog:
    """Counts jit cache growth per step (recompiles). A shape/dtype change
    that silently recompiles the train step is the #1 TPU perf cliff; this
    makes it a counter instead of a mystery.

    ``observe(fn)`` samples ``fn._cache_size()`` and returns how many NEW
    executables appeared since the last observation of that fn (0 on first
    sight — the initial compile is expected). Holds a reference to each
    watched fn so ids stay unique."""

    def __init__(self):
        self._watched: Dict[int, Any] = {}
        self.recompiles = 0

    def seen(self, fn) -> bool:
        """Whether ``fn`` has been observed before — False means the next
        call pays the initial compile (the goodput ledger's ``compile``
        bucket, distinct from a ``recompile``)."""
        return id(fn) in self._watched

    def observe(self, fn, tracer: Optional[Tracer] = None,
                label: str = "train_step", owner: Any = None) -> int:
        size_of = getattr(fn, "_cache_size", None)
        if size_of is None:
            return 0
        try:
            size = int(size_of())
        except Exception:
            return 0
        prev = self._watched.get(id(fn))
        self._watched[id(fn)] = (fn, size)
        if prev is None:
            return 0
        delta = max(0, size - prev[1])
        if delta:
            self.recompiles += delta
            if tracer is not None:
                # gauge-only: the caller owns monitor-event fan-out
                tracer.set_counter("telemetry/recompiles", self.recompiles,
                                   owner=owner)
                tracer.instant(f"recompile:{label}", cat="warning",
                               args={"new_executables": delta,
                                     "total": self.recompiles})
        return delta


_TRACER: Optional[Tracer] = None


def get_tracer() -> Tracer:
    """The process-global tracer (created disabled; ``DSTPU_TELEMETRY=1``
    enables it from the environment for script-level use)."""
    global _TRACER
    if _TRACER is None:
        _TRACER = Tracer(
            enabled=os.environ.get("DSTPU_TELEMETRY", "") in ("1", "true"))
    return _TRACER


def configure_tracer(config=None, **overrides) -> Tracer:
    """Configure the global tracer from a ``TelemetryConfig`` block
    (runtime/config.py) or kwargs; returns it."""
    return get_tracer().configure(config, **overrides)
