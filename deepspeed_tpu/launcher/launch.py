"""Per-node process spawner with elastic group restart.

TPU-native re-design of the reference per-node launcher
(deepspeed/launcher/launch.py:216) plus the DSElasticAgent restart
behavior (deepspeed/elasticity/elastic_agent.py:28): spawns the worker
processes for THIS node, wires the rendezvous env (RANK / WORLD_SIZE /
MASTER_ADDR / MASTER_PORT → consumed by comm.init_distributed →
jax.distributed.initialize), forwards signals, and tears the whole tree
down if any child dies. With ``--max_restarts N`` a failed worker group is
respawned up to N times with exponential backoff and a fresh rendezvous
port (torch-elastic's whole-group restart semantics — user scripts resume
from their latest checkpoint). If ``DSTPU_ELASTIC_CONFIG`` holds a JSON
config with an ``elasticity`` block, a group that fails repeatedly is
re-planned to the next smaller valid world size from
``compute_elastic_config`` before the retry.

A JAX SPMD job runs ONE process per host (the process drives all local TPU
chips), so the default --nproc_per_node is 1 — unlike the reference's
process-per-GPU model. >1 is supported for the CPU-backend test rig, where N
single-device processes emulate N hosts on one machine.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

from ..utils.logging import logger


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="deepspeed_tpu per-node launcher")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--master_addr", default="127.0.0.1")
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("--max_restarts", type=int, default=0,
                   help="group restarts after a worker failure "
                        "(reference DSElasticAgent behavior); "
                        "single-node only — multi-node groups have no "
                        "cross-node restart coordinator yet")
    p.add_argument("--restart_backoff", type=float, default=1.0,
                   help="base seconds of exponential restart backoff")
    p.add_argument("--elastic_training", action="store_true",
                   help="opt in to shrinking the worker group on repeated "
                        "failures (DSTPU_ELASTIC_CONFIG elasticity block)")
    p.add_argument("--module", action="store_true",
                   help="run the script as 'python -m <script>'")
    p.add_argument("--no_python", action="store_true",
                   help="exec the script directly (not via the interpreter)")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def build_cmd(args):
    if args.no_python:
        cmd = [args.training_script]
    elif args.module:
        cmd = [sys.executable, "-m", args.training_script]
    else:
        cmd = [sys.executable, args.training_script]
    return cmd + list(args.training_script_args)


def _elastic_replan(nproc: int) -> int:
    """Next smaller valid world size from the DSTPU_ELASTIC_CONFIG
    elasticity block, or ``nproc`` unchanged if no config / no smaller
    size exists. (Single-node form of the reference agent's
    re-rendezvous-with-fewer-workers.)"""
    raw = os.environ.get("DSTPU_ELASTIC_CONFIG")
    if not raw:
        return nproc
    try:
        cfg = json.loads(raw) if raw.lstrip().startswith("{") else \
            json.load(open(raw))
        from ..elasticity.elasticity import compute_elastic_config
        _, valid = compute_elastic_config(cfg)[:2]
    except Exception as exc:  # noqa: BLE001 — a bad plan must not kill the
        logger.warning(f"elastic re-plan unavailable: {exc}")  # launcher
        return nproc
    smaller = [g for g in valid if g < nproc]
    if not smaller:
        logger.warning(f"elastic re-plan: no valid world size below "
                       f"{nproc} in {valid}; keeping {nproc}")
        return nproc
    return max(smaller)


def _run_group(args, attempt: int, nproc: int) -> int:
    """Spawn one worker group and babysit it; returns the group rc."""
    world_size = args.nnodes * nproc
    port = args.master_port + attempt     # fresh rendezvous per attempt
    procs = []

    def terminate(sig=signal.SIGTERM):
        for proc in procs:
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), sig)
                except ProcessLookupError:
                    pass

    def handler(signum, frame):
        logger.info(f"launch: forwarding signal {signum} to workers")
        terminate(signum)
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)

    for local_rank in range(nproc):
        rank = args.node_rank * nproc + local_rank
        env = dict(os.environ)
        env.update({
            "RANK": str(rank),
            "LOCAL_RANK": str(local_rank),
            "WORLD_SIZE": str(world_size),
            "MASTER_ADDR": args.master_addr,
            "MASTER_PORT": str(port),
            "DSTPU_NUM_PROCESSES": str(world_size),
            "NODE_RANK": str(args.node_rank),
            "DSTPU_RESTART_COUNT": str(attempt),
        })
        cmd = build_cmd(args)
        logger.info(f"launch: rank {rank} (attempt {attempt}) -> "
                    f"{' '.join(cmd)}")
        procs.append(subprocess.Popen(cmd, env=env, start_new_session=True))

    # babysit: if one worker dies, kill the rest (reference launch.py:119
    # sigkill-the-tree behavior)
    exit_code = 0
    alive = set(range(len(procs)))
    while alive:
        for i in sorted(alive):
            rc = procs[i].poll()
            if rc is None:
                continue
            alive.discard(i)
            if rc != 0:
                logger.error(f"launch: worker {i} exited rc={rc}; "
                             f"terminating remaining workers")
                exit_code = rc
                terminate(signal.SIGTERM)
                deadline = time.time() + 10
                for p in procs:
                    try:
                        p.wait(timeout=max(0.1, deadline - time.time()))
                    except subprocess.TimeoutExpired:
                        terminate(signal.SIGKILL)
                alive.clear()
                break
        time.sleep(0.2)
    return exit_code


def main(argv=None):
    args = parse_args(argv)
    if args.max_restarts > 0 and args.nnodes > 1:
        # each node's launcher retries independently — without a
        # cross-node coordinator the rendezvous ports/attempts
        # desynchronize, so restarts are single-node only for now
        logger.warning("launch: --max_restarts requires a cross-node "
                       "restart coordinator and nnodes>1 has none; "
                       "disabling restarts (kill-the-tree semantics)")
        args.max_restarts = 0
    nproc = args.nproc_per_node
    failures = 0
    for attempt in range(args.max_restarts + 1):
        rc = _run_group(args, attempt, nproc)
        if rc == 0:
            return 0
        failures += 1
        if attempt >= args.max_restarts:
            break
        # after two consecutive failures at this size, re-plan smaller
        # (an unhealthy member keeps killing the group — the reference
        # agent's shrink-on-re-rendezvous). Opt-in via --elastic_training.
        # nnodes==1 here, so nproc IS the world size compute_elastic_config
        # validates against.
        if failures >= 2 and args.elastic_training:
            new_nproc = _elastic_replan(nproc)
            if new_nproc != nproc:
                logger.warning(f"launch: elastic re-plan "
                               f"{nproc} -> {new_nproc} workers")
                nproc = new_nproc
                failures = 0
        backoff = args.restart_backoff * (2 ** attempt)
        logger.warning(f"launch: group failed rc={rc}; restarting in "
                       f"{backoff:.1f}s "
                       f"({args.max_restarts - attempt} restarts left)")
        time.sleep(backoff)
    return rc


if __name__ == "__main__":
    sys.exit(main())
