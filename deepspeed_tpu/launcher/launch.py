"""Per-node process spawner.

TPU-native re-design of the reference per-node launcher
(deepspeed/launcher/launch.py:216): spawns the worker processes for THIS
node, wires the rendezvous env (RANK / WORLD_SIZE / MASTER_ADDR / MASTER_PORT
→ consumed by comm.init_distributed → jax.distributed.initialize), forwards
signals, and tears the whole tree down if any child dies.

A JAX SPMD job runs ONE process per host (the process drives all local TPU
chips), so the default --nproc_per_node is 1 — unlike the reference's
process-per-GPU model. >1 is supported for the CPU-backend test rig, where N
single-device processes emulate N hosts on one machine.
"""

import argparse
import os
import signal
import subprocess
import sys
import time

from ..utils.logging import logger


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="deepspeed_tpu per-node launcher")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--master_addr", default="127.0.0.1")
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("--module", action="store_true",
                   help="run the script as 'python -m <script>'")
    p.add_argument("--no_python", action="store_true",
                   help="exec the script directly (not via the interpreter)")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def build_cmd(args):
    if args.no_python:
        cmd = [args.training_script]
    elif args.module:
        cmd = [sys.executable, "-m", args.training_script]
    else:
        cmd = [sys.executable, args.training_script]
    return cmd + list(args.training_script_args)


def main(argv=None):
    args = parse_args(argv)
    world_size = args.nnodes * args.nproc_per_node
    procs = []

    def terminate(sig=signal.SIGTERM):
        for proc in procs:
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), sig)
                except ProcessLookupError:
                    pass

    def handler(signum, frame):
        logger.info(f"launch: forwarding signal {signum} to workers")
        terminate(signum)
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)

    for local_rank in range(args.nproc_per_node):
        rank = args.node_rank * args.nproc_per_node + local_rank
        env = dict(os.environ)
        env.update({
            "RANK": str(rank),
            "LOCAL_RANK": str(local_rank),
            "WORLD_SIZE": str(world_size),
            "MASTER_ADDR": args.master_addr,
            "MASTER_PORT": str(args.master_port),
            "DSTPU_NUM_PROCESSES": str(world_size),
            "NODE_RANK": str(args.node_rank),
        })
        cmd = build_cmd(args)
        logger.info(f"launch: rank {rank} -> {' '.join(cmd)}")
        procs.append(subprocess.Popen(cmd, env=env, start_new_session=True))

    # babysit: if one worker dies, kill the rest (reference launch.py:119
    # sigkill-the-tree behavior)
    exit_code = 0
    alive = set(range(len(procs)))
    while alive:
        for i in sorted(alive):
            rc = procs[i].poll()
            if rc is None:
                continue
            alive.discard(i)
            if rc != 0:
                logger.error(f"launch: worker {i} exited rc={rc}; "
                             f"terminating remaining workers")
                exit_code = rc
                terminate(signal.SIGTERM)
                deadline = time.time() + 10
                for p in procs:
                    try:
                        p.wait(timeout=max(0.1, deadline - time.time()))
                    except subprocess.TimeoutExpired:
                        terminate(signal.SIGKILL)
                alive.clear()
                break
        time.sleep(0.2)
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
