"""Multinode runners: build the launch command for each cluster transport.

Capability match for the reference runner hierarchy
(deepspeed/launcher/multinode_runner.py: PDSHRunner :51, OpenMPIRunner
:107, MPICHRunner :160, SlurmRunner, MVAPICHRunner): each runner knows how
to fan a per-node command out over its transport. TPU deltas:

  - ssh/pdsh transports start launcher/launch.py per node (which spawns
    the SPMD process and wires RANK/MASTER_* — runner.py drives these).
  - MPI-family and SLURM transports start ONE process per node directly
    (mpirun/srun own the fan-out); the processes bootstrap from the
    transport's environment (OMPI_COMM_WORLD_RANK / SLURM_PROCID /
    MV2_COMM_WORLD_RANK) via comm.init_distributed's env discovery — the
    reference's mpi_discovery (comm.py:591-689) equivalent.
"""

import os
import shlex
import shutil
import sys
from typing import Dict, List

from ..utils.logging import logger


class MultiNodeRunner:
    """Base: subclasses emit the full local command whose execution fans
    the job out over the cluster."""

    name = "base"

    def __init__(self, args, world_info: Dict[str, int]):
        self.args = args
        self.world_info = world_info          # ordered {host: slots}
        self.user_arguments = list(args.user_args or [])

    @property
    def hosts(self) -> List[str]:
        return list(self.world_info)

    def backend_exists(self) -> bool:
        raise NotImplementedError

    def get_cmd(self, environment: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def _user_cmd(self) -> List[str]:
        args = self.args
        cmd = []
        if not args.no_python:
            cmd = [sys.executable, "-u"]
            if args.module:
                cmd.append("-m")
        return cmd + [args.user_script] + self.user_arguments

    def export_envs(self, environment) -> Dict[str, str]:
        """Env worth forwarding to remote ranks (reference exports its
        .deepspeed_env; here: the jax/TPU namespace + MASTER_*)."""
        keep = {}
        for k, v in environment.items():
            if k.startswith(("DSTPU_", "JAX_", "XLA_", "TPU_", "LIBTPU",
                             "PYTHON", "MV2_")) or k in ("MASTER_ADDR",
                                                         "MASTER_PORT"):
                keep[k] = v
        return keep


class OpenMPIRunner(MultiNodeRunner):
    name = "openmpi"

    def backend_exists(self) -> bool:
        return shutil.which("ompi_info") is not None

    def get_cmd(self, environment):
        total = len(self.hosts)
        # one SPMD process per NODE, over the FILTERED host list (the raw
        # hostfile would resurrect --exclude'd hosts); without the ppr
        # mapping Open MPI's fill-by-slot default would stack every rank
        # on the first host
        cmd = ["mpirun", "-n", str(total),
               "--host", ",".join(self.hosts),
               "--map-by", "ppr:1:node",
               "--mca", "btl", "^openib",
               "--mca", "btl_tcp_if_include", "eth0"]
        for k, v in self.export_envs(environment).items():
            cmd += ["-x", f"{k}={v}"]
        cmd += shlex.split(self.args.launcher_args)
        return cmd + self._user_cmd()


class MPICHRunner(MultiNodeRunner):
    name = "mpich"

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None and \
            shutil.which("ompi_info") is None

    def get_cmd(self, environment):
        total = len(self.hosts)
        cmd = ["mpirun", "-n", str(total), "-ppn", "1",
               "-hosts", ",".join(self.hosts)]
        for k, v in self.export_envs(environment).items():
            cmd += ["-genv", k, v]
        cmd += shlex.split(self.args.launcher_args)
        return cmd + self._user_cmd()


class MVAPICHRunner(MPICHRunner):
    name = "mvapich"

    def backend_exists(self) -> bool:
        # reference checks mpiname for MVAPICH2
        mpiname = shutil.which("mpiname")
        if mpiname is None:
            return False
        try:
            import subprocess
            out = subprocess.run([mpiname], capture_output=True, text=True,
                                 timeout=10).stdout
            return "MVAPICH2" in out
        except Exception:
            return False

    def get_cmd(self, environment):
        env = dict(environment)
        # reference sets the MV2 runtime knobs it needs
        env.setdefault("MV2_SMP_USE_CMA", "0")
        env.setdefault("MV2_DEBUG_SHOW_BACKTRACE", "1")
        total = len(self.hosts)
        cmd = ["mpirun", "-np", str(total), "-ppn", "1",
               "-hosts", ",".join(self.hosts)]
        for k, v in self.export_envs(env).items():
            cmd += ["-env", f"{k}={v}"]
        cmd += shlex.split(self.args.launcher_args)
        return cmd + self._user_cmd()


class SlurmRunner(MultiNodeRunner):
    name = "slurm"

    def backend_exists(self) -> bool:
        return shutil.which("sinfo") is not None

    def get_cmd(self, environment):
        args = self.args
        total = len(self.hosts)
        cmd = ["srun", "-n", str(total), "--ntasks-per-node=1"]
        if getattr(args, "include", ""):
            if "@" in args.include or ":" in args.include:
                raise ValueError(
                    "SLURM runner takes a plain comma node list in "
                    "--include (reference multinode_runner.py SlurmRunner "
                    "comment: slurm mode does not support the @/: syntax)")
            cmd.append(f"--nodelist={args.include}")
        if getattr(args, "exclude", ""):
            cmd.append(f"--exclude={args.exclude}")
        if getattr(args, "num_nodes", -1) > 0:
            cmd.append(f"--nodes={args.num_nodes}")
        cmd += shlex.split(args.launcher_args)
        exports = {}
        for k, v in self.export_envs(environment).items():
            if "," in v or " " in v:
                # srun's --export list is comma-delimited with no quoting
                # mechanism (LIBTPU_INIT_ARGS is conventionally
                # comma-separated) — forwarding would corrupt the list
                logger.warning(
                    f"slurm runner: not forwarding {k} (value contains "
                    f"','/' '); set it via --launcher_args "
                    f"'--export=...' or in the remote environment")
                continue
            exports[k] = v
        if exports:
            cmd.append("--export=ALL," + ",".join(
                f"{k}={v}" for k, v in exports.items()))
        return cmd + self._user_cmd()


class PDSHRunner(MultiNodeRunner):
    """Kept for API parity; runner.py's inline ssh/pdsh path predates this
    class and remains the ssh transport implementation."""

    name = "pdsh"

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment):
        env_str = " ".join(f"{k}={shlex.quote(v)}"
                           for k, v in self.export_envs(environment).items())
        remote = (f"cd {shlex.quote(os.getcwd())} && {env_str} " +
                  " ".join(map(shlex.quote, self._user_cmd())))
        return (["pdsh", "-S", "-w", ",".join(self.hosts)] +
                shlex.split(self.args.launcher_args) + [remote])


RUNNERS = {cls.name: cls for cls in
           (OpenMPIRunner, MPICHRunner, MVAPICHRunner, SlurmRunner,
            PDSHRunner)}


def get_runner(name: str, args, world_info) -> MultiNodeRunner:
    if name not in RUNNERS:
        raise ValueError(f"unknown launcher {name!r}; known: "
                         f"{sorted(RUNNERS)} + ssh")
    runner = RUNNERS[name](args, world_info)
    if not runner.backend_exists():
        logger.warning(f"launcher backend '{name}' not detected on this "
                       f"machine; the emitted command may fail")
    return runner
