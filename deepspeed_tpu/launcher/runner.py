"""Job runner — the `deepspeed_tpu` CLI entrypoint.

TPU-native re-design of the reference runner (deepspeed/launcher/
runner.py:376 + multinode_runner.py): parses a hostfile, applies
--include/--exclude filters, and starts the per-node launcher
(launcher/launch.py) on every selected host — locally for single-node, over
ssh for multinode (the PDSH role; pdsh itself is optional and shelled out to
when requested and present).

Hostfile format (reference compatible):
    worker-1 slots=4
    worker-2 slots=4

On TPU "slots" is informational (one SPMD process drives all local chips);
process count = node count, except in --backend=cpu test mode where
--nproc_per_node emulates multiple hosts on one machine.
"""

import argparse
import os
import shlex
import shutil
import subprocess
import sys

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="deepspeed_tpu launcher",
        usage="deepspeed_tpu [options] <user script> [script args]")
    p.add_argument("-H", "--hostfile", default=DLTS_HOSTFILE,
                   help="hostfile of 'host slots=N' lines")
    p.add_argument("-i", "--include", default="",
                   help="subset of hosts, e.g. 'worker-1@worker-2'")
    p.add_argument("-e", "--exclude", default="",
                   help="hosts to drop, same syntax as --include")
    p.add_argument("--num_nodes", type=int, default=-1,
                   help="cap on node count from the hostfile")
    p.add_argument("--master_addr", default=None)
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("--launcher", default="ssh",
                   choices=["ssh", "pdsh", "openmpi", "mpich", "mvapich",
                            "slurm"],
                   help="multinode transport (reference "
                        "multinode_runner.py set; MPI/SLURM transports "
                        "fan out one SPMD process per node themselves and "
                        "ranks bootstrap from OMPI_*/SLURM_* env)")
    p.add_argument("--launcher_args", default="",
                   help="extra args for ssh/pdsh")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (CPU-backend testing; TPU uses 1)")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="worker-group restarts after a failure (elastic "
                        "agent behavior; see launcher/launch.py)")
    p.add_argument("--restart_backoff", type=float, default=1.0,
                   help="base seconds of exponential restart backoff")
    p.add_argument("--elastic_training", action="store_true",
                   help="enable elastic re-plan on repeated failures "
                        "(reads the 'elasticity' block of the JSON in "
                        "DSTPU_ELASTIC_CONFIG)")
    p.add_argument("--force_multi", action="store_true",
                   help="multinode codepath even for one node")
    p.add_argument("--module", action="store_true")
    p.add_argument("--no_python", action="store_true")
    p.add_argument("--ds_report", action="store_true",
                   help="print the environment report and exit")
    p.add_argument("user_script", nargs="?")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def fetch_hostfile(path):
    """Parse 'host slots=N' lines; returns ordered {host: slots}."""
    if not os.path.isfile(path):
        return {}
    resources = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for tok in parts[1:]:
                if tok.startswith("slots="):
                    slots = int(tok.split("=")[1])
            if host in resources:
                raise ValueError(f"duplicate host {host} in hostfile")
            resources[host] = slots
    return resources


def _parse_filter(spec):
    """'host1@host2' or 'host1:0,1@host2' → {host: [slot,...] or None}."""
    out = {}
    if not spec:
        return out
    for item in spec.split("@"):
        if ":" in item:
            host, slots = item.split(":", 1)
            out[host] = [int(s) for s in slots.split(",")]
        else:
            out[item] = None
    return out


def filter_resources(resources, include, exclude):
    inc = _parse_filter(include)
    exc = _parse_filter(exclude)
    if inc and exc:
        raise ValueError("--include and --exclude are mutually exclusive")
    hosts = dict(resources)
    if inc:
        for h in inc:
            if h not in hosts:
                raise ValueError(f"--include host {h} not in hostfile")
        hosts = {h: hosts[h] for h in resources if h in inc}
        for h, slots in inc.items():
            if slots is not None:
                logger.warning(
                    f"--include slot list for {h} ignored: a TPU host runs "
                    f"one SPMD process for all its chips")
    for h, slots in exc.items():
        if slots is not None:
            logger.warning(f"--exclude slot list for {h} ignored")
            continue
        hosts.pop(h, None)
    return hosts


def _launch_cmd(args, node_rank, nnodes, master_addr):
    cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
           f"--node_rank={node_rank}", f"--nnodes={nnodes}",
           f"--nproc_per_node={args.nproc_per_node}",
           f"--master_addr={master_addr}",
           f"--master_port={args.master_port}",
           f"--max_restarts={args.max_restarts}",
           f"--restart_backoff={args.restart_backoff}"] + \
          (["--elastic_training"] if args.elastic_training else [])
    if args.module:
        cmd.append("--module")
    if args.no_python:
        cmd.append("--no_python")
    return cmd + [args.user_script] + list(args.user_args)


def main(argv=None):
    args = parse_args(argv)
    if args.ds_report:
        from ..env_report import main as report
        report()
        return 0
    if not args.user_script:
        logger.error("no user script given (see --help)")
        return 2

    resources = fetch_hostfile(args.hostfile)
    resources = filter_resources(resources, args.include, args.exclude)
    if args.num_nodes > 0:
        resources = dict(list(resources.items())[:args.num_nodes])

    multinode = bool(resources) and (len(resources) > 1 or args.force_multi)
    if not multinode:
        # single node: run the per-node launcher in-process
        master = args.master_addr or "127.0.0.1"
        cmd = _launch_cmd(args, node_rank=0, nnodes=1, master_addr=master)
        logger.info(f"cmd = {' '.join(map(shlex.quote, cmd))}")
        return subprocess.call(cmd)

    hosts = list(resources)
    master = args.master_addr or hosts[0]
    if args.launcher in ("openmpi", "mpich", "mvapich", "slurm"):
        # mpirun/srun own the fan-out: emit ONE local command; remote
        # ranks bootstrap from the transport env (comm.init_distributed
        # discovery)
        from .multinode_runner import get_runner
        env = dict(os.environ, MASTER_ADDR=master,
                   MASTER_PORT=str(args.master_port))
        runner = get_runner(args.launcher, args, resources)
        cmd = runner.get_cmd(env)
        logger.info(f"cmd = {' '.join(map(shlex.quote, cmd))}")
        return subprocess.call(cmd, env=env)

    env_fwd = {k: v for k, v in os.environ.items()
               if k.startswith(("DSTPU_", "JAX_", "XLA_", "TPU_",
                                "PYTHON", "LIBTPU"))}
    env_str = " ".join(f"{k}={shlex.quote(v)}" for k, v in env_fwd.items())
    procs = []
    if args.launcher == "pdsh" and shutil.which("pdsh") is None:
        logger.warning("pdsh not found; falling back to ssh")
        args.launcher = "ssh"
    for rank, host in enumerate(hosts):
        node_cmd = _launch_cmd(args, node_rank=rank, nnodes=len(hosts),
                               master_addr=master)
        remote = (f"cd {shlex.quote(os.getcwd())} && {env_str} "
                  + " ".join(map(shlex.quote, node_cmd)))
        if args.launcher == "pdsh":
            full = ["pdsh", "-w", host] + shlex.split(args.launcher_args) + \
                [remote]
        else:
            full = ["ssh"] + shlex.split(args.launcher_args) + \
                [host, remote]
        logger.info(f"{host}: {' '.join(map(shlex.quote, full))}")
        procs.append(subprocess.Popen(full))
    rc = 0
    for proc in procs:
        rc = proc.wait() or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
