from .comm import (ReduceOp, init_distributed, is_initialized, get_rank,
                   get_world_size, get_local_rank, barrier, broadcast_object,
                   destroy_process_group, all_reduce, all_gather,
                   all_gather_coalesced, reduce_scatter_coalesced,
                   reduce_scatter, all_to_all, broadcast, ppermute,
                   send_recv_next, send_recv_prev, axis_index, axis_size,
                   log_summary,
                   # reference-name compatibility surface
                   all_gather_into_tensor, allgather_fn,
                   reduce_scatter_tensor, reduce_scatter_fn,
                   all_to_all_single, reduce, gather, scatter, new_group,
                   get_global_rank, monitored_barrier, isend, irecv, send,
                   recv, has_all_gather_into_tensor,
                   has_reduce_scatter_tensor,
                   # compression-aware dispatch accounting
                   comm_stats, comm_per_op_stats, reset_comm_stats)
from .compression import (CommCompressionConfig, configure_comm_compression,
                          get_comm_compression, reset_comm_compression)
from .logging import CommsLogger, get_comms_logger, configure_comms_logger
