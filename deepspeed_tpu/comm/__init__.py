from .comm import (ReduceOp, init_distributed, is_initialized, get_rank,
                   get_world_size, get_local_rank, barrier, broadcast_object,
                   destroy_process_group, all_reduce, all_gather,
                   reduce_scatter, all_to_all, broadcast, ppermute,
                   send_recv_next, send_recv_prev, axis_index, axis_size,
                   log_summary)
from .logging import CommsLogger, get_comms_logger, configure_comms_logger
