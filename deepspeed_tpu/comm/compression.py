"""``comm_compression`` — policy for the compression-aware comm dispatch.

Every device-plane collective in :mod:`deepspeed_tpu.comm.comm` consults
this module's process-global config before tracing the XLA op. Per
collective the policy is one of:

- ``"off"``   — the escape hatch: the wrapper traces the EXACT same
  ``jax.lax`` call as before the dispatch refactor, so the compiled
  program is byte-identical to an uncompressed build.
- ``"fp32"``  — route through the explicit dispatch implementations but
  keep full-precision wire payloads. Numerically ~equal to ``off`` (the
  reduction order changes), NOT bitwise; exists so before/after byte
  telemetry is measured through the same instrumentation.
- ``"int8"``  — blockwise int8 wire payload + per-block f32 scales
  (ZeRO++ qwZ/qgZ, arxiv 2306.10209; EQuARX-style XLA-native lowering,
  arxiv 2506.17615).
- ``"fp8_block"`` — same blockwise codec with an fp8 (e4m3) carrier;
  needs a jaxlib with ``jnp.float8_e4m3fn``.

``hierarchical`` additionally turns the quantized reduce-scatter into the
two-level ZeRO++ gradient exchange: full-precision intra-host
reduce-scatter along the inner (local) subaxis, quantized inter-host
exchange along the outer (host) subaxis — see comm/quantized.py and
parallel/topology.hierarchical_axis_groups.

The config is process-global (like the comms logger): collectives are
traced inside compiled programs, so the policy must be fixed before the
step function compiles. ``DeepSpeedEngine`` installs it from the
``"comm_compression"`` JSON block at init.
"""

import dataclasses
from typing import Optional, Sequence, Tuple

from ..runtime.config_utils import ConfigError, DeepSpeedConfigModel

#: collectives the dispatch can compress (ppermute is point-to-point and
#: stays full precision; `scatter` rides the broadcast policy — it IS a
#: broadcast on the wire)
COMPRESSIBLE_OPS = ("all_reduce", "all_gather", "reduce_scatter",
                    "all_to_all", "broadcast")
POLICIES = ("off", "fp32", "int8", "fp8_block")


@dataclasses.dataclass
class CommCompressionConfig(DeepSpeedConfigModel):
    """The ``"comm_compression"`` config block (docs/comm.md)."""
    enabled: bool = False
    #: per-collective policy: off | fp32 | int8 | fp8_block
    all_reduce: str = "off"
    all_gather: str = "off"
    reduce_scatter: str = "off"
    all_to_all: str = "off"
    broadcast: str = "off"
    #: values per f32 scale block of the blockwise wire codec
    block_size: int = 256
    #: mesh axes whose collectives may compress; collectives over any other
    #: axis (pipe/model/seq) always run at full precision
    allowed_axes: Sequence[str] = ("data", "expert")
    #: two-level reduce-scatter (intra-host full precision, inter-host
    #: quantized) when the axis spans hosts
    hierarchical: bool = True
    #: members of the compressed axis per host; 0 = auto
    #: (jax.local_device_count()). The CPU fake-multichip tests set this
    #: explicitly to model a multi-host wire on one machine.
    devices_per_host: int = 0
    #: tensors smaller than this many bytes never compress — the scale
    #: overhead and the extra rounding aren't worth it (docs/comm.md,
    #: "when not to quantize")
    min_bytes: int = 2048

    def validate(self):
        for op in COMPRESSIBLE_OPS:
            pol = getattr(self, op)
            if pol not in POLICIES:
                raise ConfigError(
                    f"comm_compression.{op} must be one of {POLICIES}, "
                    f"got {pol!r}")
            if pol == "fp8_block":
                from ..ops.quant_core import FP8_DTYPE
                if FP8_DTYPE is None:
                    raise ConfigError(
                        "comm_compression: fp8_block needs a jaxlib with "
                        "float8_e4m3fn; use int8")
        if self.block_size < 1:
            raise ConfigError("comm_compression.block_size must be >= 1")
        if self.devices_per_host < 0:
            raise ConfigError(
                "comm_compression.devices_per_host must be >= 0")
        if self.min_bytes < 0:
            raise ConfigError("comm_compression.min_bytes must be >= 0")
        self.allowed_axes = tuple(self.allowed_axes)

    # ---------------------------------------------------------------- policy
    def _axis_allowed(self, axis_name) -> bool:
        axes = axis_name if isinstance(axis_name, (tuple, list)) \
            else (axis_name,)
        return all(str(a) in self.allowed_axes for a in axes)

    def policy_for(self, op: str, axis_name, nbytes: int) -> str:
        """Effective policy for one traced collective call."""
        if not self.enabled:
            return "off"
        pol = getattr(self, op, "off")
        if pol == "off":
            return "off"
        if not self._axis_allowed(axis_name):
            return "off"
        if pol in ("int8", "fp8_block") and nbytes < self.min_bytes:
            # still dispatch (byte accounting stays comparable), but keep
            # the payload full precision
            return "fp32"
        return pol

    def local_members(self, axis_size: int) -> int:
        """Members of a size-``axis_size`` compressed axis that share a
        host: the configured devices_per_host, else the process-local
        device count, clamped into a valid (host, local) split. Returns 0
        when no meaningful split exists (single host or indivisible)."""
        n = self.devices_per_host
        if n == 0:
            try:
                import jax
                n = jax.local_device_count()
            except Exception:
                return 0
        if n <= 1 or n >= axis_size or axis_size % n:
            return 0
        return n

    @property
    def zero_path_active(self) -> bool:
        """True when the engine should route ZeRO param/grad exchange
        through the explicit (shard_map) collective path: any policy a
        ZeRO step uses is non-off. ``fp32`` counts — it is the measured
        byte baseline for the compressed path."""
        return self.enabled and any(
            getattr(self, op) != "off"
            for op in ("all_reduce", "all_gather", "reduce_scatter"))


_CC = CommCompressionConfig()


def get_comm_compression() -> CommCompressionConfig:
    return _CC


def configure_comm_compression(config) -> CommCompressionConfig:
    """Install the process-global policy. Accepts a config object or the
    raw JSON dict of the ``comm_compression`` block."""
    global _CC
    if isinstance(config, dict):
        config = CommCompressionConfig.from_dict(config)
    _CC = config
    return _CC


def reset_comm_compression():
    global _CC
    _CC = CommCompressionConfig()
