"""deepspeed_tpu.comm — the communication layer.

TPU-native re-design of the reference comm wrapper (deepspeed/comm/comm.py:
torch.distributed-compatible API over NCCL). On TPU there are two distinct
planes, and this module covers both:

1. **Host/control plane** — process bootstrap and eager cross-host ops:
   ``init_distributed`` → ``jax.distributed.initialize`` (the reference's
   rendezvous, comm.py:526), ``get_rank``/``get_world_size`` →
   process indices, ``barrier``/``broadcast_obj`` via multihost utils.

2. **Device/compute plane** — collectives *inside* compiled programs:
   thin named wrappers over ``jax.lax`` collectives (psum/all_gather/
   psum_scatter/all_to_all/ppermute) for use under ``shard_map``. Each wrapper
   routes through ``timed_op`` so the CommsLogger records op/size/participants
   exactly like the reference's @timed_op (comm.py:104) — at trace time, since
   XLA owns execution scheduling.

The reference's capability fallbacks (reduce_scatter_fn → allgather+reduce,
comm.py:239) are unnecessary: XLA provides every primitive on every backend.
"""

import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import logger
# submodule import (not the telemetry package) — keeps the
# comm <-> telemetry.export import graph acyclic
from ..telemetry.trace import get_tracer
from .logging import get_comms_logger

_INITIALIZED = False


class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "prod"


# --------------------------------------------------------------------------
# Host/control plane
# --------------------------------------------------------------------------

def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method: Optional[str] = None,
                     dist_init_required: Optional[bool] = None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1):
    """Bootstrap multi-host JAX. Mirrors deepspeed.init_distributed
    (comm.py:526) including env-based discovery (comm.py:591-689): honors
    the launcher's WORLD_SIZE/RANK/MASTER_ADDR/MASTER_PORT, plus OMPI_* and
    SLURM_* variables.

    Single-process (the common TPU dev loop and the CI fake-multichip mode)
    is a no-op: jax already sees its local devices.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return

    env = os.environ
    nprocs = world_size if world_size > 0 else int(
        env.get("DSTPU_NUM_PROCESSES",
                env.get("WORLD_SIZE", env.get("OMPI_COMM_WORLD_SIZE",
                                              env.get("SLURM_NTASKS", "1")))))
    proc_id = rank if rank >= 0 else int(
        env.get("RANK", env.get("OMPI_COMM_WORLD_RANK", env.get("SLURM_PROCID", "0"))))

    # do NOT touch jax.devices()/process_count() before initialize — that
    # would initialize the XLA backend and make jax.distributed.initialize
    # raise (it must run first in the process)
    if nprocs > 1 and not jax.distributed.is_initialized():
        coordinator = init_method
        if coordinator is None:
            addr = env.get("MASTER_ADDR", "127.0.0.1")
            port = env.get("MASTER_PORT", str(distributed_port))
            coordinator = f"{addr}:{port}"
        if env.get("JAX_PLATFORMS", "").startswith("cpu") or \
                env.get("DSTPU_ACCELERATOR") == "cpu":
            # multi-process CPU backend needs cross-host collectives
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        if verbose:
            logger.info(
                f"Initializing jax.distributed: coordinator={coordinator} "
                f"rank={proc_id} world={nprocs}")
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=nprocs,
                                   process_id=proc_id)
    _INITIALIZED = True


def _dist_state():
    """The jax.distributed global state (None outside multi-process runs).
    The control plane below reads it directly — backend-independent, so it
    works even when a device plugin shadows the default backend."""
    try:
        from jax._src import distributed
        if distributed.global_state.client is not None:
            return distributed.global_state
    except Exception:
        pass
    return None


def is_initialized():
    return _INITIALIZED or _dist_state() is not None


def get_rank(group=None) -> int:
    gs = _dist_state()
    return gs.process_id if gs is not None else jax.process_index()


def get_world_size(group=None) -> int:
    gs = _dist_state()
    return gs.num_processes if gs is not None else jax.process_count()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", 0))


_barrier_count = 0


def barrier(group=None, timeout_ms: int = 600_000):
    """Cross-process barrier over the coordination service (GRPC) — no
    device collective, so it works on any backend mix. Falls back to the
    device-collective sync when the runtime is multi-process without a
    jax.distributed client (e.g. an externally-bootstrapped TPU pod)."""
    global _barrier_count
    gs = _dist_state()
    if gs is not None and gs.num_processes > 1:
        _barrier_count += 1
        gs.client.wait_at_barrier(f"dstpu_barrier_{_barrier_count}",
                                  timeout_ms)
    elif jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("deepspeed_tpu.barrier")


def broadcast_object(obj, src: int = 0):
    """Host-level object broadcast via the coordination service key-value
    store (reference p2p pickled-object sends, pipe/p2p.py:100). The entry
    is deleted after every rank has read it (no coordinator KV leak)."""
    global _barrier_count
    gs = _dist_state()
    if gs is None or gs.num_processes <= 1:
        if gs is None and jax.process_count() > 1:
            from jax.experimental import multihost_utils
            return multihost_utils.broadcast_one_to_all(
                obj, is_source=jax.process_index() == src)
        return obj
    import base64
    import pickle
    _barrier_count += 1
    key = f"dstpu_bcast_{_barrier_count}"
    if gs.process_id == src:
        payload = base64.b64encode(pickle.dumps(obj)).decode("ascii")
        gs.client.key_value_set(key, payload)
        out = obj
    else:
        payload = gs.client.blocking_key_value_get(key, 600_000)
        out = pickle.loads(base64.b64decode(payload))
    gs.client.wait_at_barrier(f"{key}_done", 600_000)
    if gs.process_id == src:
        try:
            gs.client.key_value_delete(key)
        except Exception:
            pass  # older jaxlib without delete: entry persists, job still OK
    return out


def destroy_process_group():
    global _INITIALIZED
    if jax.process_count() > 1:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    _INITIALIZED = False


# --------------------------------------------------------------------------
# Device/compute plane — collectives for use inside shard_map.
#
# Every wrapper routes through ONE compression-aware dispatch point: the
# policy from comm/compression.py (the `comm_compression` config block)
# decides per call whether to trace the plain lax op (policy "off" — the
# bitwise escape hatch: byte-identical programs to an uncompressed build),
# the full-precision explicit path ("fp32"), or the blockwise-quantized
# wire implementations in comm/quantized.py ("int8"/"fp8_block").
# Accounting records WIRE bytes per op — what a ring implementation puts on
# each member's links, compressed size when a codec ran — split into
# intra-host and inter-host traffic when the (host, local) layout is known.
# --------------------------------------------------------------------------

from .compression import get_comm_compression

_SUMLIKE = (ReduceOp.SUM, ReduceOp.AVG)


def _size_bytes(x):
    try:
        return x.size * x.dtype.itemsize
    except Exception:
        return 0


def _participants(axis_name) -> int:
    """Static axis size at trace time (psum of a python 1 folds to a
    constant — no HLO is emitted); 0 when the axis is unbound
    (eager/host context)."""
    try:
        return int(lax.psum(1, axis_name))
    except Exception:
        return 0


# Baseline per-member ring wire-byte model, from the logical payload bytes:
# all_gather's input is the SHARD (it ships n-1 copies of it); reduce_
# scatter/all_to_all move (n-1)/n of the full input per member; all_reduce
# = reduce-scatter + all-gather = 2(n-1)/n; broadcast lowers to a masked
# psum (see broadcast()) so it pays the full all-reduce ring, ~2x an
# optimal broadcast; scatter lowers to broadcast + local slice and
# inherits its wire cost under its own op name.
_BASE_WIRE = {
    "all_reduce": lambda nb, n: 2 * (n - 1) * nb // n,
    "all_gather": lambda nb, n: (n - 1) * nb,
    "reduce_scatter": lambda nb, n: (n - 1) * nb // n,
    "all_to_all": lambda nb, n: (n - 1) * nb // n,
    "broadcast": lambda nb, n: 2 * (n - 1) * nb // n,
    "scatter": lambda nb, n: 2 * (n - 1) * nb // n,
    "ppermute": lambda nb, n: nb,
}


def _base_wire(op: str, logical: int, n: int) -> int:
    if n <= 1:
        # unbound axis (host context) or single member: nothing crosses a
        # link for n==1; keep the logical size for n==0 so eager callers
        # still see their payload accounted
        return logical if n == 0 else 0
    return _BASE_WIRE[op](logical, n)


# cumulative collective accounting, maintained unconditionally — a few
# integer adds at trace time. The flight recorder diffs this per step
# record to show how much collective traffic the anomalous step carried,
# without scanning the span ring.
_COMM_OPS = 0
_COMM_WIRE_BYTES = 0
_COMM_LOGICAL_BYTES = 0
_COMM_INTER_BYTES = 0
_COMM_INTRA_BYTES = 0
_COMM_PER_OP: dict = {}


def comm_stats():
    """Cumulative collective accounting traced through the wrappers.

    ``bytes`` is WIRE bytes (per-member link traffic, compressed size when
    a quantized policy ran); ``logical_bytes`` is the uncompressed payload
    the caller handed in; ``inter_host_bytes``/``intra_host_bytes`` split
    the wire traffic by link scope when the (host, local) layout is known
    (comm_compression.devices_per_host, else the process-local device
    count)."""
    return {"ops": _COMM_OPS, "bytes": _COMM_WIRE_BYTES,
            "logical_bytes": _COMM_LOGICAL_BYTES,
            "inter_host_bytes": _COMM_INTER_BYTES,
            "intra_host_bytes": _COMM_INTRA_BYTES}


def comm_per_op_stats():
    """Per-op traced collective counts ({op name: count}). Kept apart
    from :func:`comm_stats` — whose flat numeric dict the flight
    recorder diffs per step record — so the dispatch-conformance
    auditor (analysis/hlo_audit_rules.py HLO006) can reconcile a
    compiled module's collective kinds against what the dispatch
    actually traced."""
    return dict(_COMM_PER_OP)


def reset_comm_stats():
    global _COMM_OPS, _COMM_WIRE_BYTES, _COMM_LOGICAL_BYTES
    global _COMM_INTER_BYTES, _COMM_INTRA_BYTES
    _COMM_OPS = _COMM_WIRE_BYTES = _COMM_LOGICAL_BYTES = 0
    _COMM_INTER_BYTES = _COMM_INTRA_BYTES = 0
    _COMM_PER_OP.clear()


def _split_inter(wire: int, n: int) -> int:
    """Inter-host share of a FLAT collective's wire bytes: with L members
    per host laid out host-major, H = n/L of the n ring links cross hosts,
    and every ring link carries the same traffic — so H/n of the bytes are
    inter-host. 0 when the axis fits on one host (or layout unknown)."""
    if n <= 1:
        return 0
    local = get_comm_compression().local_members(n)
    if not local:
        return 0
    return wire * (n // local) // n


def _account(op, logical, wire, n, axis_name, inter=None):
    """Record one traced collective into the cumulative counters + comms
    logger. ``inter``: explicit inter-host wire bytes (hierarchical ops
    know their legs); default = the flat ring-link model."""
    global _COMM_OPS, _COMM_WIRE_BYTES, _COMM_LOGICAL_BYTES
    global _COMM_INTER_BYTES, _COMM_INTRA_BYTES
    if inter is None:
        inter = _split_inter(wire, n)
    _COMM_OPS += 1
    _COMM_WIRE_BYTES += wire
    _COMM_LOGICAL_BYTES += logical
    _COMM_INTER_BYTES += inter
    _COMM_INTRA_BYTES += wire - inter
    _COMM_PER_OP[op] = _COMM_PER_OP.get(op, 0) + 1
    cl = get_comms_logger()
    if cl is not None and cl.enabled:
        cl.append(op, wire, str(axis_name))
    return inter


def _comm_span(name, logical, wire, axis_name, participants, policy="off"):
    """Telemetry span for one collective: op kind, logical payload bytes,
    wire bytes, mesh axis, participant count, active compression policy
    (bus bandwidth is derived at export time from WIRE bytes ÷ measured
    duration). Collectives inside compiled programs are spanned at TRACE
    time — XLA owns execution scheduling, so the per-execution wall time
    of a fused collective is only visible to ``jax.profiler``; these spans
    give per-op byte/shape accounting and trace-position instead."""
    tracer = get_tracer()
    if not tracer.enabled:
        return tracer.span(name)     # the shared no-op singleton
    args = {"op": name, "bytes": logical, "wire_bytes": wire,
            "axis": str(axis_name), "participants": participants}
    if policy != "off":
        args["policy"] = policy
    return tracer.span(name, cat="comm", args=args)


def _dispatch(op, x, axis_name, quantizable=True):
    """The single dispatch decision: (policy, participants, logical bytes).
    policy "off" means: trace the plain lax op (bitwise escape hatch)."""
    logical = _size_bytes(x)
    n = _participants(axis_name)
    cc = get_comm_compression()
    policy = cc.policy_for(op, axis_name, logical) if (quantizable and
                                                       n > 1) else "off"
    return cc, policy, n, logical


def all_reduce(x, op: str = ReduceOp.SUM, axis_name="data"):
    """lax.psum/pmax/pmin over a mesh axis. [COLLECTIVE]"""
    cc, policy, n, logical = _dispatch(
        "all_reduce", x, axis_name, quantizable=op in _SUMLIKE)
    if policy in ("int8", "fp8_block") and x.size % n == 0:
        from .quantized import (quantized_all_reduce,
                                quantized_all_reduce_wire_bytes)
        wire = quantized_all_reduce_wire_bytes(x.size, n, cc.block_size)
        _account("all_reduce", logical, wire, n, axis_name)
        with _comm_span("all_reduce", logical, wire, axis_name, n, policy):
            return quantized_all_reduce(x, axis_name, n, cc.block_size,
                                        policy, avg=op == ReduceOp.AVG)
    wire = _base_wire("all_reduce", logical, n)
    _account("all_reduce", logical, wire, n, axis_name)
    with _comm_span("all_reduce", logical, wire, axis_name, n):
        if op == ReduceOp.SUM:
            return lax.psum(x, axis_name)
        if op == ReduceOp.AVG:
            return lax.pmean(x, axis_name)
        if op == ReduceOp.MAX:
            return lax.pmax(x, axis_name)
        if op == ReduceOp.MIN:
            return lax.pmin(x, axis_name)
        if op == ReduceOp.PRODUCT:
            # EXACT product via all_gather + prod (an exp(psum(log)) trick
            # NaNs on x<=0 and loses integer precision past 2^24). PRODUCT
            # reduces are rare and small; the O(world) gather is the honest
            # primitive.
            return jnp.prod(lax.all_gather(x, axis_name), axis=0)
    raise ValueError(f"Unsupported reduce op {op}")


def all_gather(x, axis_name="data", axis: int = 0, tiled: bool = True):
    """Gather shards along `axis` from every member of the mesh axis.
    Policy int8/fp8_block ships the shard blockwise-quantized (the ZeRO-3
    param-gather wire, ZeRO++ qwZ)."""
    cc, policy, n, logical = _dispatch(
        "all_gather", x, axis_name, quantizable=tiled)
    if policy in ("int8", "fp8_block"):
        from .quantized import (quantized_all_gather,
                                quantized_all_gather_wire_bytes)
        wire = quantized_all_gather_wire_bytes(x.size, n, cc.block_size)
        _account("all_gather", logical, wire, n, axis_name)
        with _comm_span("all_gather", logical, wire, axis_name, n, policy):
            return quantized_all_gather(x, axis_name, axis, n,
                                        cc.block_size, policy)
    wire = _base_wire("all_gather", logical, n)
    _account("all_gather", logical, wire, n, axis_name)
    with _comm_span("all_gather", logical, wire, axis_name, n):
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name="data", axis: int = 0, op: str = ReduceOp.SUM):
    """psum_scatter: the ZeRO-2/3 gradient primitive
    (reference runtime/comm/coalesced_collectives.py:29).

    Policy int8/fp8_block quantizes the exchange; with
    ``comm_compression.hierarchical`` and a known (host, local) layout it
    becomes the two-level ZeRO++ qgZ path — full-precision reduce inside
    each host, quantized exchange across hosts — so only the compressed
    payload crosses the inter-host links."""
    cc, policy, n, logical = _dispatch(
        "reduce_scatter", x, axis_name, quantizable=op in _SUMLIKE)
    if policy in ("int8", "fp8_block") and x.shape[axis] % n == 0:
        from .quantized import (
            hierarchical_reduce_scatter,
            hierarchical_reduce_scatter_wire_bytes,
            quantized_reduce_scatter, quantized_reduce_scatter_wire_bytes)
        from ..parallel.topology import hierarchical_axis_groups
        avg = op == ReduceOp.AVG
        local = cc.local_members(n) if cc.hierarchical else 0
        if local:
            intra_g, inter_g = hierarchical_axis_groups(n, local)
            intra_b, inter_b = hierarchical_reduce_scatter_wire_bytes(
                x.size, n, local, cc.block_size, x.dtype.itemsize)
            wire = intra_b + inter_b
            _account("reduce_scatter", logical, wire, n, axis_name,
                     inter=inter_b)
            with _comm_span("reduce_scatter", logical, wire, axis_name, n,
                            policy):
                return hierarchical_reduce_scatter(
                    x, axis_name, axis, n, local, intra_g, inter_g,
                    cc.block_size, policy, avg)
        wire = quantized_reduce_scatter_wire_bytes(x.size, n, cc.block_size)
        _account("reduce_scatter", logical, wire, n, axis_name)
        with _comm_span("reduce_scatter", logical, wire, axis_name, n,
                        policy):
            return quantized_reduce_scatter(x, axis_name, axis, n,
                                            cc.block_size, policy, avg)
    wire = _base_wire("reduce_scatter", logical, n)
    _account("reduce_scatter", logical, wire, n, axis_name)
    with _comm_span("reduce_scatter", logical, wire, axis_name, n):
        out = lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                               tiled=True)
        if op == ReduceOp.AVG:
            out = out / axis_size(axis_name)
        return out


# ---------------------------------------------------------------- coalesced
# Bucketed forms for the overlap schedule (runtime/zero/overlap_schedule.py,
# reference runtime/comm/coalesced_collectives.py): a BUCKET of leaves moves
# in ONE collective. Accounting stays honest by construction — one op is
# recorded whose logical/wire bytes are the SUMS of the per-leaf models, so
# N buckets and N leaves log identical byte totals and differ only in the
# op count (the delta the flight recorder diffs between schedules). Under a
# quantized policy every leaf is encoded with exactly the per-leaf codec
# (same blocks, same scales) and only wire payloads are concatenated, so
# the dequantized values are bitwise identical to the per-leaf collectives.

def all_gather_coalesced(xs: Sequence, axis_name="data",
                         axes: Optional[Sequence[int]] = None):
    """Gather a bucket of shards in one collective; returns the per-leaf
    gathered tensors (each = ``all_gather(x, axis_name, axis)``)."""
    xs = list(xs)
    axes = [0] * len(xs) if axes is None else list(axes)
    logical = sum(_size_bytes(x) for x in xs)
    n = _participants(axis_name)
    cc = get_comm_compression()
    policy = cc.policy_for("all_gather", axis_name, logical) if n > 1 \
        else "off"
    if policy in ("int8", "fp8_block"):
        from .quantized import (quantized_all_gather_coalesced,
                                quantized_all_gather_coalesced_wire_bytes)
        wire = quantized_all_gather_coalesced_wire_bytes(
            [x.size for x in xs], n, cc.block_size)
        _account("all_gather", logical, wire, n, axis_name)
        with _comm_span("all_gather", logical, wire, axis_name, n, policy):
            return quantized_all_gather_coalesced(xs, axis_name, axes, n,
                                                  cc.block_size, policy)
    wire = sum(_base_wire("all_gather", _size_bytes(x), n) for x in xs)
    _account("all_gather", logical, wire, n, axis_name)
    with _comm_span("all_gather", logical, wire, axis_name, n):
        if n <= 1:
            return [lax.all_gather(x, axis_name, axis=a, tiled=True)
                    for x, a in zip(xs, axes)]
        flat = jnp.concatenate([x.reshape(-1) for x in xs])
        g = lax.all_gather(flat, axis_name)          # [n, total]
        outs = []
        off = 0
        for x, axis in zip(xs, axes):
            seg = g[:, off:off + x.size].reshape((n,) + x.shape)
            off += x.size
            out = jnp.moveaxis(seg, 0, axis)
            shape = list(x.shape)
            shape[axis] *= n
            outs.append(out.reshape(shape))
        return outs


def reduce_scatter_coalesced(xs: Sequence, axis_name="data",
                             axes: Optional[Sequence[int]] = None,
                             op: str = ReduceOp.SUM):
    """Reduce-scatter a bucket of full-size tensors in one collective;
    returns the per-leaf reduced shards (each =
    ``reduce_scatter(x, axis_name, axis, op)``)."""
    xs = list(xs)
    axes = [0] * len(xs) if axes is None else list(axes)
    logical = sum(_size_bytes(x) for x in xs)
    n = _participants(axis_name)
    cc = get_comm_compression()
    policy = cc.policy_for("reduce_scatter", axis_name, logical) \
        if (op in _SUMLIKE and n > 1) else "off"
    if policy in ("int8", "fp8_block") and \
            all(x.shape[a] % n == 0 for x, a in zip(xs, axes)):
        from .quantized import (
            hierarchical_reduce_scatter_coalesced,
            hierarchical_reduce_scatter_coalesced_wire_bytes,
            quantized_reduce_scatter_coalesced,
            quantized_reduce_scatter_coalesced_wire_bytes)
        from ..parallel.topology import hierarchical_axis_groups
        avg = op == ReduceOp.AVG
        sizes = [x.size for x in xs]
        local = cc.local_members(n) if cc.hierarchical else 0
        if local:
            intra_g, inter_g = hierarchical_axis_groups(n, local)
            intra_b, inter_b = \
                hierarchical_reduce_scatter_coalesced_wire_bytes(
                    sizes, n, local, cc.block_size, xs[0].dtype.itemsize)
            wire = intra_b + inter_b
            _account("reduce_scatter", logical, wire, n, axis_name,
                     inter=inter_b)
            with _comm_span("reduce_scatter", logical, wire, axis_name, n,
                            policy):
                return hierarchical_reduce_scatter_coalesced(
                    xs, axis_name, axes, n, local, intra_g, inter_g,
                    cc.block_size, policy, avg)
        wire = quantized_reduce_scatter_coalesced_wire_bytes(
            sizes, n, cc.block_size)
        _account("reduce_scatter", logical, wire, n, axis_name)
        with _comm_span("reduce_scatter", logical, wire, axis_name, n,
                        policy):
            return quantized_reduce_scatter_coalesced(
                xs, axis_name, axes, n, cc.block_size, policy, avg)
    wire = sum(_base_wire("reduce_scatter", _size_bytes(x), n) for x in xs)
    _account("reduce_scatter", logical, wire, n, axis_name)
    with _comm_span("reduce_scatter", logical, wire, axis_name, n):
        if n <= 1:
            outs = [lax.psum_scatter(x, axis_name, scatter_dimension=a,
                                     tiled=True) for x, a in zip(xs, axes)]
        else:
            rows = jnp.concatenate(
                [jnp.moveaxis(x, a, 0).reshape(n, -1)
                 for x, a in zip(xs, axes)], axis=1)       # [n, total//n]
            red = lax.psum_scatter(rows.reshape(-1), axis_name,
                                   scatter_dimension=0, tiled=True)
            outs = []
            off = 0
            for x, a in zip(xs, axes):
                sz = x.size // n
                rest = tuple(s for i, s in enumerate(x.shape) if i != a)
                seg = red[off:off + sz].reshape((x.shape[a] // n,) + rest)
                off += sz
                outs.append(jnp.moveaxis(seg, 0, a))
        if op == ReduceOp.AVG:
            outs = [o / axis_size(axis_name) for o in outs]
        return outs


def all_to_all(x, axis_name="expert", split_axis: int = 0, concat_axis: int = 0):
    """MoE dispatch/combine primitive (reference sharded_moe.py:90 _AllToAll)."""
    cc, policy, n, logical = _dispatch("all_to_all", x, axis_name)
    if policy in ("int8", "fp8_block") and x.shape[split_axis] % n == 0:
        from .quantized import (quantized_all_to_all,
                                quantized_all_to_all_wire_bytes)
        wire = quantized_all_to_all_wire_bytes(x.size, n, cc.block_size)
        _account("all_to_all", logical, wire, n, axis_name)
        with _comm_span("all_to_all", logical, wire, axis_name, n, policy):
            return quantized_all_to_all(x, axis_name, split_axis,
                                        concat_axis, n, cc.block_size,
                                        policy)
    wire = _base_wire("all_to_all", logical, n)
    _account("all_to_all", logical, wire, n, axis_name)
    with _comm_span("all_to_all", logical, wire, axis_name, n):
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def _broadcast_impl(x, src, axis_name, op_label):
    """Shared broadcast lowering (broadcast + scatter account under their
    own op names but put the same masked-psum ring on the wire)."""
    cc, policy, n, logical = _dispatch("broadcast", x, axis_name)
    if policy in ("int8", "fp8_block"):
        from .quantized import (quantized_broadcast,
                                quantized_broadcast_wire_bytes)
        wire = quantized_broadcast_wire_bytes(x.size, n, cc.block_size)
        _account(op_label, logical, wire, n, axis_name)
        with _comm_span(op_label, logical, wire, axis_name, n, policy):
            return quantized_broadcast(x, src, axis_name, n, cc.block_size,
                                       policy)
    wire = _base_wire("broadcast", logical, n)
    _account(op_label, logical, wire, n, axis_name)
    with _comm_span(op_label, logical, wire, axis_name, n):
        idx = lax.axis_index(axis_name)
        # where, not multiply: non-src members may hold NaN/inf placeholders
        # (torch broadcast ignores their buffers entirely)
        return lax.psum(jnp.where(idx == src, x, jnp.zeros_like(x)),
                        axis_name)


def broadcast(x, src: int = 0, axis_name="data"):
    """src's value on every member, as psum of the masked value.

    XLA exposes no one-to-many collective inside SPMD programs (ppermute
    requires unique sources), so broadcast = all-reduce of a one-hot
    contribution. Cost: a ring all-reduce moves ~2·N per link regardless of
    world size — about 2x an optimal broadcast and CONSTANT in world size,
    which is why this is also how GSPMD itself materializes broadcasts."""
    return _broadcast_impl(x, src, axis_name, "broadcast")


def ppermute(x, perm: Sequence, axis_name="pipe"):
    """Point-to-point ring/pipeline exchange (reference pipe/p2p.py).
    Never compressed: pipeline activations are latency-bound single hops."""
    logical = _size_bytes(x)
    n = _participants(axis_name)
    wire = _base_wire("ppermute", logical, n)
    _account("ppermute", logical, wire, n, axis_name)
    with _comm_span("ppermute", logical, wire, axis_name, n):
        return lax.ppermute(x, axis_name, perm=perm)


def send_recv_next(x, axis_name="pipe"):
    """Shift +1 along axis (stage i → stage i+1), wrapping."""
    n = int(axis_size(axis_name))
    return ppermute(x, [(i, (i + 1) % n) for i in range(n)], axis_name)


def send_recv_prev(x, axis_name="pipe"):
    n = int(axis_size(axis_name))
    return ppermute(x, [(i, (i - 1) % n) for i in range(n)], axis_name)


# --------------------------------------------------------------------------
# Reference-name compatibility surface (deepspeed.comm parity). torch's
# in/out-tensor contracts are functional under XLA (return the result);
# rank-rooted ops are SUPERSETS — every member gets the root's result,
# which costs the same as the rooted op on a ring and is how GSPMD itself
# lowers them.
# --------------------------------------------------------------------------

def all_gather_into_tensor(x, axis_name="data", axis: int = 0):
    """reference comm.py all_gather_into_tensor (functional: returns the
    gathered tensor instead of writing into an output buffer)."""
    return all_gather(x, axis_name, axis=axis)


# reference allgather_fn dispatches to all_gather_into_tensor when the
# backend has it; XLA always does
allgather_fn = all_gather_into_tensor


def reduce_scatter_tensor(x, axis_name="data", axis: int = 0,
                          op: str = ReduceOp.SUM):
    return reduce_scatter(x, axis_name, axis=axis, op=op)


reduce_scatter_fn = reduce_scatter_tensor


def all_to_all_single(x, axis_name="expert", split_axis: int = 0,
                      concat_axis: int = 0):
    return all_to_all(x, axis_name, split_axis=split_axis,
                      concat_axis=concat_axis)


def reduce(x, dst: int = 0, axis_name="data", op: str = ReduceOp.SUM):
    """Rooted reduce; under SPMD every member receives the result (torch
    leaves non-dst outputs undefined — this is a superset)."""
    del dst
    return all_reduce(x, axis_name=axis_name, op=op)


def gather(x, dst: int = 0, axis_name="data", axis: int = 0):
    """Rooted gather; superset semantics (all members get the result)."""
    del dst
    return all_gather(x, axis_name, axis=axis)


def scatter(x, src: int = 0, axis_name="data", axis: int = 0):
    """Member i receives src's i-th shard along ``axis``. Non-src members'
    inputs are fully ignored (broadcast uses where-masking, so NaN/inf
    placeholders are fine). Accounted once, under its OWN op name with the
    broadcast lowering's wire cost (it used to inherit a "broadcast" entry
    at the full-tensor count, which hid its real identity from the
    before/after compression ratios)."""
    full = _broadcast_impl(x, src, axis_name, "scatter")
    n = _participants(axis_name)
    if full.shape[axis] % n:
        raise ValueError(f"scatter: dim {axis} ({full.shape[axis]}) must "
                         f"divide by axis size {n}")
    chunk = full.shape[axis] // n
    return lax.dynamic_slice_in_dim(full, lax.axis_index(axis_name) * chunk,
                                    chunk, axis)


def new_group(ranks):
    """Reference new_group returns a torch process group. XLA collectives
    are mesh-axis-scoped instead: build the mesh with the axes you need
    (parallel/topology.initialize_mesh) and pass the axis name to the
    collectives. The returned rank list works as the ``group`` argument of
    :func:`get_global_rank`."""
    logger.info("comm.new_group: XLA collectives are mesh-axis-scoped; "
                "use initialize_mesh axes for device collectives. "
                "Returning the rank list for host-plane rank mapping.")
    return list(ranks)


def get_global_rank(group=None, group_rank: int = 0) -> int:
    """Map a group-local rank to a global rank (reference comm.py
    get_global_rank). ``group``: a rank list from :func:`new_group`, or
    None for the world group."""
    if group is None:
        return group_rank
    return list(group)[group_rank]


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    """Barrier with logging (reference monitored_barrier; the hang
    diagnostics live in the launcher's failure detection here).
    ``timeout``: datetime.timedelta or seconds, forwarded to the barrier."""
    del group, wait_all_ranks
    logger.info(f"monitored_barrier enter (rank {get_rank()})")
    if timeout is None:
        barrier()
    else:
        seconds = timeout.total_seconds() if hasattr(
            timeout, "total_seconds") else float(timeout)
        barrier(timeout_ms=int(seconds * 1000))
    logger.info(f"monitored_barrier exit (rank {get_rank()})")


def _no_host_p2p(name, alternative):
    raise ValueError(
        f"comm.{name} is not supported on TPU: XLA owns collective "
        f"scheduling inside compiled programs, so host-driven "
        f"point-to-point has no mapping. Use {alternative} inside the "
        f"compiled step (see runtime/pipe/engine.py for the pipeline "
        f"exchange pattern).")


def isend(tensor, dst, **kw):
    _no_host_p2p("isend", "comm.ppermute / send_recv_next")


def irecv(tensor, src, **kw):
    _no_host_p2p("irecv", "comm.ppermute / send_recv_prev")


def send(tensor, dst, **kw):
    _no_host_p2p("send", "comm.ppermute / send_recv_next")


def recv(tensor, src, **kw):
    _no_host_p2p("recv", "comm.ppermute / send_recv_prev")


def has_all_gather_into_tensor() -> bool:
    return True


def has_reduce_scatter_tensor() -> bool:
    return True


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    # psum of a python 1 folds to the static axis size at trace time
    # (lax.axis_size only exists in newer jax releases)
    return lax.psum(1, axis_name)


def log_summary():
    cl = get_comms_logger()
    if cl is not None:
        cl.log_summary()
