"""deepspeed_tpu.comm — the communication layer.

TPU-native re-design of the reference comm wrapper (deepspeed/comm/comm.py:
torch.distributed-compatible API over NCCL). On TPU there are two distinct
planes, and this module covers both:

1. **Host/control plane** — process bootstrap and eager cross-host ops:
   ``init_distributed`` → ``jax.distributed.initialize`` (the reference's
   rendezvous, comm.py:526), ``get_rank``/``get_world_size`` →
   process indices, ``barrier``/``broadcast_obj`` via multihost utils.

2. **Device/compute plane** — collectives *inside* compiled programs:
   thin named wrappers over ``jax.lax`` collectives (psum/all_gather/
   psum_scatter/all_to_all/ppermute) for use under ``shard_map``. Each wrapper
   routes through ``timed_op`` so the CommsLogger records op/size/participants
   exactly like the reference's @timed_op (comm.py:104) — at trace time, since
   XLA owns execution scheduling.

The reference's capability fallbacks (reduce_scatter_fn → allgather+reduce,
comm.py:239) are unnecessary: XLA provides every primitive on every backend.
"""

import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import logger
# submodule import (not the telemetry package) — keeps the
# comm <-> telemetry.export import graph acyclic
from ..telemetry.trace import get_tracer
from .logging import get_comms_logger

_INITIALIZED = False


class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "prod"


# --------------------------------------------------------------------------
# Host/control plane
# --------------------------------------------------------------------------

def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method: Optional[str] = None,
                     dist_init_required: Optional[bool] = None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1):
    """Bootstrap multi-host JAX. Mirrors deepspeed.init_distributed
    (comm.py:526) including env-based discovery (comm.py:591-689): honors
    the launcher's WORLD_SIZE/RANK/MASTER_ADDR/MASTER_PORT, plus OMPI_* and
    SLURM_* variables.

    Single-process (the common TPU dev loop and the CI fake-multichip mode)
    is a no-op: jax already sees its local devices.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return

    env = os.environ
    nprocs = world_size if world_size > 0 else int(
        env.get("DSTPU_NUM_PROCESSES",
                env.get("WORLD_SIZE", env.get("OMPI_COMM_WORLD_SIZE",
                                              env.get("SLURM_NTASKS", "1")))))
    proc_id = rank if rank >= 0 else int(
        env.get("RANK", env.get("OMPI_COMM_WORLD_RANK", env.get("SLURM_PROCID", "0"))))

    # do NOT touch jax.devices()/process_count() before initialize — that
    # would initialize the XLA backend and make jax.distributed.initialize
    # raise (it must run first in the process)
    if nprocs > 1 and not jax.distributed.is_initialized():
        coordinator = init_method
        if coordinator is None:
            addr = env.get("MASTER_ADDR", "127.0.0.1")
            port = env.get("MASTER_PORT", str(distributed_port))
            coordinator = f"{addr}:{port}"
        if env.get("JAX_PLATFORMS", "").startswith("cpu") or \
                env.get("DSTPU_ACCELERATOR") == "cpu":
            # multi-process CPU backend needs cross-host collectives
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        if verbose:
            logger.info(
                f"Initializing jax.distributed: coordinator={coordinator} "
                f"rank={proc_id} world={nprocs}")
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=nprocs,
                                   process_id=proc_id)
    _INITIALIZED = True


def _dist_state():
    """The jax.distributed global state (None outside multi-process runs).
    The control plane below reads it directly — backend-independent, so it
    works even when a device plugin shadows the default backend."""
    try:
        from jax._src import distributed
        if distributed.global_state.client is not None:
            return distributed.global_state
    except Exception:
        pass
    return None


def is_initialized():
    return _INITIALIZED or _dist_state() is not None


def get_rank(group=None) -> int:
    gs = _dist_state()
    return gs.process_id if gs is not None else jax.process_index()


def get_world_size(group=None) -> int:
    gs = _dist_state()
    return gs.num_processes if gs is not None else jax.process_count()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", 0))


_barrier_count = 0


def barrier(group=None, timeout_ms: int = 600_000):
    """Cross-process barrier over the coordination service (GRPC) — no
    device collective, so it works on any backend mix. Falls back to the
    device-collective sync when the runtime is multi-process without a
    jax.distributed client (e.g. an externally-bootstrapped TPU pod)."""
    global _barrier_count
    gs = _dist_state()
    if gs is not None and gs.num_processes > 1:
        _barrier_count += 1
        gs.client.wait_at_barrier(f"dstpu_barrier_{_barrier_count}",
                                  timeout_ms)
    elif jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("deepspeed_tpu.barrier")


def broadcast_object(obj, src: int = 0):
    """Host-level object broadcast via the coordination service key-value
    store (reference p2p pickled-object sends, pipe/p2p.py:100). The entry
    is deleted after every rank has read it (no coordinator KV leak)."""
    global _barrier_count
    gs = _dist_state()
    if gs is None or gs.num_processes <= 1:
        if gs is None and jax.process_count() > 1:
            from jax.experimental import multihost_utils
            return multihost_utils.broadcast_one_to_all(
                obj, is_source=jax.process_index() == src)
        return obj
    import base64
    import pickle
    _barrier_count += 1
    key = f"dstpu_bcast_{_barrier_count}"
    if gs.process_id == src:
        payload = base64.b64encode(pickle.dumps(obj)).decode("ascii")
        gs.client.key_value_set(key, payload)
        out = obj
    else:
        payload = gs.client.blocking_key_value_get(key, 600_000)
        out = pickle.loads(base64.b64decode(payload))
    gs.client.wait_at_barrier(f"{key}_done", 600_000)
    if gs.process_id == src:
        try:
            gs.client.key_value_delete(key)
        except Exception:
            pass  # older jaxlib without delete: entry persists, job still OK
    return out


def destroy_process_group():
    global _INITIALIZED
    if jax.process_count() > 1:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    _INITIALIZED = False


# --------------------------------------------------------------------------
# Device/compute plane — collectives for use inside shard_map
# --------------------------------------------------------------------------

def _size_bytes(x):
    try:
        return x.size * x.dtype.itemsize
    except Exception:
        return 0


# cumulative collective accounting (ops + payload bytes), maintained
# unconditionally — two integer adds at trace time. The flight recorder
# diffs this per step record to show how much collective traffic the
# anomalous step carried, without scanning the span ring.
_COMM_OPS = 0
_COMM_BYTES = 0


def comm_stats():
    """Cumulative {ops, bytes} traced through the collective wrappers."""
    return {"ops": _COMM_OPS, "bytes": _COMM_BYTES}


def _log(name, tensor, axis_name):
    global _COMM_OPS, _COMM_BYTES
    _COMM_OPS += 1
    _COMM_BYTES += _size_bytes(tensor)
    cl = get_comms_logger()
    if cl is not None and cl.enabled:
        cl.append(name, _size_bytes(tensor), str(axis_name))


def _comm_span(name, tensor, axis_name):
    """Telemetry span for one collective: op kind, payload bytes, mesh axis,
    participant count (bus bandwidth is derived at export time from bytes ÷
    measured duration). Collectives inside compiled programs are spanned at
    TRACE time — XLA owns execution scheduling, so the per-execution wall
    time of a fused collective is only visible to ``jax.profiler``; these
    spans give per-op byte/shape accounting and trace-position instead."""
    tracer = get_tracer()
    if not tracer.enabled:
        return tracer.span(name)     # the shared no-op singleton
    try:
        # psum of a python 1 folds to the (static) axis size at trace time
        participants = int(lax.psum(1, axis_name))
    except Exception:                # axis unbound: eager/host context
        participants = 0
    return tracer.span(name, cat="comm",
                       args={"op": name, "bytes": _size_bytes(tensor),
                             "axis": str(axis_name),
                             "participants": participants})


def all_reduce(x, op: str = ReduceOp.SUM, axis_name="data"):
    """lax.psum/pmax/pmin over a mesh axis. [COLLECTIVE]"""
    _log("all_reduce", x, axis_name)
    with _comm_span("all_reduce", x, axis_name):
        if op == ReduceOp.SUM:
            return lax.psum(x, axis_name)
        if op == ReduceOp.AVG:
            return lax.pmean(x, axis_name)
        if op == ReduceOp.MAX:
            return lax.pmax(x, axis_name)
        if op == ReduceOp.MIN:
            return lax.pmin(x, axis_name)
        if op == ReduceOp.PRODUCT:
            # EXACT product via all_gather + prod (an exp(psum(log)) trick
            # NaNs on x<=0 and loses integer precision past 2^24). PRODUCT
            # reduces are rare and small; the O(world) gather is the honest
            # primitive.
            return jnp.prod(lax.all_gather(x, axis_name), axis=0)
    raise ValueError(f"Unsupported reduce op {op}")


def all_gather(x, axis_name="data", axis: int = 0, tiled: bool = True):
    """Gather shards along `axis` from every member of the mesh axis."""
    _log("all_gather", x, axis_name)
    with _comm_span("all_gather", x, axis_name):
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name="data", axis: int = 0, op: str = ReduceOp.SUM):
    """psum_scatter: the ZeRO-2/3 gradient primitive
    (reference runtime/comm/coalesced_collectives.py:29)."""
    _log("reduce_scatter", x, axis_name)
    with _comm_span("reduce_scatter", x, axis_name):
        out = lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                               tiled=True)
        if op == ReduceOp.AVG:
            out = out / axis_size(axis_name)
        return out


def all_to_all(x, axis_name="expert", split_axis: int = 0, concat_axis: int = 0):
    """MoE dispatch/combine primitive (reference sharded_moe.py:90 _AllToAll)."""
    _log("all_to_all", x, axis_name)
    with _comm_span("all_to_all", x, axis_name):
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def broadcast(x, src: int = 0, axis_name="data"):
    """src's value on every member, as psum of the masked value.

    XLA exposes no one-to-many collective inside SPMD programs (ppermute
    requires unique sources), so broadcast = all-reduce of a one-hot
    contribution. Cost: a ring all-reduce moves ~2·N per link regardless of
    world size — about 2x an optimal broadcast and CONSTANT in world size,
    which is why this is also how GSPMD itself materializes broadcasts."""
    _log("broadcast", x, axis_name)
    with _comm_span("broadcast", x, axis_name):
        idx = lax.axis_index(axis_name)
        # where, not multiply: non-src members may hold NaN/inf placeholders
        # (torch broadcast ignores their buffers entirely)
        return lax.psum(jnp.where(idx == src, x, jnp.zeros_like(x)),
                        axis_name)


def ppermute(x, perm: Sequence, axis_name="pipe"):
    """Point-to-point ring/pipeline exchange (reference pipe/p2p.py)."""
    _log("ppermute", x, axis_name)
    with _comm_span("ppermute", x, axis_name):
        return lax.ppermute(x, axis_name, perm=perm)


def send_recv_next(x, axis_name="pipe"):
    """Shift +1 along axis (stage i → stage i+1), wrapping."""
    n = axis_size(axis_name)
    return ppermute(x, [(i, (i + 1) % n) for i in range(n)], axis_name)


def send_recv_prev(x, axis_name="pipe"):
    n = axis_size(axis_name)
    return ppermute(x, [(i, (i - 1) % n) for i in range(n)], axis_name)


# --------------------------------------------------------------------------
# Reference-name compatibility surface (deepspeed.comm parity). torch's
# in/out-tensor contracts are functional under XLA (return the result);
# rank-rooted ops are SUPERSETS — every member gets the root's result,
# which costs the same as the rooted op on a ring and is how GSPMD itself
# lowers them.
# --------------------------------------------------------------------------

def all_gather_into_tensor(x, axis_name="data", axis: int = 0):
    """reference comm.py all_gather_into_tensor (functional: returns the
    gathered tensor instead of writing into an output buffer)."""
    return all_gather(x, axis_name, axis=axis)


# reference allgather_fn dispatches to all_gather_into_tensor when the
# backend has it; XLA always does
allgather_fn = all_gather_into_tensor


def reduce_scatter_tensor(x, axis_name="data", axis: int = 0,
                          op: str = ReduceOp.SUM):
    return reduce_scatter(x, axis_name, axis=axis, op=op)


reduce_scatter_fn = reduce_scatter_tensor


def all_to_all_single(x, axis_name="expert", split_axis: int = 0,
                      concat_axis: int = 0):
    return all_to_all(x, axis_name, split_axis=split_axis,
                      concat_axis=concat_axis)


def reduce(x, dst: int = 0, axis_name="data", op: str = ReduceOp.SUM):
    """Rooted reduce; under SPMD every member receives the result (torch
    leaves non-dst outputs undefined — this is a superset)."""
    del dst
    return all_reduce(x, axis_name=axis_name, op=op)


def gather(x, dst: int = 0, axis_name="data", axis: int = 0):
    """Rooted gather; superset semantics (all members get the result)."""
    del dst
    return all_gather(x, axis_name, axis=axis)


def scatter(x, src: int = 0, axis_name="data", axis: int = 0):
    """Member i receives src's i-th shard along ``axis``. Non-src members'
    inputs are fully ignored (broadcast uses where-masking, so NaN/inf
    placeholders are fine). Logged once, by the inner broadcast."""
    full = broadcast(x, src=src, axis_name=axis_name)
    n = lax.axis_size(axis_name)
    if full.shape[axis] % n:
        raise ValueError(f"scatter: dim {axis} ({full.shape[axis]}) must "
                         f"divide by axis size {n}")
    chunk = full.shape[axis] // n
    return lax.dynamic_slice_in_dim(full, lax.axis_index(axis_name) * chunk,
                                    chunk, axis)


def new_group(ranks):
    """Reference new_group returns a torch process group. XLA collectives
    are mesh-axis-scoped instead: build the mesh with the axes you need
    (parallel/topology.initialize_mesh) and pass the axis name to the
    collectives. The returned rank list works as the ``group`` argument of
    :func:`get_global_rank`."""
    logger.info("comm.new_group: XLA collectives are mesh-axis-scoped; "
                "use initialize_mesh axes for device collectives. "
                "Returning the rank list for host-plane rank mapping.")
    return list(ranks)


def get_global_rank(group=None, group_rank: int = 0) -> int:
    """Map a group-local rank to a global rank (reference comm.py
    get_global_rank). ``group``: a rank list from :func:`new_group`, or
    None for the world group."""
    if group is None:
        return group_rank
    return list(group)[group_rank]


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    """Barrier with logging (reference monitored_barrier; the hang
    diagnostics live in the launcher's failure detection here).
    ``timeout``: datetime.timedelta or seconds, forwarded to the barrier."""
    del group, wait_all_ranks
    logger.info(f"monitored_barrier enter (rank {get_rank()})")
    if timeout is None:
        barrier()
    else:
        seconds = timeout.total_seconds() if hasattr(
            timeout, "total_seconds") else float(timeout)
        barrier(timeout_ms=int(seconds * 1000))
    logger.info(f"monitored_barrier exit (rank {get_rank()})")


def _no_host_p2p(name, alternative):
    raise ValueError(
        f"comm.{name} is not supported on TPU: XLA owns collective "
        f"scheduling inside compiled programs, so host-driven "
        f"point-to-point has no mapping. Use {alternative} inside the "
        f"compiled step (see runtime/pipe/engine.py for the pipeline "
        f"exchange pattern).")


def isend(tensor, dst, **kw):
    _no_host_p2p("isend", "comm.ppermute / send_recv_next")


def irecv(tensor, src, **kw):
    _no_host_p2p("irecv", "comm.ppermute / send_recv_prev")


def send(tensor, dst, **kw):
    _no_host_p2p("send", "comm.ppermute / send_recv_next")


def recv(tensor, src, **kw):
    _no_host_p2p("recv", "comm.ppermute / send_recv_prev")


def has_all_gather_into_tensor() -> bool:
    return True


def has_reduce_scatter_tensor() -> bool:
    return True


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.axis_size(axis_name)


def log_summary():
    cl = get_comms_logger()
    if cl is not None:
        cl.log_summary()
