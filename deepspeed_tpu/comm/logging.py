"""Comms logger.

Re-design of the reference CommsLogger (deepspeed/utils/comms_logging.py:61)
for a compiled runtime: collectives are recorded when *traced* (op name, bytes,
mesh axis, call count). Wall-time/bandwidth per op is not observable from
inside a compiled program; for that, ``profiling.trace`` wraps jax.profiler.
Bandwidth estimates here use the analytic algbw/busbw formulas from the
reference (comms_logging.py:28 calc_bw_log) applied to measured step time when
provided.
"""

from collections import defaultdict
from typing import Optional

from ..utils.logging import logger


def get_msg_size_str(size_bytes):
    for unit in ["B", "KB", "MB", "GB", "TB"]:
        if size_bytes < 1024:
            return f"{size_bytes:.2f} {unit}"
        size_bytes /= 1024
    return f"{size_bytes:.2f} PB"


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n: int):
    """Analytic algorithm/bus bandwidth (reference comms_logging.py:28)."""
    if duration_s <= 0:
        return 0.0, 0.0
    algbw = size_bytes / duration_s
    if comm_op in ("all_gather", "reduce_scatter", "all_to_all"):
        busbw = algbw * ((n - 1) / max(n, 1))
    elif comm_op == "all_reduce":
        busbw = algbw * (2 * (n - 1) / max(n, 1))
    else:
        busbw = algbw
    return algbw / 1e9, busbw / 1e9  # GB/s


class CommsLogger:
    def __init__(self, enabled=False, verbose=False, prof_all=True,
                 debug=False, prof_ops=None):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.debug = debug
        self.prof_ops = prof_ops or []
        self.comms_dict = defaultdict(lambda: defaultdict(lambda: [0, 0]))

    def configure(self, config):
        self.enabled = config.enabled
        self.verbose = config.verbose
        self.prof_all = config.prof_all
        self.debug = config.debug
        self.prof_ops = list(config.prof_ops)

    def append(self, op_name: str, size_bytes: int, axis_name: str):
        if not self.enabled:
            return
        if self.prof_ops and not self.prof_all and op_name not in self.prof_ops:
            return
        rec = self.comms_dict[op_name][(size_bytes, axis_name)]
        rec[0] += 1
        rec[1] += size_bytes
        if self.verbose:
            logger.info(f"comm op: {op_name} | axis: {axis_name} | "
                        f"msg size: {get_msg_size_str(size_bytes)}")

    def log_summary(self):
        if not self.comms_dict:
            logger.info("CommsLogger: no collectives recorded")
            return
        logger.info(f"{'Comm. Op':<16}{'Axis':<10}{'Message Size':<16}{'Count':<8}{'Total':<14}")
        for op, sizes in self.comms_dict.items():
            for (size, axis), (count, total) in sorted(sizes.items()):
                logger.info(f"{op:<16}{axis:<10}{get_msg_size_str(size):<16}"
                            f"{count:<8}{get_msg_size_str(total):<14}")

    def reset(self):
        self.comms_dict.clear()


_COMMS_LOGGER: Optional[CommsLogger] = None


def get_comms_logger() -> CommsLogger:
    global _COMMS_LOGGER
    if _COMMS_LOGGER is None:
        _COMMS_LOGGER = CommsLogger()
    return _COMMS_LOGGER


def configure_comms_logger(config):
    cl = get_comms_logger()
    cl.configure(config)
    return cl
