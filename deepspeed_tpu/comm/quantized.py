"""Quantized + hierarchical collective implementations.

The compressed lowerings the comm dispatch (comm/comm.py) routes to when a
``comm_compression`` policy is active. All functions run INSIDE compiled
programs (under shard_map over a bound mesh axis) and genuinely move the
compressed carrier over the interconnect: the ``jax.lax`` collectives here
are traced on int8 (or fp8-bitcast-int8) payloads plus small f32 scale
tensors — XLA ships exactly those bytes.

Wire format: the blockwise codec from ops/quant_core.py — contiguous
blocks of ``block`` values, one f32 scale per block (ZeRO++ qwZ,
arxiv 2306.10209 §4.1). The hierarchical reduce-scatter is the qgZ
gradient exchange: full-precision reduce within a host (cheap ICI),
quantized exchange across hosts (the expensive DCN hop), as EQuARX
(arxiv 2506.17615) does natively in XLA.

Every public collective has a ``*_wire_bytes`` companion: the analytic
per-participant link-byte model the dispatch records into the comm
telemetry (comm_stats / spans / flight recorder). The models count what a
ring implementation moves per member, split into intra-host and
inter-host traffic when the (host, local) split is known.

Accuracy note: quantization error is bounded per block by scale/2 =
absmax_block/(2*qmax); the hierarchical reduce-scatter quantizes AFTER the
intra-host reduction, so the error scales with the number of HOSTS, not
the number of devices.
"""

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from ..ops.quant_core import (FP8_DTYPE, block_count, dequantize_blockwise,
                              quantize_blockwise, wire_nbytes)


def _effective_block(row_size: int, block: Optional[int]) -> Optional[int]:
    """A block that never straddles the per-member rows of an exchange:
    the configured block when it divides the row, else one scale per row."""
    if block and block > 0 and row_size % block == 0:
        return block
    return row_size


def _psum_carrier(q):
    """(payload, restore) for a masked-psum transport of the wire dtype:
    int8 sums directly; fp8 has no add on every backend, so it rides an
    int8 bitcast (bit-identical — only one member contributes non-zero)."""
    if q.dtype == jnp.int8:
        return q, lambda s: s
    return (lax.bitcast_convert_type(q, jnp.int8),
            lambda s: lax.bitcast_convert_type(s, q.dtype))


# ------------------------------------------------------------------ all_gather

def quantized_all_gather(x, axis_name, axis: int, n: int,
                         block: int, wire: str):
    """Blockwise-quantized tiled all-gather: each member ships its shard as
    int8/fp8 + per-block f32 scales; receivers dequantize and concatenate
    along ``axis`` — semantics of ``lax.all_gather(tiled=True)`` up to
    quantization error of the SENDER's shard (the ZeRO-3 param gather)."""
    q, scales = quantize_blockwise(x, block, wire)
    gq = lax.all_gather(q, axis_name)                 # [n, *shape] wire dtype
    gs = lax.all_gather(scales, axis_name)            # [n, nblocks] f32
    nb = gs.shape[1]
    deq = gq.reshape(n, nb, -1).astype(jnp.float32) * gs[:, :, None]
    deq = deq.reshape((n,) + x.shape)
    out = jnp.moveaxis(deq, 0, axis)                  # tiled concat on `axis`
    shape = list(x.shape)
    shape[axis] *= n
    return out.reshape(shape).astype(x.dtype)


def quantized_all_gather_wire_bytes(size: int, n: int, block: int) -> int:
    """Per-member link bytes: (n-1) copies of the compressed shard."""
    return (n - 1) * wire_nbytes(size, block)


# -------------------------------------------------------------- reduce_scatter

def _rows_quantize(rows, block: int, wire: str):
    """Quantize a [n, row] matrix with blocks aligned to rows; returns
    (q [n, row], scales [n, nb_row])."""
    n, row = rows.shape
    eff = _effective_block(row, block)
    q, scales = quantize_blockwise(rows, eff, wire)
    return q, scales.reshape(n, -1)


def _a2a_dequant_sum(rows, axis_name, groups, block, wire):
    """Quantize per-destination rows, all-to-all them (int8/fp8 wire),
    dequantize the received rows and sum: one quantized reduce-scatter leg.
    rows: [g, row] where g = group size; returns [row] f32 sums."""
    q, scales = _rows_quantize(rows, block, wire)
    rq = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                        tiled=False, axis_index_groups=groups)
    rs = lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0,
                        tiled=False, axis_index_groups=groups)
    nb = rs.shape[1]
    deq = rq.reshape(rows.shape[0], nb, -1).astype(jnp.float32) \
        * rs[:, :, None]
    return jnp.sum(deq.reshape(rows.shape), axis=0)


def quantized_reduce_scatter(x, axis_name, axis: int, n: int,
                             block: int, wire: str, avg: bool):
    """Flat (single-level) quantized reduce-scatter: quantize the n
    per-destination chunks, all-to-all int8, dequantize + sum locally.
    Semantics of ``lax.psum_scatter(tiled=True)`` up to quantization error
    of the UNREDUCED contributions."""
    xm = jnp.moveaxis(x, axis, 0)
    chunk = xm.shape[0] // n
    rows = xm.reshape(n, -1)                           # [n, chunk*rest]
    total = _a2a_dequant_sum(rows, axis_name, None, block, wire)
    if avg:
        total = total / n
    out = total.reshape((chunk,) + xm.shape[1:])
    return jnp.moveaxis(out, 0, axis).astype(x.dtype)


def quantized_reduce_scatter_wire_bytes(size: int, n: int,
                                        block: int) -> int:
    """Per-member link bytes: sends (n-1) of its n compressed chunks."""
    row = size // n
    eff = _effective_block(row, block)
    return (n - 1) * wire_nbytes(row, eff)


def hierarchical_reduce_scatter(x, axis_name, axis: int, n: int,
                                local: int, intra_groups, inter_groups,
                                block: int, wire: str, avg: bool):
    """Two-level ZeRO++-style reduce-scatter over a flat axis of ``n``
    members laid out host-major with ``local`` members per host:

      1. chunk-permute locally so the result lands in standard
         reduce-scatter order (free: a reshape/transpose of local data),
      2. full-precision ``psum_scatter`` within each host (intra links),
      3. blockwise-quantized all-to-all + dequant-sum across hosts
         (the only inter-host traffic: int8/fp8 + scales).

    Matches ``lax.psum_scatter(tiled=True)`` semantics up to quantization
    error of the HOST-REDUCED partial sums."""
    hosts = n // local
    xm = jnp.moveaxis(x, axis, 0)
    dim = xm.shape[0]
    chunk = dim // n
    # standard rs gives member i = h*local + l chunk i; the two-level
    # exchange naturally yields chunk l*hosts + h — pre-swap the (host,
    # local) chunk grid so they coincide
    y = xm.reshape(hosts, local, chunk, *xm.shape[1:])
    y = jnp.swapaxes(y, 0, 1).reshape(dim, *xm.shape[1:])
    # leg 1: intra-host reduce-scatter, full precision
    part = lax.psum_scatter(y, axis_name, scatter_dimension=0,
                            axis_index_groups=intra_groups, tiled=True)
    # leg 2: inter-host quantized exchange (all_to_all + dequant-sum)
    rows = part.reshape(hosts, -1)                 # [hosts, chunk*rest]
    total = _a2a_dequant_sum(rows, axis_name, inter_groups, block, wire)
    if avg:
        total = total / n
    out = total.reshape((chunk,) + xm.shape[1:])
    return jnp.moveaxis(out, 0, axis).astype(x.dtype)


def hierarchical_reduce_scatter_wire_bytes(
        size: int, n: int, local: int, block: int,
        elem_bytes: int) -> Tuple[int, int]:
    """(intra_bytes, inter_bytes) per member: full-precision intra-host
    reduce-scatter of the whole payload, then the quantized inter-host
    exchange of the host-reduced 1/local slice."""
    hosts = n // local
    intra = (local - 1) * (size // local) * elem_bytes
    row = size // (local * hosts)
    eff = _effective_block(row, block)
    inter = (hosts - 1) * wire_nbytes(row, eff)
    return intra, inter


# ------------------------------------------------- coalesced (bucketed) forms
#
# One collective per BUCKET of leaves (runtime/zero/overlap_schedule.py):
# each leaf is quantized with EXACTLY the per-leaf codec rules above —
# same blocks, same scales — and only the wire payloads are concatenated.
# The exchanged bytes and the dequantized values are therefore bitwise
# identical to running the per-leaf collectives one by one, for any
# bucketing; what changes is the op count (N leaves -> 1 collective) and
# the schedule structure the bucketed exchange builds from it.

def quantized_all_gather_coalesced(xs, axis_name, axes, n: int,
                                   block: int, wire: str):
    """Blockwise-quantized tiled all-gather of a bucket of leaves in one
    collective pair (payload + scales). Returns the per-leaf gathered
    tensors, each bitwise identical to ``quantized_all_gather``."""
    qs, ss = [], []
    for x in xs:
        q, s = quantize_blockwise(x, block, wire)
        qs.append(q.reshape(-1))
        ss.append(s)
    gq = lax.all_gather(jnp.concatenate(qs), axis_name)    # [n, total]
    gs = lax.all_gather(jnp.concatenate(ss), axis_name)    # [n, nb_total]
    outs = []
    off = soff = 0
    for x, axis in zip(xs, axes):
        nb = block_count(x.size, block)
        q = gq[:, off:off + x.size]
        s = gs[:, soff:soff + nb]
        off += x.size
        soff += nb
        deq = q.reshape(n, nb, -1).astype(jnp.float32) * s[:, :, None]
        deq = deq.reshape((n,) + x.shape)
        out = jnp.moveaxis(deq, 0, axis)
        shape = list(x.shape)
        shape[axis] *= n
        outs.append(out.reshape(shape).astype(x.dtype))
    return outs


def quantized_all_gather_coalesced_wire_bytes(sizes, n: int,
                                              block: int) -> int:
    return sum(quantized_all_gather_wire_bytes(s, n, block) for s in sizes)


def _leaf_rows(x, axis, n: int):
    """[n, x.size//n] per-member rows of one reduce-scatter leaf (row m =
    member m's chunk along ``axis``)."""
    return jnp.moveaxis(x, axis, 0).reshape(n, -1)


def _unleaf_rows(total, x, axis, n: int):
    """Inverse of :func:`_leaf_rows` for the reduced [x.size//n] chunk."""
    rest = tuple(s for i, s in enumerate(x.shape) if i != axis)
    out = total.reshape((x.shape[axis] // n,) + rest)
    return jnp.moveaxis(out, 0, axis).astype(x.dtype)


def quantized_reduce_scatter_coalesced(xs, axis_name, axes, n: int,
                                       block: int, wire: str, avg: bool):
    """Flat quantized reduce-scatter of a bucket in one all-to-all pair;
    per-leaf results bitwise identical to ``quantized_reduce_scatter``."""
    qs, ss = [], []
    for x, axis in zip(xs, axes):
        q, s = _rows_quantize(_leaf_rows(x, axis, n), block, wire)
        qs.append(q)
        ss.append(s)
    rq = lax.all_to_all(jnp.concatenate(qs, axis=1), axis_name,
                        split_axis=0, concat_axis=0, tiled=False)
    rs = lax.all_to_all(jnp.concatenate(ss, axis=1), axis_name,
                        split_axis=0, concat_axis=0, tiled=False)
    outs = []
    off = soff = 0
    for x, axis in zip(xs, axes):
        sz = x.size // n
        nb = ss[len(outs)].shape[1]
        q = rq[:, off:off + sz]
        s = rs[:, soff:soff + nb]
        off += sz
        soff += nb
        deq = q.reshape(n, nb, -1).astype(jnp.float32) * s[:, :, None]
        total = jnp.sum(deq.reshape(n, sz), axis=0)
        if avg:
            total = total / n
        outs.append(_unleaf_rows(total, x, axis, n))
    return outs


def quantized_reduce_scatter_coalesced_wire_bytes(sizes, n: int,
                                                  block: int) -> int:
    return sum(quantized_reduce_scatter_wire_bytes(s, n, block)
               for s in sizes)


def hierarchical_reduce_scatter_coalesced(xs, axis_name, axes, n: int,
                                          local: int, intra_groups,
                                          inter_groups, block: int,
                                          wire: str, avg: bool):
    """Two-level (ZeRO++ qgZ) reduce-scatter of a bucket: ONE intra-host
    full-precision psum_scatter + ONE inter-host quantized all-to-all
    pair for all leaves together; per-leaf results bitwise identical to
    ``hierarchical_reduce_scatter``."""
    hosts = n // local
    # leg 1: per-leaf chunk-grid swap, then one intra-host reduce-scatter
    # of the concatenated [local, dim/local * rest] rows
    zs = []
    for x, axis in zip(xs, axes):
        xm = jnp.moveaxis(x, axis, 0)
        dim = xm.shape[0]
        chunk = dim // n
        y = xm.reshape(hosts, local, chunk, *xm.shape[1:])
        y = jnp.swapaxes(y, 0, 1).reshape(dim, *xm.shape[1:])
        zs.append(y.reshape(local, -1))
    part = lax.psum_scatter(jnp.concatenate(zs, axis=1), axis_name,
                            scatter_dimension=0,
                            axis_index_groups=intra_groups,
                            tiled=True).reshape(-1)
    # leg 2: per-leaf quantized rows, one inter-host all-to-all pair
    qs, ss = [], []
    offs = []
    off = 0
    for x, z in zip(xs, zs):
        width = z.shape[1]               # hosts * chunk * rest values
        rows = part[off:off + width].reshape(hosts, -1)
        off += width
        q, s = _rows_quantize(rows, block, wire)
        qs.append(q)
        ss.append(s)
    rq = lax.all_to_all(jnp.concatenate(qs, axis=1), axis_name,
                        split_axis=0, concat_axis=0, tiled=False,
                        axis_index_groups=inter_groups)
    rs = lax.all_to_all(jnp.concatenate(ss, axis=1), axis_name,
                        split_axis=0, concat_axis=0, tiled=False,
                        axis_index_groups=inter_groups)
    outs = []
    off = soff = 0
    for i, (x, axis) in enumerate(zip(xs, axes)):
        sz = x.size // (local * hosts)
        nb = ss[i].shape[1]
        q = rq[:, off:off + sz]
        s = rs[:, soff:soff + nb]
        off += sz
        soff += nb
        deq = q.reshape(hosts, nb, -1).astype(jnp.float32) * s[:, :, None]
        total = jnp.sum(deq.reshape(hosts, sz), axis=0)
        if avg:
            total = total / n
        outs.append(_unleaf_rows(total, x, axis, n))
    return outs


def hierarchical_reduce_scatter_coalesced_wire_bytes(
        sizes, n: int, local: int, block: int,
        elem_bytes: int) -> Tuple[int, int]:
    intra = inter = 0
    for s in sizes:
        i, e = hierarchical_reduce_scatter_wire_bytes(
            s, n, local, block, elem_bytes)
        intra += i
        inter += e
    return intra, inter


# ------------------------------------------------------------------ all_reduce

def quantized_all_reduce(x, axis_name, n: int, block: int, wire: str,
                         avg: bool):
    """Quantized ring-style AVERAGE/SUM: quantized reduce-scatter of the
    flattened tensor, then quantized all-gather of the reduced chunks
    (both legs int8/fp8 wire). Requires x.size % n == 0 — the dispatch
    falls back to full precision otherwise."""
    xf = x.reshape(-1)
    chunk = quantized_reduce_scatter(xf, axis_name, 0, n, block, wire, avg)
    full = quantized_all_gather(chunk, axis_name, 0, n, block, wire)
    return full.reshape(x.shape).astype(x.dtype)


def quantized_all_reduce_wire_bytes(size: int, n: int, block: int) -> int:
    return (quantized_reduce_scatter_wire_bytes(size, n, block) +
            quantized_all_gather_wire_bytes(size // n, n, block))


# ------------------------------------------------------------------ all_to_all

def quantized_all_to_all(x, axis_name, split_axis: int, concat_axis: int,
                         n: int, block: int, wire: str):
    """Blockwise-quantized tiled all-to-all (the MoE dispatch/combine wire):
    quantize the n per-destination slices, exchange int8/fp8 + scales,
    dequantize and reassemble with ``lax.all_to_all(tiled=True)``
    semantics."""
    xm = jnp.moveaxis(x, split_axis, 0)                # [dim_s, *rest]
    ds = xm.shape[0] // n
    rows = xm.reshape(n, -1)                           # [n, ds*rest]
    q, scales = _rows_quantize(rows, block, wire)
    rq = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                        tiled=False)
    rs = lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0,
                        tiled=False)
    nb = rs.shape[1]
    deq = rq.reshape(n, nb, -1).astype(jnp.float32) * rs[:, :, None]
    blocks = deq.reshape(n, ds, *xm.shape[1:])         # [n, split/n, *rest]
    blocks = jnp.moveaxis(blocks, 1, split_axis + 1)   # restore layout
    out = jnp.moveaxis(blocks, 0, concat_axis)         # tiled concat
    shape = list(x.shape)
    shape[split_axis] //= n
    shape[concat_axis] *= n
    return out.reshape(shape).astype(x.dtype)


def quantized_all_to_all_wire_bytes(size: int, n: int, block: int) -> int:
    row = size // n
    eff = _effective_block(row, block)
    return (n - 1) * wire_nbytes(row, eff)


# ------------------------------------------------------------------- broadcast

def quantized_broadcast(x, src: int, axis_name, n: int, block: int,
                        wire: str):
    """Quantized broadcast-via-masked-psum: only src contributes non-zero
    int8 blocks, so the integer psum reconstructs src's payload exactly
    (no overflow possible); fp8 rides an int8 bitcast. Wire cost is the
    psum ring on the COMPRESSED payload — ~2x the compressed size instead
    of ~2x full precision."""
    q, scales = quantize_blockwise(x, block, wire)
    idx = lax.axis_index(axis_name)
    payload, restore = _psum_carrier(q)
    summed = lax.psum(jnp.where(idx == src, payload,
                                jnp.zeros_like(payload)), axis_name)
    sscales = lax.psum(jnp.where(idx == src, scales,
                                 jnp.zeros_like(scales)), axis_name)
    return dequantize_blockwise(restore(summed), sscales, x.dtype)


def quantized_broadcast_wire_bytes(size: int, n: int, block: int) -> int:
    return int(2 * (n - 1) / n * wire_nbytes(size, block))
