"""Flops profiler — jaxpr cost analysis + engine step hook.

Capability match for the reference flops profiler
(profiling/flops_profiler/profiler.py:23 ``FlopsProfiler``: monkey-patches
~50 torch functionals to count FLOPs/MACs, module-tree report, engine
activation at a configured step). TPU-native translation: the model is a
traced program, so instead of patching call sites we WALK THE JAXPR —
every dot_general/conv/elementwise equation contributes analytically, scans
multiply by trip count — and cross-check totals against XLA's own
``compiled.cost_analysis()``. The per-primitive table replaces the torch
module tree (function-level attribution; jax has no module hierarchy at
trace time).

Engine hook: at ``flops_profiler.profile_step`` the engine profiles its
compiled train step and prints/writes the report (reference
engine.py:1646-1664 start/stop wiring).
"""

import math
import re
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax import core


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _dot_general_flops(eqn) -> int:
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    dims = eqn.params["dimension_numbers"]
    (contract_a, _), (batch_a, _) = dims
    batch = _prod(a.shape[i] for i in batch_a)
    contract = _prod(a.shape[i] for i in contract_a)
    m = _prod(a.shape[i] for i in range(len(a.shape))
              if i not in contract_a and i not in batch_a)
    n = _prod(b.shape[i] for i in range(len(b.shape))
              if i not in dims[0][1] and i not in dims[1][1])
    return 2 * batch * m * n * contract


def _conv_flops(eqn) -> int:
    rhs = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    # per output element the fan-in is kernel_spatial x in_channels =
    # prod(kernel shape) / out_channels (default HWIO kernel layout)
    fan_in = _prod(rhs.shape) // max(1, rhs.shape[-1])
    return 2 * _prod(out.shape) * fan_in


_ELEMENTWISE = {
    "add", "sub", "mul", "div", "max", "min", "pow", "exp", "log", "tanh",
    "logistic", "rsqrt", "sqrt", "neg", "abs", "sign", "floor", "ceil",
    "round", "erf", "integer_pow", "select_n", "clamp", "and", "or", "xor",
    "not", "lt", "le", "gt", "ge", "eq", "ne", "convert_element_type",
    "cos", "sin",
}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
           "reduce_and", "reduce_or", "argmax", "argmin", "cumsum",
           "cumlogsumexp", "cummax"}


#: model phases recognised in named_scope stacks (models/gpt2.py _block
#: et al. annotate these; reference profiler.py:239 prints the torch
#: module tree — the phase tree is the jax equivalent, since there is no
#: module hierarchy at trace time, only the name stack)
PHASES = ("embed", "attn", "mlp", "moe", "head")


#: token-boundary match: under autodiff the stack segments are wrapped
#: ('jvp(attn)', 'transpose(jvp(mlp))'), and raw substring search would
#: misfire on identifiers like 'num_heads'/'embedding'
_PHASE_RE = re.compile(
    r"(?<![A-Za-z0-9_])(" + "|".join(PHASES) + r")(?![A-Za-z0-9_])")


def _phase_of(eqn) -> str:
    try:
        stack = str(eqn.source_info.name_stack)
    except Exception:
        return "other"
    m = _PHASE_RE.search(stack)
    return m.group(1) if m else "other"


def jaxpr_flops(jaxpr, breakdown: Optional[Dict[str, int]] = None,
                mult: int = 1, phases: Optional[Dict[str, int]] = None) -> int:
    """Analytic FLOPs of a (closed) jaxpr; scans multiply by length.
    ``phases`` collects per-named-scope-phase totals (embed/attn/mlp/...)."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        flops = 0
        inner_mult = mult
        if name == "dot_general":
            flops = _dot_general_flops(eqn)
        elif name == "conv_general_dilated":
            flops = _conv_flops(eqn)
        elif name in _ELEMENTWISE:
            flops = _prod(eqn.outvars[0].aval.shape)
        elif name in _REDUCE:
            flops = _prod(eqn.invars[0].aval.shape)
        elif name == "scan":
            length = eqn.params.get("length", 1)
            total += jaxpr_flops(eqn.params["jaxpr"], breakdown,
                                 mult * length, phases)
            continue
        elif name == "while":
            # trip count unknown at trace time: count one iteration
            total += jaxpr_flops(eqn.params["body_jaxpr"], breakdown, mult,
                                 phases)
            continue
        elif name == "cond":
            branches = eqn.params.get("branches", ())
            if branches:  # one branch executes: take the max, and merge
                #           only ITS breakdown (totals must match the table)
                flops_per = []
                for b in branches:
                    bd, ph = {}, {}
                    flops_per.append((jaxpr_flops(b, bd, mult, ph), bd, ph))
                best_flops, best_bd, best_ph = max(flops_per,
                                                   key=lambda t: t[0])
                total += best_flops
                if breakdown is not None:
                    for k, v in best_bd.items():
                        breakdown[k] = breakdown.get(k, 0) + v
                if phases is not None:
                    for k, v in best_ph.items():
                        phases[k] = phases.get(k, 0) + v
            continue
        elif "jaxpr" in eqn.params:  # pjit / remat / custom_vjp call, etc.
            total += jaxpr_flops(eqn.params["jaxpr"], breakdown, mult,
                                 phases)
            continue
        elif "call_jaxpr" in eqn.params:
            total += jaxpr_flops(eqn.params["call_jaxpr"], breakdown, mult,
                                 phases)
            continue
        flops *= inner_mult
        total += flops
        if flops:
            if breakdown is not None:
                breakdown[name] = breakdown.get(name, 0) + flops
            if phases is not None:
                ph = _phase_of(eqn)
                phases[ph] = phases.get(ph, 0) + flops
    return total


def _num_to_string(num, precision=2):
    for unit, scale in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(num) >= scale:
            return f"{num / scale:.{precision}f} {unit}"
    return str(num)


class FlopsProfiler:
    """profile(fn, *args) → dict report. fn may be jitted or plain."""

    def __init__(self, config=None):
        self.config = config

    def profile(self, fn, *args, **kwargs) -> Dict[str, Any]:
        breakdown: Dict[str, int] = {}
        xla_flops = None
        if hasattr(fn, "lower"):
            # cost_analysis on the LOWERED stage only (no .compile() — an
            # AOT compile would NOT hit the jit executable cache and can
            # cost minutes on a real model mid-training); normalized by
            # the shared HLO cost core (telemetry/hlo_cost.py), the same
            # parser hlo_audit and the compile ledger consume
            try:
                from ..telemetry.hlo_cost import cost_summary
                cost = cost_summary(fn.lower(*args, **kwargs).cost_analysis())
                xla_flops = cost.get("flops")
            except Exception:
                pass
        closed = jax.make_jaxpr(lambda *a, **k: fn(*a, **k))(*args, **kwargs)
        phases: Dict[str, int] = {}
        total = jaxpr_flops(closed, breakdown, phases=phases)
        return {"flops": total, "macs": total // 2,
                "xla_flops": xla_flops, "per_primitive": breakdown,
                "per_phase": phases}

    def report(self, prof: Dict[str, Any], params: Optional[int] = None,
               latency_s: Optional[float] = None, top: int = 10,
               wall_fractions: Optional[Dict[str, float]] = None) -> str:
        """Reference-style tree report (profiler.py:239 prints the torch
        module tree; the phase tree is the jax equivalent). When a device
        trace is available, pass ``wall_fractions`` from
        :func:`wall_fractions_from_trace` for MEASURED per-phase wall —
        otherwise the wall column is flops-proportional and labelled so."""
        if not wall_fractions:
            wall_fractions = None   # {} = no trace found: honest fallback
        lines = ["-" * 60, "deepspeed_tpu flops profiler", "-" * 60]
        if params is not None:
            lines.append(f"params:               {_num_to_string(params)}")
        lines.append(f"flops (analytic):     {_num_to_string(prof['flops'])}")
        if prof.get("xla_flops"):
            lines.append(
                f"flops (XLA cost):     {_num_to_string(prof['xla_flops'])}")
        lines.append(f"MACs:                 {_num_to_string(prof['macs'])}")
        if latency_s:
            lines.append(f"latency:              {latency_s * 1e3:.2f} ms")
            lines.append(
                f"achieved:             "
                f"{_num_to_string(prof['flops'] / latency_s)}FLOPS")
        per_phase = prof.get("per_phase") or {}
        if per_phase:
            wall_src = "measured" if wall_fractions else "flops-proportional"
            lines.append(f"model tree (phases; wall = {wall_src}):")
            order = [p for p in PHASES if p in per_phase] + \
                sorted(k for k in per_phase if k not in PHASES)
            for ph in order:
                fl = per_phase[ph]
                pct = 100.0 * fl / max(1, prof["flops"])
                if wall_fractions is not None and ph not in wall_fractions:
                    # never mix units: a phase the trace didn't see prints
                    # n/a instead of smuggling in its flops fraction
                    wall_col = "  n/a wall"
                    wf = None
                else:
                    wf = (wall_fractions or {}).get(
                        ph, fl / max(1, prof["flops"]))
                    wall_col = f"{100 * wf:5.1f}% wall"
                line = (f"  {ph:<10} {_num_to_string(fl):>12}  "
                        f"{pct:5.1f}% flops  {wall_col}")
                if latency_s and wf is not None:
                    line += f"  ({wf * latency_s * 1e3:7.2f} ms)"
                lines.append(line)
        items = sorted(prof["per_primitive"].items(), key=lambda kv: -kv[1])
        lines.append("top primitives:")
        for name, fl in items[:top]:
            pct = 100.0 * fl / max(1, prof["flops"])
            lines.append(f"  {name:<28} {_num_to_string(fl):>12}  {pct:5.1f}%")
        lines.append("-" * 60)
        return "\n".join(lines)


def wall_fractions_from_trace(trace_dir: str) -> Dict[str, float]:
    """Measured per-phase wall fractions from a ``jax.profiler`` trace.

    XLA op/fusion names carry the named_scope stack of their constituent
    HLOs, so device self-time can be attributed to the same phases the
    analytic tree uses. Returns {} when no trace is found (callers fall
    back to flops-proportional wall)."""
    import glob
    import gzip
    import json
    import os

    files = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not files:
        return {}
    with gzip.open(sorted(files)[-1], "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    tid_names = {(e["pid"], e["tid"]): e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e.get("name") == "thread_name"}
    per_phase: Dict[str, float] = {}
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or \
                tid_names.get((e["pid"], e["tid"])) != "XLA Ops":
            continue
        dur = float(e.get("dur", 0.0))
        # fusion names don't always carry the scope; the event metadata
        # (args: long_name / tf_op / hlo metadata) usually does. Token-
        # boundary match so 'num_heads'/'embedding' don't misattribute to
        # 'head'/'embed'; XLA fuses across scope boundaries, so a fusion
        # matching several phases splits its time evenly between them
        # rather than crediting whichever token appears first.
        hay = e.get("name", "") + " " + " ".join(
            str(v) for v in (e.get("args") or {}).values())
        found = sorted(set(_PHASE_RE.findall(hay)))
        if not found:
            found = ["other"]
        for ph in found:
            per_phase[ph] = per_phase.get(ph, 0.0) + dur / len(found)
        total += dur
    if total <= 0:
        return {}
    return {ph: d / total for ph, d in per_phase.items()}


def get_model_profile(model, batch, rng=None) -> Dict[str, Any]:
    """Reference get_model_profile(): profile a ModelSpec's forward."""
    params = model.init(rng if rng is not None else jax.random.PRNGKey(0))
    prof = FlopsProfiler().profile(
        lambda p, b: model.apply(p, b, rng=None, train=False), params, batch)
    prof["params"] = sum(int(np.prod(x.shape))
                         for x in jax.tree.leaves(params))
    return prof
