"""Flops profiler — jaxpr cost analysis + engine step hook.

Capability match for the reference flops profiler
(profiling/flops_profiler/profiler.py:23 ``FlopsProfiler``: monkey-patches
~50 torch functionals to count FLOPs/MACs, module-tree report, engine
activation at a configured step). TPU-native translation: the model is a
traced program, so instead of patching call sites we WALK THE JAXPR —
every dot_general/conv/elementwise equation contributes analytically, scans
multiply by trip count — and cross-check totals against XLA's own
``compiled.cost_analysis()``. The per-primitive table replaces the torch
module tree (function-level attribution; jax has no module hierarchy at
trace time).

Engine hook: at ``flops_profiler.profile_step`` the engine profiles its
compiled train step and prints/writes the report (reference
engine.py:1646-1664 start/stop wiring).
"""

import math
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax import core


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _dot_general_flops(eqn) -> int:
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    dims = eqn.params["dimension_numbers"]
    (contract_a, _), (batch_a, _) = dims
    batch = _prod(a.shape[i] for i in batch_a)
    contract = _prod(a.shape[i] for i in contract_a)
    m = _prod(a.shape[i] for i in range(len(a.shape))
              if i not in contract_a and i not in batch_a)
    n = _prod(b.shape[i] for i in range(len(b.shape))
              if i not in dims[0][1] and i not in dims[1][1])
    return 2 * batch * m * n * contract


def _conv_flops(eqn) -> int:
    rhs = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    # per output element the fan-in is kernel_spatial x in_channels =
    # prod(kernel shape) / out_channels (default HWIO kernel layout)
    fan_in = _prod(rhs.shape) // max(1, rhs.shape[-1])
    return 2 * _prod(out.shape) * fan_in


_ELEMENTWISE = {
    "add", "sub", "mul", "div", "max", "min", "pow", "exp", "log", "tanh",
    "logistic", "rsqrt", "sqrt", "neg", "abs", "sign", "floor", "ceil",
    "round", "erf", "integer_pow", "select_n", "clamp", "and", "or", "xor",
    "not", "lt", "le", "gt", "ge", "eq", "ne", "convert_element_type",
    "cos", "sin",
}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
           "reduce_and", "reduce_or", "argmax", "argmin", "cumsum",
           "cumlogsumexp", "cummax"}


def jaxpr_flops(jaxpr, breakdown: Optional[Dict[str, int]] = None,
                mult: int = 1) -> int:
    """Analytic FLOPs of a (closed) jaxpr; scans multiply by length."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        flops = 0
        inner_mult = mult
        if name == "dot_general":
            flops = _dot_general_flops(eqn)
        elif name == "conv_general_dilated":
            flops = _conv_flops(eqn)
        elif name in _ELEMENTWISE:
            flops = _prod(eqn.outvars[0].aval.shape)
        elif name in _REDUCE:
            flops = _prod(eqn.invars[0].aval.shape)
        elif name == "scan":
            length = eqn.params.get("length", 1)
            total += jaxpr_flops(eqn.params["jaxpr"], breakdown,
                                 mult * length)
            continue
        elif name == "while":
            # trip count unknown at trace time: count one iteration
            total += jaxpr_flops(eqn.params["body_jaxpr"], breakdown, mult)
            continue
        elif name == "cond":
            branches = eqn.params.get("branches", ())
            if branches:  # one branch executes: take the max, and merge
                #           only ITS breakdown (totals must match the table)
                per_branch = [({}, b) for b in branches]
                flops_per = [(jaxpr_flops(b, bd, mult), bd)
                             for bd, b in per_branch]
                best_flops, best_bd = max(flops_per, key=lambda t: t[0])
                total += best_flops
                if breakdown is not None:
                    for k, v in best_bd.items():
                        breakdown[k] = breakdown.get(k, 0) + v
            continue
        elif "jaxpr" in eqn.params:  # pjit / remat / custom_vjp call, etc.
            total += jaxpr_flops(eqn.params["jaxpr"], breakdown, mult)
            continue
        elif "call_jaxpr" in eqn.params:
            total += jaxpr_flops(eqn.params["call_jaxpr"], breakdown, mult)
            continue
        flops *= inner_mult
        total += flops
        if breakdown is not None and flops:
            breakdown[name] = breakdown.get(name, 0) + flops
    return total


def _num_to_string(num, precision=2):
    for unit, scale in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(num) >= scale:
            return f"{num / scale:.{precision}f} {unit}"
    return str(num)


class FlopsProfiler:
    """profile(fn, *args) → dict report. fn may be jitted or plain."""

    def __init__(self, config=None):
        self.config = config

    def profile(self, fn, *args, **kwargs) -> Dict[str, Any]:
        breakdown: Dict[str, int] = {}
        xla_flops = None
        if hasattr(fn, "lower"):
            # cost_analysis on the LOWERED stage only (no .compile() — an
            # AOT compile would NOT hit the jit executable cache and can
            # cost minutes on a real model mid-training)
            try:
                cost = fn.lower(*args, **kwargs).cost_analysis()
                if isinstance(cost, (list, tuple)):
                    cost = cost[0] if cost else None
                if cost:
                    xla_flops = cost.get("flops")
            except Exception:
                pass
        closed = jax.make_jaxpr(lambda *a, **k: fn(*a, **k))(*args, **kwargs)
        total = jaxpr_flops(closed, breakdown)
        return {"flops": total, "macs": total // 2,
                "xla_flops": xla_flops, "per_primitive": breakdown}

    def report(self, prof: Dict[str, Any], params: Optional[int] = None,
               latency_s: Optional[float] = None, top: int = 10) -> str:
        lines = ["-" * 60, "deepspeed_tpu flops profiler", "-" * 60]
        if params is not None:
            lines.append(f"params:               {_num_to_string(params)}")
        lines.append(f"flops (analytic):     {_num_to_string(prof['flops'])}")
        if prof.get("xla_flops"):
            lines.append(
                f"flops (XLA cost):     {_num_to_string(prof['xla_flops'])}")
        lines.append(f"MACs:                 {_num_to_string(prof['macs'])}")
        if latency_s:
            lines.append(f"latency:              {latency_s * 1e3:.2f} ms")
            lines.append(
                f"achieved:             "
                f"{_num_to_string(prof['flops'] / latency_s)}FLOPS")
        items = sorted(prof["per_primitive"].items(), key=lambda kv: -kv[1])
        lines.append("top primitives:")
        for name, fl in items[:top]:
            pct = 100.0 * fl / max(1, prof["flops"])
            lines.append(f"  {name:<28} {_num_to_string(fl):>12}  {pct:5.1f}%")
        lines.append("-" * 60)
        return "\n".join(lines)


def get_model_profile(model, batch, rng=None) -> Dict[str, Any]:
    """Reference get_model_profile(): profile a ModelSpec's forward."""
    params = model.init(rng if rng is not None else jax.random.PRNGKey(0))
    prof = FlopsProfiler().profile(
        lambda p, b: model.apply(p, b, rng=None, train=False), params, batch)
    prof["params"] = sum(int(np.prod(x.shape))
                         for x in jax.tree.leaves(params))
    return prof
