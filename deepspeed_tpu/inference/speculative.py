"""Speculative decoding over the slot pool — draft runtimes + sampling.

The serving decode loop emits one token per compiled tick; speculation
turns each tick into ``accepted + 1`` tokens for roughly two dispatches:
a cheap DRAFT model proposes K tokens per slot (the whole K-step
autoregressive proposal is ONE compiled ``lax.scan`` —
``InferenceEngine.slot_draft_propose``), then the target model verifies
all K in ONE batched, statically-shaped forward
(``GPT2Model.verify_with_slots`` via ``slot_verify_step``), accepting
the longest matching prefix and rolling rejected KV columns back INSIDE
the compiled step.

**Verification is exact-match against the target's own deterministic
per-position sample.** Every emitted token — greedy or sampled — equals
what the non-speculative path would emit at that position, because both
paths sample with the same key, derived ONLY from ``(request seed,
cache column)`` (never tick or slot index). That buys three guarantees
the fleet already depends on:

- the token stream is **bitwise identical with speculation on or off**
  (the draft can only accelerate, never change, the output);
- a failover survivor **replays the identical stream** — the router's
  delivered-position dedup still yields every streamed position exactly
  once, now for sampled requests too;
- the draft maximizes acceptance by sampling with the SAME per-position
  key (a coupling: two similar distributions pushed through one uniform
  draw usually pick the same token).

The trade: at high temperature, exact-match acceptance is lower than
lossless rejection-sampling speculation. At/near greedy — the serving
common case — they coincide.

Draft flavors (``speculative.draft``):

- ``mode="self"`` — **self-speculative fallback**: the draft is the
  target's own first ``layers`` blocks (a zero-copy slice of the
  stacked ``blocks`` leaves) under the target's final norm + unembed —
  no second model has to fit HBM.
- ``mode="model"`` — a separate small GPT-2 config (own params; same
  vocab) for when a trained draft exists.
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

__all__ = ["DraftRuntime", "build_draft", "draft_key", "row_keys",
           "sample_rows"]


def draft_key(cfg) -> tuple:
    """Hashable identity of a draft config — the engine caches one
    DraftRuntime (params included) per distinct key, so N co-resident
    replicas with one shared InferenceEngine also share draft weights."""
    return ("self" if cfg.mode == "self" else "model",
            int(getattr(cfg, "layers", 0)), int(getattr(cfg, "n_layer", 0)),
            int(getattr(cfg, "n_embd", 0)), int(getattr(cfg, "n_head", 0)),
            int(getattr(cfg, "seed", 0)))


@dataclasses.dataclass
class DraftRuntime:
    """A draft model ready to propose: spec + params + shardings."""
    model: Any
    params: Any
    param_shardings: Any
    mode: str
    layers: int
    key: tuple

    @property
    def describe(self) -> str:
        cfg = self.model.config
        if self.mode == "self":
            return f"self(layers={self.layers})"
        return f"model({cfg.n_layer}L/{cfg.n_embd}d)"


def _draft_shardings(engine, model):
    from ..runtime.zero.partition import ZeroShardingPlanner
    rules = model.partition_rules() if hasattr(model, "partition_rules") \
        else []
    planner = ZeroShardingPlanner(engine.mesh_manager, stage=0, rules=rules)
    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    return planner.param_shardings(shapes)


def build_draft(engine, cfg) -> DraftRuntime:
    """Build a DraftRuntime for ``engine`` from a DraftConfig-shaped
    object (``mode``/``layers``/``n_layer``/``n_embd``/``n_head``/
    ``seed``). ``self`` mode slices the target's stacked blocks —
    requires fp serving weights (weight-only int8 params have no layer
    axis to slice)."""
    target = engine.module
    tcfg = target.config
    mode = getattr(cfg, "mode", "self")
    if mode == "self":
        if getattr(engine, "_quant", None) is not None:
            raise ValueError(
                "self-speculative draft slices the target's stacked block "
                "leaves, which weight-only int8 serving params do not "
                "expose; serve fp weights or configure draft.mode='model'")
        layers = int(getattr(cfg, "layers", 0)) or max(1, tcfg.n_layer // 2)
        if not 1 <= layers <= tcfg.n_layer:
            raise ValueError(
                f"speculative.draft.layers={layers} outside "
                f"[1, {tcfg.n_layer}]")
        model = type(target)(dataclasses.replace(tcfg, n_layer=layers))
        shardings = _draft_shardings(engine, model)

        def slice_params(p):
            out = {k: v for k, v in p.items() if k != "blocks"}
            out["blocks"] = jax.tree.map(lambda leaf: leaf[:layers],
                                         p["blocks"])
            return out

        with engine.mesh:
            params = jax.jit(slice_params,
                             out_shardings=shardings)(engine.params)
        return DraftRuntime(model=model, params=params,
                            param_shardings=shardings, mode="self",
                            layers=layers, key=draft_key(cfg))
    if mode != "model":
        raise ValueError(f"speculative.draft.mode must be self|model, "
                         f"got {mode!r}")
    over = {}
    for name in ("n_layer", "n_embd", "n_head"):
        val = int(getattr(cfg, name, 0))
        if val:
            over[name] = val
    dcfg = dataclasses.replace(tcfg, **over)
    if dcfg.n_embd % dcfg.n_head:
        raise ValueError(
            f"draft n_embd={dcfg.n_embd} not divisible by "
            f"n_head={dcfg.n_head}")
    model = type(target)(dcfg)     # same family => same vocab/positions
    shardings = _draft_shardings(engine, model)
    rng = jax.random.PRNGKey(int(getattr(cfg, "seed", 0)))
    with engine.mesh:
        params = jax.jit(
            lambda r: jax.tree.map(engine._cast_leaf, model.init(r)),
            out_shardings=shardings)(rng)
    return DraftRuntime(model=model, params=params,
                        param_shardings=shardings, mode="model",
                        layers=dcfg.n_layer, key=draft_key(cfg))


# --------------------------------------------------------------------------
# deterministic per-request sampling
# --------------------------------------------------------------------------

def row_keys(seeds, cols):
    """One PRNG key per row, derived ONLY from ``(seed, cache column)``
    — the replay-determinism contract: a failover survivor (or the same
    request at a different tick/slot) regenerates the identical key for
    every token position. seeds [S] int32; cols [S] int32 -> [S] keys."""
    return jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c))(
            seeds, cols)


def sample_rows(logits, temps, top_ks, top_ps, keys, vocab):
    """Per-row greedy / temperature / top-k / top-p sampling with
    per-row keys. logits [S, V_padded]; temps/top_ps f32 [S]; top_ks
    i32 [S] (0 = off); keys [S]. Greedy rows (temps <= 0) are fp32
    argmax over the real vocab — bitwise the ``generate()`` contract.
    Sampled rows follow HF's warper order: temperature, then top-k,
    then top-p on the top-k-renormalized distribution."""
    last = logits[:, :vocab].astype(jnp.float32)
    greedy = jnp.argmax(last, axis=-1).astype(jnp.int32)
    v = last.shape[-1]
    scaled = last / jnp.maximum(temps, 1e-6)[:, None]
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(desc, jnp.clip(top_ks - 1, 0, v - 1)[:, None],
                              axis=-1)
    k_on = (top_ks > 0)[:, None]
    masked = jnp.where(k_on & (scaled < kth), -jnp.inf, scaled)
    # top-p on the top-k survivors (exactly the first k sorted entries)
    eff_k = jnp.where(top_ks > 0, top_ks, v)
    desc = jnp.where(jnp.arange(v)[None, :] < eff_k[:, None], desc, -jnp.inf)
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_ps[:, None]
    thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    p_on = (top_ps < 1.0)[:, None]
    masked = jnp.where(p_on & (masked < thresh), -jnp.inf, masked)
    sampled = jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


def sampling_arrays(n: int):
    """Neutral per-slot sampling registers (greedy, no truncation):
    (temps f32, top_ks i32, top_ps f32, seeds i32)."""
    import numpy as np
    return (np.zeros((n,), np.float32), np.zeros((n,), np.int32),
            np.ones((n,), np.float32), np.zeros((n,), np.int32))
