"""Inference runtime (reference deepspeed/inference/)."""

from .config import DeepSpeedInferenceConfig
from .engine import InferenceEngine
