"""InferenceEngine — TP-sharded serving with a compiled KV-cache decode loop.

TPU-native re-design of the reference inference engine
(reference deepspeed/inference/engine.py:89 ``InferenceEngine``). The torch
engine mutates the module in place (kernel injection, CUDA graphs); here the
engine owns a params pytree sharded over the 'model' mesh axis and three
compiled programs:

  prefill:  [B, T_prompt] -> (logits, cache)     (cache write 0..T)
  decode:   one token through the cache          (reference softmax_context,
            csrc/transformer/inference/csrc/pt_binding.cpp:1747)
  generate: prefill + lax.scan over decode steps + sampling, ONE dispatch
            per generate() call — the XLA answer to CUDA-graph capture
            (reference inference/engine.py:500 _capture_graph).

TP serving reuses the model's training partition rules through the stage-0
sharding planner (reference auto-TP, module_inject/auto_tp.py:13, falls out
of the same rules). Sampling: greedy / temperature / top-k, with EOS
short-circuit semantics matching HF generate defaults.
"""

import math
from collections import OrderedDict
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.api import ModelSpec
from ..telemetry.trace import get_tracer
from ..parallel.topology import (DeviceMeshManager, default_devices,
                                 initialize_mesh, get_mesh_manager)
from ..runtime.zero.partition import ZeroShardingPlanner
from ..utils.logging import log_dist, logger
from .config import DeepSpeedInferenceConfig


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


def _sample_one(logits_row, temp, top_k, top_p, seed, col, vocab):
    """Single-row sampling at cache column ``col`` (the position the
    sampled token will be FED at): the key derives only from
    ``(seed, col)``, so serving replays — across ticks, slots, and
    replicas — regenerate the identical token (speculative.row_keys)."""
    from .speculative import row_keys, sample_rows
    keys = row_keys(seed[None], col[None])
    return sample_rows(logits_row[None], temp[None], top_k[None],
                       top_p[None], keys, vocab)[0]


def _lane_slice(leaf, slot_idx):
    """One slot's lane of a pool leaf (slot axis is 1): ``[d0, 1, ...]``."""
    start = (0, slot_idx) + (0,) * (leaf.ndim - 2)
    sizes = (leaf.shape[0], 1) + leaf.shape[2:]
    return lax.dynamic_slice(leaf, start, sizes)


def _lane_update(leaf, lane, slot_idx):
    """Write a lane back into a pool leaf at slot ``slot_idx``."""
    start = (0, slot_idx) + (0,) * (leaf.ndim - 2)
    return lax.dynamic_update_slice(leaf, lane.astype(leaf.dtype), start)


class InferenceEngine:
    """Callable engine: ``engine(input_ids)`` -> logits;
    ``engine.generate(...)`` -> token ids."""

    def __init__(self, model, config: DeepSpeedInferenceConfig = None,
                 params=None, mesh_manager: Optional[DeviceMeshManager] = None):
        if config is None:
            config = DeepSpeedInferenceConfig()
        self._config = config
        self.dtype = config.dtype

        # HF torch modules (the reference's primary input) are converted by
        # the injection layer into a deepspeed_tpu model spec + params.
        if not isinstance(model, ModelSpec):
            from ..module_inject import replace_transformer_layer
            model, params = replace_transformer_layer(model, config)
        self.module = model

        tp = config.tensor_parallel.tp_size
        if mesh_manager is not None:
            self.mesh_manager = mesh_manager
        else:
            devices = default_devices()
            if len(devices) % tp != 0:
                raise ValueError(
                    f"tp_size={tp} does not divide device count {len(devices)}")
            self.mesh_manager = initialize_mesh(
                dp=len(devices) // tp, tp=tp, devices=devices)
        self.mesh = self.mesh_manager.mesh

        rules = model.partition_rules() if hasattr(model, "partition_rules") \
            else []
        self.planner = ZeroShardingPlanner(self.mesh_manager, stage=0,
                                           rules=rules)

        rng = jax.random.PRNGKey(config.seed)
        param_shapes = jax.eval_shape(model.init, rng)
        self.param_shardings = self.planner.param_shardings(param_shapes)
        # int8 weight-only serving (reference GroupQuantizer at injection,
        # module_inject/replace_module.py:140): block weights become
        # QuantizedWeight pytree nodes; fp-layout shardings are kept for
        # checkpoint loads, which land in fp then quantize.
        self._quant = (config.quant
                       if config.quant is not None and config.quant.enabled
                       else None)
        self._fp_shardings = self.param_shardings
        self._fp_template = param_shapes
        if self._quant is not None:
            from .quantization import quantized_shardings
            self.param_shardings = quantized_shardings(self._fp_shardings,
                                                       param_shapes)
        self._recast_fn = None
        #: the checkpoint weights_version these params came from (0 =
        #: unversioned: fresh init or a pre-rollout checkpoint); the
        #: rollout plane compares it across replicas and KV handoffs
        self.weights_version = 0
        with self.mesh:
            if params is not None:
                self.params = self.recast(params)
            else:
                self.params = jax.jit(
                    lambda r: self._finalize_tree(
                        jax.tree.map(self._cast_leaf, model.init(r))),
                    out_shardings=self.param_shardings)(rng)
        if config.checkpoint:
            self.load_checkpoint(config.checkpoint)
        if self._quant is not None:
            from .quantization import describe
            log_dist(describe(self.params), ranks=[0])

        self._cache_rules = (model.cache_partition_rules()
                             if hasattr(model, "cache_partition_rules") else [])
        # Compiled-program cache, LRU-capped at config.compiled_cache_size:
        # shape buckets accumulate across a serving process's lifetime
        # (every distinct (batch, prompt, new_tokens) is an entry) and each
        # holds a compiled executable. Slot-serving programs live in
        # _slot_fns, exempt from eviction — the continuous-batching decode
        # step must compile exactly once per pool shape.
        self._fns: "OrderedDict[Any, Any]" = OrderedDict()
        self._slot_fns: Dict[Any, Any] = {}
        # compile ledger (telemetry/compileplane.py), attached by the
        # serving layer when its compile_plane block is on: every serving
        # program (forward, generate bucket, prefill bucket, fused decode,
        # pool init) becomes a compile event with an arg fingerprint
        self.compile_plane = None
        n_params = sum(int(np.prod(s.shape))
                       for s in jax.tree.leaves(param_shapes))
        log_dist(f"InferenceEngine initialized: params={n_params/1e6:.1f}M "
                 f"tp={tp} dtype={jnp.dtype(self.dtype).name} "
                 f"max_tokens={config.max_tokens}", ranks=[0])

    # ------------------------------------------------------------------ utils
    def _cast_leaf(self, x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(self.dtype)
        return x

    def _finalize_tree(self, params):
        """Apply weight-only quantization when configured (jit-safe)."""
        if self._quant is None:
            return params
        from .quantization import quantize_tree
        return quantize_tree(params, self._quant.group_size,
                             self._quant.bits)

    def recast(self, params):
        """Cast/re-shard a params tree into the serving layout (quantizing
        when int8 serving is on) — compiled per input structure; the hybrid
        engine refreshes fp training params through this after every
        optimizer step."""
        from .quantization import is_quantized
        if self._recast_fn is None:
            def rc(p):
                p = jax.tree.map(
                    lambda x: x if is_quantized(x) else self._cast_leaf(x),
                    p, is_leaf=is_quantized)
                return self._finalize_tree(p)
            self._recast_fn = jax.jit(rc, out_shardings=self.param_shardings)
        with self.mesh:
            return self._recast_fn(params)

    def _batch_sharding(self, batch_size: int):
        """Serving batches can be any size: shard over the dp axes only when
        divisible, else replicate (small-batch decode)."""
        if batch_size % self.mesh_manager.dp_world_size == 0:
            return self.mesh_manager.batch_sharding(False)
        return NamedSharding(self.mesh, P())

    def _cache_shardings(self, cache_shapes, rules=None):
        planner = ZeroShardingPlanner(self.mesh_manager, stage=0,
                                      rules=self._cache_rules
                                      if rules is None else rules)
        return planner.param_shardings(cache_shapes)

    def _observe_compile(self, label, fn, args, names=None):
        """Compile-ledger hook: no-op unless the serving layer attached a
        ledger. Observes BEFORE the call — the fused decode step donates
        its pool (its args must be read while still live), and the other
        serving programs don't donate, so before-the-call is the one
        ordering that works for all of them."""
        cp = self.compile_plane
        if cp is None:
            return
        try:
            cp.observe(label, fn, args, names=names, mesh=self.mesh)
        except Exception as e:   # observability must never fail a request
            logger.warning(f"compile plane: observe failed: {e}")

    def _fn_get(self, key):
        """LRU lookup in the compiled-program cache."""
        fn = self._fns.get(key)
        if fn is not None:
            self._fns.move_to_end(key)
        return fn

    def _fn_put(self, key, fn):
        """Insert into the compiled-program cache, evicting the least
        recently used entries past config.compiled_cache_size."""
        self._fns[key] = fn
        self._fns.move_to_end(key)
        cap = getattr(self._config, "compiled_cache_size", 0) or 0
        while cap > 0 and len(self._fns) > cap:
            old_key, _ = self._fns.popitem(last=False)
            logger.debug(
                f"InferenceEngine: evicting compiled program {old_key} "
                f"(compiled_cache_size={cap})")
        return fn

    def load_checkpoint(self, load_dir, tag=None):
        """Load a deepspeed_tpu training checkpoint (any source mp/dp layout
        — universal reshard-on-load) into the serving shardings. Checkpoints
        are fp; int8 serving quantizes after the reshard."""
        from ..runtime.checkpointing import read_weights_version
        self.params = self._load_params(load_dir, tag)
        self.weights_version = read_weights_version(load_dir, tag=tag)
        return load_dir

    def _load_params(self, load_dir, tag=None):
        """Checkpoint params resharded into this engine's serving layout
        (structure-gated: a drifted leaf set raises with the per-leaf
        diff BEFORE anything moves to device)."""
        from ..runtime.checkpointing import load_params_for_inference
        with self.mesh:
            params = load_params_for_inference(
                load_dir, tag=tag, like=self._fp_template,
                shardings=self._fp_shardings, cast=self._cast_leaf)
            if self._quant is not None:
                params = jax.jit(self._finalize_tree,
                                 out_shardings=self.param_shardings)(params)
        return params

    def with_params(self, params, weights_version=None):
        """A shallow engine view sharing this engine's module, mesh,
        planner, and compiled-program caches but serving ``params`` —
        the rollout plane's vNext standup. Identical shapes mean the
        shared executables serve both versions with ZERO new compiles;
        only the params pointer (and the reported version) differ."""
        import copy
        view = copy.copy(self)
        view.params = params
        if weights_version is not None:
            view.weights_version = int(weights_version)
        return view

    def load_version(self, load_dir, tag=None):
        """Load a checkpoint WITHOUT mutating this engine: returns a
        shallow view (``with_params``) serving the new weights at the
        checkpoint's ``weights_version``. The structure gate and the
        integrity manifest both run before the view exists, so a bad
        checkpoint aborts here — never after traffic moved."""
        from ..runtime.checkpointing import read_weights_version
        params = self._load_params(load_dir, tag)
        return self.with_params(
            params, read_weights_version(load_dir, tag=tag))

    # ---------------------------------------------------------------- forward
    def forward(self, input_ids, **kwargs):
        """Full-sequence logits (scoring path, no cache)."""
        input_ids = jnp.asarray(input_ids)
        key = ("fwd", input_ids.shape)
        fn = self._fn_get(key)
        if fn is None:
            def fwd(params, ids):
                logits, _ = self.module.logits(params, ids, train=False,
                                               return_aux_loss=True)
                return logits
            fn = self._fn_put(key, jax.jit(
                fwd, in_shardings=(self.param_shardings,
                                   self._batch_sharding(input_ids.shape[0]))))
        self._observe_compile("fwd", fn, (self.params, input_ids),
                              names=("params", "input_ids"))
        with self.mesh:
            return fn(self.params, input_ids)

    __call__ = forward

    # --------------------------------------------------------------- generate
    def generate(self, input_ids, max_new_tokens: int = 64,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 max_length: Optional[int] = None, top_p: float = 1.0,
                 num_beams: int = 1, attention_mask=None,
                 length_penalty: float = 1.0):
        """Autoregressive generation, one compiled program per
        (prompt_shape, max_new_tokens) bucket. Returns [B, T+max_new_tokens]
        (prompt + generated; positions after EOS hold eos_token_id).
        ``num_beams > 1`` runs deterministic beam search (temperature/
        top-k/top-p must be off). ``attention_mask`` [B, T] (HF convention,
        1 = real token) serves LEFT-padded batches of uneven prompts: pad
        columns never act as keys and logical positions shift per row."""
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if temperature <= 0.0 and (top_k or top_p < 1.0):
            raise ValueError(
                "top_k/top_p require temperature > 0 (temperature<=0 means "
                "greedy decoding, which would silently ignore them); pass "
                "temperature=1.0 for plain top-k/top-p sampling")
        if num_beams < 1:
            raise ValueError(f"num_beams must be >= 1, got {num_beams}")
        if num_beams == 1 and length_penalty != 1.0:
            raise ValueError(
                "length_penalty only applies to beam search "
                f"(got length_penalty={length_penalty} with num_beams=1)")
        if num_beams > 1 and (temperature > 0 or top_k or top_p < 1.0):
            raise ValueError(
                "beam search is deterministic: temperature/top_k/top_p "
                "cannot be combined with num_beams > 1")
        input_ids = jnp.asarray(input_ids)
        if input_ids.ndim == 1:
            input_ids = input_ids[None]
        b, t = input_ids.shape
        pad_counts = None
        if attention_mask is not None:
            attention_mask = jnp.asarray(attention_mask)
            if attention_mask.shape != (b, t):
                raise ValueError(
                    f"attention_mask shape {attention_mask.shape} != "
                    f"input_ids shape {(b, t)}")
            if num_beams > 1:
                raise NotImplementedError(
                    "attention_mask (padded prompts) + beam search is not "
                    "supported yet")
            # HF left-padding: mask must be 0..0 1..1 per row — enforce it
            # (a right-padded mask would silently shift positions wrongly
            # and sample from a pad token's hidden state)
            pad_counts = (t - attention_mask.sum(-1)).astype(jnp.int32)
            expect = jnp.arange(t)[None, :] >= pad_counts[:, None]
            if not bool(jnp.all(attention_mask.astype(bool) == expect)):
                raise ValueError(
                    "attention_mask must be contiguous LEFT padding "
                    "(rows of 0..0 1..1); right-padded or interior-zero "
                    "masks are not supported")
        if max_length is not None:
            max_new_tokens = max(0, max_length - t)
        if max_new_tokens <= 0:
            return input_ids  # prompt already at/over max_length
        n_pos = getattr(getattr(self.module, "config", None),
                        "n_positions", None)
        if n_pos is not None and t + max_new_tokens > n_pos:
            raise ValueError(
                f"generate: prompt {t} + max_new_tokens {max_new_tokens} "
                f"exceeds the model's context length n_positions={n_pos}")
        cache_len = min(_next_pow2(t + max_new_tokens),
                        max(self._config.max_tokens, t + max_new_tokens))
        if t + max_new_tokens > self._config.max_tokens:
            logger.warning(
                f"generate: {t}+{max_new_tokens} tokens exceeds config "
                f"max_tokens={self._config.max_tokens} "
                f"(reference inference/engine.py:588 guard); growing cache")

        key = ("gen", b, t, max_new_tokens, float(temperature), top_k,
               float(top_p), eos_token_id, num_beams, pad_counts is not None,
               float(length_penalty))
        fn = self._fn_get(key)
        if fn is None:
            if num_beams > 1:
                fn = self._build_beam_generate(
                    b, t, cache_len, max_new_tokens, num_beams, eos_token_id,
                    length_penalty)
            else:
                fn = self._build_generate(
                    b, t, cache_len, max_new_tokens, temperature, top_k,
                    top_p, eos_token_id, padded=pad_counts is not None)
            self._fn_put(key, fn)
        tr = get_tracer()
        gen_key = jax.random.PRNGKey(seed)
        gen_args = (self.params, input_ids, gen_key) if num_beams > 1 else \
            (self.params, input_ids, gen_key, pad_counts)
        self._observe_compile("generate", fn, gen_args,
                              names=("params", "input_ids", "rng",
                                     "pad_counts"))
        with tr.span("generate", cat="inference",
                     args={"batch": b, "prompt_len": t,
                           "max_new_tokens": max_new_tokens,
                           "num_beams": num_beams}) as sp:
            with self.mesh:
                out = fn(*gen_args)
            if tr.sync_spans:
                sp.sync_on(out)
        return out

    def _build_generate(self, b, t, cache_len, max_new_tokens, temperature,
                        top_k, top_p, eos_token_id, padded=False):
        model = self.module
        vocab = model.config.vocab_size

        def sample(logits, key):
            # logits [B, V_padded]; restrict to the real vocab
            logits = logits[:, :vocab].astype(jnp.float32)
            if temperature <= 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits = logits / temperature
            if top_k or top_p < 1.0:     # one descending sort serves both
                desc = jnp.sort(logits, axis=-1)[:, ::-1]
            if top_k:
                logits = jnp.where(logits < desc[:, top_k - 1][:, None],
                                   -jnp.inf, logits)
                # top-k survivors are exactly the first k sorted entries
                desc = jnp.where(
                    jnp.arange(desc.shape[-1])[None] < top_k, desc, -jnp.inf)
            if top_p < 1.0:
                # nucleus: keep the smallest prefix of descending-prob
                # tokens whose mass reaches top_p (always >= 1 token),
                # computed on the top-k-RENORMALIZED distribution — HF's
                # TopK-then-TopP warper order
                probs = jax.nn.softmax(desc, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                keep = (cum - probs) < top_p
                thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1,
                                 keepdims=True)
                logits = jnp.where(logits >= thresh, logits, -jnp.inf)
            return jax.random.categorical(key, logits, axis=-1).astype(
                jnp.int32)

        cache_shapes = jax.eval_shape(
            lambda: model.init_kv_cache(b, cache_len, dtype=self.dtype))
        cache_specs = jax.tree.map(
            lambda sh: sh.spec, self._cache_shardings(cache_shapes))

        def constrain(cache):
            return lax.with_sharding_constraint(cache, cache_specs)

        def run(params, prompt, key, pad_counts=None):
            pc = pad_counts if padded else None
            cache = constrain(
                model.init_kv_cache(b, cache_len, dtype=self.dtype))
            logits, cache = model.apply_with_cache(params, prompt, cache,
                                                   jnp.int32(0),
                                                   pad_counts=pc)
            tok = sample(logits[:, -1], key)
            finished = (jnp.zeros((b,), jnp.bool_) if eos_token_id is None
                        else tok == eos_token_id)

            def step(carry, i):
                cache, tok, finished, key = carry
                key, sub = jax.random.split(key)
                # tok was sampled for position t+i-1; write its K/V there
                logits, cache = model.apply_with_cache(
                    params, tok[:, None], cache, t + i - 1, pad_counts=pc)
                cache = constrain(cache)
                nxt = sample(logits[:, -1], sub)
                if eos_token_id is not None:
                    nxt = jnp.where(finished, eos_token_id, nxt)
                    finished = finished | (nxt == eos_token_id)
                return (cache, nxt, finished, key), tok

            if max_new_tokens > 1:
                (_, last, _, _), toks = lax.scan(
                    step, (cache, tok, finished, key),
                    jnp.arange(1, max_new_tokens, dtype=jnp.int32))
                toks = jnp.concatenate([toks.T, last[:, None]], axis=-1)
            else:
                toks = tok[:, None]
            return jnp.concatenate([prompt, toks], axis=-1)

        return jax.jit(run, in_shardings=(
            self.param_shardings, self._batch_sharding(b), None, None))

    def _build_beam_generate(self, b, t, cache_len, max_new_tokens, k,
                             eos_token_id, length_penalty=1.0):
        """Deterministic beam search, fully in-jit (reference parity:
        inference/engine.py:588 delegates beams to HF generate; here the
        whole search — expand, score, reorder-cache, backtrack-free
        sequence buffer — is one compiled program)."""
        model = self.module
        vocab = model.config.vocab_size
        NEG = jnp.float32(-1e30)
        cache_shapes = jax.eval_shape(
            lambda: model.init_kv_cache(b * k, cache_len, dtype=self.dtype))
        cache_specs = jax.tree.map(
            lambda sh: sh.spec, self._cache_shardings(cache_shapes))

        def run(params, prompt, _key):
            # prefill ONCE at batch B, then tile the cache to B*K beams
            small = model.init_kv_cache(b, cache_len, dtype=self.dtype)
            logits, small = model.apply_with_cache(params, prompt, small,
                                                   jnp.int32(0))
            cache = lax.with_sharding_constraint(
                jax.tree.map(lambda c: jnp.repeat(c, k, axis=1), small),
                cache_specs)
            logp = jax.nn.log_softmax(
                logits[:, -1, :vocab].astype(jnp.float32), axis=-1)
            logp = jnp.repeat(logp, k, axis=0).reshape(b, k, vocab)
            # beams start identical: only beam 0 may propose, or the top-k
            # picks would be k copies of the same token
            first = jnp.where(jnp.arange(k)[None, :, None] == 0, logp[:, :1],
                              NEG)
            scores, flat = lax.top_k(first.reshape(b, k * vocab), k)
            tok = (flat % vocab).astype(jnp.int32)          # [B, K]
            finished = (tok == eos_token_id) if eos_token_id is not None \
                else jnp.zeros((b, k), jnp.bool_)
            lengths = jnp.ones((b, k), jnp.float32)   # generated incl. EOS
            seqs = jnp.zeros((b, k, max_new_tokens), jnp.int32)
            seqs = seqs.at[:, :, 0].set(tok)

            def step(carry, i):
                cache, seqs, tok, scores, finished, lengths = carry
                logits, cache = model.apply_with_cache(
                    params, tok.reshape(b * k, 1), cache, t + i - 1)
                logp = jax.nn.log_softmax(
                    logits[:, -1, :vocab].astype(jnp.float32), axis=-1)
                logp = logp.reshape(b, k, vocab)
                if eos_token_id is not None:
                    # finished beams: frozen score, only-EOS continuation
                    only_eos = jnp.where(
                        jnp.arange(vocab)[None, None] == eos_token_id,
                        0.0, NEG)
                    logp = jnp.where(finished[..., None], only_eos, logp)
                total = scores[..., None] + logp            # [B, K, V]
                scores, flat = lax.top_k(total.reshape(b, k * vocab), k)
                parent = flat // vocab                      # [B, K]
                tok = (flat % vocab).astype(jnp.int32)
                # reorder beam state by parent
                gather = jnp.take_along_axis
                seqs = gather(seqs, parent[..., None], axis=1)
                seqs = seqs.at[:, :, i].set(tok)
                finished = gather(finished, parent, axis=1)
                lengths = gather(lengths, parent, axis=1)
                # unfinished beams grew by one token (incl. a fresh EOS);
                # already-finished beams' appended EOS is padding
                lengths = lengths + (~finished).astype(jnp.float32)
                if eos_token_id is not None:
                    finished = finished | (tok == eos_token_id)
                flat_parent = (jnp.arange(b)[:, None] * k +
                               parent).reshape(b * k)
                cache = lax.with_sharding_constraint(
                    jax.tree.map(
                        lambda c: jnp.take(c, flat_parent, axis=1), cache),
                    cache_specs)
                return (cache, seqs, tok, scores, finished, lengths), None

            if max_new_tokens > 1:
                (cache, seqs, tok, scores, finished, lengths), _ = lax.scan(
                    step, (cache, seqs, tok, scores, finished, lengths),
                    jnp.arange(1, max_new_tokens, dtype=jnp.int32))
            # HF default semantics: pick by score / length**length_penalty
            # (length_penalty 1.0) so beams that hit EOS early are not
            # unconditionally favored
            norm = scores / jnp.power(jnp.maximum(lengths, 1.0),
                                      jnp.float32(length_penalty))
            best = jnp.argmax(norm, axis=-1)                # [B]
            out = jnp.take_along_axis(seqs, best[:, None, None],
                                      axis=1)[:, 0]         # [B, max_new]
            if eos_token_id is not None:
                # positions after EOS hold eos_token_id (sampled-path
                # semantics)
                hit = jnp.cumsum(
                    (out == eos_token_id).astype(jnp.int32), axis=-1)
                out = jnp.where(hit > 1, eos_token_id, out)
            return jnp.concatenate([prompt, out], axis=-1)

        return jax.jit(run, in_shardings=(
            self.param_shardings, self._batch_sharding(b), None))

    # ------------------------------------------------- slot-serving protocol
    # Entry points for the continuous-batching serving layer
    # (deepspeed_tpu/serving/): a fixed pool of decode slots — batch rows of
    # one statically-shaped KV cache — so admission/retirement of requests
    # never changes a compiled shape. Three programs: prefill-into-slot
    # (one per pow2 prompt bucket), the fused all-slot decode step (compiles
    # EXACTLY once per (num_slots, max_len)), and pool init. All are exempt
    # from the _fns LRU: evicting the decode step would silently recompile
    # the serving hot path.

    def _pool_shardings(self, num_slots: int, max_len: int,
                        quantize: bool = False, model=None):
        """Cache-rule shardings for the slot pool, with any mesh axis that
        does not divide its dimension dropped to replication (num_slots is
        operator-chosen and rarely divides the dp axes; heads-over-'model'
        TP is the sharding that matters for serving). With ``quantize``,
        returns a QuantizedSlotPool of shardings: q leaves keep the fp
        spec, per-column scale leaves keep it minus the trailing hd axis.
        ``model`` overrides the cached model (the speculative DRAFT pool
        follows the draft model's cache rules)."""
        rules = None
        if model is None:
            model = self.module
        else:
            rules = (model.cache_partition_rules()
                     if hasattr(model, "cache_partition_rules") else [])
        shapes = jax.eval_shape(
            lambda: model.init_kv_cache(num_slots, max_len,
                                        dtype=self.dtype))
        shardings = self._cache_shardings(shapes, rules=rules)

        def axis_size(ax):
            names = ax if isinstance(ax, (tuple, list)) else (ax,)
            size = 1
            for n in names:
                size *= self.mesh.shape[n]
            return size

        def fix(sh, leaf):
            spec = tuple(sh.spec) + (None,) * (len(leaf.shape) - len(sh.spec))
            kept = tuple(ax if ax is not None and dim % axis_size(ax) == 0
                         else None
                         for ax, dim in zip(spec, leaf.shape))
            return NamedSharding(self.mesh, P(*kept))

        fixed = jax.tree.map(fix, shardings, shapes)
        if not quantize:
            return fixed
        from .kv_quant import QuantizedSlotPool

        def drop_hd(sh, leaf):
            spec = tuple(sh.spec) + (None,) * (len(leaf.shape) - len(sh.spec))
            return NamedSharding(self.mesh, P(*spec[:-1]))

        return QuantizedSlotPool(
            q=fixed, scales=jax.tree.map(drop_hd, fixed, shapes))

    @staticmethod
    def _is_quantized_pool(pool) -> bool:
        from .kv_quant import QuantizedSlotPool
        return isinstance(pool, QuantizedSlotPool)

    @staticmethod
    def _pool_dims(pool):
        """(num_slots, max_len, quantized) from any pool flavor."""
        quantized = InferenceEngine._is_quantized_pool(pool)
        leaf = jax.tree.leaves(pool.q if quantized else pool)[0]
        return int(leaf.shape[1]), int(leaf.shape[-2]), quantized

    def _read_lane(self, pool, slot_idx, quantized):
        """One slot's lane as an fp mini-cache [L, 1, H, max_len, hd]
        (jit-safe; dequantizes just the lane for quantized pools)."""
        if not quantized:
            return jax.tree.map(lambda leaf: _lane_slice(leaf, slot_idx),
                                pool)
        from .kv_quant import dequantize_kv
        return jax.tree.map(
            lambda qc, sc: dequantize_kv(_lane_slice(qc, slot_idx),
                                         _lane_slice(sc, slot_idx),
                                         self.dtype),
            pool.q, pool.scales)

    @staticmethod
    def _write_lane(pool, mini, slot_idx, quantized):
        """Write an fp mini-cache back into slot ``slot_idx`` (jit-safe;
        re-quantizes only this lane for quantized pools — per-column
        scales keep the round-trip of untouched columns exact)."""
        if not quantized:
            return jax.tree.map(
                lambda pc, mc: _lane_update(pc, mc, slot_idx), pool, mini)
        from .kv_quant import QuantizedSlotPool, quantize_kv
        pairs = jax.tree.map(quantize_kv, mini)
        istup = lambda t: isinstance(t, tuple)   # noqa: E731
        mini_q = jax.tree.map(lambda p: p[0], pairs, is_leaf=istup)
        mini_s = jax.tree.map(lambda p: p[1], pairs, is_leaf=istup)
        return QuantizedSlotPool(
            q=jax.tree.map(lambda pc, mc: _lane_update(pc, mc, slot_idx),
                           pool.q, mini_q),
            scales=jax.tree.map(
                lambda pc, mc: _lane_update(pc, mc, slot_idx),
                pool.scales, mini_s))

    def init_slot_pool(self, num_slots: int, max_len: int,
                       quantize: bool = False):
        """Allocate the slot-pool KV cache [L, num_slots, H, max_len, hd],
        once, at static shape. ``quantize=True`` allocates it int8 with
        per-column f32 scales (inference/kv_quant.py) — ~4x the slots per
        HBM byte; the slot programs transparently branch on the pool
        type."""
        key = ("slot_pool", num_slots, max_len) + \
            (("q8",) if quantize else ())
        fn = self._slot_fns.get(key)
        if fn is None:
            if quantize:
                from .kv_quant import quantize_pool

                def build():
                    return quantize_pool(self.module.init_kv_cache(
                        num_slots, max_len, dtype=self.dtype))
            else:
                def build():
                    return self.module.init_kv_cache(num_slots, max_len,
                                                     dtype=self.dtype)
            fn = self._slot_fns[key] = jax.jit(
                build, out_shardings=self._pool_shardings(
                    num_slots, max_len, quantize=quantize))
        self._observe_compile("slot_pool", fn, ())
        with self.mesh:
            return fn()

    def slot_prefill(self, pool, slot: int, prompt, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 1.0, seed: int = 0):
        """Prefill ``prompt`` (1-D int array) into ``pool`` slot ``slot`` and
        sample the first generated token. The prompt is right-padded to a
        pow2 bucket (one compile per bucket; pad K/V beyond the prompt is
        masked until overwritten by decode writes). Sampling is
        deterministic per ``(seed, position)`` — replay-safe. Returns
        (new_pool, first_token:int)."""
        model = self.module
        vocab = model.config.vocab_size
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        t = prompt.shape[0]
        num_slots, max_len, quantized = self._pool_dims(pool)
        if not 0 < t <= max_len:
            raise ValueError(f"prompt length {t} not in [1, {max_len}]")
        bucket = min(_next_pow2(t), max_len)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t] = prompt
        fkey = ("slot_prefill", bucket, max_len) + \
            (("q8",) if quantized else ())
        fn = self._slot_fns.get(fkey)
        if fn is None:
            pool_shardings = self._pool_shardings(num_slots, max_len,
                                                  quantize=quantized)

            def pf(params, ids, pool, slot_idx, last_idx, temp, top_k,
                   top_p, seed):
                mini = model.init_kv_cache(1, max_len, dtype=self.dtype)
                logits, mini = model.apply_with_cache(params, ids, mini,
                                                      jnp.int32(0))
                pool = self._write_lane(pool, mini, slot_idx, quantized)
                last = jnp.take(logits[0], last_idx, axis=0)
                # the first token is FED at column last_idx + 1
                tok = _sample_one(last, temp, top_k, top_p, seed,
                                  last_idx + 1, vocab)
                return pool, tok

            fn = self._slot_fns[fkey] = jax.jit(pf, in_shardings=(
                self.param_shardings, None, pool_shardings, None, None, None,
                None, None, None), out_shardings=(pool_shardings, None))
        pf_args = (self.params, jnp.asarray(ids), pool, jnp.int32(slot),
                   jnp.int32(t - 1), jnp.float32(temperature),
                   jnp.int32(top_k), jnp.float32(top_p), jnp.int32(seed))
        self._observe_compile("slot_prefill", fn, pf_args,
                              names=("params", "ids", "pool", "slot",
                                     "last_idx", "temperature", "top_k",
                                     "top_p", "seed"))
        with self.mesh:
            pool, tok = fn(*pf_args)
        return pool, int(tok)

    def slot_suffix_prefill(self, pool, slot: int, tokens, start_pos: int,
                            temperature: float = 0.0, top_k: int = 0,
                            top_p: float = 1.0, seed: int = 0):
        """Prefill only the SUFFIX ``tokens`` of a prompt into slot
        ``slot`` whose lane already holds valid K/V for cache columns
        ``[0, start_pos)`` — the prefix-reuse fast path
        (serving/fleet/prefix_cache.py): after ``slot_copy_lane`` from a
        cached donor, only the tokens past the shared prefix run through
        the stack. The suffix is right-padded to a pow2 bucket (one
        compile per bucket, shared with every start_pos — the offset is a
        traced scalar); callers size the bucket via
        ``prefix_cache.reuse_plan`` so ``start_pos + bucket <= max_len``.
        Returns (new_pool, next_token:int)."""
        model = self.module
        vocab = model.config.vocab_size
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        t = tokens.shape[0]
        num_slots, max_len, quantized = self._pool_dims(pool)
        if t < 1:
            raise ValueError("suffix must carry at least one token (the "
                             "sampled next token needs a query position)")
        bucket = min(_next_pow2(t), max_len)
        if start_pos < 0 or start_pos + bucket > max_len:
            raise ValueError(
                f"suffix bucket [{start_pos}, {start_pos + bucket}) exceeds "
                f"max_len={max_len}; plan the reuse offset with "
                f"prefix_cache.reuse_plan")
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t] = tokens
        fkey = ("slot_suffix", bucket, max_len) + \
            (("q8",) if quantized else ())
        fn = self._slot_fns.get(fkey)
        if fn is None:
            pool_shardings = self._pool_shardings(num_slots, max_len,
                                                  quantize=quantized)

            def spf(params, ids, pool, slot_idx, start_pos, last_idx, temp,
                    top_k, top_p, seed):
                mini = self._read_lane(pool, slot_idx, quantized)
                logits, mini = model.apply_with_cache(params, ids, mini,
                                                      start_pos)
                pool = self._write_lane(pool, mini, slot_idx, quantized)
                last = jnp.take(logits[0], last_idx, axis=0)
                tok = _sample_one(last, temp, top_k, top_p, seed,
                                  start_pos + last_idx + 1, vocab)
                return pool, tok

            fn = self._slot_fns[fkey] = jax.jit(spf, in_shardings=(
                self.param_shardings, None, pool_shardings, None, None, None,
                None, None, None, None), out_shardings=(pool_shardings, None))
        spf_args = (self.params, jnp.asarray(ids), pool, jnp.int32(slot),
                    jnp.int32(start_pos), jnp.int32(t - 1),
                    jnp.float32(temperature), jnp.int32(top_k),
                    jnp.float32(top_p), jnp.int32(seed))
        self._observe_compile("slot_suffix_prefill", fn, spf_args,
                              names=("params", "ids", "pool", "slot",
                                     "start_pos", "last_idx", "temperature",
                                     "top_k", "top_p", "seed"))
        with self.mesh:
            pool, tok = fn(*spf_args)
        return pool, int(tok)

    def slot_chunk_prefill(self, pool, slot: int, tokens, start_pos: int):
        """Write ONE CHUNK of a prompt's K/V into slot ``slot`` at cache
        columns ``[start_pos, start_pos+len(tokens))`` without sampling —
        the building block of chunked prefill (serving/scheduler.py): a
        long prompt is admitted as a sequence of fixed-size chunks
        interleaved with decode ticks, so no decode tick ever waits on
        more than ``chunk_tokens`` of prefill work. The chunk is
        right-padded to a pow2 bucket (one compiled program per
        (bucket, pool) flavor — the scheduler always sends full
        ``chunk_tokens`` chunks, so steady state is exactly ONE flavor);
        the logits head is dead code and XLA eliminates it
        (``chunk_prefill_with_cache``). Pad columns past the chunk hold
        garbage K/V until the next chunk (or a decode write) overwrites
        them, exactly like a fresh prefill's pad tail. The FINAL chunk of
        a prompt never comes through here — it runs
        ``slot_suffix_prefill`` so the first token is sampled at the same
        ``(seed, position)`` key a monolithic prefill would use (bitwise
        token parity). Returns the new pool."""
        model = self.module
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        t = tokens.shape[0]
        num_slots, max_len, quantized = self._pool_dims(pool)
        if t < 1:
            raise ValueError("chunk must carry at least one token")
        bucket = min(_next_pow2(t), max_len)
        if start_pos < 0 or start_pos + bucket > max_len:
            raise ValueError(
                f"chunk bucket [{start_pos}, {start_pos + bucket}) exceeds "
                f"max_len={max_len}; size chunks so every bucket fits")
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t] = tokens
        fkey = ("slot_chunk", num_slots, bucket, max_len) + \
            (("q8",) if quantized else ())
        fn = self._slot_fns.get(fkey)
        if fn is None:
            pool_shardings = self._pool_shardings(num_slots, max_len,
                                                  quantize=quantized)

            def cpf(params, ids, pool, slot_idx, start_pos):
                mini = self._read_lane(pool, slot_idx, quantized)
                mini = model.chunk_prefill_with_cache(params, ids, mini,
                                                      start_pos)
                return self._write_lane(pool, mini, slot_idx, quantized)

            fn = self._slot_fns[fkey] = jax.jit(cpf, in_shardings=(
                self.param_shardings, None, pool_shardings, None, None),
                out_shardings=pool_shardings, donate_argnums=(2,))
        cpf_args = (self.params, jnp.asarray(ids), pool, jnp.int32(slot),
                    jnp.int32(start_pos))
        self._observe_compile("slot_chunk_prefill", fn, cpf_args,
                              names=("params", "ids", "pool", "slot",
                                     "start_pos"))
        with self.mesh:
            return fn(*cpf_args)

    def slot_chunk_executables(self, num_slots: int, max_len: int,
                               bucket: int,
                               quantized: Optional[bool] = None) -> int:
        """Compiled-executable count behind the chunk-prefill program for
        one pow2 bucket flavor — the compile-once evidence the chunked-
        prefill tests assert (mirrors slot_decode_executables)."""
        keys = {None: (("slot_chunk", num_slots, bucket, max_len),
                       ("slot_chunk", num_slots, bucket, max_len, "q8")),
                False: (("slot_chunk", num_slots, bucket, max_len),),
                True: (("slot_chunk", num_slots, bucket, max_len, "q8"),)}
        total = 0
        for fkey in keys[quantized]:
            fn = self._slot_fns.get(fkey)
            if fn is not None:
                total += fn._cache_size()
        return total

    def slot_copy_lane(self, pool, src: int, dst: int):
        """Copy slot ``src``'s whole cache lane over slot ``dst``'s —
        device-side, no host round-trip, quantized lanes copy their q and
        scale slices verbatim (no requantization). The prefix-reuse
        admission path: copy the donor lane, then suffix-prefill from the
        shared-prefix boundary; stale donor columns past the new request's
        length are masked until decode overwrites them, exactly like a
        fresh prefill's pad columns."""
        num_slots, max_len, quantized = self._pool_dims(pool)
        fkey = ("slot_copy", num_slots, max_len) + \
            (("q8",) if quantized else ())
        fn = self._slot_fns.get(fkey)
        if fn is None:
            pool_shardings = self._pool_shardings(num_slots, max_len,
                                                  quantize=quantized)

            def cp(pool, src_idx, dst_idx):
                return jax.tree.map(
                    lambda leaf: _lane_update(
                        leaf, _lane_slice(leaf, src_idx), dst_idx), pool)

            fn = self._slot_fns[fkey] = jax.jit(
                cp, out_shardings=pool_shardings)
        cp_args = (pool, jnp.int32(src), jnp.int32(dst))
        self._observe_compile("slot_copy", fn, cp_args,
                              names=("pool", "src", "dst"))
        with self.mesh:
            return fn(*cp_args)

    def slot_extract_lane(self, pool, slot: int):
        """Slot ``slot``'s cache lane as a HOST pytree (np arrays) — the
        payload of a KVHandoff (serving/fleet/handoff.py). Quantized pools
        hand off their int8 q + f32 scale slices directly: the wire cost
        of a disaggregated prefill→decode transfer is the quantized lane,
        not a dequantized copy."""
        num_slots, max_len, quantized = self._pool_dims(pool)
        fkey = ("slot_extract", num_slots, max_len) + \
            (("q8",) if quantized else ())
        fn = self._slot_fns.get(fkey)
        if fn is None:
            def ex(pool, idx):
                return jax.tree.map(lambda leaf: _lane_slice(leaf, idx),
                                    pool)

            fn = self._slot_fns[fkey] = jax.jit(ex)
        ex_args = (pool, jnp.int32(slot))
        self._observe_compile("slot_extract", fn, ex_args,
                              names=("pool", "slot"))
        with self.mesh:
            lane = fn(*ex_args)
        return jax.device_get(lane)

    def slot_insert_lane(self, pool, slot: int, lane):
        """Insert a lane (from ``slot_extract_lane``, possibly another
        replica's pool) into slot ``slot``. Handles every quantization
        pairing: fp lanes quantize on the way into a quantized pool,
        quantized lanes dequantize into an fp pool — so a prefill replica
        and a decode replica need not share a KV storage format."""
        num_slots, max_len, pool_q = self._pool_dims(pool)
        lane_q = self._is_quantized_pool(lane)
        fkey = ("slot_insert", num_slots, max_len, pool_q, lane_q)
        fn = self._slot_fns.get(fkey)
        if fn is None:
            pool_shardings = self._pool_shardings(num_slots, max_len,
                                                  quantize=pool_q)
            from .kv_quant import (QuantizedSlotPool, dequantize_pool,
                                   quantize_pool)

            def ins(pool, lane, idx):
                if pool_q and not lane_q:
                    lane = quantize_pool(lane)
                elif not pool_q and lane_q:
                    lane = dequantize_pool(lane, self.dtype)
                if pool_q:
                    return QuantizedSlotPool(
                        q=jax.tree.map(
                            lambda pc, mc: _lane_update(pc, mc, idx),
                            pool.q, lane.q),
                        scales=jax.tree.map(
                            lambda pc, mc: _lane_update(pc, mc, idx),
                            pool.scales, lane.scales))
                return jax.tree.map(
                    lambda pc, mc: _lane_update(pc, mc, idx), pool, lane)

            fn = self._slot_fns[fkey] = jax.jit(
                ins, out_shardings=pool_shardings)
        ins_args = (pool, lane, jnp.int32(slot))
        self._observe_compile("slot_insert", fn, ins_args,
                              names=("pool", "lane", "slot"))
        with self.mesh:
            return fn(*ins_args)

    def slot_decode_step(self, pool, toks, positions, temps, top_ks=None,
                         top_ps=None, seeds=None):
        """One fused decode step over ALL slots: feed token ``toks[s]`` at
        cache column ``positions[s]`` and sample the next token per slot
        (greedy where temps[s] <= 0; per-row top-k/top-p with keys
        derived from ``(seeds[s], position)`` otherwise — deterministic
        replay). Inactive slots pass dummy inputs and their outputs are
        ignored by the scheduler. Returns (new_pool, next_tokens [S])."""
        model = self.module
        vocab = model.config.vocab_size
        num_slots, max_len, quantized = self._pool_dims(pool)
        fkey = ("slot_decode", num_slots, max_len) + \
            (("q8",) if quantized else ())
        fn = self._slot_fns.get(fkey)
        if fn is None:
            pool_shardings = self._pool_shardings(num_slots, max_len,
                                                  quantize=quantized)
            from .speculative import row_keys, sample_rows

            def dec(params, pool, toks, positions, temps, top_ks, top_ps,
                    seeds):
                if quantized:
                    from .kv_quant import dequantize_pool, quantize_pool
                    fp = dequantize_pool(pool, self.dtype)
                else:
                    fp = pool
                logits, fp = model.decode_with_slots(
                    params, toks[:, None], fp, positions)
                # the sampled token will be FED at column positions + 1
                # ("sample" scope: the perf plane buckets this tail apart
                # from the model forward it follows)
                with jax.named_scope("sample"):
                    keys = row_keys(seeds, positions + 1)
                    nxt = sample_rows(logits[:, -1], temps, top_ks,
                                      top_ps, keys, vocab)
                # re-quantize on the way out: per-column scales make the
                # round-trip of every column this step did not write exact,
                # so old tokens never re-accumulate quantization error
                pool = quantize_pool(fp) if quantized else fp
                return pool, nxt

            # donate the pool: decode is state-in/state-out per tick, and
            # an undonated pool keeps TWO pool-sized buffers live across
            # every step — the kv_slots HBM doubling ds_tpu_lint's
            # donation auditor (HLO005) flags. Every caller rebinds the
            # pool from the return (scheduler.py decode tick included).
            fn = self._slot_fns[fkey] = jax.jit(dec, in_shardings=(
                self.param_shardings, pool_shardings, None, None, None, None,
                None, None),
                out_shardings=(pool_shardings, None),
                donate_argnums=(1,))
        n = len(np.asarray(toks).reshape(-1))
        if top_ks is None:
            top_ks = np.zeros((n,), np.int32)
        if top_ps is None:
            top_ps = np.ones((n,), np.float32)
        if seeds is None:
            seeds = np.zeros((n,), np.int32)
        dec_args = (self.params, pool, jnp.asarray(toks, jnp.int32),
                    jnp.asarray(positions, jnp.int32),
                    jnp.asarray(temps, jnp.float32),
                    jnp.asarray(top_ks, jnp.int32),
                    jnp.asarray(top_ps, jnp.float32),
                    jnp.asarray(seeds, jnp.int32))
        self._observe_compile("slot_decode", fn, dec_args,
                              names=("params", "pool", "toks", "positions",
                                     "temps", "top_ks", "top_ps", "seeds"))
        with self.mesh:
            pool, nxt = fn(*dec_args)
        return pool, np.asarray(nxt)

    def slot_decode_executables(self, num_slots: int, max_len: int,
                                quantized: Optional[bool] = None) -> int:
        """Number of compiled executables behind the fused decode step —
        the serving tests assert this stays at 1 per pool flavor
        (compile-once decode; fp and quantized pools are separate
        programs). ``quantized`` selects one flavor; None sums both."""
        keys = {None: (("slot_decode", num_slots, max_len),
                       ("slot_decode", num_slots, max_len, "q8")),
                False: (("slot_decode", num_slots, max_len),),
                True: (("slot_decode", num_slots, max_len, "q8"),)}
        total = 0
        for fkey in keys[quantized]:
            fn = self._slot_fns.get(fkey)
            if fn is not None:
                total += fn._cache_size()
        return total

    # -------------------------------------------- speculative decode protocol
    # Draft-model speculation over the slot pool (inference/speculative.py):
    # a cheap draft proposes K tokens per slot in ONE compiled lax.scan,
    # the target verifies all K in ONE batched verify_with_slots forward,
    # and per-slot accept/rollback of KV columns happens INSIDE the
    # compiled verify step. Both pools are donated (state-in/state-out per
    # tick — ds_tpu_lint HLO005 audits the lowered programs).

    def init_draft(self, draft_cfg):
        """Build (or fetch the cached) DraftRuntime for ``draft_cfg`` —
        co-resident replicas sharing this engine share draft weights."""
        from .speculative import build_draft, draft_key
        if not hasattr(self, "_drafts"):
            self._drafts: Dict[Any, Any] = {}
        key = draft_key(draft_cfg)
        draft = self._drafts.get(key)
        if draft is None:
            draft = self._drafts[key] = build_draft(self, draft_cfg)
            log_dist(f"InferenceEngine: draft runtime ready "
                     f"({draft.describe})", ranks=[0])
        return draft

    def init_draft_pool(self, draft, num_slots: int, max_len: int):
        """Allocate the draft model's slot-pool KV cache (fp — the draft
        is already the cheap side of the trade), once, at static shape."""
        fkey = ("draft_pool", num_slots, max_len, draft.key)
        fn = self._slot_fns.get(fkey)
        if fn is None:
            fn = self._slot_fns[fkey] = jax.jit(
                lambda: draft.model.init_kv_cache(num_slots, max_len,
                                                  dtype=self.dtype),
                out_shardings=self._pool_shardings(num_slots, max_len,
                                                   model=draft.model))
        self._observe_compile("draft_pool", fn, ())
        with self.mesh:
            return fn()

    def draft_prefill(self, draft, dpool, slot: int, prompt):
        """Prefill ``prompt`` into the DRAFT pool's slot lane (pow2
        buckets like slot_prefill; logits are discarded — only the K/V
        matter, XLA dead-code-eliminates the head). The draft pool is
        donated. Returns the new draft pool."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        t = prompt.shape[0]
        num_slots = int(jax.tree.leaves(dpool)[0].shape[1])
        max_len = int(jax.tree.leaves(dpool)[0].shape[-2])
        if not 0 < t <= max_len:
            raise ValueError(f"prompt length {t} not in [1, {max_len}]")
        bucket = min(_next_pow2(t), max_len)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t] = prompt
        fkey = ("draft_prefill", bucket, num_slots, max_len, draft.key)
        fn = self._slot_fns.get(fkey)
        if fn is None:
            pool_shardings = self._pool_shardings(num_slots, max_len,
                                                  model=draft.model)

            def dpf(dparams, ids, dpool, slot_idx):
                mini = draft.model.init_kv_cache(1, max_len,
                                                 dtype=self.dtype)
                _logits, mini = draft.model.apply_with_cache(
                    dparams, ids, mini, jnp.int32(0))
                return self._write_lane(dpool, mini, slot_idx, False)

            fn = self._slot_fns[fkey] = jax.jit(dpf, in_shardings=(
                draft.param_shardings, None, pool_shardings, None),
                out_shardings=pool_shardings, donate_argnums=(2,))
        dpf_args = (draft.params, jnp.asarray(ids), dpool, jnp.int32(slot))
        self._observe_compile("draft_prefill", fn, dpf_args,
                              names=("draft_params", "ids", "draft_pool",
                                     "slot"))
        with self.mesh:
            return fn(*dpf_args)

    def slot_draft_propose(self, draft, dpool, toks, positions, temps,
                           top_ks, top_ps, seeds, k: int):
        """Propose ``k`` draft tokens per slot: a single compiled
        ``lax.scan`` of k+1 draft decode steps (the extra step writes the
        last proposal's K/V so a fully-accepted block leaves no gap in
        the draft lane). The draft samples with the SAME
        ``(seed, column)`` keys the target verify uses — the coupling
        that maximizes exact-match acceptance. Draft pool donated.
        Returns (new_dpool, draft_tokens [S, k])."""
        vocab = draft.model.config.vocab_size
        num_slots = int(jax.tree.leaves(dpool)[0].shape[1])
        max_len = int(jax.tree.leaves(dpool)[0].shape[-2])
        fkey = ("slot_draft", num_slots, max_len, int(k), draft.key)
        fn = self._slot_fns.get(fkey)
        if fn is None:
            pool_shardings = self._pool_shardings(num_slots, max_len,
                                                  model=draft.model)
            from .speculative import row_keys, sample_rows

            def prop(dparams, dpool, toks, positions, temps, top_ks,
                     top_ps, seeds):
                def body(carry, _):
                    dpool, tok, pos = carry
                    logits, dpool = draft.model.decode_with_slots(
                        dparams, tok[:, None], dpool, pos)
                    keys = row_keys(seeds, pos + 1)
                    nxt = sample_rows(logits[:, -1], temps, top_ks, top_ps,
                                      keys, vocab)
                    return (dpool, nxt, pos + 1), nxt

                (dpool, _, _), drafts = lax.scan(
                    body, (dpool, toks, positions), None, length=k + 1)
                return dpool, jnp.transpose(drafts[:k])      # [S, k]

            fn = self._slot_fns[fkey] = jax.jit(prop, in_shardings=(
                draft.param_shardings, pool_shardings, None, None, None,
                None, None, None),
                out_shardings=(pool_shardings, None), donate_argnums=(1,))
        prop_args = (draft.params, dpool, jnp.asarray(toks, jnp.int32),
                     jnp.asarray(positions, jnp.int32),
                     jnp.asarray(temps, jnp.float32),
                     jnp.asarray(top_ks, jnp.int32),
                     jnp.asarray(top_ps, jnp.float32),
                     jnp.asarray(seeds, jnp.int32))
        self._observe_compile("slot_draft", fn, prop_args,
                              names=("draft_params", "draft_pool", "toks",
                                     "positions", "temps", "top_ks",
                                     "top_ps", "seeds"))
        with self.mesh:
            dpool, drafts = fn(*prop_args)
        return dpool, np.asarray(drafts)

    def slot_verify_step(self, pool, toks, draft_toks, positions, temps,
                         top_ks=None, top_ps=None, seeds=None):
        """Verify ``k`` draft tokens per slot in ONE batched forward and
        advance every slot by its accepted prefix plus one target token.
        Acceptance is EXACT MATCH against the target's own deterministic
        per-position sample (greedy argmax at temps<=0), so the emitted
        stream is bitwise what the non-speculative path would emit.
        Rejected KV columns are rolled back INSIDE the compiled step:
        every column past ``positions[s] + accepts[s]`` is restored to
        its pre-verify value (for int8 pools the restore is exact by the
        per-column-scale round-trip guarantee). The target pool is
        donated. Returns (new_pool, target_tokens [S, k+1],
        accepts [S] in [0, k]) — the emitted tokens for slot s are
        ``target_tokens[s, :accepts[s] + 1]``."""
        model = self.module
        vocab = model.config.vocab_size
        num_slots, max_len, quantized = self._pool_dims(pool)
        draft_toks = np.asarray(draft_toks, np.int32)
        k = int(draft_toks.shape[1])
        fkey = ("slot_verify", num_slots, max_len, k) + \
            (("q8",) if quantized else ())
        fn = self._slot_fns.get(fkey)
        if fn is None:
            pool_shardings = self._pool_shardings(num_slots, max_len,
                                                  quantize=quantized)
            from .speculative import row_keys, sample_rows

            def ver(params, pool, toks, draft_toks, positions, temps,
                    top_ks, top_ps, seeds):
                if quantized:
                    from .kv_quant import dequantize_pool, quantize_pool
                    fp_old = dequantize_pool(pool, self.dtype)
                else:
                    fp_old = pool
                block = jnp.concatenate([toks[:, None], draft_toks], axis=1)
                logits, fp_new = model.verify_with_slots(
                    params, block, fp_old, positions)      # [S, k+1, V]
                # target's candidate at offset j would be FED at column
                # positions + j + 1 — the same key the plain decode path
                # (and the draft) derives for that position. The "verify"
                # scope covers sampling + accept math + rollback so the
                # perf plane prices the whole accept/reject tail as one
                # bucket distinct from the batched forward above.
                with jax.named_scope("verify"):
                    cols = positions[:, None] + 1 + \
                        jnp.arange(k + 1)[None, :]         # [S, k+1]
                    tgt = jax.vmap(
                        lambda lg, cs: sample_rows(
                            lg, temps, top_ks, top_ps,
                            row_keys(seeds, cs), vocab),
                        in_axes=(1, 1), out_axes=1)(logits, cols)
                    match = (draft_toks == tgt[:, :k]).astype(jnp.int32)
                    accepts = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                # rollback INSIDE the step: only columns this verify
                # WROTE and the accept prefix covers keep their new
                # values — everything else (untouched columns AND
                # rejected writes) restores to the pre-verify lane
                with jax.named_scope("verify"):
                    cols_ax = jnp.arange(max_len)[None, :]
                    keep = (cols_ax >= positions[:, None]) & \
                        (cols_ax <= (positions + accepts)[:, None])  # [S, C]
                    if quantized:
                        # restore in QUANTIZED space: original q/scale
                        # BYTES are copied verbatim for every non-kept
                        # column, so rolled-back int8 lanes are bit-exact
                        # — the untouched-column guarantee by
                        # construction, immune even to ulp-level
                        # requantization drift
                        newq = quantize_pool(fp_new)

                        def rbq(new, old):
                            return jnp.where(keep[None, :, None, :, None],
                                             new, old)

                        def rbs(new, old):
                            return jnp.where(keep[None, :, None, :],
                                             new, old)

                        from .kv_quant import QuantizedSlotPool
                        out_pool = QuantizedSlotPool(
                            q=jax.tree.map(rbq, newq.q, pool.q),
                            scales=jax.tree.map(rbs, newq.scales,
                                                pool.scales))
                    else:
                        def rb(new, old):
                            return jnp.where(keep[None, :, None, :, None],
                                             new, old)

                        out_pool = jax.tree.map(rb, fp_new, fp_old)
                return out_pool, tgt, accepts.astype(jnp.int32)

            fn = self._slot_fns[fkey] = jax.jit(ver, in_shardings=(
                self.param_shardings, pool_shardings, None, None, None,
                None, None, None, None),
                out_shardings=(pool_shardings, None, None),
                donate_argnums=(1,))
        n = len(np.asarray(toks).reshape(-1))
        if top_ks is None:
            top_ks = np.zeros((n,), np.int32)
        if top_ps is None:
            top_ps = np.ones((n,), np.float32)
        if seeds is None:
            seeds = np.zeros((n,), np.int32)
        ver_args = (self.params, pool, jnp.asarray(toks, jnp.int32),
                    jnp.asarray(draft_toks, jnp.int32),
                    jnp.asarray(positions, jnp.int32),
                    jnp.asarray(temps, jnp.float32),
                    jnp.asarray(top_ks, jnp.int32),
                    jnp.asarray(top_ps, jnp.float32),
                    jnp.asarray(seeds, jnp.int32))
        self._observe_compile("slot_verify", fn, ver_args,
                              names=("params", "pool", "toks", "draft_toks",
                                     "positions", "temps", "top_ks",
                                     "top_ps", "seeds"))
        with self.mesh:
            pool, tgt, accepts = fn(*ver_args)
        return pool, np.asarray(tgt), np.asarray(accepts)

    def slot_verify_executables(self, num_slots: int, max_len: int, k: int,
                                quantized: Optional[bool] = None) -> int:
        """Compiled-executable count behind the speculative verify step
        for one K flavor — the pow2-K compile-once evidence the tests
        assert (mirrors slot_decode_executables)."""
        keys = {None: (("slot_verify", num_slots, max_len, k),
                       ("slot_verify", num_slots, max_len, k, "q8")),
                False: (("slot_verify", num_slots, max_len, k),),
                True: (("slot_verify", num_slots, max_len, k, "q8"),)}
        total = 0
        for fkey in keys[quantized]:
            fn = self._slot_fns.get(fkey)
            if fn is not None:
                total += fn._cache_size()
        return total

    # ------------------------------------------------------------- properties
    @property
    def config(self):
        return self._config

    @property
    def mp_world_size(self):
        return self.mesh_manager.tp

    def eval(self):
        return self

    def half(self):
        """Reference API: cast to fp16 (here: the configured low dtype)."""
        self.params = self.recast(self.params)
        return self
