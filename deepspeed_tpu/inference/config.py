"""Inference config.

TPU-native counterpart of the reference ``DeepSpeedInferenceConfig``
(reference deepspeed/inference/config.py): same JSON surface (dtype,
tensor_parallel.tp_size, max_out_tokens, replace_with_kernel_inject, ...) on
the dataclass config base. CUDA-graph and quantization knobs are accepted for
config compatibility; cuda-graph is meaningless under XLA (everything is a
compiled program already) and warns.
"""

import dataclasses
from typing import Any, Dict, Optional

import jax.numpy as jnp

from ..runtime.config_utils import ConfigError, DeepSpeedConfigModel
from ..utils.logging import logger

_DTYPES = {
    "float32": jnp.float32, "fp32": jnp.float32,
    "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
    "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
    "int8": jnp.int8,
}


@dataclasses.dataclass
class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """reference inference/config.py DeepSpeedTPConfig."""
    enabled: bool = True
    tp_size: int = 1
    mpu: Any = None
    tp_group: Any = None

    def validate(self):
        if self.tp_size < 1:
            raise ConfigError(f"tp_size must be >= 1, got {self.tp_size}")


@dataclasses.dataclass
class DeepSpeedMoEConfig(DeepSpeedConfigModel):
    enabled: bool = False
    ep_size: int = 1
    moe_experts: Any = dataclasses.field(default_factory=lambda: [1])
    type: str = "standard"


@dataclasses.dataclass
class QuantizationConfig(DeepSpeedConfigModel):
    enabled: bool = False
    bits: int = 8
    group_size: int = 64


@dataclasses.dataclass
class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    """reference inference/config.py:70 DeepSpeedInferenceConfig."""
    kernel_inject: bool = False            # replace_with_kernel_inject
    dtype: Any = "bfloat16"
    tensor_parallel: Any = None            # dict -> DeepSpeedTPConfig
    injection_policy: Any = None
    replace_method: str = "auto"
    moe: Any = None
    quant: Any = None
    checkpoint: Optional[str] = None       # checkpoint dir / json path
    base_dir: str = ""
    max_tokens: int = 1024                 # alias: max_out_tokens
    min_out_tokens: int = 1
    max_batch_size: Optional[int] = None
    enable_cuda_graph: bool = False        # accepted; warns (XLA == compiled)
    triangular_masking: bool = True
    return_tuple: bool = True
    training_mp_size: int = 1
    replace_with_kernel_inject: bool = False
    mp_size: int = 1                       # deprecated alias for tp_size
    seed: int = 0
    # LRU cap on the compiled-program cache (forward/generate shape
    # buckets); 0 disables eviction. Slot-serving programs are exempt.
    compiled_cache_size: int = 64

    ALIASES = {"max_out_tokens": "max_tokens"}

    def validate(self):
        if isinstance(self.dtype, str):
            key = self.dtype.lower().replace("torch.", "")
            if key not in _DTYPES:
                raise ConfigError(f"unknown inference dtype {self.dtype!r}; "
                                  f"one of {sorted(_DTYPES)}")
            self.dtype = _DTYPES[key]
        if self.tensor_parallel is None:
            self.tensor_parallel = DeepSpeedTPConfig(
                tp_size=max(self.mp_size, 1))
        elif isinstance(self.tensor_parallel, dict):
            self.tensor_parallel = DeepSpeedTPConfig.from_dict(
                self.tensor_parallel)
        if self.mp_size > 1 and self.tensor_parallel.tp_size == 1:
            self.tensor_parallel.tp_size = self.mp_size
        if isinstance(self.moe, dict):
            self.moe = DeepSpeedMoEConfig.from_dict(self.moe)
        elif isinstance(self.moe, bool):
            self.moe = DeepSpeedMoEConfig(enabled=self.moe)
        if isinstance(self.quant, dict):
            self.quant = QuantizationConfig.from_dict(self.quant)
        elif isinstance(self.quant, bool):
            self.quant = QuantizationConfig(enabled=self.quant)
        if self.dtype is jnp.int8:
            # reference semantics (inference/engine.py dtype=torch.int8):
            # int8 means weight-only quantized serving; activations/compute
            # stay in bf16
            if self.quant is None:
                self.quant = QuantizationConfig(enabled=True)
            self.quant.enabled = True
            self.dtype = jnp.bfloat16
        if self.quant is not None and self.quant.enabled and \
                self.quant.bits != 8:
            raise ConfigError(
                f"weight-only quantized serving supports bits=8 "
                f"(got {self.quant.bits})")
        if self.enable_cuda_graph:
            logger.warning("enable_cuda_graph is a no-op on TPU: XLA programs "
                           "are already captured/replayed whole")
        if self.kernel_inject or self.replace_with_kernel_inject:
            self.kernel_inject = self.replace_with_kernel_inject = True
        if self.max_tokens < 1:
            raise ConfigError("max_tokens must be >= 1")
        if self.compiled_cache_size < 0:
            raise ConfigError("compiled_cache_size must be >= 0 "
                              "(0 disables eviction)")
