"""Quantized KV slot pool — int8 cache lanes with per-column scales.

The serving slot pool (`serving/kv_slots.py`) is the HBM budget of a
decode replica: `[L, num_slots, H, max_model_len, hd]` in the model
dtype, resident for the process lifetime. Storing it int8 multiplies the
concurrent slots a replica can hold per HBM byte by ~3-4x (1 byte/value
plus one f32 scale per `hd` values, vs 4 for fp32), which is the
difference between 8 and 30 concurrent users per replica at the same
budget — the ZeRO++-style trade (arxiv 2306.10209) applied to KV state
instead of wire traffic, via the same `ops/quant_core` scale math.

Scale granularity is **per cache column** (one f32 scale per
`[layer, slot, head, position]`, absmax over the `hd` values of that
column). Per-column scales are what make an *incrementally written*
quantized cache sound: prefill and decode touch whole columns, so a
write re-quantizes only the columns it produced, and the round-trip
`quantize(dequantize(q))` of every untouched column is exact (the absmax
element of a block quantizes to ±127 exactly, pinning the block's scale)
— repeated passes through the decode step never compound error on old
tokens. Each K/V value is quantized exactly once, when its column is
first written.

`QuantizedSlotPool` is a registered pytree whose first leaves mirror the
fp pool's leaf order (so shape probes like
``jax.tree.leaves(pool)[0].shape[1]`` keep meaning `num_slots`), and the
engine's slot programs (`inference/engine.py`) branch on its type at
trace time: decode dequantizes the pool inside the compiled step and
re-quantizes the updated pool on the way out; prefill and lane
copy/extract/insert touch only their lane's q/scale slices and never
materialize the full fp pool.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ..ops.quant_core import INT8_QMAX, round_clip, symmetric_scale

__all__ = ["QuantizedSlotPool", "quantize_kv", "dequantize_kv",
           "quantize_pool", "dequantize_pool", "pool_nbytes"]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedSlotPool:
    """int8 KV pool + per-column f32 scales.

    ``q``: the fp pool's tree with every leaf ``[..., hd]`` in int8;
    ``scales``: the same tree with the trailing ``hd`` axis dropped
    (one f32 scale per column). Flatten order puts ``q`` first so
    generic leaf-shape probes on the pool keep working.
    """
    q: Any
    scales: Any

    def tree_flatten(self):
        return (self.q, self.scales), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scales = children
        return cls(q=q, scales=scales)


def quantize_kv(x):
    """One cache leaf ``[..., hd]`` -> (q int8 ``[..., hd]``,
    scales f32 ``[...]``) with per-column symmetric scales."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = symmetric_scale(absmax, INT8_QMAX)
    q = round_clip(xf / scale[..., None], -INT8_QMAX, INT8_QMAX, jnp.int8)
    return q, scale


def dequantize_kv(q, scales, dtype=jnp.float32):
    """(q, scales) -> float leaf of ``q.shape`` in ``dtype``."""
    return (q.astype(jnp.float32) * scales[..., None]).astype(dtype)


def quantize_pool(pool) -> QuantizedSlotPool:
    """fp pool tree -> QuantizedSlotPool (jit-safe)."""
    pairs = jax.tree.map(quantize_kv, pool)
    return QuantizedSlotPool(
        q=jax.tree.map(lambda p: p[0], pairs,
                       is_leaf=lambda t: isinstance(t, tuple)),
        scales=jax.tree.map(lambda p: p[1], pairs,
                            is_leaf=lambda t: isinstance(t, tuple)))


def dequantize_pool(pool: QuantizedSlotPool, dtype=jnp.float32):
    """QuantizedSlotPool -> fp pool tree in ``dtype`` (jit-safe)."""
    return jax.tree.map(lambda q, s: dequantize_kv(q, s, dtype),
                        pool.q, pool.scales)


def pool_nbytes(pool) -> int:
    """Resident bytes of a pool — fp tree or QuantizedSlotPool (q bytes +
    scale bytes). The capacity-per-HBM-byte comparison in
    benchmarks/serving.py --fleet reads this."""
    return sum(leaf.size * jnp.dtype(leaf.dtype).itemsize
               for leaf in jax.tree.leaves(pool))
