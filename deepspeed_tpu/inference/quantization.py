"""Int8 weight-only quantized serving.

Capability match for the reference's int8 inference path
(module_inject/replace_module.py:140 ``GroupQuantizer`` quantizes fused
weights at injection time; csrc/transformer/inference/csrc/dequantize.cu:195
dequantizes inside the fused GEMMs). TPU-native re-design: a quantized
weight is a registered pytree node — int8 payload + per-group fp32 scales —
whose ``astype()`` IS the dequant. Model code already touches every matmul
weight through ``.astype(compute_dtype)`` (the mixed-precision contract), so
dequant lands exactly where the reference's kernel fusion puts it, and XLA
fuses the int8→bf16 multiply-by-scale into the consumer matmul's operand
pipeline. Memory wins: weights resident in HBM at ~half the bf16 bytes —
the decode path is weight-bandwidth-bound, so resident-int8 also lifts
tokens/s at small batch.

Grouping is along the LAST axis (per-row groups), which keeps the leading
layer axis of stacked [L, ...] leaves intact — ``lax.scan`` over layers
slices the q/scale leaves coherently, and tensor-parallel shardings on
non-last axes apply unchanged.
"""

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.logging import log_dist


@jax.tree_util.register_pytree_node_class
class QuantizedWeight:
    """int8 weight + per-group scales; ``astype`` dequantizes.

    q: int8, the original weight shape.
    scale: fp32, shape = q.shape[:-1] + (groups,).
    """

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):  # reported dtype is the payload's
        return self.q.dtype

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def astype(self, dt):
        """Dequantize: the serving matmuls call this in place of the usual
        bf16 cast (reference dequantize.cu:195 inside qkv/mlp GEMMs)."""
        group = self.q.shape[-1] // self.scale.shape[-1]
        w = self.q.astype(jnp.float32) * jnp.repeat(self.scale, group,
                                                    axis=-1)
        return w.astype(dt)


def is_quantized(x) -> bool:
    return isinstance(x, QuantizedWeight)


def quantize_leaf(w, group_size: int = 64, bits: int = 8) -> QuantizedWeight:
    """Symmetric per-group int8 quantization along the last axis
    (reference GroupQuantizer semantics, replace_module.py:140)."""
    assert bits == 8, "weight-only serving supports 8-bit payloads"
    last = w.shape[-1]
    gs = group_size if last % group_size == 0 else last
    groups = last // gs
    wg = w.astype(jnp.float32).reshape(*w.shape[:-1], groups, gs)
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(wg), axis=-1), 1e-8) / qmax
    q = jnp.round(wg / scale[..., None]).clip(-qmax, qmax)
    return QuantizedWeight(q.reshape(w.shape).astype(jnp.int8), scale)


def _default_predicate(path, leaf) -> bool:
    """Quantize matmul-shaped floating weights of the transformer blocks —
    the reference GroupQuantizer scope (replace_module.py:140 quantizes
    fused layer weights, not embeddings/norms/biases). Stacked [L, ...]
    leaves make per-layer vectors LOOK 2-D, so the filter requires a real
    matrix (both trailing dims substantial) AND rejects norm/bias names.
    Also excluded: token/position embeddings (wte doubles as the logit
    head, the most quantization-sensitive matmul, and wpe is indexed with
    dynamic_slice before any dtype cast)."""
    if getattr(leaf, "ndim", 0) < 2:
        return False
    if not jnp.issubdtype(leaf.dtype, jnp.floating):
        return False
    if min(leaf.shape[-1], leaf.shape[-2]) < 16:
        return False  # [L, d] norm/bias stacks, tiny projections
    names = [str(getattr(k, "key", k)) for k in path]
    last = names[-1] if names else ""
    if last.endswith(("_b", "bias", "scale", "norm", "gamma", "beta")):
        return False
    skip = ("wpe", "wte", "embed", "position", "lm_head")
    return not any(s in n for n in names for s in skip)


def quantize_tree(params, group_size: int = 64, bits: int = 8,
                  predicate=_default_predicate):
    """Quantize the selected leaves of a params pytree (jit-safe).
    Idempotent: already-quantized nodes pass through untouched."""
    return jax.tree_util.tree_map_with_path(
        lambda kp, x: x if is_quantized(x)
        else quantize_leaf(x, group_size, bits) if predicate(kp, x) else x,
        params, is_leaf=lambda x: is_quantized(x))


def quantized_shardings(param_shardings, param_shapes,
                        predicate=_default_predicate):
    """Sharding tree matching ``quantize_tree``'s output structure: q keeps
    the weight's spec; scales replicate their (possibly non-divisible)
    group axis while keeping leading-axis sharding (tp/pp)."""
    def one(kp, sh, shape_leaf):
        if not predicate(kp, shape_leaf):
            return sh
        spec = tuple(sh.spec) if sh.spec else ()
        spec = spec + (None,) * (len(shape_leaf.shape) - len(spec))
        scale_spec = spec[:-1] + (None,)
        return QuantizedWeight(
            NamedSharding(sh.mesh, P(*spec)),
            NamedSharding(sh.mesh, P(*scale_spec)))
    return jax.tree_util.tree_map_with_path(one, param_shardings,
                                            param_shapes)


def tree_nbytes(params) -> int:
    return sum(x.nbytes for x in jax.tree.leaves(params))


def describe(params) -> str:
    n_q = sum(1 for kp, x in
              jax.tree_util.tree_flatten_with_path(
                  params, is_leaf=is_quantized)[0] if is_quantized(x))
    return (f"int8 weight-only serving: {n_q} quantized weights, "
            f"{tree_nbytes(params) / 2**20:.1f} MiB resident")
