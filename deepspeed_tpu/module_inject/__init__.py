"""Module injection: HF → deepspeed_tpu conversion + AutoTP.

Reference: deepspeed/module_inject/ (replace_module.py, policy.py,
auto_tp.py)."""

from .policy import replace_transformer_layer, register_policy, policy_for
from .auto_tp import auto_tp_rules
