"""Injection policies: HF torch modules → deepspeed_tpu model + params.

TPU-native counterpart of the reference's kernel-injection layer
(reference module_inject/replace_module.py:276 ``replace_transformer_layer``,
module_inject/policy.py ``TransformerPolicy``, containers/gpt2.py). The torch
version swaps nn.Modules for fused-CUDA modules in place; on TPU "injection"
means: read the architecture + weights out of the HF module ONCE, emit

    (deepspeed_tpu ModelSpec, params pytree)

and let the inference engine compile/shard it. Per-architecture policies
register themselves by HF class name, exactly like reference
replace_policy.py's ``replace_policies`` list.
"""

from typing import Any, Callable, Dict, Tuple

import numpy as np

from ..utils.logging import logger

_POLICIES: Dict[str, Callable] = {}


def register_policy(*hf_class_names):
    def deco(fn):
        for name in hf_class_names:
            _POLICIES[name] = fn
        return fn
    return deco


def policy_for(model) -> Callable:
    for klass in type(model).__mro__:
        if klass.__name__ in _POLICIES:
            return _POLICIES[klass.__name__]
    raise ValueError(
        f"no injection policy for {type(model).__name__}; known: "
        f"{sorted(_POLICIES)} (reference replace_policy.py registry)")


def _np(t):
    return np.asarray(t.detach().cpu().numpy(), dtype=np.float32)


def _lin_w(lin):
    """HF nn.Linear stores [out, in]; transpose into our x @ w convention."""
    return _np(lin.weight).T


def _stack(layers, field):
    return np.stack([field(h) for h in layers])


def deinterleave_qkv_rows(w, n_head, head_dim):
    """[3D, D] fused qkv whose rows are per-head [q|k|v] blocks (BLOOM,
    GPT-NeoX, Megatron-LM layout) → [D, 3D] head-major q|k|v (this repo's
    convention)."""
    d = w.shape[1]
    w = w.reshape(n_head, 3, head_dim, d)
    return np.concatenate([w[:, i].reshape(n_head * head_dim, d)
                           for i in range(3)], axis=0).T


def deinterleave_qkv_bias(b, n_head, head_dim):
    b = b.reshape(n_head, 3, head_dim)
    return np.concatenate([b[:, i].reshape(n_head * head_dim)
                           for i in range(3)])


@register_policy("GPT2LMHeadModel", "GPT2Model")
def gpt2_policy(model) -> Tuple[Any, Any]:
    """HF GPT-2 → stacked-layer GPT2Model params.

    HF Conv1D stores weights [in, out] — our convention (x @ w) directly, no
    transpose (reference containers/gpt2.py HFGPT2LayerPolicy notes the same
    Conv1D quirk)."""
    import jax.numpy as jnp
    from ..models.gpt2 import GPT2Config, GPT2Model

    hf = model.transformer if hasattr(model, "transformer") else model
    hf_cfg = model.config
    cfg = GPT2Config(
        vocab_size=hf_cfg.vocab_size,
        n_positions=hf_cfg.n_positions,
        n_embd=hf_cfg.n_embd,
        n_layer=hf_cfg.n_layer,
        n_head=hf_cfg.n_head,
        layer_norm_epsilon=hf_cfg.layer_norm_epsilon,
        pad_vocab_to_multiple=1,
    )
    spec = GPT2Model(cfg)

    stack = lambda field: np.stack([field(h) for h in hf.h])
    blocks = {
        "ln1_scale": stack(lambda h: _np(h.ln_1.weight)),
        "ln1_bias": stack(lambda h: _np(h.ln_1.bias)),
        "qkv_w": stack(lambda h: _np(h.attn.c_attn.weight)),
        "qkv_b": stack(lambda h: _np(h.attn.c_attn.bias)),
        "attn_proj_w": stack(lambda h: _np(h.attn.c_proj.weight)),
        "attn_proj_b": stack(lambda h: _np(h.attn.c_proj.bias)),
        "ln2_scale": stack(lambda h: _np(h.ln_2.weight)),
        "ln2_bias": stack(lambda h: _np(h.ln_2.bias)),
        "mlp_fc_w": stack(lambda h: _np(h.mlp.c_fc.weight)),
        "mlp_fc_b": stack(lambda h: _np(h.mlp.c_fc.bias)),
        "mlp_proj_w": stack(lambda h: _np(h.mlp.c_proj.weight)),
        "mlp_proj_b": stack(lambda h: _np(h.mlp.c_proj.bias)),
    }
    params = {
        "wte": _np(hf.wte.weight),
        "wpe": _np(hf.wpe.weight),
        "blocks": {k: jnp.asarray(v) for k, v in blocks.items()},
        "ln_f_scale": _np(hf.ln_f.weight),
        "ln_f_bias": _np(hf.ln_f.bias),
    }
    params = {k: (jnp.asarray(v) if not isinstance(v, dict) else v)
              for k, v in params.items()}
    return spec, params


@register_policy("OPTForCausalLM", "OPTModel")
def opt_policy(model) -> Tuple[Any, Any]:
    """HF OPT → stacked-layer OPTModel params (reference
    module_inject/containers/opt.py HFOPTLayerPolicy). HF Linear stores
    [out, in]: transposed into our x @ w convention; separate q/k/v
    projections concat into the fused qkv. OPT-350M (post-LN,
    word_embed_proj_dim != hidden) is rejected, matching the policy
    contract in models/opt.py."""
    import jax.numpy as jnp
    from ..models.opt import OPTConfig, OPTModel

    hf_cfg = model.config
    if not getattr(hf_cfg, "do_layer_norm_before", True):
        raise ValueError("post-LN OPT variants (350M) are not supported")
    if getattr(hf_cfg, "word_embed_proj_dim",
               hf_cfg.hidden_size) != hf_cfg.hidden_size:
        raise ValueError("OPT word_embed_proj_dim != hidden_size "
                         "is not supported")
    act = getattr(hf_cfg, "activation_function", "relu")
    if act not in ("relu", "gelu", "gelu_new"):
        raise ValueError(f"unsupported OPT activation {act!r}")
    if hf_cfg.ffn_dim % hf_cfg.hidden_size != 0:
        raise ValueError(
            f"ffn_dim {hf_cfg.ffn_dim} not a multiple of hidden_size "
            f"{hf_cfg.hidden_size} — cfg.mlp_ratio would silently disagree "
            f"with the loaded weights")
    dec = model.model.decoder if hasattr(model, "model") else model.decoder
    cfg = OPTConfig(
        vocab_size=hf_cfg.vocab_size,
        n_positions=hf_cfg.max_position_embeddings,
        n_embd=hf_cfg.hidden_size,
        n_layer=hf_cfg.num_hidden_layers,
        n_head=hf_cfg.num_attention_heads,
        mlp_ratio=hf_cfg.ffn_dim // hf_cfg.hidden_size,
        activation="relu" if act == "relu" else "gelu",  # Galactica = gelu
        pad_vocab_to_multiple=1,
    )
    spec = OPTModel(cfg)

    import functools
    stack = functools.partial(_stack, dec.layers)
    lin_w = _lin_w

    def qkv_w(h):
        a = h.self_attn
        return np.concatenate([lin_w(a.q_proj), lin_w(a.k_proj),
                               lin_w(a.v_proj)], axis=1)

    def qkv_b(h):
        a = h.self_attn
        return np.concatenate([_np(a.q_proj.bias), _np(a.k_proj.bias),
                               _np(a.v_proj.bias)])

    blocks = {
        "ln1_scale": stack(lambda h: _np(h.self_attn_layer_norm.weight)),
        "ln1_bias": stack(lambda h: _np(h.self_attn_layer_norm.bias)),
        "qkv_w": stack(qkv_w),
        "qkv_b": stack(qkv_b),
        "attn_proj_w": stack(lambda h: lin_w(h.self_attn.out_proj)),
        "attn_proj_b": stack(lambda h: _np(h.self_attn.out_proj.bias)),
        "ln2_scale": stack(lambda h: _np(h.final_layer_norm.weight)),
        "ln2_bias": stack(lambda h: _np(h.final_layer_norm.bias)),
        "mlp_fc_w": stack(lambda h: lin_w(h.fc1)),
        "mlp_fc_b": stack(lambda h: _np(h.fc1.bias)),
        "mlp_proj_w": stack(lambda h: lin_w(h.fc2)),
        "mlp_proj_b": stack(lambda h: _np(h.fc2.bias)),
    }
    params = {
        "wte": jnp.asarray(_np(dec.embed_tokens.weight)),
        # HF embed_positions already carries the +2 offset rows
        "wpe": jnp.asarray(_np(dec.embed_positions.weight)),
        "blocks": {k: jnp.asarray(v) for k, v in blocks.items()},
        "ln_f_scale": jnp.asarray(_np(dec.final_layer_norm.weight)),
        "ln_f_bias": jnp.asarray(_np(dec.final_layer_norm.bias)),
    }
    return spec, params


@register_policy("LlamaForCausalLM", "MistralForCausalLM")
def llama_policy(model) -> Tuple[Any, Any]:
    """HF LLaMA/Mistral → stacked-layer LlamaModel params. HF Linear stores
    [out, in] (transposed into x @ w); q/k/v concat into the fused qkv;
    rotary needs no weight permutation (both sides use the rotate_half
    convention). Reference counterpart: auto-TP handling of LLaMA
    (module_inject/auto_tp.py)."""
    import jax.numpy as jnp
    from ..models.llama import LlamaConfig, LlamaModel

    hf_cfg = model.config
    act = getattr(hf_cfg, "hidden_act", "silu")
    if act not in ("silu", "swish"):
        raise ValueError(f"unsupported LLaMA activation {act!r}")
    # reject silently-wrong conversions instead of mis-modeling them
    scaling = getattr(hf_cfg, "rope_scaling", None)
    if scaling and scaling.get("rope_type",
                               scaling.get("type", "default")) != "default":
        raise ValueError(
            f"rope_scaling={scaling!r} is not supported (plain rotary only); "
            f"logits would silently diverge from HF")
    if getattr(hf_cfg, "attention_bias", False):
        raise ValueError("attention_bias=True LLaMA variants not supported")
    explicit_hd = getattr(hf_cfg, "head_dim", None)
    if explicit_hd is not None and \
            explicit_hd != hf_cfg.hidden_size // hf_cfg.num_attention_heads:
        raise ValueError(
            f"head_dim={explicit_hd} != hidden_size/num_heads "
            f"({hf_cfg.hidden_size}/{hf_cfg.num_attention_heads}) "
            f"is not supported")
    dec = model.model if hasattr(model, "model") else model
    cfg = LlamaConfig(
        vocab_size=hf_cfg.vocab_size,
        n_positions=hf_cfg.max_position_embeddings,
        n_embd=hf_cfg.hidden_size,
        n_layer=hf_cfg.num_hidden_layers,
        n_head=hf_cfg.num_attention_heads,
        n_kv_head=getattr(hf_cfg, "num_key_value_heads",
                          hf_cfg.num_attention_heads),
        mlp_hidden=hf_cfg.intermediate_size,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        # a window >= context can never mask anything — normalize to None so
        # such checkpoints keep full-context attention (incl. under SP)
        sliding_window=(lambda w: w if w is not None and
                        w < hf_cfg.max_position_embeddings else None)(
                            getattr(hf_cfg, "sliding_window", None)),
        layer_norm_epsilon=hf_cfg.rms_norm_eps,
        tie_word_embeddings=getattr(hf_cfg, "tie_word_embeddings", False),
        pad_vocab_to_multiple=1,
    )
    spec = LlamaModel(cfg)

    import functools
    stack = functools.partial(_stack, dec.layers)
    lin_w = _lin_w

    def qkv_w(h):
        a = h.self_attn
        return np.concatenate([lin_w(a.q_proj), lin_w(a.k_proj),
                               lin_w(a.v_proj)], axis=1)

    blocks = {
        "ln1_scale": stack(lambda h: _np(h.input_layernorm.weight)),
        "qkv_w": stack(qkv_w),
        "attn_proj_w": stack(lambda h: lin_w(h.self_attn.o_proj)),
        "ln2_scale": stack(lambda h: _np(h.post_attention_layernorm.weight)),
        "gate_w": stack(lambda h: lin_w(h.mlp.gate_proj)),
        "up_w": stack(lambda h: lin_w(h.mlp.up_proj)),
        "down_w": stack(lambda h: lin_w(h.mlp.down_proj)),
    }
    params = {
        "wte": jnp.asarray(_np(dec.embed_tokens.weight)),
        "blocks": {k: jnp.asarray(v) for k, v in blocks.items()},
        "ln_f_scale": jnp.asarray(_np(dec.norm.weight)),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(_np(model.lm_head.weight))
    return spec, params


@register_policy("BloomForCausalLM", "BloomModel")
def bloom_policy(model) -> Tuple[Any, Any]:
    """HF BLOOM → stacked-layer BloomModel params (reference
    module_inject/containers/bloom.py BLOOMLayerPolicy). The HF fused
    query_key_value weight is head-interleaved ([H, 3, hd, D] rows);
    de-interleave into our head-major q|k|v concat convention."""
    import jax.numpy as jnp
    from ..models.bloom import BloomConfig, BloomModel

    hf_cfg = model.config
    if getattr(hf_cfg, "apply_residual_connection_post_layernorm", False):
        raise ValueError(
            "apply_residual_connection_post_layernorm BLOOM variants are "
            "not supported; residuals would silently diverge from HF")
    h = hf_cfg.n_head
    d = hf_cfg.hidden_size
    hd = d // h
    cfg = BloomConfig(
        vocab_size=hf_cfg.vocab_size,
        n_positions=getattr(hf_cfg, "seq_length", 2048),
        n_embd=d,
        n_layer=hf_cfg.n_layer,
        n_head=h,
        layer_norm_epsilon=hf_cfg.layer_norm_epsilon,
        pad_vocab_to_multiple=1,
    )
    spec = BloomModel(cfg)
    tr = model.transformer if hasattr(model, "transformer") else model

    import functools
    stack = functools.partial(_stack, tr.h)

    def qkv_w(blk):
        return deinterleave_qkv_rows(
            _np(blk.self_attention.query_key_value.weight), h, hd)

    def qkv_b(blk):
        return deinterleave_qkv_bias(
            _np(blk.self_attention.query_key_value.bias), h, hd)

    lin_w = _lin_w

    blocks = {
        "ln1_scale": stack(lambda b: _np(b.input_layernorm.weight)),
        "ln1_bias": stack(lambda b: _np(b.input_layernorm.bias)),
        "qkv_w": stack(qkv_w),
        "qkv_b": stack(qkv_b),
        "attn_proj_w": stack(lambda b: lin_w(b.self_attention.dense)),
        "attn_proj_b": stack(lambda b: _np(b.self_attention.dense.bias)),
        "ln2_scale": stack(lambda b: _np(b.post_attention_layernorm.weight)),
        "ln2_bias": stack(lambda b: _np(b.post_attention_layernorm.bias)),
        "mlp_fc_w": stack(lambda b: lin_w(b.mlp.dense_h_to_4h)),
        "mlp_fc_b": stack(lambda b: _np(b.mlp.dense_h_to_4h.bias)),
        "mlp_proj_w": stack(lambda b: lin_w(b.mlp.dense_4h_to_h)),
        "mlp_proj_b": stack(lambda b: _np(b.mlp.dense_4h_to_h.bias)),
    }
    params = {
        "wte": jnp.asarray(_np(tr.word_embeddings.weight)),
        "emb_ln_scale": jnp.asarray(_np(tr.word_embeddings_layernorm.weight)),
        "emb_ln_bias": jnp.asarray(_np(tr.word_embeddings_layernorm.bias)),
        "blocks": {k: jnp.asarray(v) for k, v in blocks.items()},
        "ln_f_scale": jnp.asarray(_np(tr.ln_f.weight)),
        "ln_f_bias": jnp.asarray(_np(tr.ln_f.bias)),
    }
    return spec, params


@register_policy("GPTNeoXForCausalLM")
def gpt_neox_policy(model) -> Tuple[Any, Any]:
    """HF GPT-NeoX/Pythia → stacked-layer GPTNeoXModel params (reference
    module_inject/containers/gptneox.py GPTNEOXLayerPolicy). The fused
    query_key_value is head-interleaved like BLOOM's; de-interleave into
    head-major q|k|v."""
    import functools
    import jax.numpy as jnp
    from ..models.gpt_neox import GPTNeoXConfig, GPTNeoXModel

    hf_cfg = model.config
    act = getattr(hf_cfg, "hidden_act", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_fast", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported NeoX activation {act!r}")
    scaling = getattr(hf_cfg, "rope_scaling", None)
    if scaling and scaling.get("rope_type",
                               scaling.get("type", "default")) != "default":
        raise ValueError(
            f"rope_scaling={scaling!r} is not supported (plain rotary only); "
            f"logits would silently diverge from HF")
    if hf_cfg.intermediate_size % hf_cfg.hidden_size != 0:
        raise ValueError("intermediate_size must be a multiple of "
                         "hidden_size")
    h = hf_cfg.num_attention_heads
    d = hf_cfg.hidden_size
    hd = d // h
    cfg = GPTNeoXConfig(
        vocab_size=hf_cfg.vocab_size,
        n_positions=hf_cfg.max_position_embeddings,
        n_embd=d,
        n_layer=hf_cfg.num_hidden_layers,
        n_head=h,
        mlp_ratio=hf_cfg.intermediate_size // d,
        rotary_pct=hf_cfg.rotary_pct,
        rope_theta=getattr(hf_cfg, "rotary_emb_base", 10000.0),
        use_parallel_residual=getattr(hf_cfg, "use_parallel_residual", True),
        activation="gelu_exact" if act == "gelu" else "gelu",
        layer_norm_epsilon=hf_cfg.layer_norm_eps,
        pad_vocab_to_multiple=1,
    )
    spec = GPTNeoXModel(cfg)
    nx = model.gpt_neox if hasattr(model, "gpt_neox") else model
    stack = functools.partial(_stack, nx.layers)

    def qkv_w(blk):
        return deinterleave_qkv_rows(
            _np(blk.attention.query_key_value.weight), h, hd)

    def qkv_b(blk):
        return deinterleave_qkv_bias(
            _np(blk.attention.query_key_value.bias), h, hd)

    blocks = {
        "ln1_scale": stack(lambda b: _np(b.input_layernorm.weight)),
        "ln1_bias": stack(lambda b: _np(b.input_layernorm.bias)),
        "qkv_w": stack(qkv_w),
        "qkv_b": stack(qkv_b),
        "attn_proj_w": stack(lambda b: _lin_w(b.attention.dense)),
        "attn_proj_b": stack(lambda b: _np(b.attention.dense.bias)),
        "ln2_scale": stack(
            lambda b: _np(b.post_attention_layernorm.weight)),
        "ln2_bias": stack(lambda b: _np(b.post_attention_layernorm.bias)),
        "mlp_fc_w": stack(lambda b: _lin_w(b.mlp.dense_h_to_4h)),
        "mlp_fc_b": stack(lambda b: _np(b.mlp.dense_h_to_4h.bias)),
        "mlp_proj_w": stack(lambda b: _lin_w(b.mlp.dense_4h_to_h)),
        "mlp_proj_b": stack(lambda b: _np(b.mlp.dense_4h_to_h.bias)),
    }
    params = {
        "wte": jnp.asarray(_np(nx.embed_in.weight)),
        "blocks": {k: jnp.asarray(v) for k, v in blocks.items()},
        "ln_f_scale": jnp.asarray(_np(nx.final_layer_norm.weight)),
        "ln_f_bias": jnp.asarray(_np(nx.final_layer_norm.bias)),
        "lm_head": jnp.asarray(_np(model.embed_out.weight)),
    }
    return spec, params


@register_policy("GPTNeoForCausalLM", "GPTNeoModel")
def gpt_neo_policy(model) -> Tuple[Any, Any]:
    """HF GPT-Neo → GPTNeoModel params (reference
    module_inject/containers/gptneo.py HFGPTNEOLayerPolicy). Quirks handled:
    separate q/k/v Linears WITHOUT bias (out_proj keeps one), alternating
    global/local attention from config.attention_layers, and Neo's
    UNSCALED q·k — folded into the q weight as q *= sqrt(head_dim) so the
    shared scaled-attention kernel reproduces it."""
    import math

    import jax.numpy as jnp
    from ..models.gpt_neo import GPTNeoConfig, GPTNeoModel

    hf = model.transformer if hasattr(model, "transformer") else model
    hf_cfg = model.config
    kinds = tuple(hf_cfg.attention_layers)  # e.g. ("global","local",...)
    cfg = GPTNeoConfig(
        vocab_size=hf_cfg.vocab_size,
        n_positions=hf_cfg.max_position_embeddings,
        n_embd=hf_cfg.hidden_size,
        n_layer=hf_cfg.num_layers,
        n_head=hf_cfg.num_heads,
        layer_norm_epsilon=hf_cfg.layer_norm_epsilon,
        activation="gelu",  # gelu_new == tanh-approx gelu (our default)
        local_window=getattr(hf_cfg, "window_size", 256),
        attention_layers=kinds,
        pad_vocab_to_multiple=1,
    )
    spec = GPTNeoModel(cfg)
    d = cfg.n_embd
    qscale = math.sqrt(cfg.head_dim)

    def qkv_w(h):
        a = h.attn.attention
        return np.concatenate([_lin_w(a.q_proj) * qscale, _lin_w(a.k_proj),
                               _lin_w(a.v_proj)], axis=-1)

    stack = lambda field: np.stack([field(h) for h in hf.h])
    blocks = {
        "ln1_scale": stack(lambda h: _np(h.ln_1.weight)),
        "ln1_bias": stack(lambda h: _np(h.ln_1.bias)),
        "qkv_w": stack(qkv_w),
        "qkv_b": np.zeros((cfg.n_layer, 3 * d), np.float32),  # Neo: no bias
        "attn_proj_w": stack(lambda h: _lin_w(h.attn.attention.out_proj)),
        "attn_proj_b": stack(lambda h: _np(h.attn.attention.out_proj.bias)),
        "ln2_scale": stack(lambda h: _np(h.ln_2.weight)),
        "ln2_bias": stack(lambda h: _np(h.ln_2.bias)),
        "mlp_fc_w": stack(lambda h: _lin_w(h.mlp.c_fc)),
        "mlp_fc_b": stack(lambda h: _np(h.mlp.c_fc.bias)),
        "mlp_proj_w": stack(lambda h: _lin_w(h.mlp.c_proj)),
        "mlp_proj_b": stack(lambda h: _np(h.mlp.c_proj.bias)),
    }
    params = {
        "wte": _np(hf.wte.weight),
        "wpe": _np(hf.wpe.weight),
        "blocks": {k: jnp.asarray(v) for k, v in blocks.items()},
        "ln_f_scale": _np(hf.ln_f.weight),
        "ln_f_bias": _np(hf.ln_f.bias),
    }
    params = {k: (jnp.asarray(v) if not isinstance(v, dict) else v)
              for k, v in params.items()}
    return spec, params


@register_policy("GPTJForCausalLM")
def gptj_policy(model) -> Tuple[Any, Any]:
    """HF GPT-J → stacked-layer GPTNeoXModel params in its GPT-J flavor
    (reference module_inject/containers/gptj.py HFGPTJLayerPolicy): shared
    block LayerNorm, interleaved partial rotary, no attention biases,
    LM head with bias."""
    import functools
    import jax.numpy as jnp
    from ..models.gpt_neox import GPTNeoXModel, gptj_config

    hf_cfg = model.config
    act = getattr(hf_cfg, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported GPT-J activation {act!r}")
    d = hf_cfg.n_embd
    inner = getattr(hf_cfg, "n_inner", None) or 4 * d
    if inner % d != 0:
        raise ValueError("n_inner must be a multiple of n_embd")
    cfg = gptj_config(
        vocab_size=hf_cfg.vocab_size,
        n_positions=hf_cfg.n_positions,
        n_embd=d,
        n_layer=hf_cfg.n_layer,
        n_head=hf_cfg.n_head,
        mlp_ratio=inner // d,
        rotary_ndims=hf_cfg.rotary_dim,
        activation="gelu_exact" if act == "gelu" else "gelu",
        layer_norm_epsilon=hf_cfg.layer_norm_epsilon,
        pad_vocab_to_multiple=1,
    )
    spec = GPTNeoXModel(cfg)
    tr = model.transformer if hasattr(model, "transformer") else model
    stack = functools.partial(_stack, tr.h)

    def qkv_w(blk):
        a = blk.attn
        return np.concatenate([_lin_w(a.q_proj), _lin_w(a.k_proj),
                               _lin_w(a.v_proj)], axis=1)

    blocks = {
        "ln1_scale": stack(lambda b: _np(b.ln_1.weight)),
        "ln1_bias": stack(lambda b: _np(b.ln_1.bias)),
        "qkv_w": stack(qkv_w),
        "attn_proj_w": stack(lambda b: _lin_w(b.attn.out_proj)),
        "mlp_fc_w": stack(lambda b: _lin_w(b.mlp.fc_in)),
        "mlp_fc_b": stack(lambda b: _np(b.mlp.fc_in.bias)),
        "mlp_proj_w": stack(lambda b: _lin_w(b.mlp.fc_out)),
        "mlp_proj_b": stack(lambda b: _np(b.mlp.fc_out.bias)),
    }
    params = {
        "wte": jnp.asarray(_np(tr.wte.weight)),
        "blocks": {k: jnp.asarray(v) for k, v in blocks.items()},
        "ln_f_scale": jnp.asarray(_np(tr.ln_f.weight)),
        "ln_f_bias": jnp.asarray(_np(tr.ln_f.bias)),
        "lm_head": jnp.asarray(_np(model.lm_head.weight)),
        "lm_head_b": jnp.asarray(_np(model.lm_head.bias)),
    }
    return spec, params


@register_policy("BertForMaskedLM", "BertForPreTraining")
def bert_policy(model) -> Tuple[Any, Any]:
    """HF BERT → stacked-layer BertModel params (reference
    module_inject/containers/bert.py HFBertLayerPolicy). Post-LN encoder;
    separate q/k/v concat into fused qkv; MLM transform + tied decoder +
    vocab bias."""
    import functools
    import jax.numpy as jnp
    from ..models.bert import BertConfig, BertModel

    hf_cfg = model.config
    act = getattr(hf_cfg, "hidden_act", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported BERT activation {act!r}")
    if hf_cfg.intermediate_size % hf_cfg.hidden_size != 0:
        raise ValueError("intermediate_size must be a multiple of "
                         "hidden_size")
    cfg = BertConfig(
        vocab_size=hf_cfg.vocab_size,
        n_positions=hf_cfg.max_position_embeddings,
        type_vocab_size=hf_cfg.type_vocab_size,
        n_embd=hf_cfg.hidden_size,
        n_layer=hf_cfg.num_hidden_layers,
        n_head=hf_cfg.num_attention_heads,
        mlp_ratio=hf_cfg.intermediate_size // hf_cfg.hidden_size,
        activation="gelu_exact" if act == "gelu" else "gelu",
        layer_norm_epsilon=hf_cfg.layer_norm_eps,
        pad_vocab_to_multiple=1,
    )
    spec = BertModel(cfg)
    bert = model.bert if hasattr(model, "bert") else model
    emb = bert.embeddings
    stack = functools.partial(_stack, bert.encoder.layer)

    def qkv_w(blk):
        a = blk.attention.self
        return np.concatenate([_lin_w(a.query), _lin_w(a.key),
                               _lin_w(a.value)], axis=1)

    def qkv_b(blk):
        a = blk.attention.self
        return np.concatenate([_np(a.query.bias), _np(a.key.bias),
                               _np(a.value.bias)])

    blocks = {
        "qkv_w": stack(qkv_w),
        "qkv_b": stack(qkv_b),
        "attn_out_w": stack(lambda b: _lin_w(b.attention.output.dense)),
        "attn_out_b": stack(lambda b: _np(b.attention.output.dense.bias)),
        "attn_ln_scale": stack(
            lambda b: _np(b.attention.output.LayerNorm.weight)),
        "attn_ln_bias": stack(
            lambda b: _np(b.attention.output.LayerNorm.bias)),
        "inter_w": stack(lambda b: _lin_w(b.intermediate.dense)),
        "inter_b": stack(lambda b: _np(b.intermediate.dense.bias)),
        "out_w": stack(lambda b: _lin_w(b.output.dense)),
        "out_b": stack(lambda b: _np(b.output.dense.bias)),
        "out_ln_scale": stack(lambda b: _np(b.output.LayerNorm.weight)),
        "out_ln_bias": stack(lambda b: _np(b.output.LayerNorm.bias)),
    }
    pred = model.cls.predictions
    params = {
        "wte": jnp.asarray(_np(emb.word_embeddings.weight)),
        "wpe": jnp.asarray(_np(emb.position_embeddings.weight)),
        "tte": jnp.asarray(_np(emb.token_type_embeddings.weight)),
        "emb_ln_scale": jnp.asarray(_np(emb.LayerNorm.weight)),
        "emb_ln_bias": jnp.asarray(_np(emb.LayerNorm.bias)),
        "blocks": {k: jnp.asarray(v) for k, v in blocks.items()},
        "mlm_dense_w": jnp.asarray(_lin_w(pred.transform.dense)),
        "mlm_dense_b": jnp.asarray(_np(pred.transform.dense.bias)),
        "mlm_ln_scale": jnp.asarray(_np(pred.transform.LayerNorm.weight)),
        "mlm_ln_bias": jnp.asarray(_np(pred.transform.LayerNorm.bias)),
        "mlm_bias": jnp.asarray(_np(pred.bias)),
    }
    return spec, params


@register_policy("DistilBertForMaskedLM")
def distil_bert_policy(model) -> Tuple[Any, Any]:
    """HF DistilBERT → BertModel params (reference module_inject/containers/
    distil_bert.py HFDistilBertLayerPolicy). Architecturally BERT without
    token-type embeddings (tte maps to a zero row) and with renamed
    submodules; same post-LN encoder + MLM transform head."""
    import functools
    import jax.numpy as jnp
    from ..models.bert import BertConfig, BertModel

    hf_cfg = model.config
    act = getattr(hf_cfg, "activation", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported DistilBERT activation {act!r}")
    if hf_cfg.hidden_dim % hf_cfg.dim != 0:
        raise ValueError("hidden_dim must be a multiple of dim")
    cfg = BertConfig(
        vocab_size=hf_cfg.vocab_size,
        n_positions=hf_cfg.max_position_embeddings,
        type_vocab_size=1,
        n_embd=hf_cfg.dim,
        n_layer=hf_cfg.n_layers,
        n_head=hf_cfg.n_heads,
        mlp_ratio=hf_cfg.hidden_dim // hf_cfg.dim,
        activation="gelu_exact" if act == "gelu" else "gelu",
        layer_norm_epsilon=1e-12,
        pad_vocab_to_multiple=1,
    )
    spec = BertModel(cfg)
    db = model.distilbert if hasattr(model, "distilbert") else model
    emb = db.embeddings
    stack = functools.partial(_stack, db.transformer.layer)

    def qkv_w(blk):
        a = blk.attention
        return np.concatenate([_lin_w(a.q_lin), _lin_w(a.k_lin),
                               _lin_w(a.v_lin)], axis=1)

    def qkv_b(blk):
        a = blk.attention
        return np.concatenate([_np(a.q_lin.bias), _np(a.k_lin.bias),
                               _np(a.v_lin.bias)])

    blocks = {
        "qkv_w": stack(qkv_w),
        "qkv_b": stack(qkv_b),
        "attn_out_w": stack(lambda b: _lin_w(b.attention.out_lin)),
        "attn_out_b": stack(lambda b: _np(b.attention.out_lin.bias)),
        "attn_ln_scale": stack(lambda b: _np(b.sa_layer_norm.weight)),
        "attn_ln_bias": stack(lambda b: _np(b.sa_layer_norm.bias)),
        "inter_w": stack(lambda b: _lin_w(b.ffn.lin1)),
        "inter_b": stack(lambda b: _np(b.ffn.lin1.bias)),
        "out_w": stack(lambda b: _lin_w(b.ffn.lin2)),
        "out_b": stack(lambda b: _np(b.ffn.lin2.bias)),
        "out_ln_scale": stack(lambda b: _np(b.output_layer_norm.weight)),
        "out_ln_bias": stack(lambda b: _np(b.output_layer_norm.bias)),
    }
    params = {
        "wte": jnp.asarray(_np(emb.word_embeddings.weight)),
        "wpe": jnp.asarray(_np(emb.position_embeddings.weight)),
        "tte": jnp.zeros((1, hf_cfg.dim), jnp.float32),
        "emb_ln_scale": jnp.asarray(_np(emb.LayerNorm.weight)),
        "emb_ln_bias": jnp.asarray(_np(emb.LayerNorm.bias)),
        "blocks": {k: jnp.asarray(v) for k, v in blocks.items()},
        "mlm_dense_w": jnp.asarray(_lin_w(model.vocab_transform)),
        "mlm_dense_b": jnp.asarray(_np(model.vocab_transform.bias)),
        "mlm_ln_scale": jnp.asarray(_np(model.vocab_layer_norm.weight)),
        "mlm_ln_bias": jnp.asarray(_np(model.vocab_layer_norm.bias)),
        "mlm_bias": jnp.asarray(_np(model.vocab_projector.bias)),
    }
    return spec, params


def _clip_tower_blocks(layers):
    """Shared CLIP encoder-layer mapping (text and vision towers are the
    same pre-LN block)."""
    import functools
    stack = functools.partial(_stack, layers)

    def qkv_w(blk):
        a = blk.self_attn
        return np.concatenate([_lin_w(a.q_proj), _lin_w(a.k_proj),
                               _lin_w(a.v_proj)], axis=1)

    def qkv_b(blk):
        a = blk.self_attn
        return np.concatenate([_np(a.q_proj.bias), _np(a.k_proj.bias),
                               _np(a.v_proj.bias)])

    return {
        "ln1_scale": stack(lambda b: _np(b.layer_norm1.weight)),
        "ln1_bias": stack(lambda b: _np(b.layer_norm1.bias)),
        "qkv_w": stack(qkv_w),
        "qkv_b": stack(qkv_b),
        "attn_proj_w": stack(lambda b: _lin_w(b.self_attn.out_proj)),
        "attn_proj_b": stack(lambda b: _np(b.self_attn.out_proj.bias)),
        "ln2_scale": stack(lambda b: _np(b.layer_norm2.weight)),
        "ln2_bias": stack(lambda b: _np(b.layer_norm2.bias)),
        "mlp_fc_w": stack(lambda b: _lin_w(b.mlp.fc1)),
        "mlp_fc_b": stack(lambda b: _np(b.mlp.fc1.bias)),
        "mlp_proj_w": stack(lambda b: _lin_w(b.mlp.fc2)),
        "mlp_proj_b": stack(lambda b: _np(b.mlp.fc2.bias)),
    }


@register_policy("CLIPModel")
def clip_policy(model) -> Tuple[Any, Any]:
    """HF CLIPModel → dual-tower CLIPModel params (reference
    module_inject/containers/clip.py HFCLIPLayerPolicy). The stride==kernel
    patch conv flattens into patch_w [3p², D]."""
    import jax.numpy as jnp
    from ..models.clip import (CLIPConfig, CLIPModel, CLIPTextConfig,
                               CLIPVisionConfig)

    tc, vc = model.config.text_config, model.config.vision_config
    for c in (tc, vc):
        act = getattr(c, "hidden_act", "quick_gelu")
        if act not in ("quick_gelu", "gelu"):
            raise ValueError(f"unsupported CLIP activation {act!r}")
        if c.intermediate_size % c.hidden_size != 0:
            raise ValueError("intermediate_size must be a multiple of "
                             "hidden_size")
    # HF pools at argmax(token id) when eos_token_id==2 (legacy) and at the
    # first eos position otherwise (PR #24773)
    hf_eos = getattr(tc, "eos_token_id", 2)
    cfg = CLIPConfig(
        text=CLIPTextConfig(
            vocab_size=tc.vocab_size,
            n_positions=tc.max_position_embeddings,
            n_embd=tc.hidden_size,
            n_layer=tc.num_hidden_layers,
            n_head=tc.num_attention_heads,
            mlp_ratio=tc.intermediate_size // tc.hidden_size,
            activation="gelu_exact" if tc.hidden_act == "gelu"
            else "quick_gelu",
            layer_norm_epsilon=tc.layer_norm_eps,
            eos_token_id=None if hf_eos == 2 else hf_eos,
        ),
        vision=CLIPVisionConfig(
            image_size=vc.image_size,
            patch_size=vc.patch_size,
            n_embd=vc.hidden_size,
            n_layer=vc.num_hidden_layers,
            n_head=vc.num_attention_heads,
            mlp_ratio=vc.intermediate_size // vc.hidden_size,
            activation="gelu_exact" if vc.hidden_act == "gelu"
            else "quick_gelu",
            layer_norm_epsilon=vc.layer_norm_eps,
        ),
        projection_dim=model.config.projection_dim,
    )
    spec = CLIPModel(cfg)
    tm, vm = model.text_model, model.vision_model

    text = {
        "wte": jnp.asarray(_np(tm.embeddings.token_embedding.weight)),
        "wpe": jnp.asarray(_np(tm.embeddings.position_embedding.weight)),
        "blocks": {k: jnp.asarray(v) for k, v in
                   _clip_tower_blocks(tm.encoder.layers).items()},
        "ln_f_scale": jnp.asarray(_np(tm.final_layer_norm.weight)),
        "ln_f_bias": jnp.asarray(_np(tm.final_layer_norm.bias)),
    }
    d = vc.hidden_size
    patch = _np(vm.embeddings.patch_embedding.weight)    # [D, 3, p, p]
    vision = {
        "patch_w": jnp.asarray(patch.reshape(d, -1).T),  # [3p², D]
        "class_emb": jnp.asarray(_np(vm.embeddings.class_embedding)),
        "wpe": jnp.asarray(_np(vm.embeddings.position_embedding.weight)),
        "pre_ln_scale": jnp.asarray(_np(vm.pre_layrnorm.weight)),
        "pre_ln_bias": jnp.asarray(_np(vm.pre_layrnorm.bias)),
        "blocks": {k: jnp.asarray(v) for k, v in
                   _clip_tower_blocks(vm.encoder.layers).items()},
        "ln_f_scale": jnp.asarray(_np(vm.post_layernorm.weight)),
        "ln_f_bias": jnp.asarray(_np(vm.post_layernorm.bias)),
    }
    params = {
        "text": text,
        "vision": vision,
        "text_proj": jnp.asarray(_lin_w(model.text_projection)),
        "visual_proj": jnp.asarray(_lin_w(model.visual_projection)),
        "logit_scale": jnp.asarray(_np(model.logit_scale)),
    }
    return spec, params


def replace_transformer_layer(model, config=None) -> Tuple[Any, Any]:
    """Entry point (reference module_inject/replace_module.py:276). Dispatch
    by policy; unknown architectures fall back to AutoTP-style generic
    handling only if a policy exists — otherwise raise (no silent wrap)."""
    policy = policy_for(model)
    spec, params = policy(model)
    logger.info(f"injected {type(model).__name__} -> "
                f"{type(spec).__name__} ({policy.__name__})")
    return spec, params


def _cfg_get(config, name, default):
    """diffusers configs are attr-style or FrozenDict-style."""
    if isinstance(config, dict):
        return config.get(name, default)
    return getattr(config, name, default)


@register_policy("UNet2DConditionModel")
def unet_policy(model) -> Tuple[Any, Any]:
    """diffusers UNet2DConditionModel → (UNet2DConditionSpec, flat params)
    (reference module_inject/containers/unet.py + the generic diffusers
    injection at replace_module.py:184). Weights keep their diffusers
    state_dict names; convs go OIHW→HWIO, linears [out,in]→[in,out].
    Only the standard SD topology (cross-attn on all but the last level)
    is supported — anything else raises rather than mis-injecting."""
    from ..models.diffusion import (UNet2DConditionConfig,
                                    UNet2DConditionSpec, convert_state_dict)

    c = model.config
    get = lambda name, default: _cfg_get(c, name, default)  # noqa: E731
    nb = len(get("block_out_channels", (32, 64)))
    down_types = tuple(get("down_block_types",
                           ("CrossAttnDownBlock2D",) * (nb - 1) +
                           ("DownBlock2D",)))
    up_types = tuple(get("up_block_types",
                         ("UpBlock2D",) +
                         ("CrossAttnUpBlock2D",) * (nb - 1)))
    want_down = ("CrossAttnDownBlock2D",) * (nb - 1) + ("DownBlock2D",)
    want_up = ("UpBlock2D",) + ("CrossAttnUpBlock2D",) * (nb - 1)
    if down_types != want_down or up_types != want_up:
        raise ValueError(
            f"unsupported UNet topology: down={down_types} up={up_types}; "
            f"this injection supports the standard SD layout "
            f"down={want_down} up={want_up}")
    head = get("attention_head_dim", 8)
    # diffusers quirk: attention_head_dim IS the head count (per level
    # when a list)
    head = tuple(head) if isinstance(head, (list, tuple)) else (int(head),)
    cfg = UNet2DConditionConfig(
        in_channels=get("in_channels", 4),
        out_channels=get("out_channels", 4),
        block_out_channels=tuple(get("block_out_channels", (32, 64))),
        layers_per_block=get("layers_per_block", 2),
        cross_attention_dim=get("cross_attention_dim", 32),
        attention_head_dim=head,
        norm_num_groups=get("norm_num_groups", 32),
        norm_eps=get("norm_eps", 1e-5),
        sample_size=get("sample_size", 32))
    return UNet2DConditionSpec(cfg), convert_state_dict(model.state_dict())


@register_policy("AutoencoderKL")
def vae_policy(model) -> Tuple[Any, Any]:
    """diffusers AutoencoderKL → (AutoencoderKLSpec, flat params)
    (reference module_inject/containers/vae.py)."""
    from ..models.diffusion import (AutoencoderKLConfig, AutoencoderKLSpec,
                                    convert_state_dict)

    c = model.config
    get = lambda name, default: _cfg_get(c, name, default)  # noqa: E731
    cfg = AutoencoderKLConfig(
        in_channels=get("in_channels", 3),
        out_channels=get("out_channels", 3),
        latent_channels=get("latent_channels", 4),
        block_out_channels=tuple(get("block_out_channels", (32, 64))),
        layers_per_block=get("layers_per_block", 1),
        norm_num_groups=get("norm_num_groups", 32),
        scaling_factor=get("scaling_factor", 0.18215))
    return AutoencoderKLSpec(cfg), convert_state_dict(model.state_dict())
