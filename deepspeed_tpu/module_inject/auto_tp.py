"""AutoTP — policy-free tensor-parallel sharding rules.

Counterpart of reference module_inject/auto_tp.py:13 ``AutoTP``: the torch
version walks the module graph to find linears and decide which get
all-reduce (row) vs plain (column) sharding. Here models are pytrees, so
AutoTP derives partition rules from leaf paths/shapes:

- name heuristics first (the reference's tp_parser policy knowledge):
  qkv/fc/up/gate → column-parallel (output dim over 'model'),
  proj/out/down/o_proj → row-parallel (input dim over 'model');
- unnamed 2D leaves alternate column/row in traversal order, which keeps
  matmul chains collective-free until the row-parallel reduce, exactly the
  Megatron pairing AutoTP aims for.
"""

import re
from typing import List, Tuple

import jax

from ..models.api import param_path_tree
from ..parallel.topology import MODEL_AXIS

_COL = re.compile(r"(qkv|query|key|value|c_attn|fc|up_proj|gate_proj|wi|"
                  r"dense_h_to_4h)", re.I)
_ROW = re.compile(r"(proj\b|c_proj|out|o_proj|down_proj|wo|dense_4h_to_h|"
                  r"attn_proj|mlp_proj)", re.I)
# never TP-shard: norms, biases, embeddings-by-name (stacked [L, d] leaves
# look 2D but aren't matmuls)
_SKIP = re.compile(r"(ln|norm|bias|scale|emb|wte|wpe|pos)", re.I)


def auto_tp_rules(params_like, tp_size: int) -> List[Tuple[str, Tuple]]:
    """Emit (path_regex, spec) partition rules for a params pytree."""
    if tp_size <= 1:
        return []
    paths = jax.tree.leaves(param_path_tree(params_like))
    leaves = jax.tree.leaves(params_like)
    rules = []
    next_is_col = True
    for path, leaf in zip(paths, leaves):
        shape = getattr(leaf, "shape", ())
        if len(shape) < 2 or _SKIP.search(path):
            continue
        # the last two dims are the matmul dims (leading dims: layer stacks)
        d_in, d_out = shape[-2], shape[-1]
        named = _ROW.search(path) or _COL.search(path)
        if not named and min(d_in, d_out) < 32:
            continue  # stacked vector ([L, d]) masquerading as 2D
        col_ok = d_out % tp_size == 0
        row_ok = d_in % tp_size == 0
        if _ROW.search(path) and row_ok:
            spec = [None] * (len(shape) - 2) + [MODEL_AXIS, None]
            next_is_col = True
        elif _COL.search(path) and col_ok:
            spec = [None] * (len(shape) - 2) + [None, MODEL_AXIS]
            next_is_col = False
        elif col_ok and next_is_col:
            spec = [None] * (len(shape) - 2) + [None, MODEL_AXIS]
            next_is_col = False
        elif row_ok:
            spec = [None] * (len(shape) - 2) + [MODEL_AXIS, None]
            next_is_col = True
        else:
            continue
        rules.append((f"^{re.escape(path)}$", tuple(spec)))
    return rules
