"""Process/device topology over a JAX device mesh.

TPU-native re-design of the reference topology layer
(deepspeed/runtime/pipe/topology.py:12 ``ProcessTopology``, :251
``PipelineParallelGrid``; deepspeed/utils/groups.py). Where the reference
builds NCCL process groups from a cartesian rank grid, here the grid IS a
``jax.sharding.Mesh`` with named axes, and "process groups" are mesh-axis
subsets consumed by pjit/shard_map — XLA lowers collectives onto ICI/DCN.

Canonical axis order (outer → inner, chosen so that the innermost axes map to
the fastest ICI links and the data axes are contiguous for ZeRO sharding):

    ('pipe', 'data', 'expert', 'seq', 'model')

- ``data`` × ``expert`` together form the reference's data-parallel world
  (groups.py:108: ep_size divides dp_world; expert-dp = dp/ep).
- ZeRO shards optimizer state / grads / params over ('data', 'expert').
- MoE all-to-all dispatch runs over 'expert'.
- Sequence parallelism (ring attention / Ulysses) runs over 'seq'.
- Tensor parallelism runs over 'model' (innermost → fastest ICI).
"""

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
MESH_AXES = (PIPE_AXIS, DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)

# The composite data-parallel sharding axes used by ZeRO.
DP_AXES = (DATA_AXIS, EXPERT_AXIS)


def hierarchical_axis_groups(axis_size: int, devices_per_host: int):
    """Split a flat mesh axis into (host, local) subaxes for two-level
    collectives (the ZeRO++ hierarchical exchange, comm/quantized.py).

    Returns ``(intra_groups, inter_groups)`` as ``axis_index_groups`` lists
    for the ``jax.lax`` collectives: intra groups hold the ``devices_per_
    host`` consecutive members that share a host (host-major member order —
    exactly how ``initialize_mesh`` lays out ``jax.devices()``, which is
    process-major); inter groups hold the members at the same local offset
    across hosts. ``(None, None)`` when no meaningful split exists
    (devices_per_host <= 1, >= axis_size, or not a divisor)."""
    local = int(devices_per_host)
    if local <= 1 or local >= axis_size or axis_size % local:
        return None, None
    hosts = axis_size // local
    intra = [[h * local + l for l in range(local)] for h in range(hosts)]
    inter = [[h * local + l for h in range(hosts)] for l in range(local)]
    return intra, inter


def default_devices():
    """Device list for mesh construction, via the accelerator facade so that
    DSTPU_ACCELERATOR=cpu (the test harness) selects the virtual CPU devices
    even when a TPU plugin owns the default backend."""
    import os
    if os.environ.get("DSTPU_ACCELERATOR") == "cpu":
        return jax.devices("cpu")
    return jax.devices()


class ProcessTopology:
    """Named-axis cartesian topology; API shaped after the reference
    ProcessTopology (topology.py:12) but backed by numpy index math over
    device ids rather than rank lists + NCCL groups."""

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        assert len(axes) == len(dims)
        self.axes = list(axes)
        self.dims = list(int(d) for d in dims)
        self._grid = np.arange(int(np.prod(self.dims))).reshape(self.dims)

    def get_rank(self, **coords) -> int:
        idx = tuple(coords[a] for a in self.axes)
        return int(self._grid[idx])

    def get_coord(self, rank: int):
        pos = np.argwhere(self._grid == rank)[0]
        return dict(zip(self.axes, (int(p) for p in pos)))

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)]

    def get_axis_names(self) -> List[str]:
        return list(self.axes)

    def world_size(self) -> int:
        return int(np.prod(self.dims))

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """All groups of ranks that vary only along `axis`
        (reference topology.py:131)."""
        ax = self.axes.index(axis)
        moved = np.moveaxis(self._grid, ax, -1).reshape(-1, self.dims[ax])
        return [list(map(int, row)) for row in moved]

    def filter_match(self, **coords) -> List[int]:
        ranks = []
        for r in range(self.world_size()):
            c = self.get_coord(r)
            if all(c[k] == v for k, v in coords.items()):
                ranks.append(r)
        return ranks


class DeviceMeshManager:
    """Owns the global ``jax.sharding.Mesh`` and the named-sharding helpers.

    The single place the rest of the framework asks "how is X sharded".
    Replaces reference groups.py globals (_WORLD_GROUP/_EXPERT_PARALLEL_GROUP/
    ...) with mesh-axis bookkeeping.
    """

    def __init__(self,
                 pp: int = 1,
                 dp: Optional[int] = None,
                 ep: int = 1,
                 sp: int = 1,
                 tp: int = 1,
                 devices=None):
        devices = devices if devices is not None else default_devices()
        n = len(devices)
        fixed = pp * ep * sp * tp
        if dp is None:
            if n % fixed != 0:
                raise ValueError(
                    f"{n} devices not divisible by pp*ep*sp*tp={fixed}")
            dp = n // fixed
        total = pp * dp * ep * sp * tp
        if total != n:
            raise ValueError(
                f"mesh {pp}x{dp}x{ep}x{sp}x{tp}={total} != device count {n}")
        self.topology = ProcessTopology(MESH_AXES, (pp, dp, ep, sp, tp))
        dev_array = np.asarray(devices).reshape(pp, dp, ep, sp, tp)
        self.mesh = Mesh(dev_array, MESH_AXES)
        self.pp, self.dp, self.ep, self.sp, self.tp = pp, dp, ep, sp, tp

    # ---- sizes ----
    @property
    def dp_world_size(self) -> int:
        """Full data-parallel degree (data × expert), reference groups.py."""
        return self.dp * self.ep

    def axis_size(self, axis: str) -> int:
        return self.topology.get_dim(axis)

    # ---- shardings ----
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_spec(self, shard_seq: bool = True) -> P:
        """Batch dim over the dp axes; sequence dim over 'seq' if enabled."""
        if self.sp > 1 and shard_seq:
            return P(DP_AXES, SEQ_AXIS)
        return P(DP_AXES)

    def batch_sharding(self, shard_seq: bool = True) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec(shard_seq))

    def data_host_groups(self, devices_per_host: int = 0):
        """(intra, inter) ``axis_index_groups`` splitting the 'data' axis
        into (host, local) subaxes for hierarchical collectives;
        ``devices_per_host`` 0 = this process's local device count."""
        if devices_per_host == 0:
            devices_per_host = jax.local_device_count()
        return hierarchical_axis_groups(self.axis_size(DATA_AXIS),
                                        devices_per_host)

    def __enter__(self):
        self._ctx = self.mesh
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


_MESH_MANAGER: Optional[DeviceMeshManager] = None


def initialize_mesh(pp=1, dp=None, ep=1, sp=1, tp=1, devices=None) -> DeviceMeshManager:
    """Create (or replace) the global mesh. Analogue of groups.initialize
    (deepspeed/utils/groups.py:46)."""
    global _MESH_MANAGER
    _MESH_MANAGER = DeviceMeshManager(pp=pp, dp=dp, ep=ep, sp=sp, tp=tp, devices=devices)
    return _MESH_MANAGER


def get_mesh_manager() -> DeviceMeshManager:
    global _MESH_MANAGER
    if _MESH_MANAGER is None:
        _MESH_MANAGER = DeviceMeshManager()
    return _MESH_MANAGER


def reset_mesh():
    global _MESH_MANAGER
    _MESH_MANAGER = None
