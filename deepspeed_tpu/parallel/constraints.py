"""Sharding-constraint helpers.

``maybe_constraint`` applies ``lax.with_sharding_constraint`` only when a mesh
context is active AND the named axes exist in it — so model code can annotate
intent unconditionally (the GSPMD analogue of the reference's explicit
collectives) and still run un-meshed (single-device tests, numerics oracles).
Axes of size 1 are kept (no-op for XLA, zero cost).
"""

from jax import lax
from jax._src.mesh import thread_resources
from jax.sharding import PartitionSpec as P


def active_mesh():
    """The context mesh, or None."""
    mesh = thread_resources.env.physical_mesh
    return None if mesh.empty else mesh


def _filter_spec(spec: P, axis_names) -> P:
    out = []
    for s in spec:
        if s is None:
            out.append(None)
        elif isinstance(s, (tuple, list)):
            kept = tuple(a for a in s if a in axis_names)
            out.append(kept if kept else None)
        else:
            out.append(s if s in axis_names else None)
    return P(*out)


def maybe_constraint(x, *spec):
    """with_sharding_constraint(x, P(*spec)) if a mesh is active, else x."""
    mesh = active_mesh()
    if mesh is None:
        return x
    return lax.with_sharding_constraint(x, _filter_spec(P(*spec),
                                                        set(mesh.axis_names)))
