from .topology import (ProcessTopology, DeviceMeshManager, initialize_mesh,
                       get_mesh_manager, reset_mesh, MESH_AXES, DP_AXES,
                       PIPE_AXIS, DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)
