"""deepspeed_tpu.zero — the user-facing ZeRO namespace.

Reference surface: ``deepspeed.zero.Init`` (construct a model with
params partitioned from birth, zero/partition_parameters.py:601),
``deepspeed.zero.GatheredParameters`` (temporarily materialize full
params for host access/mutation, partition_parameters.py:2014), and
``register_external_parameter``.

TPU-native translation:

- **Init**: partitioned-from-birth is the DEFAULT here — ModelSpec.init
  is a pure function the engine jit-compiles with sharded out_shardings,
  so full parameters never materialize on one device at any stage (the
  thing zero.Init exists to prevent in torch, where nn.Module.__init__
  eagerly allocates). The context manager is kept for source
  compatibility: it validates its arguments and is otherwise a
  documented no-op.
- **GatheredParameters**: real work — gathers the engine's sharded
  leaves to host numpy (jax global arrays reassemble across the mesh),
  yields them for inspection/mutation, and on exit writes mutations back
  through the engine's param shardings.
"""

import contextlib

import numpy as np

from .utils.logging import log_dist

_INIT_KEYS = {"module", "data_parallel_group", "mem_efficient_linear",
              "remote_device", "pin_memory", "config_dict_or_path",
              "config", "enabled", "dtype", "mpu", "param_dict",
              "sequence_data_parallel_group"}


class Init:
    """Source-compatible ``with deepspeed_tpu.zero.Init(): ...`` context.

    Params here are jit-initialized INTO their shardings at engine build
    (runtime/engine.py out_shardings on the init fn), so there is no
    eager full-size allocation for this context to intercept — entering
    it is a no-op by design, kept so reference training scripts port
    unchanged. Unknown kwargs raise (accepted = active)."""

    def __init__(self, **kwargs):
        unknown = set(kwargs) - _INIT_KEYS
        if unknown:
            raise ValueError(f"zero.Init: unknown arguments {sorted(unknown)}")
        self.enabled = kwargs.get("enabled", True)
        if self.enabled:
            log_dist("zero.Init: params are jit-initialized sharded-from-"
                     "birth on TPU; context is a compatibility no-op",
                     ranks=[0])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


@contextlib.contextmanager
def GatheredParameters(target, modifier_rank=None, fwd_module=None,
                       enabled=True):
    """Materialize full params on host; optionally write mutations back.

    ``target``: a DeepSpeedEngine (any ZeRO stage, incl. ZeRO-Offload /
    Infinity — the gather reads the authoritative fp32 masters and the
    write-back follows the same protocol as checkpoint load, refreshing
    device params and invalidating param pages), or a bare params pytree
    (read-only: like the reference with ``modifier_rank=None``, mutations
    are NOT synchronized — pass the engine to write back).

    ``modifier_rank``: None = read-only gather (reference default). Any
    int = write mutations back on exit; SPMD has no per-rank divergence,
    so every value behaves like rank 0."""
    import jax

    is_engine = hasattr(target, "params") and hasattr(target, "_config")
    if not enabled:
        yield target.params if is_engine else target
        return
    if not is_engine:
        if modifier_rank is not None:
            raise ValueError(
                "zero.GatheredParameters: write-back (modifier_rank set) "
                "needs the ENGINE, not a bare params tree — jax arrays "
                "are immutable, so there is no in-place mutation to sync")
        yield jax.tree.map(lambda x: np.array(x), target)
        return

    offload = getattr(target, "_offload", None)
    if offload is not None:
        host = offload.masters_tree(copy=True)
    else:
        host = jax.tree.map(lambda x: np.array(x), target.params)
    yield host
    if modifier_rank is None:
        return   # read-only contract, like the reference default
    if offload is not None:
        # same write-back protocol as checkpoint load
        # (runtime/checkpointing.py:172-227): masters are authoritative;
        # device params re-derive from them
        for i, w in enumerate(jax.tree.leaves(host)):
            offload.masters[i][...] = np.asarray(w, np.float32).reshape(-1)
        runner = getattr(target, "_param_runner", None)
        if runner is not None:
            with target.mesh:
                target.params = runner.resident_params()
            runner._invalidate_pages()
        else:
            target.params = offload.device_params()
    else:
        with target.mesh:
            target.params = jax.device_put(host, target.param_shardings)
    log_dist("zero.GatheredParameters: host mutations resharded into the "
             "engine", ranks=[0])


def register_external_parameter(module, parameter):
    """Reference: tells ZeRO-3 about params accessed outside the module
    tree so the prefetcher gathers them (partition_parameters.py:294).
    Unnecessary here — every array a jitted step touches is visible to
    XLA's dataflow, so there is nothing to register. No-op kept for
    source compatibility."""
    del module, parameter
    log_dist("zero.register_external_parameter: no-op on TPU (XLA sees "
             "every traced array; nothing to prefetch manually)", ranks=[0])
