from .monitor import MonitorMaster, TensorBoardMonitor, WandbMonitor, CsvMonitor
