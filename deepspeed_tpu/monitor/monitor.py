"""Monitoring fan-out.

Re-implementation of deepspeed/monitor/monitor.py:29 ``MonitorMaster``:
an event sink `write_events([(tag, value, step)])` fanning out to
TensorBoard / W&B / CSV sinks, each config-gated. Only the data-parallel-
coordinating process writes (reference: rank-0 guard in each monitor).
"""

import csv
import hashlib
import os
import re
from typing import List, Tuple

import jax

from ..utils.logging import logger


class Monitor:
    def __init__(self, config):
        self.enabled = bool(config.enabled)

    def write_events(self, event_list):
        raise NotImplementedError

    def close(self):
        """Release sink resources (file handles, writers). Idempotent."""


#: anything outside this set is filesystem-hostile somewhere (spaces, ':'
#: on Windows/mac, '*?<>|' glob/shell chars, '/' separators) — collapse it
_TAG_HOSTILE = re.compile(r"[^A-Za-z0-9_.\-]+")


class CsvMonitor(Monitor):
    """reference monitor/csv_monitor.py"""

    def __init__(self, config):
        super().__init__(config)
        self.output_path = config.output_path or "csv_monitor_output"
        self.job_name = config.job_name
        self._files = {}
        self._claimed = {}   # sanitized filename -> originating tag
        if self.enabled and jax.process_index() == 0:
            os.makedirs(os.path.join(self.output_path, self.job_name),
                        exist_ok=True)

    def _safe_name(self, tag):
        """Sanitize a tag into a single path component: strip every
        filesystem-hostile character (not just '/'), kill '..' path
        climbing, and guard against two tags colliding onto one file."""
        safe = _TAG_HOSTILE.sub("_", tag).lstrip(".")
        if not safe or set(safe) <= {".", "_"}:
            safe = "tag"
        owner = self._claimed.get(safe)
        if owner is not None and owner != tag:
            safe = f"{safe}-{hashlib.md5(tag.encode()).hexdigest()[:8]}"
        self._claimed[safe] = tag
        return safe

    def _file_for(self, tag):
        if tag not in self._files:
            safe = self._safe_name(tag)
            path = os.path.join(self.output_path, self.job_name, f"{safe}.csv")
            f = open(path, "a", newline="")
            self._files[tag] = (f, csv.writer(f))
        return self._files[tag]

    def write_events(self, event_list):
        if not self.enabled or jax.process_index() != 0:
            return
        for tag, value, step in event_list:
            f, writer = self._file_for(tag)
            writer.writerow([step, value])
            f.flush()

    def close(self):
        for f, _ in self._files.values():
            try:
                f.close()
            except OSError as e:
                logger.warning(f"CsvMonitor: close failed: {e}")
        self._files = {}


class TensorBoardMonitor(Monitor):
    """reference monitor/tensorboard.py — uses torch's SummaryWriter if
    importable, else degrades to disabled with a warning."""

    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if self.enabled and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter
                os.makedirs(config.output_path or "./runs", exist_ok=True)
                self.summary_writer = SummaryWriter(
                    log_dir=os.path.join(config.output_path or "./runs",
                                         config.job_name))
            except Exception as e:
                logger.warning(f"tensorboard unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list):
        if self.summary_writer is None:
            return
        for tag, value, step in event_list:
            self.summary_writer.add_scalar(tag, value, step)
        self.summary_writer.flush()

    def close(self):
        if self.summary_writer is not None:
            self.summary_writer.close()
            self.summary_writer = None


class WandbMonitor(Monitor):
    """reference monitor/wandb.py"""

    def __init__(self, config):
        super().__init__(config)
        self._wandb = None
        if self.enabled and jax.process_index() == 0:
            try:
                import wandb
                wandb.init(project=config.project, group=config.group,
                           entity=config.team)
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list):
        if self._wandb is None:
            return
        # one wandb.log per step, not one network call per event: a batch
        # of same-step tags (the common _post_step shape) is a single log
        by_step = {}
        for tag, value, step in event_list:
            by_step.setdefault(step, {})[tag] = value
        for step in sorted(by_step):
            self._wandb.log(by_step[step], step=step)

    def close(self):
        if self._wandb is not None:
            try:
                self._wandb.finish()
            except Exception as e:
                logger.warning(f"wandb finish failed: {e}")
            self._wandb = None


class MonitorMaster(Monitor):
    """reference monitor/monitor.py:29 — owns all sinks (TensorBoard, W&B,
    CSV, plus the telemetry/Prometheus sink from the ``prometheus``
    config block)."""

    def __init__(self, ds_config):
        # telemetry sink import is deferred: telemetry/export.py imports
        # comm/logging.py, and importing it at module load would cycle
        from ..telemetry.monitor_sink import TelemetryMonitor
        self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb_monitor = WandbMonitor(ds_config.wandb)
        self.csv_monitor = CsvMonitor(ds_config.csv_monitor)
        self.prometheus_monitor = TelemetryMonitor(
            getattr(ds_config, "prometheus", None))
        self._sinks = (self.tb_monitor, self.wandb_monitor, self.csv_monitor,
                       self.prometheus_monitor)
        self.enabled = any(s.enabled for s in self._sinks)

    def write_events(self, event_list: List[Tuple[str, float, int]]):
        if not self.enabled:
            return
        for sink in self._sinks:
            if sink.enabled:
                sink.write_events(event_list)

    def close(self):
        """Close every sink (the serving engine's drain path calls this;
        CSV handles would otherwise leak for the process lifetime)."""
        for sink in self._sinks:
            sink.close()
