"""Compression-aware training (QAT / pruning).

Capability match for the reference compression library
(compression/compress.py:95 ``init_compression``, basic_layer.py:121
``LinearLayer_Compress``, scheduler.py ``compression_scheduler``): weight
quantization-aware training, magnitude/structured pruning, and a step
scheduler that switches techniques on after their offset.

TPU-native design: the reference rewrites nn.Modules in place; here
``init_compression(model, config)`` returns a WRAPPED ModelSpec whose apply
transforms the param pytree — fake-quantizing / masking every leaf whose
path matches a configured group — before the inner model runs. The
transforms are pure jnp (ops/quantizer_ops fake_quantize + top-k masks), so
they trace into the SAME compiled train step; flipping a technique on at
its schedule_offset retraces once (the engine recompiles when the scheduler
reports a flip)."""

import re
from typing import Any, Dict, List

import jax
import jax.numpy as jnp

from ..models.api import ModelSpec, param_path_tree
from ..ops.quantizer_ops import (binary_quantize, fake_quantize,
                                 ternary_quantize)
from ..utils.logging import log_dist
from .config import CompressionConfig, TechniqueConfig


def _match(path: str, patterns: List[str]) -> bool:
    for pat in patterns:
        if pat == "*" or re.search(pat, path):
            return True
    return False


# ---------------------------------------------------------------- transforms
def _keep_topk_mask(norms, ratio: float, dtype):
    """1/0 mask keeping the ``ratio`` highest-norm entries (k clamped to
    [1, n] so dense_ratio >= 1 keeps everything instead of wrapping the
    sort index negative)."""
    n = norms.shape[0]
    k = min(n, max(1, int(round(n * ratio))))
    thresh = jnp.sort(norms)[n - k]
    return (norms >= thresh).astype(dtype)


def quantize_leaf(w, params: Dict[str, Any]):
    """QAT fake-quant (LinearLayer_Compress weight quantization), incl.
    the reference's 1-bit binary and 2-bit ternary regimes
    (basic_layer.py:90-100 dispatch; utils.py Binary/TernaryQuantizer)
    and Embedding_Compress's token-wise grouping (basic_layer.py:102:
    ``quantization_groups: "token_wise"`` -> one group per embedding row)."""
    bits = int(params.get("target_bits", params.get("bits", 8)))
    groups = params.get("quantization_groups", params.get("groups", 1))
    if groups == "token_wise":
        groups = int(w.shape[0]) if w.ndim >= 2 else 1
    groups = int(groups)
    sym = params.get("quantization_type", "symmetric") != "asymmetric"
    if w.size % max(groups, 1) != 0:
        groups = 1
    if bits <= 2 and not sym:
        raise ValueError("only symmetric quantization is supported for "
                         "binary/ternary weights")
    if bits == 1:
        return binary_quantize(w, groups=groups)
    if bits == 2:
        return ternary_quantize(w, groups=groups)
    return fake_quantize(w, groups=groups, bits=bits, symmetric=sym)


def sparse_prune_leaf(w, params: Dict[str, Any]):
    """Unstructured magnitude pruning at `dense_ratio` kept weights."""
    ratio = float(params.get("dense_ratio", 0.5))
    mask = _keep_topk_mask(jnp.abs(w.reshape(-1)), ratio, w.dtype)
    return w * mask.reshape(w.shape)


def row_prune_leaf(w, params: Dict[str, Any]):
    """Structured row pruning: keep the highest-L1 rows (2D leaves)."""
    if w.ndim < 2:
        return w
    ratio = float(params.get("dense_ratio", 0.5))
    rows = w.shape[0]
    norms = jnp.sum(jnp.abs(w.reshape(rows, -1)), axis=1)
    mask = _keep_topk_mask(norms, ratio, w.dtype)
    return w * mask.reshape((rows,) + (1,) * (w.ndim - 1))


def head_prune_leaf(w, params: Dict[str, Any]):
    """Attention-head pruning: zero whole heads by output-column blocks of
    an attention projection (num_heads from the group params)."""
    heads = int(params.get("num_heads", 1))
    if heads <= 1 or w.ndim < 2 or w.shape[-1] % heads != 0:
        return w
    ratio = float(params.get("dense_ratio", 0.5))
    hd = w.shape[-1] // heads
    blocks = w.reshape(w.shape[:-1] + (heads, hd))
    norms = jnp.sum(jnp.abs(blocks.reshape(-1, heads, hd)), axis=(0, 2))
    mask = _keep_topk_mask(norms, ratio, w.dtype)
    return (blocks * mask[:, None]).reshape(w.shape)


def channel_prune_leaf(w, params: Dict[str, Any]):
    """Conv output-channel pruning (reference basic_layer.py:404
    Conv2dLayer_Compress.enable_channel_pruning: L1 norm per output
    channel). Our conv kernels are HWIO, so the output channel is the LAST
    axis — norms reduce over (kh, kw, in) and the mask broadcasts on -1.
    Non-4D leaves (biases, norm scales matched by a broad pattern) pass
    through untouched."""
    if w.ndim != 4:
        return w
    ratio = float(params.get("dense_ratio", 0.5))
    norms = jnp.sum(jnp.abs(w), axis=(0, 1, 2))
    return w * _keep_topk_mask(norms, ratio, w.dtype)


_TRANSFORMS = [
    ("sparse_pruning", sparse_prune_leaf),
    ("row_pruning", row_prune_leaf),
    ("head_pruning", head_prune_leaf),
    ("channel_pruning", channel_prune_leaf),
    ("weight_quantization", quantize_leaf),   # quant LAST (after masks)
]

#: techniques with a schedule_offset (param transforms + activation quant)
_SCHEDULED = [n for n, _ in _TRANSFORMS] + ["activation_quantization"]


class CompressionScheduler:
    """Step scheduler (reference compression/scheduler.py): a technique is
    LIVE once global_step >= its schedule_offset. step() returns True when
    any liveness flips — the engine's cue to retrace."""

    def __init__(self, config: CompressionConfig):
        self.config = config
        self.global_step = 0
        self._live = {}
        self._update()

    def _update(self):
        changed = False
        for name in _SCHEDULED:
            tc: TechniqueConfig = getattr(self.config, name)
            live = bool(tc and tc.enabled and
                        self.global_step >= tc.schedule_offset)
            if self._live.get(name) != live:
                self._live[name] = live
                changed = True
        return changed

    def is_live(self, name: str) -> bool:
        return self._live.get(name, False)

    def step(self, global_step: int) -> bool:
        self.global_step = global_step
        return self._update()


class CompressedModel(ModelSpec):
    """ModelSpec wrapper applying the live transforms to matching leaves."""

    def __init__(self, inner: ModelSpec, config: CompressionConfig):
        self.inner = inner
        self.compression_config = config
        self.compression_scheduler = CompressionScheduler(config)
        self.config = getattr(inner, "config", None)
        self._zero_match_warned = set()

    def init(self, rng):
        return self.inner.init(rng)

    def compress_params(self, params, force_all: bool = False):
        """Apply the live transforms (force_all: every ENABLED technique
        regardless of schedule — the export/redundancy_clean path, which
        may run in a fresh process whose scheduler sits at step 0)."""
        paths = param_path_tree(params)
        for name, fn in _TRANSFORMS:
            tc: TechniqueConfig = getattr(self.compression_config, name)
            live = (tc is not None and tc.enabled) if force_all else \
                self.compression_scheduler.is_live(name)
            if not live:
                continue

            applied = []

            def leaf(path, w):
                if not hasattr(w, "ndim") or not jnp.issubdtype(
                        w.dtype, jnp.floating):
                    return w
                for group in tc.groups:
                    if _match(path, group.modules):
                        out = fn(w, group.params)
                        if out is not w:   # transforms return w unchanged
                            applied.append(path)  # when inapplicable
                        return out
                return w

            params = jax.tree.map(leaf, paths, params)
            if not applied and name not in self._zero_match_warned:
                # accepted = active: an enabled technique whose patterns
                # match no applicable leaf would otherwise be silently inert
                self._zero_match_warned.add(name)
                log_dist(f"compression: '{name}' is enabled but transformed "
                         f"ZERO leaves — check different_groups modules "
                         f"patterns against the model's param paths",
                         ranks=[0])
        return params

    def _act_bits(self, force_all: bool = False):
        """Activation-quant bits when live (reference basic_layer.py
        QuantAct), else None. The inner model applies it at block inputs
        (GPT2Model.apply act_bits kwarg)."""
        tc = self.compression_config.activation_quantization
        if tc is None or not tc.enabled:
            return None
        if not force_all and not self.compression_scheduler.is_live(
                "activation_quantization"):
            return None
        for group in tc.groups:
            return int(group.params.get("bits",
                                        group.params.get("target_bits", 8)))
        return 8

    def apply(self, params, batch, rng=None, train=True, **kwargs):
        bits = self._act_bits()
        if bits is not None:
            kwargs["act_bits"] = bits
        return self.inner.apply(self.compress_params(params), batch,
                                rng=rng, train=train, **kwargs)

    # inference surfaces see the SAME compressed weights as training —
    # otherwise serve/train behavior silently diverges
    def logits(self, params, *args, **kwargs):
        return self.inner.logits(self.compress_params(params), *args,
                                 **kwargs)

    def apply_with_cache(self, params, *args, **kwargs):
        return self.inner.apply_with_cache(self.compress_params(params),
                                           *args, **kwargs)

    def partition_rules(self):
        return self.inner.partition_rules()

    def __getattr__(self, name):
        return getattr(self.inner, name)


def init_compression(model, deepspeed_config, mpu=None) -> CompressedModel:
    """Reference entrypoint (compress.py:95): wrap the model per the
    `compression_training` block; no-op wrap if nothing is enabled."""
    if hasattr(deepspeed_config, "_param_dict"):
        deepspeed_config = deepspeed_config._param_dict
    if isinstance(deepspeed_config, str):
        import json
        with open(deepspeed_config) as f:
            deepspeed_config = json.load(f)
    config = CompressionConfig.parse(deepspeed_config)
    if config.activation_quantization and \
            config.activation_quantization.enabled:
        import inspect
        sig = inspect.signature(model.apply).parameters
        if "act_bits" not in sig and not any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in sig.values()):
            raise ValueError(
                f"activation_quantization enabled but "
                f"{type(model).__name__}.apply() does not accept "
                f"'act_bits' — this model cannot honor the setting")
    if config.layer_reduction.get("enabled"):
        model = reduce_student_model(model, config)
    enabled = [n for n in _SCHEDULED
               if getattr(config, n) and getattr(config, n).enabled]
    if not enabled and not config.layer_reduction.get("enabled"):
        log_dist("init_compression: no technique enabled; model unchanged",
                 ranks=[0])
        return model
    wrapped = CompressedModel(model, config)
    log_dist(f"init_compression: techniques={enabled} "
             f"layer_reduction={config.layer_reduction.get('enabled', False)}",
             ranks=[0])
    return wrapped


def _teacher_layer_list(lr: Dict[str, Any], n_teacher: int) -> List[int]:
    keep = int(lr.get("keep_number_layer", n_teacher))
    layers = lr.get("teacher_layer")
    if layers is None:
        # reference default: evenly spaced teacher layers
        stride = max(1, n_teacher // keep)
        layers = list(range(0, n_teacher, stride))[:keep]
    layers = [int(i) for i in layers]
    if len(layers) != keep:
        raise ValueError(
            f"layer_reduction: teacher_layer has {len(layers)} entries but "
            f"keep_number_layer={keep}")
    bad = [i for i in layers if not 0 <= i < n_teacher]
    if bad:
        raise ValueError(f"layer_reduction: teacher_layer ids {bad} outside "
                         f"the teacher's {n_teacher} layers")
    return layers


def reduce_student_model(model, config) -> Any:
    """Layer reduction (reference compress.py:167 + helper.py): a student
    with keep_number_layer layers of the teacher architecture. With this
    repo's stacked [L, ...] leaves the depth change is one config field."""
    import dataclasses
    lr = config.layer_reduction if isinstance(config, CompressionConfig) \
        else CompressionConfig.parse(config).layer_reduction
    inner = model.inner if isinstance(model, CompressedModel) else model
    mcfg = inner.config
    keep = int(lr.get("keep_number_layer", mcfg.n_layer))
    if keep == mcfg.n_layer:
        return model
    student = type(inner)(dataclasses.replace(mcfg, n_layer=keep))
    log_dist(f"layer_reduction: student n_layer={keep} "
             f"(teacher {mcfg.n_layer})", ranks=[0])
    return student


def student_initialization(student_model, teacher_params, deepspeed_config):
    """Distillation init (reference compress.py:167
    ``student_initialization``): copy the configured teacher layers into
    the student's stacked blocks — a single take() on the layer axis — and
    every non-layer module (embeddings, final LN, head) verbatim."""
    if hasattr(deepspeed_config, "_param_dict"):
        deepspeed_config = deepspeed_config._param_dict
    config = CompressionConfig.parse(deepspeed_config)
    lr = config.layer_reduction
    if not lr.get("enabled"):
        raise ValueError("student_initialization requires "
                         "compression_training.layer_reduction.enabled")
    inner = student_model.inner \
        if isinstance(student_model, CompressedModel) else student_model
    bkey = "blocks"
    n_teacher = next(iter(
        jax.tree.leaves(teacher_params[bkey]))).shape[0]
    layers = _teacher_layer_list(lr, n_teacher)
    idx = jnp.asarray(layers, jnp.int32)
    out = {k: v for k, v in teacher_params.items() if k != bkey}
    out[bkey] = jax.tree.map(lambda x: jnp.take(x, idx, axis=0),
                             teacher_params[bkey])
    want = inner.config.n_layer
    if len(layers) != want:
        raise ValueError(
            f"student has n_layer={want} but layer_reduction selects "
            f"{len(layers)} teacher layers")
    log_dist(f"student_initialization: teacher layers {layers} -> student",
             ranks=[0])
    return out


def redundancy_clean(model, deepspeed_config=None):
    """Reference post-training cleanup (compress.py redundancy_clean):
    bakes every ENABLED transform into the weights permanently (not just
    the currently-live ones — export may run in a fresh process whose
    scheduler is at step 0). Returns params -> cleaned params."""
    if isinstance(model, CompressedModel):
        return lambda p: model.compress_params(p, force_all=True)
    return lambda p: p
