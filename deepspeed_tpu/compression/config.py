"""Compression config parsing (reference compression/config.py +
constants.py schema): the `compression_training` block with
weight_quantization / activation_quantization / sparse_pruning /
row_pruning / head_pruning / layer_reduction groups. Each technique has
shared_parameters (enabled, schedule_offset, ...) and different_groups
({name: {params: {...}, modules: [patterns]}})."""

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class TechniqueGroup:
    name: str
    params: Dict[str, Any]
    modules: List[str]          # regex/substring patterns over param paths
    related_modules: Optional[List[str]] = None


@dataclasses.dataclass
class TechniqueConfig:
    enabled: bool = False
    schedule_offset: int = 0
    shared: Dict[str, Any] = dataclasses.field(default_factory=dict)
    groups: List[TechniqueGroup] = dataclasses.field(default_factory=list)

    @classmethod
    def parse(cls, block: Dict[str, Any]) -> "TechniqueConfig":
        shared = dict(block.get("shared_parameters", {}))
        groups = []
        for name, g in (block.get("different_groups") or {}).items():
            groups.append(TechniqueGroup(
                name=name, params=dict(g.get("params", {})),
                modules=list(g.get("modules", ["*"])),
                related_modules=g.get("related_modules")))
        return cls(enabled=bool(shared.get("enabled", False)),
                   schedule_offset=int(shared.get("schedule_offset", 0)),
                   shared=shared, groups=groups)


#: technique block names in the ``compression_training`` config section
#: (reference compression/constants.py: WEIGHT_QUANTIZATION ..
#: CHANNEL_PRUNING:160)
TECHNIQUE_BLOCKS = ("weight_quantization", "activation_quantization",
                    "sparse_pruning", "row_pruning", "head_pruning",
                    "channel_pruning")


@dataclasses.dataclass
class CompressionConfig:
    weight_quantization: TechniqueConfig = None
    activation_quantization: TechniqueConfig = None
    sparse_pruning: TechniqueConfig = None
    row_pruning: TechniqueConfig = None
    head_pruning: TechniqueConfig = None
    channel_pruning: TechniqueConfig = None
    layer_reduction: Dict[str, Any] = None

    @classmethod
    def parse(cls, ds_config: Dict[str, Any]) -> "CompressionConfig":
        block = (ds_config or {}).get("compression_training", {}) or {}
        unknown = set(block) - set(TECHNIQUE_BLOCKS) - {"layer_reduction"}
        if unknown:
            # accepted = active: an unknown technique block would be
            # silently inert, which reads as "compression on" to the user
            raise ValueError(
                f"unknown compression_training blocks {sorted(unknown)}; "
                f"known: {sorted(TECHNIQUE_BLOCKS) + ['layer_reduction']}")
        kwargs = {name: TechniqueConfig.parse(block.get(name, {}))
                  for name in TECHNIQUE_BLOCKS}
        wq = kwargs["weight_quantization"]
        for g in wq.groups:
            bits = int(g.params.get("target_bits", g.params.get("bits", 8)))
            if bits <= 2 and g.params.get(
                    "quantization_type", "symmetric") == "asymmetric":
                # validate at parse time, not when the technique goes live
                # at schedule_offset hours into a run
                raise ValueError(
                    f"weight_quantization group '{g.name}': only symmetric "
                    f"quantization is supported for binary/ternary "
                    f"({bits}-bit) weights")
        return cls(layer_reduction=dict(block.get("layer_reduction", {}) or {}),
                   **kwargs)

    def any_enabled(self) -> bool:
        return any(getattr(self, n) is not None and getattr(self, n).enabled
                   for n in TECHNIQUE_BLOCKS)
