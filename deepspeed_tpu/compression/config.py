"""Compression config parsing (reference compression/config.py +
constants.py schema): the `compression_training` block with
weight_quantization / activation_quantization / sparse_pruning /
row_pruning / head_pruning / layer_reduction groups. Each technique has
shared_parameters (enabled, schedule_offset, ...) and different_groups
({name: {params: {...}, modules: [patterns]}})."""

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class TechniqueGroup:
    name: str
    params: Dict[str, Any]
    modules: List[str]          # regex/substring patterns over param paths
    related_modules: Optional[List[str]] = None


@dataclasses.dataclass
class TechniqueConfig:
    enabled: bool = False
    schedule_offset: int = 0
    shared: Dict[str, Any] = dataclasses.field(default_factory=dict)
    groups: List[TechniqueGroup] = dataclasses.field(default_factory=list)

    @classmethod
    def parse(cls, block: Dict[str, Any]) -> "TechniqueConfig":
        shared = dict(block.get("shared_parameters", {}))
        groups = []
        for name, g in (block.get("different_groups") or {}).items():
            groups.append(TechniqueGroup(
                name=name, params=dict(g.get("params", {})),
                modules=list(g.get("modules", ["*"])),
                related_modules=g.get("related_modules")))
        return cls(enabled=bool(shared.get("enabled", False)),
                   schedule_offset=int(shared.get("schedule_offset", 0)),
                   shared=shared, groups=groups)


@dataclasses.dataclass
class CompressionConfig:
    weight_quantization: TechniqueConfig = None
    activation_quantization: TechniqueConfig = None
    sparse_pruning: TechniqueConfig = None
    row_pruning: TechniqueConfig = None
    head_pruning: TechniqueConfig = None
    layer_reduction: Dict[str, Any] = None

    @classmethod
    def parse(cls, ds_config: Dict[str, Any]) -> "CompressionConfig":
        block = (ds_config or {}).get("compression_training", {}) or {}
        return cls(
            weight_quantization=TechniqueConfig.parse(
                block.get("weight_quantization", {})),
            activation_quantization=TechniqueConfig.parse(
                block.get("activation_quantization", {})),
            sparse_pruning=TechniqueConfig.parse(
                block.get("sparse_pruning", {})),
            row_pruning=TechniqueConfig.parse(
                block.get("row_pruning", {})),
            head_pruning=TechniqueConfig.parse(
                block.get("head_pruning", {})),
            layer_reduction=dict(block.get("layer_reduction", {}) or {}))

    def any_enabled(self) -> bool:
        return any(t is not None and t.enabled for t in (
            self.weight_quantization, self.activation_quantization,
            self.sparse_pruning, self.row_pruning, self.head_pruning))
