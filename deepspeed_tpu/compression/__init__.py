"""Compression library (reference deepspeed/compression): QAT, pruning,
layer reduction, scheduler."""

from .compress import (CompressedModel, CompressionScheduler,
                       init_compression, redundancy_clean)
from .config import CompressionConfig

__all__ = ["init_compression", "redundancy_clean", "CompressedModel",
           "CompressionScheduler", "CompressionConfig"]
