"""Memory-efficient custom-VJP building blocks for the training hot path.

Round-3 HLO profiling of the GPT-2 125M fused step showed the layer-scan
stash dominated by autodiff residuals that are pure recompute-bait:

- ``jax.nn.gelu`` (tanh approx) linearizes into SIX saved ``[B,T,4D]``
  bf16 intermediates per layer (~3.6 GB/micro at 125M bs8) — its
  derivative is a closed-form elementwise function of the input.
- LayerNorm saves its fp32 normalized tensor and friends (three f32
  ``[B,T,D]`` buffers per LN, ~2.4 GB/micro) — recomputable from the
  bf16 input plus the tiny per-row (mean, rstd).
- ``log_softmax`` over the vocab materializes an f32 ``[B,T,V]``
  (~1.65 GB at 125M) where streaming reductions over the bf16 logits
  suffice.

These custom-VJP versions save only the (already materialized) inputs and
O(rows) statistics, cutting ~10 GB of HBM round-trip per micro step. This
is the TPU-shaped counterpart of the reference's hand-written fused
backward kernels (reference csrc/transformer/gelu_kernels.cu,
normalize_kernels.cu d_gelu/d_ln, softmax_kernels.cu
cross-entropy path): same goal — never spill wide intermediates — but via
VJP rules + XLA fusion instead of CUDA.

Numerics: all stats and gradients accumulate in fp32; outputs/grads are
cast back to the input dtype. Parity with ``jax.grad`` of the naive
compositions is tested in tests/unit/test_memory_efficient.py.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

_SQRT_2_OVER_PI = 0.7978845608028654
_GELU_C = 0.044715


# ------------------------------------------------------------------ layer norm

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm(x, scale, bias, eps=1e-5):
    """LayerNorm with fp32 stats; saves (x, mean, rstd) instead of the
    fp32 normalized tensor."""
    y, _ = _ln_fwd_impl(x, scale, bias, eps)
    return y


def _ln_fwd_impl(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    xhat = (xf - mean) * rstd
    y = xhat * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(x.dtype), (mean, rstd)


def _ln_fwd(x, scale, bias, eps):
    y, (mean, rstd) = _ln_fwd_impl(x, scale, bias, eps)
    return y, (x, scale, bias, mean, rstd)


def _ln_bwd(eps, res, g):
    x, scale, bias, mean, rstd = res
    gf = g.astype(jnp.float32)
    xf = x.astype(jnp.float32)
    xhat = (xf - mean) * rstd                       # recomputed, not saved
    sf = scale.astype(jnp.float32)
    dxhat = gf * sf
    # dx = rstd * (dxhat - mean(dxhat) - xhat * mean(dxhat*xhat))
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = rstd * (dxhat - m1 - xhat * m2)
    reduce_axes = tuple(range(x.ndim - 1))
    dscale = jnp.sum(gf * xhat, axis=reduce_axes)
    dbias = jnp.sum(gf, axis=reduce_axes)
    return (dx.astype(x.dtype), dscale.astype(scale.dtype),
            dbias.astype(bias.dtype))


layer_norm.defvjp(_ln_fwd, _ln_bwd)


# ----------------------------------------------------------------- activations

def _make_unary(fwd_f32, grad_f32, name):
    """Elementwise activation whose VJP saves ONLY the input and evaluates
    a closed-form derivative in fp32."""

    @jax.custom_vjp
    def act(x):
        return fwd_f32(x.astype(jnp.float32)).astype(x.dtype)

    def fwd(x):
        return act(x), (x,)

    def bwd(res, g):
        (x,) = res
        xf = x.astype(jnp.float32)
        return ((g.astype(jnp.float32) * grad_f32(xf)).astype(x.dtype),)

    act.defvjp(fwd, bwd)
    act.__name__ = name
    return act


def _gelu_tanh_f32(x):
    u = _SQRT_2_OVER_PI * (x + _GELU_C * x * x * x)
    return 0.5 * x * (1.0 + jnp.tanh(u))


def _gelu_tanh_grad_f32(x):
    u = _SQRT_2_OVER_PI * (x + _GELU_C * x * x * x)
    t = jnp.tanh(u)
    du = _SQRT_2_OVER_PI * (1.0 + 3.0 * _GELU_C * x * x)
    return 0.5 * (1.0 + t) + 0.5 * x * (1.0 - t * t) * du


def _gelu_exact_f32(x):
    return 0.5 * x * (1.0 + lax.erf(x * (2.0 ** -0.5)))


def _gelu_exact_grad_f32(x):
    cdf = 0.5 * (1.0 + lax.erf(x * (2.0 ** -0.5)))
    pdf = jnp.exp(-0.5 * x * x) * (1.0 / jnp.sqrt(2.0 * jnp.pi))
    return cdf + x * pdf


def _silu_f32(x):
    return x * jax.nn.sigmoid(x)


def _silu_grad_f32(x):
    s = jax.nn.sigmoid(x)
    return s * (1.0 + x * (1.0 - s))


def _quick_gelu_f32(x):
    return x * jax.nn.sigmoid(1.702 * x)


def _quick_gelu_grad_f32(x):
    s = jax.nn.sigmoid(1.702 * x)
    return s * (1.0 + 1.702 * x * (1.0 - s))


gelu = _make_unary(_gelu_tanh_f32, _gelu_tanh_grad_f32, "gelu")
gelu_exact = _make_unary(_gelu_exact_f32, _gelu_exact_grad_f32, "gelu_exact")
silu = _make_unary(_silu_f32, _silu_grad_f32, "silu")
quick_gelu = _make_unary(_quick_gelu_f32, _quick_gelu_grad_f32, "quick_gelu")


# -------------------------------------------------------------- cross entropy

@jax.custom_vjp
def dense_xent_sum(logits, labels, valid):
    """Sum over valid tokens of next-token NLL, WITHOUT materializing the
    f32 log-softmax tensor. logits: [..., V] (any float dtype; leading
    dims arbitrary — do NOT pre-flatten: merging a padded sublane dim
    forces a full copy of the logits); labels: [...] int32 (already
    clamped to range); valid: [...] bool.

    Saves (logits, lse, labels, valid): backward streams one pass over the
    bf16 logits computing (softmax - onehot) * g. Divide by the valid
    count OUTSIDE (it is autodiff-transparent there)."""
    nll, _ = _xent_impl(logits, labels, valid)
    return nll


def _xent_impl(logits, labels, valid):
    lf = logits.astype(jnp.float32)
    m = jnp.max(lf, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1))
    tgt = jnp.take_along_axis(lf, labels[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, lse - tgt, 0.0)
    return jnp.sum(nll), lse


def _xent_fwd(logits, labels, valid):
    total, lse = _xent_impl(logits, labels, valid)
    return total, (logits, lse, labels, valid)


def _xent_bwd(res, g):
    logits, lse, labels, valid = res
    lf = logits.astype(jnp.float32)
    p = jnp.exp(lf - lse[..., None])
    cols = lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    onehot = cols == labels[..., None]
    scale = jnp.where(valid, g, 0.0).astype(jnp.float32)[..., None]
    dlogits = (p - onehot.astype(jnp.float32)) * scale
    return dlogits.astype(logits.dtype), None, None


dense_xent_sum.defvjp(_xent_fwd, _xent_bwd)
