"""Grouped quantization kernels.

Capability match for the reference quantization ops
(csrc/quantization/pt_binding.cpp:141-160 ``ds_quantize_*``/``quantize``/
``dequantize``; quantize.cu, fake_quantizer.cu): per-group symmetric or
asymmetric integer quantization with optional stochastic rounding, plus the
"fake quant" (quantize→dequantize in one op) used by QAT/MoQ. All shapes are
static and the math is elementwise + per-group reductions, so XLA fuses it
into a handful of kernels — a handwritten Pallas kernel would buy nothing
here (the op is bandwidth-bound and already minimal).

Layout: x is reshaped to [groups, -1]; scales (and zero points for
asymmetric) are per-group fp32. int8/int4 target widths supported; int4
values live in an int8 carrier in [-8, 7] (packing is a storage concern the
caller owns, as in the reference's quantization_utils.h).

The scale/round/clip math itself lives in ops/quant_core.py — the shared
core the compressed collectives and comm wire codecs also use.
"""

from functools import partial
from types import SimpleNamespace

import jax
import jax.numpy as jnp

from .quant_core import (absmean_scale, asymmetric_scale_zero, qrange,
                         round_clip, symmetric_scale)


@partial(jax.jit, static_argnums=(1, 2, 3, 4))
def quantize(x, groups: int = 1, bits: int = 8, symmetric: bool = True,
             stochastic: bool = False, rng=None):
    """x: any shape, size divisible by groups.
    Returns (q int8, scale f32[groups]) for symmetric or
            (q int8/uint8, scale, zero_point) for asymmetric."""
    orig_shape = x.shape
    xg = x.reshape(groups, -1).astype(jnp.float32)
    qmin, qmax = qrange(bits, symmetric)
    if symmetric:
        absmax = jnp.max(jnp.abs(xg), axis=1, keepdims=True)
        scale = symmetric_scale(absmax, qmax)
        scaled = xg / scale
    else:
        lo = jnp.min(xg, axis=1, keepdims=True)
        hi = jnp.max(xg, axis=1, keepdims=True)
        scale, zero = asymmetric_scale_zero(lo, hi, qmin, qmax)
        scaled = xg / scale + zero
    carrier = jnp.int8 if symmetric else jnp.uint8  # asym range is [0, 2^b-1]
    q = round_clip(scaled, qmin, qmax, carrier, stochastic, rng)
    q = q.reshape(orig_shape)
    if symmetric:
        return q, scale.reshape(groups)
    return q, scale.reshape(groups), zero.reshape(groups)


@partial(jax.jit, static_argnums=(3,))
def dequantize(q, scale, zero_point=None, groups: int = 1):
    orig_shape = q.shape
    qg = q.reshape(groups, -1).astype(jnp.float32)
    scale = scale.reshape(groups, 1)
    if zero_point is not None:
        qg = qg - zero_point.reshape(groups, 1)
    return (qg * scale).reshape(orig_shape)


def fake_quantize(x, groups: int = 1, bits: int = 8, symmetric: bool = True,
                  stochastic: bool = False, rng=None):
    """quantize→dequantize (the reference ds_quantize_fp32/fp16 semantics:
    returns the quantization-error-injected tensor in the input dtype) —
    the QAT/MoQ primitive.

    Straight-through estimator: the VALUE is the quantized tensor but the
    GRADIENT flows as identity (x + stop_grad(q(x) - x)) — without this,
    round() kills the gradient and quantization-aware TRAINING never
    trains (reference fake_quantizer.cu relies on torch's autograd-opaque
    kernel for the same effect)."""
    out = quantize(x, groups, bits, symmetric, stochastic, rng)
    if symmetric:
        q, scale = out
        deq = dequantize(q, scale, groups=groups).astype(x.dtype)
    else:
        q, scale, zero = out
        deq = dequantize(q, scale, zero, groups=groups).astype(x.dtype)
    return x + jax.lax.stop_gradient(deq - x)


def binary_quantize(x, groups: int = 1):
    """1-bit weight quantization with straight-through gradients
    (reference compression/utils.py:189 BinaryQuantizer): per-group
    alpha = mean(|x|), value = alpha * sign(x)."""
    xg = x.reshape(groups, -1).astype(jnp.float32)
    alpha = absmean_scale(xg, axis=1, keepdims=True)
    deq = (alpha * jnp.sign(xg)).reshape(x.shape).astype(x.dtype)
    return x + jax.lax.stop_gradient(deq - x)


def ternary_quantize(x, groups: int = 1):
    """2-bit {-a, 0, +a} quantization with straight-through gradients
    (reference compression/utils.py:148 TernaryQuantizer): per-group
    threshold 0.7 * mean(|x|); alpha = mean(|x|) over surviving weights."""
    xg = x.reshape(groups, -1).astype(jnp.float32)
    thres = 0.7 * absmean_scale(xg, axis=1, keepdims=True)
    mask = (jnp.abs(xg) > thres).astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
    alpha = jnp.sum(jnp.abs(xg) * mask, axis=1, keepdims=True) / denom
    deq = (alpha * jnp.sign(xg) * mask).reshape(x.shape).astype(x.dtype)
    return x + jax.lax.stop_gradient(deq - x)


def quantization_error(x, groups=1, bits=8, symmetric=True):
    """Mean-squared quantization error (MoQ precision-switch diagnostics)."""
    return jnp.mean(jnp.square(
        x.astype(jnp.float32) -
        fake_quantize(x, groups, bits, symmetric).astype(jnp.float32)))


def get_ops(backend: str = "tpu"):
    return SimpleNamespace(quantize=quantize, dequantize=dequantize,
                           fake_quantize=fake_quantize,
                           binary_quantize=binary_quantize,
                           ternary_quantize=ternary_quantize,
                           quantization_error=quantization_error)
