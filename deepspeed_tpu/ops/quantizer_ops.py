"""Grouped quantization kernels.

Capability match for the reference quantization ops
(csrc/quantization/pt_binding.cpp:141-160 ``ds_quantize_*``/``quantize``/
``dequantize``; quantize.cu, fake_quantizer.cu): per-group symmetric or
asymmetric integer quantization with optional stochastic rounding, plus the
"fake quant" (quantize→dequantize in one op) used by QAT/MoQ. All shapes are
static and the math is elementwise + per-group reductions, so XLA fuses it
into a handful of kernels — a handwritten Pallas kernel would buy nothing
here (the op is bandwidth-bound and already minimal).

Layout: x is reshaped to [groups, -1]; scales (and zero points for
asymmetric) are per-group fp32. int8/int4 target widths supported; int4
values live in an int8 carrier in [-8, 7] (packing is a storage concern the
caller owns, as in the reference's quantization_utils.h).
"""

from functools import partial
from types import SimpleNamespace

import jax
import jax.numpy as jnp


def _qrange(bits, symmetric):
    if symmetric:
        qmax = float(2 ** (bits - 1) - 1)
        return -qmax, qmax          # symmetric keeps zero exact
    return 0.0, float(2 ** bits - 1)


@partial(jax.jit, static_argnums=(1, 2, 3, 4))
def quantize(x, groups: int = 1, bits: int = 8, symmetric: bool = True,
             stochastic: bool = False, rng=None):
    """x: any shape, size divisible by groups.
    Returns (q int8, scale f32[groups]) for symmetric or
            (q int8/uint8, scale, zero_point) for asymmetric."""
    orig_shape = x.shape
    xg = x.reshape(groups, -1).astype(jnp.float32)
    qmin, qmax = _qrange(bits, symmetric)
    if symmetric:
        absmax = jnp.max(jnp.abs(xg), axis=1, keepdims=True)
        scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
        scaled = xg / scale
    else:
        lo = jnp.min(xg, axis=1, keepdims=True)
        hi = jnp.max(xg, axis=1, keepdims=True)
        scale = jnp.where(hi > lo, (hi - lo) / (qmax - qmin), 1.0)
        zero = qmin - lo / scale
        scaled = xg / scale + zero
    if stochastic:
        if rng is None:
            raise ValueError(
                "stochastic=True requires an rng key — a fixed key would "
                "add the SAME noise every call, biasing the rounding")
        noise = jax.random.uniform(rng, scaled.shape) - 0.5
        q = jnp.floor(scaled + 0.5 + noise)
    else:
        q = jnp.rint(scaled)
    carrier = jnp.int8 if symmetric else jnp.uint8  # asym range is [0, 2^b-1]
    q = jnp.clip(q, qmin, qmax).astype(carrier)
    q = q.reshape(orig_shape)
    if symmetric:
        return q, scale.reshape(groups)
    return q, scale.reshape(groups), zero.reshape(groups)


@partial(jax.jit, static_argnums=(3,))
def dequantize(q, scale, zero_point=None, groups: int = 1):
    orig_shape = q.shape
    qg = q.reshape(groups, -1).astype(jnp.float32)
    scale = scale.reshape(groups, 1)
    if zero_point is not None:
        qg = qg - zero_point.reshape(groups, 1)
    return (qg * scale).reshape(orig_shape)


def fake_quantize(x, groups: int = 1, bits: int = 8, symmetric: bool = True,
                  stochastic: bool = False, rng=None):
    """quantize→dequantize (the reference ds_quantize_fp32/fp16 semantics:
    returns the quantization-error-injected tensor in the input dtype) —
    the QAT/MoQ primitive.

    Straight-through estimator: the VALUE is the quantized tensor but the
    GRADIENT flows as identity (x + stop_grad(q(x) - x)) — without this,
    round() kills the gradient and quantization-aware TRAINING never
    trains (reference fake_quantizer.cu relies on torch's autograd-opaque
    kernel for the same effect)."""
    out = quantize(x, groups, bits, symmetric, stochastic, rng)
    if symmetric:
        q, scale = out
        deq = dequantize(q, scale, groups=groups).astype(x.dtype)
    else:
        q, scale, zero = out
        deq = dequantize(q, scale, zero, groups=groups).astype(x.dtype)
    return x + jax.lax.stop_gradient(deq - x)


def binary_quantize(x, groups: int = 1):
    """1-bit weight quantization with straight-through gradients
    (reference compression/utils.py:189 BinaryQuantizer): per-group
    alpha = mean(|x|), value = alpha * sign(x)."""
    xg = x.reshape(groups, -1).astype(jnp.float32)
    alpha = jnp.mean(jnp.abs(xg), axis=1, keepdims=True)
    deq = (alpha * jnp.sign(xg)).reshape(x.shape).astype(x.dtype)
    return x + jax.lax.stop_gradient(deq - x)


def ternary_quantize(x, groups: int = 1):
    """2-bit {-a, 0, +a} quantization with straight-through gradients
    (reference compression/utils.py:148 TernaryQuantizer): per-group
    threshold 0.7 * mean(|x|); alpha = mean(|x|) over surviving weights."""
    xg = x.reshape(groups, -1).astype(jnp.float32)
    thres = 0.7 * jnp.mean(jnp.abs(xg), axis=1, keepdims=True)
    mask = (jnp.abs(xg) > thres).astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
    alpha = jnp.sum(jnp.abs(xg) * mask, axis=1, keepdims=True) / denom
    deq = (alpha * jnp.sign(xg) * mask).reshape(x.shape).astype(x.dtype)
    return x + jax.lax.stop_gradient(deq - x)


def quantization_error(x, groups=1, bits=8, symmetric=True):
    """Mean-squared quantization error (MoQ precision-switch diagnostics)."""
    return jnp.mean(jnp.square(
        x.astype(jnp.float32) -
        fake_quantize(x, groups, bits, symmetric).astype(jnp.float32)))


def get_ops(backend: str = "tpu"):
    return SimpleNamespace(quantize=quantize, dequantize=dequantize,
                           fake_quantize=fake_quantize,
                           binary_quantize=binary_quantize,
                           ternary_quantize=ternary_quantize,
                           quantization_error=quantization_error)
