"""Sequence-parallel attention: ring attention + Ulysses all-to-all.

ABSENT in the reference (SURVEY.md §5: v0.9.1 has no SP/ring/Ulysses —
its long-sequence story is block-sparse attention + activation partitioning);
this module is the TPU-native long-context answer the north-star metric
requires. Two strategies over the ``seq`` mesh axis:

- **Ulysses** (DeepSpeed-Ulysses style head↔sequence all-to-all): attention
  needs full sequence per head, so reshard [B, H, T/sp, D] → [B, H/sp, T, D],
  run ordinary flash attention on full-length sequences of a head subset,
  reshard back. Implemented as sharding CONSTRAINTS — GSPMD lowers the
  reshard to the all-to-all the reference would issue over NCCL. Composes
  with pp/tp/ZeRO because nothing is manual.
- **Ring attention**: K/V blocks rotate around the ``seq`` ring
  (lax.ppermute) while each device keeps its Q shard; online-softmax
  accumulators (m, l, o) merge per block — attention memory stays
  O(T/sp) per device, enabling sequences that don't fit any single chip.
  shard_map manual over 'seq' only; differentiable through the scan.

Both match the dense reference_attention numerics (tests/unit/test_seq_parallel.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.constraints import active_mesh, maybe_constraint
from ..parallel.topology import DP_AXES as _BATCH_AXES, SEQ_AXIS
from .flash_attention import flash_attention


def seq_axis_size() -> int:
    mesh = active_mesh()
    if mesh is None:
        return 1
    return int(mesh.shape.get(SEQ_AXIS, 1))


def ulysses_attention(q, k, v, causal=True, softmax_scale=None,
                      dropout_rate=0.0, dropout_rng=None, backend="auto",
                      bias=None, window=None):
    """q,k,v: [B, H, T, D] with T sharded over 'seq'. Reshard heads↔sequence
    around a full-sequence attention (DeepSpeed-Ulysses; the reference has
    no equivalent — see module docstring). After the all-to-all each device
    holds FULL sequences of a head subset, so per-head additive bias
    (ALiBi) and sliding windows work unchanged — the bias head dim simply
    shards with the heads."""
    # all-to-all #1: gather sequence, scatter heads
    spec_heads = (_BATCH_AXES, SEQ_AXIS, None, None)
    q = maybe_constraint(q, *spec_heads)
    k = maybe_constraint(k, *spec_heads)
    v = maybe_constraint(v, *spec_heads)
    if bias is not None and bias.ndim == 3:    # [H, T, T] → shard heads
        bias = maybe_constraint(bias, SEQ_AXIS, None, None)
    elif bias is not None and bias.ndim == 4 and bias.shape[0] == 1:
        bias = maybe_constraint(bias, None, SEQ_AXIS, None, None)
    out = flash_attention(q, k, v, causal=causal, softmax_scale=softmax_scale,
                          dropout_rate=dropout_rate, dropout_rng=dropout_rng,
                          backend=backend, bias=bias, window=window)
    # all-to-all #2: back to sequence-sharded, full heads
    return maybe_constraint(out, _BATCH_AXES, None, SEQ_AXIS, None)


def _ring_attention_local(q, k, v, causal, scale, axis_name, sp,
                          bias=None, window=None):
    """Per-device body: q,k,v [B, H, Tl, D] local shards (bias [H, Tl, T]:
    q rows local, key columns GLOBAL); returns [B,H,Tl,D]. K/V rotate sp
    times around the ring; online softmax merges blocks."""
    b, h, tl, d = q.shape
    sid = lax.axis_index(axis_name)
    q32 = q.astype(jnp.float32) * scale
    neg = jnp.float32(-1e30)

    def step(carry, i):
        k_blk, v_blk, m, l, o = carry
        # k_blk arrived from device (sid - i) % sp → its global block index
        src = (sid - i) % sp
        logits = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            k_blk.astype(jnp.float32))
        if bias is not None:
            rows = bias.shape[1]               # tl (full bias) or 1 (ALiBi)
            blk_bias = lax.dynamic_slice(
                bias, (0, 0, src * tl), (h, rows, tl)).astype(jnp.float32)
            logits = logits + blk_bias[None]
        q_pos = sid * tl + jnp.arange(tl)[:, None]
        k_pos = src * tl + jnp.arange(tl)[None, :]
        if causal:
            keep = q_pos >= k_pos
            if window is not None:
                keep &= (q_pos - k_pos) < window
            logits = jnp.where(keep[None, None], logits, neg)
        blk_max = jnp.max(logits, axis=-1)                       # [B,H,Tl]
        new_m = jnp.maximum(m, blk_max)
        # renormalize old accumulators, accumulate this block
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(logits - new_m[..., None])                   # [B,H,Tl,Tk]
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        perm = [(j, (j + 1) % sp) for j in range(sp)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, new_m, l_new, o_new), None

    m0 = jnp.full((b, h, tl), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, tl), jnp.float32)
    o0 = jnp.zeros((b, h, tl, d), jnp.float32)
    (k_last, v_last, m, l, o), _ = lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(sp))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, causal=True, softmax_scale=None, bias=None,
                   window=None):
    """q,k,v: [B, H, T, D] with T sharded over 'seq'. O(T/sp) attention
    memory per device; K/V blocks ride the ICI ring (ppermute). ``bias``
    [H, T, T] (ALiBi) shards its q-row dim with the ring; every device
    keeps the full key-column extent and slices the arriving block's
    columns."""
    mesh = active_mesh()
    sp = seq_axis_size()
    if mesh is None or sp == 1:
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale, bias=bias,
                               window=window)
    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5
    # manual over 'seq' only: specs name just the manual axis, the batch
    # dims stay under auto/GSPMD (dp sharding untouched)
    spec = P(None, None, SEQ_AXIS, None)
    body = functools.partial(_ring_attention_local, causal=causal,
                             scale=scale, axis_name=SEQ_AXIS, sp=sp,
                             window=window)
    if bias is None:
        return jax.shard_map(
            lambda a, b_, c: body(a, b_, c),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={SEQ_AXIS}, check_vma=False)(q, k, v)
    if bias.ndim == 4:
        if bias.shape[0] != 1:
            raise NotImplementedError(
                "batch-dependent attention bias under ring attention")
        bias = bias[0]                         # → [H, Tq|1, Tk]
    if bias.shape[1] == 1:
        # ALiBi: key-position-only bias, replicated (cols sliced per block)
        bias_spec = P(None, None, None)
    else:
        bias_spec = P(None, SEQ_AXIS, None)    # q rows local, k cols global
    return jax.shard_map(
        lambda a, b_, c, bb: body(a, b_, c, bias=bb),
        mesh=mesh, in_specs=(spec, spec, spec, bias_spec), out_specs=spec,
        axis_names={SEQ_AXIS}, check_vma=False)(q, k, v, bias)


def sp_attention(q, k, v, causal=True, softmax_scale=None, dropout_rate=0.0,
                 dropout_rng=None, impl="ulysses", backend="auto", bias=None,
                 window=None):
    """Dispatch by impl when the 'seq' axis is live; plain flash otherwise.
    ``bias`` (additive logits bias, e.g. ALiBi — [H, T, T]) and ``window``
    (sliding-window causal) work on BOTH sequence-parallel paths: under
    Ulysses the bias head dim shards with the heads; under ring the bias
    q-row dim shards with the ring and arriving key blocks slice their
    columns. NOTE: Ulysses+bias runs the dense XLA attention on the FULL
    gathered sequence (the Pallas kernels take no bias) — O(T^2) logits
    per device; for ALiBi at long T prefer impl='ring', which stays
    O(T*T/sp)."""
    if impl not in ("ulysses", "ring"):
        raise ValueError(f"sp_attention impl must be 'ulysses' or 'ring', "
                         f"got {impl!r}")
    if window is not None and not causal:
        raise ValueError("sliding window requires causal=True")
    if seq_axis_size() == 1:
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale,
                               dropout_rate=dropout_rate,
                               dropout_rng=dropout_rng, backend=backend,
                               bias=bias, window=window)
    if impl == "ring":
        if dropout_rate > 0.0:
            raise NotImplementedError(
                "ring attention does not support attention dropout; use "
                "sp_attention='ulysses' or dropout=0")
        return ring_attention(q, k, v, causal=causal,
                              softmax_scale=softmax_scale, bias=bias,
                              window=window)
    return ulysses_attention(q, k, v, causal=causal,
                             softmax_scale=softmax_scale,
                             dropout_rate=dropout_rate,
                             dropout_rng=dropout_rng, backend=backend,
                             bias=bias, window=window)
