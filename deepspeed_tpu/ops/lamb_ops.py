"""Fused LAMB — layerwise-adaptive Adam with per-tensor trust ratio.

Capability match for the reference FusedLamb
(csrc/lamb/fused_lamb_cuda_kernel.cu:478, ops/lamb/fused_lamb.py): Adam
moments plus a per-tensor ||w||/||update|| trust ratio scaling the step.
One jitted pytree update; XLA fuses the elementwise chains and the two
norms per tensor reduce on-chip.
"""

from functools import partial
from types import SimpleNamespace

import jax
import jax.numpy as jnp


def _lamb_math(p, g, m, v, step, lr, beta1, beta2, eps, weight_decay,
               max_coeff, min_coeff):
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    bc1 = 1 - beta1 ** step
    bc2 = 1 - beta2 ** step
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if weight_decay:
        update = update + weight_decay * p32
    w_norm = jnp.linalg.norm(p32.reshape(-1))
    u_norm = jnp.linalg.norm(update.reshape(-1))
    trust = jnp.where((w_norm > 0) & (u_norm > 0),
                      jnp.clip(w_norm / u_norm, min_coeff, max_coeff), 1.0)
    return (p32 - lr * trust * update).astype(p.dtype), m, v


@partial(jax.jit, static_argnums=(9,))
def _fused_lamb(params, grads, m, v, step, lr, beta1, beta2, eps,
                weight_decay, max_coeff, min_coeff):
    p_flat, treedef = jax.tree.flatten(params)
    outs = [_lamb_math(p, g, mm, vv, step, lr, beta1, beta2, eps,
                       weight_decay, max_coeff, min_coeff)
            for p, g, mm, vv in zip(p_flat, jax.tree.leaves(grads),
                                    jax.tree.leaves(m), jax.tree.leaves(v))]
    new_p, new_m, new_v = zip(*outs)
    return (jax.tree.unflatten(treedef, new_p),
            jax.tree.unflatten(treedef, new_m),
            jax.tree.unflatten(treedef, new_v))


def fused_lamb(params, grads, m, v, step, lr, beta1=0.9, beta2=0.999,
               eps=1e-6, weight_decay=0.0, max_coeff=10.0, min_coeff=0.01):
    """One LAMB step over a pytree; returns (params, m, v)."""
    return _fused_lamb(params, grads, m, v, jnp.float32(step),
                       jnp.float32(lr), jnp.float32(beta1),
                       jnp.float32(beta2), jnp.float32(eps),
                       float(weight_decay), jnp.float32(max_coeff),
                       jnp.float32(min_coeff))


def init_state(params):
    zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    return zeros, jax.tree.map(jnp.copy, zeros)


def get_ops(backend: str = "tpu"):
    return SimpleNamespace(fused_lamb=fused_lamb, init_state=init_state)
