"""Transformer inference ops: KV-cache attention + fused decode helpers.

Capability match for the reference inference kernels
(csrc/transformer/inference/csrc/pt_binding.cpp:1747-1811 —
``softmax_context`` (attention + KV-cache append), ``residual_add_bias``,
``apply_rotary_pos_emb``; inference_context.h workspace). The KV cache here
is an explicit pytree the caller threads through jit (functional — no global
workspace), and cache append is a dynamic_update_slice the compiler keeps
in-place under donation. The inference engine (inference/engine.py) builds
its decode loop out of these pieces via the model's apply_with_cache.

Why the decode hot loop is tightly-fused XLA rather than a Pallas kernel
(the deliberate TPU answer to the reference's fused ``softmax_context``
CUDA kernel): at T=1 decode is HBM-bandwidth-bound — the step's cost is
one streaming read of the KV cache plus the weight matmuls, and XLA
already lowers score→mask→softmax→combine into fused loops over that
single pass without materializing intermediates in HBM (the [B,H,1,L]
score tile is KB-scale). A hand kernel would re-buy the same bandwidth
with added grid overhead at M=1; the places a custom decode kernel DOES
pay on TPU — paged/blocked caches, speculative multi-token verify — are
future shapes, not this one. Decode throughput is measured by
benchmarks/decode.py.
"""

from types import SimpleNamespace

import jax
import jax.numpy as jnp
from jax import lax


def update_kv_cache(k_cache, v_cache, k_new, v_new, start_pos):
    """Append [B, H, T_new, D] at start_pos (softmax_context's cache
    append). Caches: [B, H, T_max, D]."""
    k_cache = lax.dynamic_update_slice(
        k_cache, k_new.astype(k_cache.dtype), (0, 0, start_pos, 0))
    v_cache = lax.dynamic_update_slice(
        v_cache, v_new.astype(v_cache.dtype), (0, 0, start_pos, 0))
    return k_cache, v_cache


def cached_attention(q, k_cache, v_cache, cur_len, softmax_scale=None):
    """Attention of q [B, H, T_q, D] against the first cur_len cache
    entries, causal within the query block (the softmax_context compute).
    cur_len = start_pos + T_q (a traced scalar is fine)."""
    *_, t_q, d = q.shape
    t_max = k_cache.shape[-2]
    scale = softmax_scale if softmax_scale is not None else 1.0 / jnp.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(k_cache.dtype),
                        k_cache) * scale
    logits = logits.astype(jnp.float32)
    q_pos = cur_len - t_q + jnp.arange(t_q)[:, None]
    k_pos = jnp.arange(t_max)[None, :]
    visible = k_pos <= q_pos
    logits = jnp.where(visible[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v_cache.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v_cache).astype(q.dtype)


def residual_add_bias(hidden, residual, bias=None):
    """Fused residual+bias (pt_binding residual_add_bias)."""
    out = hidden + residual
    if bias is not None:
        out = out + bias
    return out


def apply_rotary_pos_emb(q, k, positions, base: float = 10000.0):
    """RoPE over the last dim (apply_rotary_pos_emb.cu). q/k: [B,H,T,D],
    positions: [T] absolute positions."""
    d = q.shape[-1]
    half = d // 2
    freq = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, None].astype(jnp.float32) * freq[None, :]  # [T,half]
    cos = jnp.cos(angles)[None, None]
    sin = jnp.sin(angles)[None, None]

    def rot(x):
        x1, x2 = x[..., :half], x[..., half:]
        xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
        return jnp.concatenate(
            [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin],
            axis=-1).astype(x.dtype)

    return rot(q), rot(k)


def vector_matmul(x, w, transpose_w: bool = False):
    """The reference's vector_matmul decode GEMV — on TPU just a matmul the
    MXU handles; kept as an API point for op parity."""
    return x @ (w.T if transpose_w else w)


def get_ops(backend: str = "tpu"):
    return SimpleNamespace(update_kv_cache=update_kv_cache,
                           cached_attention=cached_attention,
                           residual_add_bias=residual_add_bias,
                           apply_rotary_pos_emb=apply_rotary_pos_emb,
                           vector_matmul=vector_matmul)
