"""Fused transformer training ops.

Capability match for the reference fused training layer
(csrc/transformer/ds_transformer_cuda.cpp:1037-1052 forward/backward;
normalize_kernels.cu, gelu_kernels.cu, softmax_kernels.cu,
dropout_kernels.cu): the building blocks of a fused encoder block —
layer-norm, bias-GELU, masked softmax, dropout-add — plus a whole fused
block (attention + MLP with pre/post-LN). On TPU these are jnp compositions
that XLA fuses into the surrounding matmuls; the attention core dispatches
to the Pallas flash kernel (ops/pallas/flash_attention.py) through the same
seam the model uses. Backward comes from jax.grad — no hand-written bwd
kernels to maintain (the reference's backward_fp16 et al).
"""

from types import SimpleNamespace

import jax
import jax.numpy as jnp


def layer_norm(x, scale, bias, eps: float = 1e-5):
    """Fused LN (normalize_kernels.cu): stats in fp32, output in x.dtype.
    Custom-VJP: backward recomputes the normalized tensor from (x, mean,
    rstd) instead of stashing it (ops/memory_efficient.py)."""
    from ..memory_efficient import layer_norm as _ln
    return _ln(x, scale, bias, eps)


def bias_gelu(x, bias=None, approximate: bool = True):
    """Fused bias + GELU (gelu_kernels.cu; tanh approximation like the
    reference's gelu(sqrt(2/pi)(x+0.044715x^3)) form). Custom-VJP saves
    only the activation input."""
    from ..memory_efficient import gelu, gelu_exact
    if bias is not None:
        x = x + bias
    return gelu(x) if approximate else gelu_exact(x)


def bias_relu(x, bias=None):
    if bias is not None:
        x = x + bias
    return jax.nn.relu(x)


def bias_dropout_add(x, bias, residual, rate: float, rng, train: bool):
    """Fused bias + dropout + residual add (dropout_kernels.cu
    bias_add_dropout_residual)."""
    if bias is not None:
        x = x + bias
    if train and rate > 0.0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
        x = x * keep / (1.0 - rate)
    return x + residual


def masked_softmax(logits, mask=None, causal: bool = False):
    """Attention softmax in fp32 with additive masking
    (softmax_kernels.cu attn_softmax)."""
    lf = logits.astype(jnp.float32)
    t_q, t_k = lf.shape[-2], lf.shape[-1]
    if causal:
        cm = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        lf = jnp.where(cm, lf, -1e30)
    if mask is not None:
        lf = jnp.where(mask, lf, -1e30)
    return jax.nn.softmax(lf, axis=-1).astype(logits.dtype)


def transformer_layer(x, p, n_head: int, rng=None, train: bool = True,
                      dropout: float = 0.0, pre_layer_norm: bool = True,
                      causal: bool = True, attn_backend: str = "auto"):
    """A whole fused transformer block (the DeepSpeedTransformerLayer
    contract, ops/transformer/transformer.py): params dict p holds
    ln1/ln2 {scale,bias}, attn {wqkv, bqkv, wo, bo}, mlp {wi, bi, wo, bo}.
    x: [B, T, D]."""
    from ..flash_attention import flash_attention

    d = x.shape[-1]
    hd = d // n_head

    def rngs(i):
        return None if rng is None else jax.random.fold_in(rng, i)

    h = layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"]) \
        if pre_layer_norm else x
    qkv = h @ p["attn"]["wqkv"] + p["attn"]["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        b, tl, _ = t.shape
        return t.reshape(b, tl, n_head, hd).transpose(0, 2, 1, 3)

    ctx = flash_attention(heads(q), heads(k), heads(v), causal=causal,
                          backend=attn_backend)
    b, _, tl, _ = ctx.shape
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, tl, d)
    attn_out = ctx @ p["attn"]["wo"]
    x = bias_dropout_add(attn_out, p["attn"]["bo"], x, dropout, rngs(0),
                         train)
    if not pre_layer_norm:
        x = layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"])

    h = layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"]) \
        if pre_layer_norm else x
    h = bias_gelu(h @ p["mlp"]["wi"], p["mlp"]["bi"])
    mlp_out = h @ p["mlp"]["wo"]
    x = bias_dropout_add(mlp_out, p["mlp"]["bo"], x, dropout, rngs(1), train)
    if not pre_layer_norm:
        x = layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"])
    return x


def init_layer_params(rng, d: int, d_ff: int = None, dtype=jnp.float32):
    """Initializer for transformer_layer's param dict."""
    d_ff = d_ff or 4 * d
    ks = jax.random.split(rng, 4)
    init = jax.nn.initializers.normal(0.02)
    return {
        "ln1": {"scale": jnp.ones((d,), dtype), "bias": jnp.zeros((d,), dtype)},
        "ln2": {"scale": jnp.ones((d,), dtype), "bias": jnp.zeros((d,), dtype)},
        "attn": {"wqkv": init(ks[0], (d, 3 * d), dtype),
                 "bqkv": jnp.zeros((3 * d,), dtype),
                 "wo": init(ks[1], (d, d), dtype),
                 "bo": jnp.zeros((d,), dtype)},
        "mlp": {"wi": init(ks[2], (d, d_ff), dtype),
                "bi": jnp.zeros((d_ff,), dtype),
                "wo": init(ks[3], (d_ff, d), dtype),
                "bo": jnp.zeros((d,), dtype)},
    }


def get_ops(backend: str = "tpu"):
    return SimpleNamespace(layer_norm=layer_norm, bias_gelu=bias_gelu,
                           bias_relu=bias_relu,
                           bias_dropout_add=bias_dropout_add,
                           masked_softmax=masked_softmax,
                           transformer_layer=transformer_layer,
                           init_layer_params=init_layer_params)
