"""Random-LTD token ops: sample / gather / scatter.

Capability match for the reference random-ltd kernels
(csrc/random_ltd/pt_binding.cpp:211-215 ``token_sort_``/``token_gather``/
``token_scatter_``; ops/random_ltd/dropping_utils.py): random layer-token-drop
subsamples a per-layer token subset, runs the layer on the kept tokens, and
scatters outputs back into the full sequence. The CUDA sort/gather/scatter
kernels map to argsort/take_along_axis/scatter — native XLA ops the compiler
tiles well; indices are SORTED so kept tokens preserve causal order (the
reference's token_sort_ post-pass).
"""

from functools import partial
from types import SimpleNamespace

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(1, 2, 3))
def sample_token_indices(rng, keep: int, batch: int, seqlen: int):
    """[B, keep] sorted indices of kept tokens per sequence
    (gpt_sample_tokens semantics: random subset, order preserving)."""
    def per_seq(r):
        perm = jax.random.permutation(r, seqlen)
        return jnp.sort(perm[:keep])
    return jax.vmap(per_seq)(jax.random.split(rng, batch))


@jax.jit
def token_gather(x, indices):
    """x: [B, T, ...]; indices: [B, K] → [B, K, ...]."""
    idx = indices.reshape(indices.shape + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, idx, axis=1)


@jax.jit
def token_scatter(base, values, indices):
    """Inverse of token_gather: place values[B,K,...] at indices into
    base[B,T,...] (kept tokens updated, dropped tokens keep base)."""
    idx = indices.reshape(indices.shape + (1,) * (base.ndim - 2))
    idx = jnp.broadcast_to(idx, values.shape)
    return jnp.put_along_axis(base, idx, values, axis=1, inplace=False)


def get_ops(backend: str = "tpu"):
    return SimpleNamespace(sample_token_indices=sample_token_indices,
                           token_gather=token_gather,
                           token_scatter=token_scatter)
