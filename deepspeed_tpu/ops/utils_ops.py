"""Flatten/unflatten utilities.

Capability match for the reference utils op (csrc/utils/
flatten_unflatten.cpp ``flatten``/``unflatten``, loaded at engine.py:377):
pack a pytree of arrays into one flat fp32 host buffer and back. On TPU the
in-jit equivalent is free (pytrees + donation), so this surface exists for
HOST-side consumers: checkpoint packing, NVMe swap staging, comm payloads.
"""

from types import SimpleNamespace
from typing import Any, List, Tuple

import jax
import numpy as np


def flatten(tree, dtype=np.float32) -> Tuple[np.ndarray, Any]:
    """Pytree of FLOATING arrays → (flat 1-D buffer in `dtype`, spec).
    Raises on non-float leaves — casting ints through float32 would silently
    corrupt values outside its exact range; use flatten_bytes for mixed
    trees."""
    leaves, treedef = jax.tree.flatten(tree)
    arrs = [np.asarray(x) for x in leaves]
    for a in arrs:
        if not np.issubdtype(a.dtype, np.floating):
            raise TypeError(
                f"flatten: non-float leaf dtype {a.dtype}; use "
                f"flatten_bytes for exact mixed-dtype packing")
    spec = (treedef, [(a.shape, a.dtype.str) for a in arrs])
    if not arrs:
        return np.zeros(0, dtype), spec
    flat = np.concatenate([a.reshape(-1).astype(dtype, copy=False)
                           for a in arrs])
    return np.ascontiguousarray(flat, dtype), spec


def unflatten(flat: np.ndarray, spec) -> Any:
    treedef, metas = spec
    out: List[np.ndarray] = []
    off = 0
    for shape, dtype_str in metas:
        n = int(np.prod(shape or (1,)))
        out.append(flat[off:off + n].astype(np.dtype(dtype_str),
                                            copy=False).reshape(shape))
        off += n
    if off != flat.size:
        raise ValueError(f"flat buffer size {flat.size} != spec total {off}")
    return jax.tree.unflatten(treedef, out)


def flatten_bytes(tree) -> Tuple[np.ndarray, Any]:
    """Exact packing of ANY pytree: each leaf at its native dtype as raw
    bytes (uint8 buffer). Use for checkpoint/comm payloads with int leaves."""
    leaves, treedef = jax.tree.flatten(tree)
    arrs = [np.ascontiguousarray(np.asarray(x)) for x in leaves]
    spec = (treedef, [(a.shape, a.dtype.str) for a in arrs])
    if not arrs:
        return np.zeros(0, np.uint8), spec
    return np.concatenate([a.reshape(-1).view(np.uint8) for a in arrs]), spec


def unflatten_bytes(flat: np.ndarray, spec) -> Any:
    treedef, metas = spec
    out: List[np.ndarray] = []
    off = 0
    for shape, dtype_str in metas:
        dt = np.dtype(dtype_str)
        nbytes = int(np.prod(shape or (1,))) * dt.itemsize
        out.append(flat[off:off + nbytes].view(dt).reshape(shape))
        off += nbytes
    if off != flat.size:
        raise ValueError(f"flat buffer size {flat.size} != spec total {off}")
    return jax.tree.unflatten(treedef, out)


def get_ops(backend: str = "cpu"):
    return SimpleNamespace(flatten=flatten, unflatten=unflatten,
                           flatten_bytes=flatten_bytes,
                           unflatten_bytes=unflatten_bytes)
