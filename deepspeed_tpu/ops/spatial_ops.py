"""Spatial (diffusers) ops: fused NHWC bias adds.

Capability match for the reference spatial kernels
(csrc/spatial/csrc/pt_binding.cpp:109-111 ``nhwc_bias_add``/
``nhwc_bias_add_add``/``nhwc_bias_add_bias_add``, opt_bias_add.cu): the
elementwise tails of diffusion UNet/VAE convolutions. On TPU these are jnp
expressions XLA fuses into the producing conv — the value of the module is
the op-parity surface (SpatialInferenceBuilder) and NHWC layout handling.
"""

from types import SimpleNamespace

import jax.numpy as jnp


def _bias(b, x):
    return b.reshape((1,) * (x.ndim - 1) + (-1,)).astype(x.dtype)


def nhwc_bias_add(activation, bias):
    """out = act + bias (bias broadcast over the channel-last axis)."""
    return activation + _bias(bias, activation)


def nhwc_bias_add_add(activation, bias, other):
    """out = (act + bias) + other (residual add)."""
    return activation + _bias(bias, activation) + other.astype(
        activation.dtype)


def nhwc_bias_add_bias_add(activation, bias, other, other_bias):
    """out = (act + bias) + (other + other_bias)."""
    return (activation + _bias(bias, activation) +
            other.astype(activation.dtype) + _bias(other_bias, activation))


def get_ops(backend: str = "tpu"):
    return SimpleNamespace(nhwc_bias_add=nhwc_bias_add,
                           nhwc_bias_add_add=nhwc_bias_add_add,
                           nhwc_bias_add_bias_add=nhwc_bias_add_bias_add)
