"""Block-sparse attention: sparsity layouts + the sparse attention op.

Capability match for the reference sparse-attention stack
(ops/sparse_attention/sparsity_config.py — Fixed / BigBird / BSLongformer /
Variable patterns; matmul.py SDD/DSD Triton kernels; sparse_self_attention.py).
The layouts are identical block-level boolean matrices; the compute is a
different design: instead of Triton block-CSR matmuls, the op evaluates
attention with the block mask expanded inside the kernel — XLA's masked
softmax + matmul fusion skips none of the FLOPs but all of the memory games,
which on TPU (MXU-bound, big tiles) is the right starting trade; a Pallas
block-skipping kernel can slot in behind the same layout contract later.

Layout convention (reference-compatible): [H, T/block, T/block] bool; entry
[h, i, j] = may query-block i attend to key-block j.
"""

import math
from types import SimpleNamespace

import numpy as np

import jax
import jax.numpy as jnp


class SparsityConfig:
    """Base: every block visible (dense)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(f"seq_len {seq_len} not divisible by block "
                             f"{self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=bool)

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = True
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Local blocks within a window + periodic global blocks
    (reference FixedSparsityConfig semantics: local + 'different heads may
    attend different global blocks')."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = (
            num_different_global_patterns if different_layout_per_head else 1)

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        L = self.num_local_blocks
        for i in range(n):
            w0 = (i // L) * L
            for j in range(w0, min(w0 + L, n)):
                layout[:, i, j] = True
        # global: last num_global_blocks of each local window attend/are
        # attended everywhere; pattern may rotate across heads
        for h in range(self.num_heads):
            pat = h % self.num_different_global_patterns
            for w0 in range(0, n, L):
                g0 = w0 + L - self.num_global_blocks * (1 + pat)
                g0 = max(w0, g0)
                for j in range(g0, min(w0 + L, n)):
                    layout[h, :, j] = True          # vertical (everyone → g)
                    if self.horizontal_global_attention:
                        layout[h, j, :] = True      # horizontal (g → everyone)
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding-window + global blocks (BigBird)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional", seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        rng = np.random.default_rng(self.seed)
        for i in range(n):
            layout[:, i, max(0, i - w):min(n, i + w + 1)] = True
        g = self.num_global_blocks
        layout[:, :g, :] = True
        layout[:, :, :g] = True
        causal = self.attention == "unidirectional"
        for h in range(self.num_heads if self.different_layout_per_head
                       else 1):
            for i in range(n):
                hi = i + 1 if causal else n
                if hi <= 0:
                    continue
                picks = rng.integers(0, hi, size=self.num_random_blocks)
                layout[h if self.different_layout_per_head else slice(None),
                       i, picks] = True
        if causal:
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + selected global block indices (Longformer)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=(0,),
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices)
        self.global_block_end_indices = (
            list(global_block_end_indices) if global_block_end_indices
            else None)
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for i in range(n):
            layout[:, i, max(0, i - w):min(n, i + w + 1)] = True
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for s, e in spans:
            layout[:, s:e, :] = True
            layout[:, :, s:e] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


class VariableSparsityConfig(SparsityConfig):
    """Variable local window sizes + global blocks (reference
    VariableSparsityConfig: a list of local window block counts cycled over
    consecutive windows)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=(4,),
                 global_block_indices=(0,), global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False,
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks)
        self.global_block_indices = list(global_block_indices)
        self.global_block_end_indices = (
            list(global_block_end_indices) if global_block_end_indices
            else None)
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        # tile variable windows: last size repeats to cover the sequence
        start = 0
        k = 0
        while start < n:
            size = self.local_window_blocks[
                min(k, len(self.local_window_blocks) - 1)]
            end = min(start + size, n)
            layout[:, start:end, start:end] = True
            start = end
            k += 1
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for s, e in spans:
            layout[:, :, s:e] = True
            if self.horizontal_global_attention:
                layout[:, s:e, :] = True
        if self.num_random_blocks:
            rng = np.random.default_rng(self.seed)
            for i in range(n):
                picks = rng.integers(0, n, size=self.num_random_blocks)
                layout[:, i, picks] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


def layout_to_mask(layout, block):
    """[H, nb, nb] bool blocks → [H, T, T] bool token mask."""
    layout = np.asarray(layout)
    return np.repeat(np.repeat(layout, block, axis=1), block, axis=2)


def sparse_attention(q, k, v, layout, block, softmax_scale=None,
                     impl="auto"):
    """Block-sparse attention. q/k/v: [B, H, T, D]; layout [H, nb, nb].

    impl: 'auto' (Pallas block-skipping kernel on TPU when shapes fit,
    dense-masked XLA otherwise), 'pallas', or 'dense'. The Pallas path is
    the FLOP-skipping counterpart of the reference Triton SDD/DSD kernels
    (reference ops/sparse_attention/matmul.py:17)."""
    if impl in ("auto", "pallas"):
        from .pallas.block_sparse_attention import (
            sparse_attention_pallas, supported)
        ok = supported(q, layout, block)
        if impl == "pallas":
            if not ok:
                raise ValueError(
                    f"impl='pallas' requested but shapes are unsupported "
                    f"(T={q.shape[-2]}, D={q.shape[-1]}, "
                    f"fine_block={block}) — use impl='auto' for the "
                    f"dense-masked fallback")
            return sparse_attention_pallas(
                q, k, v, layout, block, softmax_scale=softmax_scale)
        on_tpu = jax.devices()[0].platform == "tpu"
        if ok and on_tpu:
            try:
                return sparse_attention_pallas(
                    q, k, v, layout, block, softmax_scale=softmax_scale)
            except Exception as exc:  # noqa: BLE001
                import warnings
                warnings.warn(
                    f"pallas block-sparse kernel failed "
                    f"({type(exc).__name__}: {exc}); falling back to the "
                    f"dense-masked path", RuntimeWarning)
    from .flash_attention import reference_attention
    mask = jnp.asarray(layout_to_mask(layout, block))[None]  # [1,H,T,T]
    return reference_attention(q, k, v, causal=False, mask=mask,
                               softmax_scale=softmax_scale)


class SparseSelfAttention:
    """Module-style wrapper (reference sparse_self_attention.py surface).

    ``key_padding_mask`` ([B,1,1,T] or [B,T] bool/int, 1 = keep) merges
    with the block layout on the dense-masked path — the Pallas
    block-skipping kernel takes no per-batch mask, so padded batches pay
    the dense fallback (the reference merges key_padding_mask into its
    attention scores the same way; kernel-level padding masks are a
    future optimization)."""

    def __init__(self, sparsity_config, softmax_scale=None):
        self.config = sparsity_config
        self.softmax_scale = softmax_scale
        self._layouts = {}

    def layout(self, seq_len):
        if seq_len % self.config.block:
            raise ValueError(
                f"seq {seq_len} not a multiple of block "
                f"{self.config.block}; use "
                f"SparseAttentionUtils.pad_to_block_size")
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, q, k, v, key_padding_mask=None):
        lay = self.layout(q.shape[-2])
        if key_padding_mask is None:
            return sparse_attention(q, k, v, lay, self.config.block,
                                    self.softmax_scale)
        from .flash_attention import reference_attention
        if key_padding_mask.ndim == 2:
            key_padding_mask = key_padding_mask[:, None, None, :]
        lm = jnp.asarray(layout_to_mask(lay, self.config.block))[None]
        return reference_attention(
            q, k, v, causal=False,
            mask=jnp.logical_and(lm, key_padding_mask.astype(bool)),
            softmax_scale=self.softmax_scale)


def get_ops(backend: str = "tpu"):
    return SimpleNamespace(
        sparse_attention=sparse_attention, layout_to_mask=layout_to_mask,
        SparsityConfig=SparsityConfig,
        FixedSparsityConfig=FixedSparsityConfig,
        BigBirdSparsityConfig=BigBirdSparsityConfig,
        BSLongformerSparsityConfig=BSLongformerSparsityConfig,
        VariableSparsityConfig=VariableSparsityConfig,
        SparseSelfAttention=SparseSelfAttention)
