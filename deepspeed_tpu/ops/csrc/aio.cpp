// Asynchronous file I/O for NVMe tensor paging (ZeRO-Infinity-style swap).
//
// TPU-native equivalent of the reference's aio library
// (csrc/aio/py_lib/py_ds_aio.cpp:16-20, deepspeed_aio_thread.cpp): a
// thread-pool handle that services pread/pwrite requests against swap files
// so optimizer-state partitions can stream to/from NVMe while the host Adam
// works on another partition. The reference builds on libaio; this uses a
// portable pthread pool over pread/pwrite — on modern kernels with multiple
// in-flight threads this saturates NVMe queues without the libaio dependency,
// and it works on every filesystem (O_DIRECT alignment games are opt-in).
//
// Tickets: every submit returns a monotonically increasing ticket; aio_wait
// blocks until that ticket completes and returns its byte count (<0 = errno).

#include <fcntl.h>
#include <unistd.h>

#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
    int64_t ticket;
    bool write;
    std::string path;
    void* buf;
    int64_t nbytes;
    int64_t offset;
};

class AioHandle {
  public:
    explicit AioHandle(int n_threads) : next_ticket_(1), shutdown_(false) {
        if (n_threads < 1) n_threads = 1;
        for (int i = 0; i < n_threads; ++i) {
            workers_.emplace_back([this] { worker(); });
        }
    }

    ~AioHandle() {
        {
            std::lock_guard<std::mutex> lk(mu_);
            shutdown_ = true;
        }
        cv_.notify_all();
        for (auto& t : workers_) t.join();
    }

    int64_t submit(bool write, const char* path, void* buf, int64_t nbytes,
                   int64_t offset) {
        std::lock_guard<std::mutex> lk(mu_);
        int64_t ticket = next_ticket_++;
        ++outstanding_;
        queue_.push_back(Request{ticket, write, path, buf, nbytes, offset});
        cv_.notify_one();
        return ticket;
    }

    int64_t wait(int64_t ticket) {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [&] {
            return done_.count(ticket) > 0 || ticket <= watermark_;
        });
        auto it = done_.find(ticket);
        if (it == done_.end()) return 0;  // subsumed by a wait_all
        int64_t rc = it->second;
        done_.erase(it);
        return rc;
    }

    // wait until no request is queued or in flight; returns 0 or the first
    // error seen since the last wait_all. Per-ticket results are dropped —
    // later wait() calls on subsumed tickets return 0 immediately (the
    // watermark) instead of blocking on an erased entry.
    int64_t wait_all() {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [&] { return outstanding_ == 0; });
        int64_t rc = first_error_;
        first_error_ = 0;
        watermark_ = next_ticket_ - 1;
        done_.clear();
        return rc;
    }

  private:
    void worker() {
        for (;;) {
            Request req;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [&] { return shutdown_ || !queue_.empty(); });
                if (shutdown_ && queue_.empty()) return;
                req = queue_.front();
                queue_.pop_front();
            }
            int64_t rc = execute(req);
            {
                std::lock_guard<std::mutex> lk(mu_);
                done_[req.ticket] = rc;
                if (rc < 0 && first_error_ == 0) first_error_ = rc;
                --outstanding_;
            }
            done_cv_.notify_all();
        }
    }

    int64_t execute(const Request& req) {
        int flags = req.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
        int fd = ::open(req.path.c_str(), flags, 0644);
        if (fd < 0) return -errno;
        int64_t total = 0;
        char* p = static_cast<char*>(req.buf);
        while (total < req.nbytes) {
            ssize_t k =
                req.write
                    ? ::pwrite(fd, p + total, req.nbytes - total,
                               req.offset + total)
                    : ::pread(fd, p + total, req.nbytes - total,
                              req.offset + total);
            if (k < 0) {
                int err = errno;
                ::close(fd);
                return -err;
            }
            if (k == 0) break;  // EOF on read
            total += k;
        }
        if (req.write) ::fsync(fd);
        ::close(fd);
        return total;
    }

    std::mutex mu_;
    std::condition_variable cv_, done_cv_;
    std::deque<Request> queue_;
    std::unordered_map<int64_t, int64_t> done_;
    std::vector<std::thread> workers_;
    int64_t next_ticket_;
    int64_t outstanding_ = 0;
    int64_t first_error_ = 0;
    int64_t watermark_ = 0;  // highest ticket subsumed by a wait_all
    bool shutdown_;
};

}  // namespace

extern "C" {

void* aio_handle_create(int n_threads) { return new AioHandle(n_threads); }

void aio_handle_destroy(void* h) { delete static_cast<AioHandle*>(h); }

int64_t aio_submit_read(void* h, const char* path, void* buf, int64_t nbytes,
                        int64_t offset) {
    return static_cast<AioHandle*>(h)->submit(false, path, buf, nbytes, offset);
}

int64_t aio_submit_write(void* h, const char* path, const void* buf,
                         int64_t nbytes, int64_t offset) {
    return static_cast<AioHandle*>(h)->submit(true, path,
                                              const_cast<void*>(buf), nbytes,
                                              offset);
}

int64_t aio_wait(void* h, int64_t ticket) {
    return static_cast<AioHandle*>(h)->wait(ticket);
}

int64_t aio_wait_all(void* h) { return static_cast<AioHandle*>(h)->wait_all(); }

}  // extern "C"
