// Host-side SIMD optimizers for offloaded ZeRO partitions.
//
// TPU-native equivalent of the reference's CPU Adam/Adagrad
// (csrc/adam/cpu_adam.cpp:303-308, csrc/adagrad/cpu_adagrad.cpp:243): when
// optimizer state is offloaded to host RAM / NVMe, the optimizer step runs on
// the host CPU over fp32 master buffers while the TPU works on the next
// micro-batch. Design differences from the reference: no global optimizer
// registry keyed by id (the Python side owns per-leaf state as numpy views and
// passes raw pointers), and bf16 (not fp16) is the device dtype, so the
// fused "step + copy back" variant emits round-to-nearest-even bfloat16.
//
// Vectorization: plain loops with #pragma omp simd — autovectorizes to
// AVX2/AVX-512 at -O3 -march=native, replacing the reference's hand-written
// AVX intrinsics (csrc/includes/simd.h) with something the compiler owns.

#include <cmath>
#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

// fp32 -> bf16 with round-to-nearest-even (matches XLA's convert semantics).
static inline uint16_t float_to_bf16(float f) {
    uint32_t bits;
    std::memcpy(&bits, &f, sizeof(bits));
    if ((bits & 0x7fffffffu) > 0x7f800000u) {  // NaN: quiet, keep payload bit
        return static_cast<uint16_t>((bits >> 16) | 0x0040u);
    }
    uint32_t lsb = (bits >> 16) & 1u;
    bits += 0x7fffu + lsb;
    return static_cast<uint16_t>(bits >> 16);
}

}  // namespace

extern "C" {

// One Adam step over a flat fp32 buffer.
//   decoupled=1 -> AdamW (decay applied to weights, not grads)
//   bias_correction=1 -> standard Adam bias correction with `step` (1-based)
// Returns 0.
int ds_adam_step(float* w, const float* g, float* m, float* v, int64_t n,
                 int64_t step, float lr, float beta1, float beta2, float eps,
                 float weight_decay, int decoupled, int bias_correction) {
    float bc1 = 1.0f, bc2 = 1.0f;
    if (bias_correction) {
        bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
        bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
    }
    const float inv_bc1 = 1.0f / bc1;
    const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float grad = g[i];
        if (weight_decay != 0.0f && !decoupled) grad += weight_decay * w[i];
        float mi = beta1 * m[i] + (1.0f - beta1) * grad;
        float vi = beta2 * v[i] + (1.0f - beta2) * grad * grad;
        m[i] = mi;
        v[i] = vi;
        float update = (mi * inv_bc1) / (std::sqrt(vi) * inv_bc2_sqrt + eps);
        if (weight_decay != 0.0f && decoupled) update += weight_decay * w[i];
        w[i] -= lr * update;
    }
    return 0;
}

// Adam step fused with the device-copy cast: also writes the updated weights
// as bf16 into `w16` (the buffer that gets device_put back to the TPU).
int ds_adam_step_copy_bf16(float* w, const float* g, float* m, float* v,
                           uint16_t* w16, int64_t n, int64_t step, float lr,
                           float beta1, float beta2, float eps,
                           float weight_decay, int decoupled,
                           int bias_correction) {
    float bc1 = 1.0f, bc2 = 1.0f;
    if (bias_correction) {
        bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
        bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
    }
    const float inv_bc1 = 1.0f / bc1;
    const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float grad = g[i];
        if (weight_decay != 0.0f && !decoupled) grad += weight_decay * w[i];
        float mi = beta1 * m[i] + (1.0f - beta1) * grad;
        float vi = beta2 * v[i] + (1.0f - beta2) * grad * grad;
        m[i] = mi;
        v[i] = vi;
        float update = (mi * inv_bc1) / (std::sqrt(vi) * inv_bc2_sqrt + eps);
        if (weight_decay != 0.0f && decoupled) update += weight_decay * w[i];
        float wi = w[i] - lr * update;
        w[i] = wi;
        w16[i] = float_to_bf16(wi);
    }
    return 0;
}

// Adagrad step (reference csrc/adagrad/cpu_adagrad.cpp behavior).
int ds_adagrad_step(float* w, const float* g, float* acc, int64_t n, float lr,
                    float eps, float weight_decay) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float grad = g[i];
        if (weight_decay != 0.0f) grad += weight_decay * w[i];
        float a = acc[i] + grad * grad;
        acc[i] = a;
        w[i] -= lr * grad / (std::sqrt(a) + eps);
    }
    return 0;
}

// Lion step (sign of interpolated momentum; used by the host offload path
// when the configured optimizer is lion).
int ds_lion_step(float* w, const float* g, float* m, int64_t n, float lr,
                 float beta1, float beta2, float weight_decay) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float grad = g[i];
        float c = beta1 * m[i] + (1.0f - beta1) * grad;
        float update = (c > 0.0f) ? 1.0f : ((c < 0.0f) ? -1.0f : 0.0f);
        if (weight_decay != 0.0f) update += weight_decay * w[i];
        w[i] -= lr * update;
        m[i] = beta2 * m[i] + (1.0f - beta2) * grad;
    }
    return 0;
}

// Utilities for the host grad path ---------------------------------------

// sum of squares (for host-side global grad norm)
double ds_norm_sq(const float* x, int64_t n) {
    double acc = 0.0;
#pragma omp parallel for reduction(+ : acc) schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        acc += static_cast<double>(x[i]) * static_cast<double>(x[i]);
    }
    return acc;
}

// any non-finite? (host overflow check for the fp16 loss-scaler path)
int ds_has_nonfinite(const float* x, int64_t n) {
    int bad = 0;
#pragma omp parallel for reduction(| : bad) schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        bad |= !std::isfinite(x[i]);
    }
    return bad;
}

// x *= a  (grad unscale / averaging)
int ds_scale(float* x, int64_t n, float a) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) x[i] *= a;
    return 0;
}

int ds_fp32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) dst[i] = float_to_bf16(src[i]);
    return 0;
}

int ds_bf16_to_fp32(const uint16_t* src, float* dst, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        uint32_t bits = static_cast<uint32_t>(src[i]) << 16;
        float f;
        std::memcpy(&f, &bits, sizeof(f));
        dst[i] = f;
    }
    return 0;
}

int ds_num_threads() {
#if defined(_OPENMP)
    return omp_get_max_threads();
#else
    return 1;
#endif
}

}  // extern "C"
