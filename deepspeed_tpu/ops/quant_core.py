"""The ONE quantization core.

Every quantization consumer in the tree used to hand-roll its own
scale/clip math: the grouped QAT kernels (``ops/quantizer_ops.py``), the
1-bit/int8 compressed allreduce (``ops/compressed_collectives.py``), MoQ
(``runtime/quantize.py``) and QAT compression (``compression/compress.py``,
both via quantizer_ops), and now the quantized wire collectives
(``comm/quantized.py``). This module is the single implementation they all
ride: symmetric/asymmetric scale computation, round+clip, blockwise
(per-contiguous-block) int8/fp8 wire codecs with per-block f32 scales, and
the sign (1-bit) codec.

Blockwise layout (the ZeRO++ qwZ wire format, arxiv 2306.10209 §4.1): the
tensor is viewed flat and cut into contiguous blocks of ``block`` values;
each block carries one f32 scale = absmax/qmax. Per-block scales bound the
round-trip error by the BLOCK's dynamic range instead of the tensor's —
the difference between ~1% and unusable for wide-tailed gradients. A
``block`` that does not divide the tensor size falls back to one
per-tensor scale (never per-element: f32 scales per element would be
larger than the f32 payload itself).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

INT8_QMAX = 127.0
#: fp8 e4m3 finite max — the "fp8-style blockwise" wire format target
FP8_QMAX = 448.0
#: None when the installed jax/ml_dtypes has no fp8 (callers must gate)
FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)

#: wire formats understood by the blockwise codec
WIRE_FORMATS = ("int8", "fp8_block")


# ---------------------------------------------------------------- scale math

def qrange(bits: int, symmetric: bool) -> Tuple[float, float]:
    """Integer target range; symmetric keeps zero exactly representable."""
    if symmetric:
        qmax = float(2 ** (bits - 1) - 1)
        return -qmax, qmax
    return 0.0, float(2 ** bits - 1)


def symmetric_scale(absmax, qmax: float):
    """absmax/qmax with the zero-block guard (scale 1 keeps q = 0 exact —
    a 0 scale would NaN the dequantize)."""
    return jnp.where(absmax > 0, absmax / qmax, 1.0).astype(jnp.float32)


def asymmetric_scale_zero(lo, hi, qmin: float, qmax: float):
    """(scale, zero_point) for the asymmetric range [lo, hi] -> [qmin, qmax]."""
    scale = jnp.where(hi > lo, (hi - lo) / (qmax - qmin), 1.0)
    zero = qmin - lo / scale
    return scale.astype(jnp.float32), zero


def round_clip(scaled, qmin: float, qmax: float, carrier,
               stochastic: bool = False, rng=None):
    """Round (nearest or stochastic) then clip into the carrier dtype."""
    if stochastic:
        if rng is None:
            raise ValueError(
                "stochastic=True requires an rng key — a fixed key would "
                "add the SAME noise every call, biasing the rounding")
        noise = jax.random.uniform(rng, scaled.shape) - 0.5
        q = jnp.floor(scaled + 0.5 + noise)
    else:
        q = jnp.rint(scaled)
    return jnp.clip(q, qmin, qmax).astype(carrier)


# ------------------------------------------------------------ blockwise codec

def block_count(size: int, block: Optional[int]) -> int:
    """Number of scale blocks the flat codec will use for ``size`` values."""
    if not block or block <= 0 or size % block != 0:
        return 1
    return size // block


def quantize_blockwise(x, block: Optional[int] = 256, wire: str = "int8"):
    """x (any shape, any float dtype) -> (q, scales).

    q: x.shape in the wire dtype (int8, or fp8 e4m3 for ``fp8_block``);
    scales: f32 [block_count]. The pair IS the wire payload of the
    quantized collectives: q.size bytes + 4*block_count bytes.
    """
    if wire not in WIRE_FORMATS:
        raise ValueError(f"unknown wire format {wire!r}; one of {WIRE_FORMATS}")
    if wire == "fp8_block" and FP8_DTYPE is None:
        raise ValueError("fp8_block wire format needs jax.numpy.float8_e4m3fn "
                         "(newer jaxlib/ml_dtypes); use int8")
    nb = block_count(x.size, block)
    xg = x.reshape(nb, -1).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xg), axis=1, keepdims=True)
    if wire == "int8":
        scale = symmetric_scale(absmax, INT8_QMAX)
        q = round_clip(xg / scale, -INT8_QMAX, INT8_QMAX, jnp.int8)
    else:
        scale = symmetric_scale(absmax, FP8_QMAX)
        # the fp8 cast itself rounds-to-nearest; values are pre-scaled into
        # the finite range so the cast never saturates
        q = (xg / scale).astype(FP8_DTYPE)
    return q.reshape(x.shape), scale.reshape(nb)


def dequantize_blockwise(q, scales, dtype=jnp.float32):
    """(q, scales) -> float tensor of q.shape in ``dtype``."""
    nb = scales.shape[0]
    xg = q.reshape(nb, -1).astype(jnp.float32) * scales.reshape(nb, 1)
    return xg.reshape(q.shape).astype(dtype)


def fake_quantize_blockwise(x, block: Optional[int] = 256, wire: str = "int8"):
    """quantize -> dequantize in the input dtype (error-injection oracle for
    tests and parity analysis)."""
    q, s = quantize_blockwise(x, block, wire)
    return dequantize_blockwise(q, s, dtype=x.dtype)


def pertensor_int8(x):
    """(q int8, scalar f32 scale) — the per-tensor special case the int8
    allreduce legs use."""
    q, s = quantize_blockwise(x, block=None, wire="int8")
    return q, s.reshape(())


def wire_nbytes(size: int, block: Optional[int], wire: str = "int8") -> int:
    """Bytes the blockwise codec puts on the wire for ``size`` values:
    1 byte/value (int8 and fp8 both) + one f32 scale per block."""
    return size + 4 * block_count(size, block)


# ----------------------------------------------------------------- sign codec

def absmean_scale(x, axis=None, keepdims=False):
    """mean(|x|) — the 1-bit codec's scale (reference compressed_allreduce
    and BinaryQuantizer both use it)."""
    return jnp.mean(jnp.abs(x), axis=axis, keepdims=keepdims)


def sign_quantize(x):
    """x -> (int8 signs, scalar f32 scale = mean|x|)."""
    scale = absmean_scale(x).astype(jnp.float32)
    sign = jnp.where(x >= 0, jnp.int8(1), jnp.int8(-1))
    return sign, scale


def sign_dequantize(sign, scale):
    return sign.astype(jnp.float32) * scale
