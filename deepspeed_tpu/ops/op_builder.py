"""Op-builder registry — the hardware-portability seam.

Re-design of op_builder/builder.py:94 ``OpBuilder``. The reference JIT-builds
CUDA extensions; here an op "build" resolves to one of:
  - a Pallas-TPU kernel (backend="tpu"),
  - the same kernel in interpret mode or a jnp reference path (backend="cpu"),
  - a compiled C++ host extension (CPU Adam / AIO), built via the C toolchain.

Accelerators dispatch through get_op_builder() by class name exactly like
accelerator/cuda_accelerator.py:238-247, so an alternate accelerator can
supply alternate builders.
"""

import importlib
from typing import Dict, Optional, Type

from ..utils.logging import logger


class OpBuilder:
    """Base builder: `load()` returns a namespace of callables."""

    NAME = "base"
    # module path holding the op implementations; must expose
    # `get_ops(backend: str) -> object`
    MODULE: Optional[str] = None

    def __init__(self, backend: str = "tpu"):
        self.backend = backend
        self._loaded = None

    def is_compatible(self, verbose=True) -> bool:
        try:
            self._import_module()
            return True
        except Exception as e:  # missing toolchain / backend
            if verbose:
                logger.warning(f"op {self.NAME} incompatible: {e}")
            return False

    def _import_module(self):
        assert self.MODULE is not None, f"{self.NAME} has no module"
        return importlib.import_module(self.MODULE, package=__package__)

    def load(self, verbose=True):
        if self._loaded is None:
            mod = self._import_module()
            self._loaded = mod.get_ops(self.backend)
        return self._loaded


class FlashAttentionBuilder(OpBuilder):
    NAME = "flash_attn"
    MODULE = ".flash_attention"


class FusedAdamBuilder(OpBuilder):
    NAME = "fused_adam"
    MODULE = ".adam.fused_adam_ops"


class FusedLambBuilder(OpBuilder):
    NAME = "fused_lamb"
    MODULE = ".lamb_ops"


class CPUAdamBuilder(OpBuilder):
    NAME = "cpu_adam"
    MODULE = ".adam.cpu_adam_ops"


class CPUAdagradBuilder(OpBuilder):
    NAME = "cpu_adagrad"
    MODULE = ".adam.cpu_adagrad_ops"


class QuantizerBuilder(OpBuilder):
    NAME = "quantizer"
    MODULE = ".quantizer_ops"


class TransformerBuilder(OpBuilder):
    NAME = "transformer"
    MODULE = ".transformer.fused_ops"


class InferenceBuilder(OpBuilder):
    NAME = "transformer_inference"
    MODULE = ".transformer.inference_ops"


class SparseAttnBuilder(OpBuilder):
    NAME = "sparse_attn"
    MODULE = ".sparse_attention_ops"


class RandomLTDBuilder(OpBuilder):
    NAME = "random_ltd"
    MODULE = ".random_ltd_ops"


class AsyncIOBuilder(OpBuilder):
    NAME = "async_io"
    MODULE = ".aio_ops"


class SpatialInferenceBuilder(OpBuilder):
    NAME = "spatial_inference"
    MODULE = ".spatial_ops"


class UtilsBuilder(OpBuilder):
    NAME = "utils"
    MODULE = ".utils_ops"


_BUILDERS: Dict[str, Type[OpBuilder]] = {
    cls.NAME: cls
    for cls in [
        FlashAttentionBuilder, FusedAdamBuilder, FusedLambBuilder,
        CPUAdamBuilder, CPUAdagradBuilder, QuantizerBuilder, TransformerBuilder,
        InferenceBuilder, SparseAttnBuilder, RandomLTDBuilder, AsyncIOBuilder,
        SpatialInferenceBuilder, UtilsBuilder
    ]
}
# reference-style class-name aliases (e.g. accelerator.get_op_builder("FusedAdamBuilder"))
_BUILDERS.update({cls.__name__: cls for cls in list(_BUILDERS.values())})


def get_builder_class(name: str, backend: str = "tpu"):
    cls = _BUILDERS.get(name)
    if cls is None:
        return None

    class _Bound(cls):
        def __init__(self):
            super().__init__(backend=backend)

    _Bound.__name__ = cls.__name__
    return _Bound


def builder_names():
    return sorted({c.NAME for c in _BUILDERS.values()})
