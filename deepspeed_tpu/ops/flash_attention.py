"""Attention op with backend dispatch.

The TPU equivalent of the reference's fused attention kernels
(csrc/transformer/softmax_kernels.cu, csrc/transformer/inference softmax/
softmax_context): a Pallas flash-attention kernel on TPU (ops/pallas/
flash_attention.py), and an XLA reference path used on CPU (tests) and as the
numerics oracle. Loaded via FlashAttentionBuilder through the accelerator
op-builder seam.
"""

from types import SimpleNamespace

import jax
import jax.numpy as jnp


def reference_attention(q, k, v, causal=True, mask=None, softmax_scale=None,
                        dropout_rate=0.0, dropout_rng=None, bias=None,
                        window=None):
    """Plain XLA attention. q,k,v: [B, H, T, D] (q may have Tq != Tk for
    decode). ``bias`` is an additive logits bias broadcastable to
    [B, H, Tq, Tk] (ALiBi). ``window`` (with causal) keeps only keys with
    q_pos - k_pos < window — Mistral sliding-window semantics. Numerics
    oracle for the Pallas kernel."""
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    scale = softmax_scale if softmax_scale is not None else 1.0 / jnp.sqrt(d)
    if window is not None and not causal:
        raise ValueError("sliding window requires causal=True")
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        # offset so the last query attends to all keys (decode-friendly)
        q_pos = jnp.arange(t_q)[:, None] + (t_k - t_q)
        k_pos = jnp.arange(t_k)[None, :]
        causal_mask = q_pos >= k_pos
        if window is not None:
            causal_mask &= (q_pos - k_pos) < window
        logits = jnp.where(causal_mask[None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _on_tpu() -> bool:
    """Will this computation run on a real TPU? jax.default_backend() is NOT
    trustworthy here — the axon plugin reports 'tpu' even under
    JAX_PLATFORMS=cpu — so prefer the active mesh's devices, then the pinned
    default device."""
    from ..parallel.constraints import active_mesh
    mesh = active_mesh()
    if mesh is not None and getattr(mesh, "devices", None) is not None:
        return mesh.devices.flat[0].platform == "tpu"
    dev = jax.config.jax_default_device
    if dev is not None:
        return getattr(dev, "platform", None) == "tpu"
    return jax.default_backend() == "tpu"


def flash_attention(q, k, v, causal=True, mask=None, softmax_scale=None,
                    dropout_rate=0.0, dropout_rng=None, backend="auto",
                    interpret=None, bias=None, window=None):
    """Dispatch: Pallas kernel on TPU, XLA reference elsewhere.

    backend="pallas" runs the Pallas kernel unconditionally and RAISES if the
    shape/features are unsupported — no silent degradation on the hot path.
    backend="xla" forces the reference path. "auto" picks Pallas only when
    running on TPU with a supported shape. ``interpret=None`` auto-enables
    interpreter mode off-TPU (CPU tests of the real kernel). ``bias`` (ALiBi
    etc.) currently routes to the XLA path."""
    from .pallas import flash_attention as pallas_fa

    if backend == "pallas":
        if bias is not None or not pallas_fa.supported(
                q, k, causal=causal, mask=mask, dropout_rate=dropout_rate,
                window=window):
            raise ValueError(
                f"pallas flash attention does not support this call "
                f"(q={q.shape} k={k.shape} causal={causal} "
                f"mask={'yes' if mask is not None else 'no'} "
                f"bias={'yes' if bias is not None else 'no'} "
                f"window={window} "
                f"dropout={dropout_rate}); pass backend='xla' explicitly")
        if interpret is None:
            interpret = not _on_tpu()
        return pallas_fa.flash_attention(q, k, v, causal, softmax_scale,
                                         None, None, interpret, window)
    if backend == "auto" and _on_tpu():
        if bias is None and pallas_fa.supported(q, k, causal=causal,
                                                mask=mask,
                                                dropout_rate=dropout_rate,
                                                window=window):
            return pallas_fa.flash_attention(q, k, v, causal, softmax_scale,
                                             None, None, False, window)
        _warn_xla_fallback(q, bias)
    if backend not in ("auto", "xla"):
        raise ValueError(f"unknown attention backend {backend!r}")
    return reference_attention(q, k, v, causal=causal, mask=mask,
                               softmax_scale=softmax_scale,
                               dropout_rate=dropout_rate,
                               dropout_rng=dropout_rng, bias=bias,
                               window=window)


_warned_fallback = False


def _warn_xla_fallback(q, bias):
    """One-time visibility for the on-TPU XLA fallback: the dense path
    materializes [B, H, Tq, Tk] fp32 logits — a real memory/bandwidth cliff
    vs the Pallas kernel (why round-1 shipped at 16% MFU unnoticed)."""
    global _warned_fallback
    if _warned_fallback:
        return
    _warned_fallback = True
    from ..utils.logging import logger
    why = "attention bias (ALiBi)" if bias is not None else \
        f"unsupported shape {tuple(q.shape)}"
    logger.warning(
        f"flash_attention: falling back to the dense XLA path on TPU "
        f"({why} is not supported by the Pallas kernel); this "
        f"materializes full [B,H,Tq,Tk] fp32 attention logits")


def get_ops(backend: str):
    return SimpleNamespace(flash_attention=flash_attention,
                           reference_attention=reference_attention)
