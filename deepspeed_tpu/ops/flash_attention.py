"""Attention op with backend dispatch.

The TPU equivalent of the reference's fused attention kernels
(csrc/transformer/softmax_kernels.cu, csrc/transformer/inference softmax/
softmax_context): a Pallas flash-attention kernel on TPU (ops/pallas/
flash_attention.py), and an XLA reference path used on CPU (tests) and as the
numerics oracle. Loaded via FlashAttentionBuilder through the accelerator
op-builder seam.
"""

import functools
from types import SimpleNamespace

import jax
import jax.numpy as jnp


def reference_attention(q, k, v, causal=True, mask=None, softmax_scale=None,
                        dropout_rate=0.0, dropout_rng=None):
    """Plain XLA attention. q,k,v: [B, H, T, D] (q may have Tq != Tk for
    decode). Numerics oracle for the Pallas kernel."""
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    scale = softmax_scale if softmax_scale is not None else 1.0 / jnp.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        # offset so the last query attends to all keys (decode-friendly)
        q_pos = jnp.arange(t_q)[:, None] + (t_k - t_q)
        k_pos = jnp.arange(t_k)[None, :]
        causal_mask = q_pos >= k_pos
        logits = jnp.where(causal_mask[None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@functools.lru_cache(None)
def _get_pallas_flash():
    from .pallas.flash_attention import flash_attention
    return flash_attention


def flash_attention(q, k, v, causal=True, mask=None, softmax_scale=None,
                    dropout_rate=0.0, dropout_rng=None, backend="auto"):
    """Dispatch: Pallas on TPU, XLA reference elsewhere."""
    use_pallas = False
    if backend == "pallas":
        use_pallas = True
    elif backend == "auto":
        try:
            use_pallas = (dropout_rate == 0.0 and mask is None
                          and jax.default_backend() == "tpu"
                          and q.shape[-2] >= 128 and q.shape[-2] == k.shape[-2]
                          and q.shape[-1] in (64, 128, 256))
        except Exception:
            use_pallas = False
    if use_pallas:
        try:
            return _get_pallas_flash()(q, k, v, causal=causal,
                                       softmax_scale=softmax_scale)
        except Exception:
            pass
    return reference_attention(q, k, v, causal=causal, mask=mask,
                               softmax_scale=softmax_scale,
                               dropout_rate=dropout_rate, dropout_rng=dropout_rng)


def get_ops(backend: str):
    return SimpleNamespace(flash_attention=flash_attention,
                           reference_attention=reference_attention)
