"""JIT build + cache for the C++ host extensions.

The TPU analogue of the reference's op_builder JIT path
(op_builder/builder.py:94 ``OpBuilder.load`` → torch cpp_extension): here the
host-side native code (CPU SIMD optimizers, async NVMe I/O) compiles once with
g++ into a shared library keyed by a source hash, loaded via ctypes. No
torch/pybind dependency — the ABI is a C API over raw pointers, and numpy
arrays supply the memory.
"""

import ctypes
import hashlib
import os
import subprocess
import threading

from ..utils.logging import logger

_CSRC = os.path.join(os.path.dirname(__file__), "csrc")
_CACHE = os.environ.get(
    "DSTPU_NATIVE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "lib"))

_lock = threading.Lock()
_loaded = {}


class NativeBuildError(RuntimeError):
    pass


def _source_path(name: str) -> str:
    return os.path.join(_CSRC, f"{name}.cpp")


def _flags(openmp: bool):
    flags = ["-O3", "-std=c++17", "-fPIC", "-shared", "-march=native"]
    if openmp:
        flags.append("-fopenmp")
    return flags


_toolchain_id = None


def _toolchain():
    """g++ version + host arch — part of the cache key because -march=native
    makes the .so host-specific (NFS-shared caches across heterogeneous
    nodes must not collide)."""
    global _toolchain_id
    if _toolchain_id is None:
        import platform
        try:
            ver = subprocess.run(["g++", "-dumpfullversion", "-dumpversion"],
                                 capture_output=True, text=True,
                                 timeout=30).stdout.strip()
        except (OSError, subprocess.TimeoutExpired):
            ver = "unknown"
        _toolchain_id = f"{ver}|{platform.machine()}|{platform.processor()}"
    return _toolchain_id


def build(name: str, openmp: bool = True) -> str:
    """Compile csrc/<name>.cpp → cached .so; returns the library path."""
    src = _source_path(name)
    if not os.path.isfile(src):
        raise NativeBuildError(f"no native source {src}")
    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    h.update(" ".join(_flags(openmp)).encode())
    h.update(_toolchain().encode())
    digest = h.hexdigest()[:16]
    lib = os.path.join(_CACHE, f"lib{name}_{digest}.so")
    if os.path.isfile(lib):
        return lib
    os.makedirs(_CACHE, exist_ok=True)
    tmp = lib + f".tmp{os.getpid()}"
    cmd = ["g++", *_flags(openmp), src, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeBuildError(f"g++ unavailable or timed out: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build of {name} failed:\n{proc.stderr[-2000:]}")
    os.replace(tmp, lib)  # atomic under concurrent builders
    logger.info(f"built native op {name} -> {lib}")
    return lib


def load_library(name: str, openmp: bool = True) -> ctypes.CDLL:
    """Build (if needed) and dlopen the named native library, cached."""
    with _lock:
        if name not in _loaded:
            _loaded[name] = ctypes.CDLL(build(name, openmp=openmp))
        return _loaded[name]


def available(name: str) -> bool:
    try:
        load_library(name)
        return True
    except NativeBuildError:
        return False
