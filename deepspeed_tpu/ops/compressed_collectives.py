"""Compressed collectives: 1-bit (sign) and int8 allreduce with error
feedback.

Capability match for the reference compressed-communication backends
(runtime/comm/nccl.py:54 ``NcclBackend.compressed_allreduce``, mpi.py,
runtime/compression/cupy.py bit-packing): the error-feedback sign-compressed
allreduce that 1-bit Adam/LAMB ride on. TPU-native translation of the
NCCL alltoall+allgather pipeline, for use INSIDE shard_map over a mesh axis:

  1. corrected = x + error                     (worker error feedback)
  2. chunk [world, n/world]; all_to_all        -> each member holds every
     worker's copy of ITS chunk                 [COLLECTIVE, int8 payload]
  3. sum chunks; server error feedback; re-compress
  4. all_gather compressed chunks              [COLLECTIVE, int8 payload]

Payloads cross the interconnect as int8 signs (plus one f32 scale per
chunk): 4x fewer bytes than f32 — the XLA collectives genuinely move int8.
Per-worker and per-chunk ("server") error state persists across calls,
preserving the unbiased-in-the-limit property the reference relies on.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .quant_core import pertensor_int8, sign_dequantize, sign_quantize


def sign_compress(x):
    """x -> (int8 sign, f32 scale) with scale = mean(|x|) (the 1-bit
    compression of the reference's compressed_allreduce). One codec —
    ops/quant_core.sign_quantize — shared with the comm wire formats."""
    return sign_quantize(x)


def sign_decompress(sign, scale):
    return sign_dequantize(sign, scale)


def sign_compress_with_error(x, error):
    """Error-feedback form, the 1-bit optimizers' primitive: returns
    (compressed float values, new_error). ONE implementation — the
    optimizers (runtime/fp16/onebit) and the collective share it."""
    corrected = x + error
    sign, scale = sign_compress(corrected)
    compressed = sign_decompress(sign, scale)
    return compressed, corrected - compressed




def onebit_allreduce(x, worker_error, server_error,
                     axis_name: str = "data") -> Tuple:
    """Error-feedback 1-bit AVERAGE over `axis_name` (inside shard_map).

    x: [n] local values (n divisible by the axis size).
    worker_error: [n] per-worker residual. server_error: [n/world] residual
    for the chunk this member owns.
    Returns (avg [n], new_worker_error [n], new_server_error [n/world]).
    """
    world = int(lax.psum(1, axis_name))  # folds statically at trace time
    n = x.shape[0]
    assert n % world == 0, f"size {n} not divisible by axis {world}"
    chunk = n // world

    corrected = x + worker_error
    sign, scale = sign_compress(corrected)
    new_worker_error = corrected - sign_decompress(sign, scale)

    # every member sends chunk j to member j (int8 over the wire);
    # scales travel alongside (world f32 scalars)
    signs_by_chunk = sign.reshape(world, chunk)
    recv = lax.all_to_all(signs_by_chunk, axis_name, split_axis=0,
                          concat_axis=0, tiled=False)          # [world, chunk]
    scales = lax.all_gather(scale, axis_name)                   # [world]
    chunk_sum = jnp.sum(recv.astype(jnp.float32) *
                        scales[:, None], axis=0) / world

    corrected_chunk = chunk_sum + server_error
    csign, cscale = sign_compress(corrected_chunk)
    new_server_error = corrected_chunk - sign_decompress(csign, cscale)

    gathered = lax.all_gather(csign, axis_name)                 # [world, chunk]
    cscales = lax.all_gather(cscale, axis_name)                 # [world]
    avg = (gathered.astype(jnp.float32) *
           cscales[:, None]).reshape(n)
    return avg, new_worker_error, new_server_error


def int8_allreduce(x, axis_name: str = "data"):
    """Quantized AVERAGE: int8 reduce-scatter + int8 allgather (the
    ZeRO++-style quantized gradient collective, zero_quantized_gradients).
    Per-tensor scales; lossy but unbiased-ish per call; no error state."""
    world = int(lax.psum(1, axis_name))  # folds statically at trace time
    n = x.shape[0]
    assert n % world == 0
    chunk = n // world
    # quantize locally (per-tensor scale, quant_core codec), exchange int8
    q, scale = pertensor_int8(x)
    recv = lax.all_to_all(q.reshape(world, chunk), axis_name, split_axis=0,
                          concat_axis=0, tiled=False)
    scales = lax.all_gather(scale, axis_name)
    chunk_avg = jnp.sum(recv.astype(jnp.float32) * scales[:, None],
                        axis=0) / world
    # re-quantize the reduced chunk for the gather leg
    cq, cscale = pertensor_int8(chunk_avg)
    gathered = lax.all_gather(cq, axis_name)
    cscales = lax.all_gather(cscale, axis_name)
    return (gathered.astype(jnp.float32) * cscales[:, None]).reshape(n)


def exact_allreduce_mean(x, axis_name: str = "data"):
    """The uncompressed oracle the tests compare against."""
    return lax.pmean(x, axis_name)
