from .op_builder import OpBuilder, get_builder_class, builder_names
