"""Sparse-attention model surgery (reference
ops/sparse_attention/sparse_attention_utils.py:14 ``SparseAttentionUtils``).

The reference patches torch BERT/RoBERTa modules in place: extend position
embeddings, swap BertSelfAttention for SparseSelfAttention, pad inputs to
the block size. The TPU-native translation operates on the param pytree
(embedding extension is an array op, not a Parameter mutation) and on the
model's ``attn_override`` hook (the attention *function* is the module
here). Like the reference, surgery supports bidirectional encoders (BERT
family) — causal models use block-sparse layouts through their own config
(``ops/pallas/block_sparse_attention.py``), where within-block causal
masking is handled by the kernel.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np

from .sparse_attention_ops import (FixedSparsityConfig, SparseSelfAttention,
                                   SparsityConfig)
from ..utils.logging import log_dist


class SparseAttentionUtils:
    """Utility surface matching the reference class name & methods."""

    @staticmethod
    def extend_position_embedding(model, params, max_position):
        """Tile the position table to a longer horizon (reference
        :14 ``extend_position_embedding`` repeats the weight rows).
        Returns (model, params) with ``wpe`` extended and the model config
        updated; the model object is rebuilt, not mutated."""
        wpe = params["wpe"]
        original = wpe.shape[0]
        if max_position <= original:
            raise ValueError(f"max_position={max_position} must exceed the "
                             f"current table ({original})")
        multiples = -(-max_position // original)
        extended = jnp.tile(wpe, (multiples, 1))[:max_position]
        params = dict(params, wpe=extended)
        new_model = type(model)(dataclasses.replace(
            model.config, n_positions=max_position))
        new_model.attn_override = getattr(model, "attn_override", None)
        if getattr(model, "_ever_traced", False):
            new_model._ever_traced = True   # keep the stale-jit warning live
        log_dist(f"extended position embeddings {original} -> {max_position}",
                 ranks=[0])
        return new_model, params

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position):
        """Reference :64 — works on any HF tokenizer."""
        tokenizer.model_max_length = max_position
        tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            model, max_position=None, sparsity_config=None, params=None):
        """Swap the model's attention for block-sparse attention
        (reference :81). Supports bidirectional encoders exposing the
        ``attn_override`` hook (BertModel family). Pass ``params`` (with
        ``max_position``) to also extend the position table in one call.

        Returns the patched model, or (model, params) when params given."""
        if sparsity_config is None:
            sparsity_config = FixedSparsityConfig(
                num_heads=model.config.n_head)
        if getattr(model, "causal_attention", False) or \
                not hasattr(model, "attn_override"):
            raise ValueError(
                f"{type(model).__name__} does not support sparse-attention "
                f"surgery; supported: bidirectional encoders with the "
                f"attn_override hook (BertModel family) — the reference "
                f"supports bert/roberta only "
                f"(sparse_attention_utils.py:110)")
        if params is not None and max_position is not None and \
                max_position > params["wpe"].shape[0]:
            model, params = SparseAttentionUtils.extend_position_embedding(
                model, params, max_position)

        sa = SparseSelfAttention(sparsity_config)   # one layout cache +
        #                                             padding-mask merge

        def sparse_attn(q, k, v, mask):
            return sa(q, k, v, key_padding_mask=mask)

        if getattr(model, "_ever_traced", False):
            # jitted executables compiled before surgery keep their dense
            # attention — the hook is read at trace time
            log_dist("WARNING: sparse-attention surgery installed after the "
                     "model already ran/traced; any jitted step compiled "
                     "earlier (e.g. a deepspeed_tpu engine built before "
                     "this call) keeps DENSE attention. Install surgery "
                     "before building the engine.", ranks=[0])
        model.attn_override = sparse_attn
        log_dist(f"sparse attention installed: "
                 f"{type(sparsity_config).__name__} block="
                 f"{sparsity_config.block}", ranks=[0])
        return model if params is None else (model, params)

    @staticmethod
    def pad_to_block_size(block_size, input_ids, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id=0,
                          model_embeddings=None):
        """Pad sequence inputs to a multiple of the sparsity block
        (reference :143). Returns (pad_len, input_ids, attention_mask,
        token_type_ids, position_ids, inputs_embeds) — padded positions
        carry attention_mask 0 so they can't leak into real tokens."""
        t = (input_ids if input_ids is not None else inputs_embeds).shape[1]
        pad_len = (-t) % block_size
        if pad_len == 0:
            return (0, input_ids, attention_mask, token_type_ids,
                    position_ids, inputs_embeds)

        def pad(x, value=0):
            if x is None:
                return None
            widths = [(0, 0), (0, pad_len)] + \
                [(0, 0)] * (np.ndim(x) - 2)
            return jnp.pad(jnp.asarray(x), widths, constant_values=value)

        if attention_mask is None:
            # always materialize the mask once padding happens — for
            # inputs_embeds-only calls too, or the pad rows would attend
            src = input_ids if input_ids is not None else inputs_embeds
            attention_mask = jnp.ones(src.shape[:2], jnp.int32)
        input_ids = pad(input_ids, pad_token_id)
        attention_mask = pad(attention_mask, 0)
        token_type_ids = pad(token_type_ids, 0)
        position_ids = pad(position_ids, 0)
        if inputs_embeds is not None and model_embeddings is not None:
            pad_embed = jnp.asarray(model_embeddings)[pad_token_id]
            tail = jnp.broadcast_to(
                pad_embed, (inputs_embeds.shape[0], pad_len,
                            inputs_embeds.shape[2]))
            inputs_embeds = jnp.concatenate([inputs_embeds, tail], axis=1)
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        """Reference :193 — strip the pad tail after the forward."""
        if pad_len:
            return sequence_output[:, :-pad_len]
        return sequence_output
