"""Host Adagrad (reference csrc/adagrad/cpu_adagrad.cpp:243) — shares the
cpu_adam native library; separate builder name kept for reference parity
(op_builder/cpu_adagrad.py)."""

from .cpu_adam_ops import get_ops as _get  # same .so, same namespace


def get_ops(backend: str = "cpu"):
    return _get(backend)
