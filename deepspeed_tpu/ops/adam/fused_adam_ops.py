"""Fused (device) Adam — multi-tensor update as one XLA program.

Capability match for the reference FusedAdam
(csrc/adam/multi_tensor_adam.cu:168 ``multi_tensor_adam``,
ops/adam/fused_adam.py): the reference launches one CUDA kernel over chunked
tensor lists; on TPU the same effect — every param's elementwise update fused
into a handful of kernels with no per-tensor launch overhead — comes from
jitting ONE update over the whole pytree and letting XLA fuse. This module
is that update as a standalone op (the engine's in-jit optimizer path uses
optax equivalents; this surface exists for direct users of the op builder).
"""

from types import SimpleNamespace

import jax
import jax.numpy as jnp


def _adam_math(p, g, m, v, step, lr, beta1, beta2, eps, weight_decay,
               decoupled, bias_correction):
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if weight_decay and not decoupled:
        g = g + weight_decay * p32
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    if bias_correction:
        bc1 = 1 - beta1 ** step
        bc2 = 1 - beta2 ** step
    else:
        bc1 = bc2 = jnp.float32(1.0)
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if weight_decay and decoupled:
        update = update + weight_decay * p32
    return (p32 - lr * update).astype(p.dtype), m, v


from functools import partial


@partial(jax.jit, static_argnums=(9, 10, 11))
def _fused_adam(params, grads, m, v, step, lr, beta1, beta2, eps,
                weight_decay, decoupled, bias_correction):
    p_flat, treedef = jax.tree.flatten(params)
    g_flat = jax.tree.leaves(grads)
    m_flat = jax.tree.leaves(m)
    v_flat = jax.tree.leaves(v)
    outs = [_adam_math(p, g, mm, vv, step, lr, beta1, beta2, eps,
                       weight_decay, decoupled, bias_correction)
            for p, g, mm, vv in zip(p_flat, g_flat, m_flat, v_flat)]
    new_p, new_m, new_v = zip(*outs)
    return (jax.tree.unflatten(treedef, new_p),
            jax.tree.unflatten(treedef, new_m),
            jax.tree.unflatten(treedef, new_v))


def fused_adam(params, grads, m, v, step, lr, beta1=0.9, beta2=0.999,
               eps=1e-8, weight_decay=0.0, adam_w_mode=True,
               bias_correction=True):
    """One Adam step over a pytree (or single array). Returns
    (params, m, v). m/v are fp32 pytrees shaped like params."""
    return _fused_adam(params, grads, m, v, jnp.float32(step),
                       jnp.float32(lr), jnp.float32(beta1),
                       jnp.float32(beta2), jnp.float32(eps),
                       float(weight_decay), bool(adam_w_mode),
                       bool(bias_correction))


def init_state(params):
    zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    return zeros, jax.tree.map(jnp.copy, zeros)


def get_ops(backend: str = "tpu"):
    return SimpleNamespace(fused_adam=fused_adam, init_state=init_state)
