"""Host (CPU) Adam — the optimizer for offloaded ZeRO partitions.

Capability match for the reference's DeepSpeedCPUAdam
(csrc/adam/cpu_adam.cpp:303-308, ops/adam/cpu_adam.py): fp32 master weights +
moments live in host RAM; the step runs on host SIMD cores via the C++
extension (ops/csrc/cpu_adam.cpp) while the TPU computes the next micro-batch.
A numpy fallback keeps the op functional where no C++ toolchain exists (the
reference hard-fails there; we degrade with a warning since the math is
identical, just slower).

Loaded through CPUAdamBuilder (ops/op_builder.py) / the accelerator seam.
"""

import ctypes
from types import SimpleNamespace

import numpy as np

from ..native_build import NativeBuildError, load_library
from ...utils.logging import logger

try:
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None

_f32p = ctypes.POINTER(ctypes.c_float)
_u16p = ctypes.POINTER(ctypes.c_uint16)


def _ptr(a: np.ndarray, typ=_f32p):
    return a.ctypes.data_as(typ)


def _lib():
    lib = load_library("cpu_adam")
    lib.ds_adam_step.restype = ctypes.c_int
    lib.ds_adam_step.argtypes = [
        _f32p, _f32p, _f32p, _f32p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_int, ctypes.c_int]
    lib.ds_adam_step_copy_bf16.restype = ctypes.c_int
    lib.ds_adam_step_copy_bf16.argtypes = [
        _f32p, _f32p, _f32p, _f32p, _u16p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_int, ctypes.c_int]
    lib.ds_adagrad_step.restype = ctypes.c_int
    lib.ds_adagrad_step.argtypes = [
        _f32p, _f32p, _f32p, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
        ctypes.c_float]
    lib.ds_lion_step.restype = ctypes.c_int
    lib.ds_lion_step.argtypes = [
        _f32p, _f32p, _f32p, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_float]
    lib.ds_norm_sq.restype = ctypes.c_double
    lib.ds_norm_sq.argtypes = [_f32p, ctypes.c_int64]
    lib.ds_has_nonfinite.restype = ctypes.c_int
    lib.ds_has_nonfinite.argtypes = [_f32p, ctypes.c_int64]
    lib.ds_scale.restype = ctypes.c_int
    lib.ds_scale.argtypes = [_f32p, ctypes.c_int64, ctypes.c_float]
    lib.ds_fp32_to_bf16.restype = ctypes.c_int
    lib.ds_fp32_to_bf16.argtypes = [_f32p, _u16p, ctypes.c_int64]
    return lib


def _check(a, dtype=np.float32):
    assert isinstance(a, np.ndarray) and a.dtype == dtype and \
        a.flags["C_CONTIGUOUS"], f"need contiguous {dtype} array, got {a.dtype}"


class NativeHostOps:
    """ctypes surface over libcpu_adam."""

    def __init__(self):
        self.lib = _lib()
        self.native = True

    def adam_step(self, w, g, m, v, step, lr, beta1, beta2, eps,
                  weight_decay=0.0, decoupled=True, bias_correction=True,
                  w16=None):
        for a in (w, g, m, v):
            _check(a)
        if w16 is not None:
            assert _BF16 is not None and w16.dtype == _BF16
            self.lib.ds_adam_step_copy_bf16(
                _ptr(w), _ptr(g), _ptr(m), _ptr(v),
                w16.ctypes.data_as(_u16p), w.size, step, lr, beta1, beta2,
                eps, weight_decay, int(decoupled), int(bias_correction))
        else:
            self.lib.ds_adam_step(
                _ptr(w), _ptr(g), _ptr(m), _ptr(v), w.size, step, lr, beta1,
                beta2, eps, weight_decay, int(decoupled), int(bias_correction))

    def adagrad_step(self, w, g, acc, lr, eps, weight_decay=0.0):
        for a in (w, g, acc):
            _check(a)
        self.lib.ds_adagrad_step(_ptr(w), _ptr(g), _ptr(acc), w.size, lr, eps,
                                 weight_decay)

    def lion_step(self, w, g, m, lr, beta1, beta2, weight_decay=0.0):
        for a in (w, g, m):
            _check(a)
        self.lib.ds_lion_step(_ptr(w), _ptr(g), _ptr(m), w.size, lr, beta1,
                              beta2, weight_decay)

    def norm_sq(self, x) -> float:
        _check(x)
        return float(self.lib.ds_norm_sq(_ptr(x), x.size))

    def has_nonfinite(self, x) -> bool:
        _check(x)
        return bool(self.lib.ds_has_nonfinite(_ptr(x), x.size))

    def scale_(self, x, a):
        _check(x)
        self.lib.ds_scale(_ptr(x), x.size, a)

    def fp32_to_bf16(self, src, dst):
        _check(src)
        assert _BF16 is not None and dst.dtype == _BF16
        self.lib.ds_fp32_to_bf16(_ptr(src), dst.ctypes.data_as(_u16p),
                                 src.size)


class NumpyHostOps:
    """Pure-numpy fallback with identical semantics (slower)."""

    native = False

    def adam_step(self, w, g, m, v, step, lr, beta1, beta2, eps,
                  weight_decay=0.0, decoupled=True, bias_correction=True,
                  w16=None):
        grad = g if (decoupled or weight_decay == 0.0) else g + weight_decay * w
        m *= beta1
        m += (1 - beta1) * grad
        v *= beta2
        v += (1 - beta2) * np.square(grad)
        if bias_correction:
            bc1 = 1 - beta1 ** step
            bc2 = 1 - beta2 ** step
        else:
            bc1 = bc2 = 1.0
        update = (m / bc1) / (np.sqrt(v / bc2) + eps)
        if decoupled and weight_decay != 0.0:
            update = update + weight_decay * w
        w -= lr * update
        if w16 is not None:
            w16[...] = w.astype(w16.dtype)

    def adagrad_step(self, w, g, acc, lr, eps, weight_decay=0.0):
        grad = g if weight_decay == 0.0 else g + weight_decay * w
        acc += np.square(grad)
        w -= lr * grad / (np.sqrt(acc) + eps)

    def lion_step(self, w, g, m, lr, beta1, beta2, weight_decay=0.0):
        update = np.sign(beta1 * m + (1 - beta1) * g)
        if weight_decay != 0.0:
            update = update + weight_decay * w
        w -= lr * update
        m *= beta2
        m += (1 - beta2) * g

    def norm_sq(self, x) -> float:
        return float(np.sum(np.square(x, dtype=np.float64)))

    def has_nonfinite(self, x) -> bool:
        return not bool(np.all(np.isfinite(x)))

    def scale_(self, x, a):
        x *= a

    def fp32_to_bf16(self, src, dst):
        dst[...] = src.astype(dst.dtype)


_cached = None


def get_ops(backend: str = "cpu"):
    """Builder entry. backend is advisory; host ops always run on the host."""
    global _cached
    if _cached is None:
        try:
            _cached = NativeHostOps()
        except (NativeBuildError, OSError) as e:
            logger.warning(f"cpu_adam native build unavailable ({e}); "
                           f"falling back to numpy host ops")
            _cached = NumpyHostOps()
    return _cached


def bf16_dtype():
    return _BF16


def get_host_ops():
    return get_ops()


def ops_namespace(backend: str = "cpu"):
    ops = get_ops(backend)
    return SimpleNamespace(
        adam_step=ops.adam_step, adagrad_step=ops.adagrad_step,
        lion_step=ops.lion_step, norm_sq=ops.norm_sq,
        has_nonfinite=ops.has_nonfinite, scale_=ops.scale_,
        fp32_to_bf16=ops.fp32_to_bf16, native=ops.native)
