"""Pallas-TPU block-sparse attention that SKIPS masked blocks.

FLOP-skipping counterpart of the reference's Triton SDD/DSD kernels
(reference deepspeed/ops/sparse_attention/matmul.py:17 block-CSR matmuls,
softmax.py): the dense-masked XLA path (ops/sparse_attention_ops.py)
computes every (q, k) tile and masks; this kernel iterates ONLY the live
key tiles of each query tile, driven by a compacted per-(head, q-tile)
column list delivered through scalar prefetch — the column index feeds the
K/V BlockSpec index_map, so dead tiles are neither DMA'd nor computed.

Design:
- The SparsityConfig layout ([H, nb, nb] bool at its own fine ``block``,
  typically 16) is coarsened to TPU-sized tiles (``tile``, default 256):
  a tile is live if any fine block inside it is. Fine-grained masking
  within a live tile comes from the fine layout, delivered as an int8
  input windowed per tile pair and expanded in-kernel.
- grid = (B*H, q_tiles, max_nnz); online-softmax accumulators persist in
  VMEM scratch across the innermost (key-tile) grid dim; steps beyond the
  row's nnz are compute-skipped with pl.when.
- backward: dq mirrors the forward (recompute p from lse); dk/dv iterate
  the TRANSPOSED plan (per key tile, the live q tiles).

Numerics oracle: the dense-masked path; parity is tested in interpret
mode (tests/unit/test_block_sparse.py) for Fixed/BigBird/Longformer
layouts including per-head patterns, forward and grads.
"""

import functools
from typing import NamedTuple, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


class _Plan(NamedTuple):
    """Host-side routing plan for one (layout, tile) pair."""
    kcols: np.ndarray      # [H, nt, max_nnz] i32 — live key-tile ids (padded
    #                        with the last live id so dead DMAs stay in range)
    nnz: np.ndarray        # [H, nt] i32
    qrows_t: np.ndarray    # [H, nt, max_nnz_t] i32 — transposed plan
    nnz_t: np.ndarray      # [H, nt] i32
    coarse: np.ndarray     # [H, nt, nt] bool
    tile: int
    fine_block: int


def build_plan(layout: np.ndarray, fine_block: int, tile: int) -> _Plan:
    layout = np.asarray(layout, bool)
    h, nb, _ = layout.shape
    r = tile // fine_block
    if tile % fine_block or nb % r:
        raise ValueError(f"tile {tile} incompatible with layout blocks "
                         f"{fine_block} x {nb}")
    nt = nb // r
    coarse = layout.reshape(h, nt, r, nt, r).any(axis=(2, 4))

    def compact(mat):  # [H, nt, nt] -> padded index lists along last dim
        nnz = mat.sum(-1).astype(np.int32)
        width = max(1, int(nnz.max()))
        idx = np.zeros((h, nt, width), np.int32)
        for hh in range(h):
            for i in range(nt):
                cols = np.nonzero(mat[hh, i])[0]
                idx[hh, i, :len(cols)] = cols
                if len(cols):          # pad with a live id (in-range DMA)
                    idx[hh, i, len(cols):] = cols[-1]
        return idx, nnz

    kcols, nnz = compact(coarse)
    qrows_t, nnz_t = compact(coarse.transpose(0, 2, 1))
    return _Plan(kcols, nnz, qrows_t, nnz_t, coarse, tile, fine_block)


# Mosaic requires the last two BlockSpec dims to be (8k, 128m): the r x r
# fine window is shipped padded inside an (8, 128) f32 tile
_FINE_PAD = (8, 128)


def pack_fine_windows(layout: np.ndarray, tile: int,
                      fine_block: int) -> np.ndarray:
    """[H, nb, nb] bool -> [H, nt, nt, 8, 128] f32 padded windows."""
    h, nb, _ = layout.shape
    r = tile // fine_block
    nt = nb // r
    win = layout.reshape(h, nt, r, nt, r).transpose(0, 1, 3, 2, 4)
    out = np.zeros((h, nt, nt) + _FINE_PAD, np.float32)
    out[..., :r, :r] = win
    return out


def _expand_fine(sub_padded, tile, fine_block):
    """[8, 128] padded fine window -> [tile, tile] bool keep-mask via two
    one-hot expansion matmuls (gathers don't lower on Mosaic; the MXU
    expansion always does): keep = E^T (sub) E with E[i, j] = [j//fb == i]."""
    r = tile // fine_block
    sub = sub_padded[:r, :r]
    e = (lax.broadcasted_iota(jnp.int32, (r, tile), 1) // fine_block ==
         lax.broadcasted_iota(jnp.int32, (r, tile), 0)).astype(jnp.float32)
    expanded = jnp.dot(e.T, jnp.dot(sub, e,
                                    preferred_element_type=jnp.float32),
                       preferred_element_type=jnp.float32)
    return expanded > 0.5


# --------------------------------------------------------------------- forward

def _fwd_kernel(kcols_ref, nnz_ref, q_ref, k_ref, v_ref, fine_ref,
                o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                scale, tile, fine_block, n_heads, max_nnz):
    b = pl.program_id(0)
    j = pl.program_id(2)
    h = b % n_heads
    i = pl.program_id(1)

    @pl.when(j == 0)
    def init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j < nnz_ref[h, i])
    def compute():
        q = q_ref[0]                              # [tile, D]
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        keep = _expand_fine(fine_ref[0, 0, 0], tile, fine_block)
        s = jnp.where(keep, s, NEG_INF)
        m, l, acc = m_ref[...], l_ref[...], acc_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(j == max_nnz - 1)
    def finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[...] + jnp.log(l)).astype(jnp.float32)


def _fwd(q, k, v, plan: _Plan, fine_i8, scale, interpret):
    b, h, t, d = q.shape
    bh = b * h
    tile = plan.tile
    nt = t // tile
    max_nnz = plan.kcols.shape[-1]
    qf, kf, vf = (x.reshape(bh, t, d) for x in (q, k, v))
    r = tile // plan.fine_block

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nt, max_nnz),
        in_specs=[
            pl.BlockSpec((1, tile, d), lambda b_, i, j, kc, nz: (b_, i, 0)),
            pl.BlockSpec((1, tile, d),
                         lambda b_, i, j, kc, nz, nh=h: (
                             b_, kc[b_ % nh, i, j], 0)),
            pl.BlockSpec((1, tile, d),
                         lambda b_, i, j, kc, nz, nh=h: (
                             b_, kc[b_ % nh, i, j], 0)),
            pl.BlockSpec((1, 1, 1) + _FINE_PAD,
                         lambda b_, i, j, kc, nz, nh=h: (
                             b_ % nh, i, kc[b_ % nh, i, j], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile, d), lambda b_, i, j, kc, nz: (b_, i, 0)),
            pl.BlockSpec((1, tile, 1), lambda b_, i, j, kc, nz: (b_, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile, d), jnp.float32),
            pltpu.VMEM((tile, 1), jnp.float32),
            pltpu.VMEM((tile, 1), jnp.float32),
        ],
    )
    # fine layout windowed [r, r] per (h, q-tile, k-tile): reshape to
    # [H, nt*r(=nb), nt*r] is exactly the fine layout itself
    kernel = functools.partial(
        _fwd_kernel, scale=scale, tile=tile, fine_block=plan.fine_block,
        n_heads=h, max_nnz=max_nnz)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, t, 1), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(plan.kcols), jnp.asarray(plan.nnz), qf, kf, vf, fine_i8)
    return out.reshape(b, h, t, d), lse.reshape(b, h, t, 1)


# -------------------------------------------------------------------- backward

def _dq_kernel(kcols_ref, nnz_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, fine_ref, dq_ref, dq_acc_ref, *,
               scale, tile, fine_block, n_heads, max_nnz):
    b = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    h = b % n_heads

    @pl.when(j == 0)
    def init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    @pl.when(j < nnz_ref[h, i])
    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        keep = _expand_fine(fine_ref[0, 0, 0], tile, fine_block)
        s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc_ref[...] += jnp.dot(ds.astype(k.dtype), k,
                                   preferred_element_type=jnp.float32)

    @pl.when(j == max_nnz - 1)
    def flush():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(qrows_ref, nnz_t_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, fine_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                *, scale, tile, fine_block, n_heads, max_nnz_t):
    b = pl.program_id(0)
    jt = pl.program_id(1)              # key tile
    it = pl.program_id(2)              # position in its live-q list
    h = b % n_heads

    @pl.when(it == 0)
    def init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    @pl.when(it < nnz_t_ref[h, jt])
    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        keep = _expand_fine(fine_ref[0, 0, 0], tile, fine_block)
        s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv_acc_ref[...] += jnp.dot(p.astype(do.dtype).T, do,
                                   preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc_ref[...] += jnp.dot(ds.astype(q.dtype).T, q,
                                   preferred_element_type=jnp.float32)

    @pl.when(it == max_nnz_t - 1)
    def flush():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, plan: _Plan, fine_i8, scale, interpret):
    b, h, t, d = q.shape
    bh = b * h
    tile = plan.tile
    nt = t // tile
    r = tile // plan.fine_block
    max_nnz = plan.kcols.shape[-1]
    max_nnz_t = plan.qrows_t.shape[-1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    qf, kf, vf, dof = (x.reshape(bh, t, d) for x in (q, k, v, do))
    lsef = lse.reshape(bh, t, 1)
    deltaf = delta.reshape(bh, t, 1)

    q_at_i = pl.BlockSpec((1, tile, d), lambda b_, i, j, kc, nz: (b_, i, 0))
    vec_at_i = pl.BlockSpec((1, tile, 1), lambda b_, i, j, kc, nz: (b_, i, 0))
    kv_at_col = pl.BlockSpec(
        (1, tile, d), lambda b_, i, j, kc, nz, nh=h: (b_, kc[b_ % nh, i, j], 0))
    fine_at = pl.BlockSpec(
        (1, 1, 1) + _FINE_PAD, lambda b_, i, j, kc, nz, nh=h: (
            b_ % nh, i, kc[b_ % nh, i, j], 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, tile=tile,
                          fine_block=plan.fine_block, n_heads=h,
                          max_nnz=max_nnz),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nt, max_nnz),
            in_specs=[q_at_i, kv_at_col, kv_at_col, q_at_i, vec_at_i,
                      vec_at_i, fine_at],
            out_specs=q_at_i,
            scratch_shapes=[pltpu.VMEM((tile, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(plan.kcols), jnp.asarray(plan.nnz),
      qf, kf, vf, dof, lsef, deltaf, fine_i8)

    # transposed plan: grid (bh, key tile, live-q position)
    q_at_row = pl.BlockSpec(
        (1, tile, d), lambda b_, jt, it, qr, nz, nh=h: (
            b_, qr[b_ % nh, jt, it], 0))
    vec_at_row = pl.BlockSpec(
        (1, tile, 1), lambda b_, jt, it, qr, nz, nh=h: (
            b_, qr[b_ % nh, jt, it], 0))
    kv_at_jt = pl.BlockSpec((1, tile, d),
                            lambda b_, jt, it, qr, nz: (b_, jt, 0))
    fine_at_t = pl.BlockSpec(
        (1, 1, 1) + _FINE_PAD, lambda b_, jt, it, qr, nz, nh=h: (
            b_ % nh, qr[b_ % nh, jt, it], jt, 0, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, tile=tile,
                          fine_block=plan.fine_block, n_heads=h,
                          max_nnz_t=max_nnz_t),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nt, max_nnz_t),
            in_specs=[q_at_row, kv_at_jt, kv_at_jt, q_at_row, vec_at_row,
                      vec_at_row, fine_at_t],
            out_specs=[kv_at_jt, kv_at_jt],
            scratch_shapes=[pltpu.VMEM((tile, d), jnp.float32),
                            pltpu.VMEM((tile, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, t, d), v.dtype)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(plan.qrows_t), jnp.asarray(plan.nnz_t),
      qf, kf, vf, dof, lsef, deltaf, fine_i8)
    return (dq.reshape(b, h, t, d), dk.reshape(b, h, t, d),
            dv.reshape(b, h, t, d))


# ------------------------------------------------------------------ public op

# custom_vjp static args must be hashable: plans live in this registry and
# cross the custom_vjp boundary as a compact digest key. Bounded FIFO (a
# training run cycles a handful of layouts; runaway layout generation must
# not leak plans).
_PLAN_CACHE = {}
_PLAN_CACHE_MAX = 32


def _get_plan(layout_key, layout=None, fine_block=None, tile=None):
    if layout_key not in _PLAN_CACHE:
        if layout is None:
            raise KeyError(
                f"block-sparse plan {layout_key!r} evicted — rebuild via "
                f"sparse_attention_pallas")
        while len(_PLAN_CACHE) >= _PLAN_CACHE_MAX:
            _PLAN_CACHE.pop(next(iter(_PLAN_CACHE)))
        _PLAN_CACHE[layout_key] = (
            build_plan(layout, fine_block, tile),
            jnp.asarray(pack_fine_windows(layout, tile, fine_block)))
    return _PLAN_CACHE[layout_key]


def default_tile(t: int, fine_block: int) -> int:
    for cand in (256, 128):
        if (t % cand == 0 and cand % fine_block == 0 and cand <= t and
                cand // fine_block <= _FINE_PAD[0]):
            return cand
    return fine_block if fine_block >= 128 else 0


def supported(q, layout, fine_block: int, tile: int = 0) -> bool:
    t, d = q.shape[-2], q.shape[-1]
    tile = tile or default_tile(t, fine_block)
    if tile < 128:                 # sub-lane tiles can't feed the MXU
        return False
    if tile // fine_block > _FINE_PAD[0]:   # fine window must fit (8, 128)
        return False
    nb = np.asarray(layout).shape[-1]
    return (q.ndim == 4 and t % tile == 0 and d % 8 == 0 and d <= 256 and
            nb * fine_block == t)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def block_sparse_attention(q, k, v, fine_i8, layout_key, scale,
                           interpret=False):
    out, _ = _bsa_fwd(q, k, v, fine_i8, layout_key, scale, interpret)
    return out


def _bsa_fwd(q, k, v, fine_i8, layout_key, scale, interpret):
    plan, _ = _get_plan(layout_key)
    out, lse = _fwd(q, k, v, plan, fine_i8, scale, interpret)
    return out, (q, k, v, fine_i8, out, lse)


def _bsa_bwd(layout_key, scale, interpret, res, g):
    plan, _ = _get_plan(layout_key)
    q, k, v, fine_i8, out, lse = res
    dq, dk, dv = _bwd(q, k, v, out, lse, g, plan, fine_i8, scale, interpret)
    return dq, dk, dv, None


block_sparse_attention.defvjp(_bsa_fwd, _bsa_bwd)


def sparse_attention_pallas(q, k, v, layout, fine_block: int,
                            softmax_scale=None, tile: int = 0,
                            interpret: bool = False):
    """Block-skipping sparse attention behind the SparsityConfig layout
    contract. q/k/v: [B, H, T, D]; layout: [H, nb, nb] bool numpy."""
    t, d = q.shape[-2], q.shape[-1]
    tile = tile or default_tile(t, fine_block)
    if not supported(q, layout, fine_block, tile):
        raise ValueError(
            f"unsupported shapes for the pallas block-sparse kernel: "
            f"t={t} d={d} tile={tile} fine_block={fine_block}")
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    import hashlib
    layout_np = np.asarray(layout, bool)
    key = (hashlib.sha1(layout_np.tobytes()).hexdigest(),
           layout_np.shape, fine_block, tile)
    _, fine_win = _get_plan(key, layout_np, fine_block, tile)
    return block_sparse_attention(q, k, v, fine_win, key, scale, interpret)
