"""Pallas-TPU flash attention (forward + backward).

TPU-native replacement for the reference's fused attention CUDA kernels
(reference csrc/transformer/softmax_kernels.cu and
csrc/transformer/ds_transformer_cuda.cpp:1037 fused layer): blocked
online-softmax attention that never materializes the [T, T] probability
matrix in HBM.

Design (not a port — shaped by the TPU memory hierarchy):
- grid = (batch, heads, num_q_blocks). Each program holds one Q block and the
  FULL K/V for its (b, h) slice in VMEM (T=8k, D=64, bf16 -> 1 MB each), and
  runs an online-softmax ``fori_loop`` over K/V blocks. Because the q-block
  index varies fastest, Pallas keeps the K/V block resident across the inner
  grid steps — K/V are fetched from HBM once per (b, h).
- causal masking prunes the K/V loop at the diagonal (dynamic trip count),
  so the kernel does ~half the work of the dense path.
- softmax statistics (m, l) are fp32 [BLK_Q, 1]; matmuls run on the MXU with
  ``preferred_element_type=f32``; inputs stay bf16.
- backward recomputes P from (q, k, lse) — flash-attention style — with two
  kernels: dq (grid over q blocks) and dk/dv (grid over k blocks), plus a
  cheap XLA precompute of delta = rowsum(dO * O).

The XLA reference path (ops/flash_attention.reference_attention) is the
numerics oracle; tests compare both fwd and grads (interpret mode on CPU).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_NT = (((1,), (1,)), ((), ()))   # [M,D]x[N,D] -> [M,N]
_NN = (((1,), (0,)), ((), ()))   # [M,K]x[K,N] -> [M,N]


def _pick_block(t: int) -> int:
    for blk in (512, 256, 128):
        if t % blk == 0:
            return blk
    raise ValueError(f"sequence length {t} not divisible by 128")


def supported(q, k, causal=True, mask=None, dropout_rate=0.0) -> bool:
    """Static shape/feature check for the Pallas path."""
    if mask is not None or dropout_rate > 0.0:
        return False
    if q.ndim != 4 or q.shape[-2] != k.shape[-2]:
        return False
    t, d = q.shape[-2], q.shape[-1]
    # full K/V per (b, h) must fit VMEM alongside fp32 accumulators: cap the
    # resident footprint; longer sequences belong to ring attention (SP)
    if t * d * q.dtype.itemsize > 4 * 1024 * 1024:
        return False
    return t >= 128 and t % 128 == 0 and d % 8 == 0 and d <= 256


# --------------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale,
                block_q, block_k, t_k):
    q = q_ref[0, 0]                              # [BQ, D]
    q_off = pl.program_id(2) * block_q
    nk = pl.cdiv(q_off + block_q, block_k) if causal else t_k // block_k

    def body(j, carry):
        acc, m, l = carry
        k_j = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_j = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k_j, _NT,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_off + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            p.astype(v_j.dtype), v_j, _NN, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = lax.fori_loop(0, nk, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, t, d = q.shape
    grid = (b, h, t // block_q)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k, t_k=t)
    flops = 4 * b * h * t * t * d // (2 if causal else 1)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, t, d), lambda bi, hi, i: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, t, d), lambda bi, hi, i: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, i: (bi, hi, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(flops),
            bytes_accessed=(3 * b * h * t * d + b * h * t * d) * q.dtype.itemsize,
            transcendentals=b * h * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# -------------------------------------------------------------------- backward

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   causal, scale, block_q, block_k, t_k):
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0]                          # [BQ, 1]
    delta = delta_ref[0, 0]
    q_off = pl.program_id(2) * block_q
    nk = pl.cdiv(q_off + block_q, block_k) if causal else t_k // block_k

    def body(j, dq):
        k_j = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_j = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k_j, _NT,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_off + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                     # [BQ, BK]
        dp = lax.dot_general(do, v_j, _NT, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + lax.dot_general(ds.astype(k_j.dtype), k_j, _NN,
                                    preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, nk, body,
                       jnp.zeros((block_q, q.shape[-1]), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, causal, scale, block_q, block_k, t_q):
    k_blk = k_ref[0, 0]                          # [BK, D]
    v_blk = v_ref[0, 0]
    k_off = pl.program_id(2) * block_k
    nq = t_q // block_q
    start = k_off // block_q if causal else 0

    def body(i, carry):
        dk, dv = carry
        q_i = q_ref[0, 0, pl.ds(i * block_q, block_q), :]
        do_i = do_ref[0, 0, pl.ds(i * block_q, block_q), :]
        lse_i = lse_ref[0, 0, pl.ds(i * block_q, block_q), :]
        delta_i = delta_ref[0, 0, pl.ds(i * block_q, block_q), :]
        s = lax.dot_general(q_i, k_blk, _NT,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_off + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_i)                   # [BQ, BK]
        dv_new = dv + lax.dot_general(
            p.astype(do_i.dtype), do_i,
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = lax.dot_general(do_i, v_blk, _NT,
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_i) * scale          # [BQ, BK]
        dk_new = dk + lax.dot_general(
            ds.astype(q_i.dtype), q_i,
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dk_new, dv_new

    d = k_blk.shape[-1]
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(start, nq, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, causal, scale, block_q, block_k, interpret):
    b, h, t, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)      # [B, H, T, 1]

    blk_spec = pl.BlockSpec((1, 1, block_q, d),
                            lambda bi, hi, i: (bi, hi, i, 0))
    full_spec = lambda tt: pl.BlockSpec((1, 1, tt, d),
                                        lambda bi, hi, i: (bi, hi, 0, 0))
    vec_blk = pl.BlockSpec((1, 1, block_q, 1),
                           lambda bi, hi, i: (bi, hi, i, 0))
    vec_full = pl.BlockSpec((1, 1, t, 1), lambda bi, hi, i: (bi, hi, 0, 0))
    flops = 4 * b * h * t * t * d // (2 if causal else 1)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, t_k=t),
        grid=(b, h, t // block_q),
        in_specs=[blk_spec, full_spec(t), full_spec(t), blk_spec,
                  vec_blk, vec_blk],
        out_specs=blk_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=int(flops * 1.5),
            bytes_accessed=5 * b * h * t * d * q.dtype.itemsize,
            transcendentals=b * h * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    kv_blk = pl.BlockSpec((1, 1, block_k, d),
                          lambda bi, hi, j: (bi, hi, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, t_q=t),
        grid=(b, h, t // block_k),
        in_specs=[full_spec(t), kv_blk, kv_blk, full_spec(t),
                  vec_full, vec_full],
        out_specs=[kv_blk, kv_blk],
        out_shape=[jax.ShapeDtypeStruct((b, h, t, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, t, d), v.dtype)],
        cost_estimate=pl.CostEstimate(
            flops=int(flops * 2.5),
            bytes_accessed=6 * b * h * t * d * q.dtype.itemsize,
            transcendentals=b * h * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------------ public op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=True, softmax_scale=None,
                    block_q=None, block_k=None, interpret=False):
    """Blocked flash attention. q,k,v: [B, H, T, D]; returns [B, H, T, D]."""
    out, _ = _flash_fwd(q, k, v, causal, softmax_scale, block_q, block_k,
                        interpret)
    return out


def _resolve(q, softmax_scale, block_q, block_k):
    t, d = q.shape[-2], q.shape[-1]
    if t % 128 != 0:
        raise ValueError(
            f"pallas flash attention requires seq length divisible by 128, "
            f"got {t}; use the XLA backend for this shape")
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    blk = _pick_block(t)
    block_q, block_k = block_q or blk, block_k or blk
    if t % block_q or t % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"sequence length {t}")
    return scale, block_q, block_k


def _flash_fwd(q, k, v, causal, softmax_scale, block_q, block_k, interpret):
    scale, bq, bk = _resolve(q, softmax_scale, block_q, block_k)
    out, lse = _fwd(q, k, v, causal, scale, bq, bk, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, softmax_scale, block_q, block_k, interpret,
               residuals, g):
    q, k, v, out, lse = residuals
    scale, bq, bk = _resolve(q, softmax_scale, block_q, block_k)
    dq, dk, dv = _bwd(q, k, v, out, lse, g, causal, scale, bq, bk, interpret)
    return dq, dk, dv


flash_attention.defvjp(lambda q, k, v, c, s, bq, bk, it:
                       _flash_fwd(q, k, v, c, s, bq, bk, it),
                       _flash_bwd)
