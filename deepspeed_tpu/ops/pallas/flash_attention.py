"""Pallas-TPU flash attention (forward + backward).

TPU-native replacement for the reference's fused attention CUDA kernels
(reference csrc/transformer/softmax_kernels.cu and
csrc/transformer/ds_transformer_cuda.cpp:1037 fused layer): blocked
online-softmax attention that never materializes the [T, T] probability
matrix in HBM.

Design (not a port — shaped by the TPU memory hierarchy AND by profiling):
- (batch, head) pairs are folded: each grid step processes GH heads at once
  with batched ``dot_general``s. Round-2 profiling showed the per-grid-step
  overhead dominating at GPT-2 scale (B=8, H=12, T=1024, D=64: the fwd
  kernel ran in the SAME wall time for causal and non-causal, and for every
  block size — the per-step matmuls were ~1.4 us of MXU work against ~2.6 us
  of step overhead). Folding GH=4..8 heads per step cuts the grid 4-8x and
  makes each step's matmul [GH, BQ, D] x [GH, D, BK] — big enough to hide
  the overhead.
- grid = (BH/GH, num_q_blocks). Each program holds GH heads' Q block and
  their FULL K/V in VMEM and runs an online-softmax ``fori_loop`` over K/V
  blocks; K/V stay resident across the inner q-block grid dim.
- causal masking prunes the K/V loop at the diagonal (dynamic trip count).
- softmax statistics (m, l) are fp32 [GH, BQ, 1]; matmuls run on the MXU
  with ``preferred_element_type=f32``; inputs stay bf16.
- backward recomputes P from (q, k, lse) — flash-attention style — with two
  kernels: dq (grid over q blocks) and dk/dv (grid over k blocks), plus a
  cheap XLA precompute of delta = rowsum(dO * O).

The XLA reference path (ops/flash_attention.reference_attention) is the
numerics oracle; tests compare both fwd and grads (interpret mode on CPU).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# batched dims: [GH,M,D] x [GH,N,D] -> [GH,M,N] (contract last, batch first)
_BNT = (((2,), (2,)), ((0,), (0,)))
# batched dims: [GH,M,K] x [GH,K,N] -> [GH,M,N]
_BNN = (((2,), (1,)), ((0,), (0,)))
# batched dims: [GH,K,M] x [GH,K,N] -> [GH,M,N] (contract first non-batch)
_BTN = (((1,), (1,)), ((0,), (0,)))

# Pallas double-buffers grid-windowed inputs, and Mosaic needs stack room
# for fp32 temporaries — budget well under the 16M scoped-vmem limit (the
# train-step context proved tighter than a standalone call: GH=4 at
# T=1024/D=64 compiled alone but blew scoped vmem inside the fused step).
# Env override for experiments: DSTPU_FLASH_VMEM_BUDGET (bytes).
import os as _os
_VMEM_BUDGET = int(_os.environ.get("DSTPU_FLASH_VMEM_BUDGET",
                                   3 * 1024 * 1024))


def _mask(s, q_off, k_off, gh, block_q, block_k, window):
    """Causal (+ optional sliding-window) keep-mask applied to one
    [GH, BQ, BK] logits block — shared by the resident and streamed
    fwd/dq/dkv kernels."""
    q_pos = q_off + lax.broadcasted_iota(jnp.int32, (gh, block_q, block_k), 1)
    k_pos = k_off + lax.broadcasted_iota(jnp.int32, (gh, block_q, block_k), 2)
    keep = q_pos >= k_pos
    if window is not None:
        keep &= (q_pos - k_pos) < window
    return jnp.where(keep, s, NEG_INF)


def _pick_blocks(t: int):
    """Largest preferred block sizes that divide t (t % 128 == 0 is already
    guaranteed by supported()/_resolve, so 128 always works). Env override
    for experiments: DSTPU_FLASH_BQ / DSTPU_FLASH_BK."""
    bq = next(b for b in (512, 256, 128) if t % b == 0)
    bk = next(b for b in (256, 128) if t % b == 0)
    bq = int(_os.environ.get("DSTPU_FLASH_BQ", bq))
    bk = int(_os.environ.get("DSTPU_FLASH_BK", bk))
    return min(t, bq), min(t, bk)


def _pick_gh(bh: int, t: int, d: int, bq: int, bk: int,
             itemsize: int = 2) -> int:
    """Largest head fold whose resident footprint fits the VMEM budget.
    ``itemsize`` is the q/k/v element size (2 for bf16, 4 for fp32 —
    fp32 inputs double the K/V, q/o and p footprints)."""
    for gh in (8, 4, 2, 1):
        if bh % gh:
            continue
        s_bytes = gh * bq * bk * (4 + itemsize)   # fp32 s + p copy
        kv_bytes = 2 * gh * t * d * itemsize
        qo_bytes = gh * bq * d * (2 * itemsize + 4)   # q, o, fp32 acc
        if s_bytes + kv_bytes + qo_bytes <= _VMEM_BUDGET:
            return gh
    return 1


# Above this K/V footprint the resident kernels (full K/V per head in VMEM)
# give way to the streamed kernels (k-blocks as a grid dimension, online
# accumulators in scratch) — the long-context single-chip path.
_RESIDENT_MAX_KV_BYTES = 1024 * 1024


def _streamed(t: int, d: int, itemsize: int) -> bool:
    return t * d * itemsize > _RESIDENT_MAX_KV_BYTES


def _pick_gh_streamed(bh: int, d: int, bq: int, bk: int,
                      itemsize: int = 2) -> int:
    for gh in (8, 4, 2, 1):
        if bh % gh:
            continue
        s_bytes = gh * bq * bk * (4 + itemsize)
        kv_bytes = 2 * gh * bk * d * itemsize * 2  # double-buffered blocks
        qo_bytes = gh * bq * d * (2 * itemsize + 4 * 3)  # q, o, acc+m+l f32
        if s_bytes + kv_bytes + qo_bytes <= _VMEM_BUDGET:
            return gh
    return 1


def supported(q, k, causal=True, mask=None, dropout_rate=0.0,
              window=None) -> bool:
    """Static shape/feature check for the Pallas path."""
    if mask is not None or dropout_rate > 0.0:
        return False
    if window is not None and (not causal or window <= 0):
        return False
    if q.ndim != 4 or q.shape[-2] != k.shape[-2]:
        return False
    if q.shape[1] != k.shape[1]:        # GQA callers repeat kv heads first
        return False
    t, d = q.shape[-2], q.shape[-1]
    # short sequences: full K/V resident per head; long sequences: streamed
    # k-block grid. Cap the total so one (b, h) pair stays addressable.
    if t > 128 * 1024:
        return False
    return t >= 128 and t % 128 == 0 and d % 8 == 0 and d <= 256


# --------------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale,
                block_q, block_k, t_k, gh, window):
    q = q_ref[...]                               # [GH, BQ, D]
    q_off = pl.program_id(1) * block_q
    nk = pl.cdiv(q_off + block_q, block_k) if causal else t_k // block_k
    # sliding window: keys below q_off - window + 1 are dead for this q block
    # (window implies causal — enforced in _resolve)
    j0 = (jnp.maximum(q_off - window + 1, 0) // block_k
          if causal and window is not None else 0)

    def body(j, carry):
        acc, m, l = carry
        k_j = k_ref[:, pl.ds(j * block_k, block_k), :]   # [GH, BK, D]
        v_j = v_ref[:, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k_j, _BNT,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _mask(s, q_off, j * block_k, gh, block_q, block_k, window)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            p.astype(v_j.dtype), v_j, _BNN, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((gh, block_q, q.shape[-1]), jnp.float32)
    m0 = jnp.full((gh, block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((gh, block_q, 1), jnp.float32)
    acc, m, l = lax.fori_loop(j0, nk, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    lse_ref[...] = m + jnp.log(l)


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret, window=None):
    b, h, t, d = q.shape
    bh = b * h
    qf, kf, vf = (x.reshape(bh, t, d) for x in (q, k, v))
    if _streamed(t, d, q.dtype.itemsize):
        gh = _pick_gh_streamed(bh, d, block_q, block_k,
                               q.dtype.itemsize)
        out, lse = _fwd_streamed(qf, kf, vf, causal, scale, block_q, block_k,
                                 interpret, window, gh)
        return out.reshape(b, h, t, d), lse.reshape(b, h, t, 1)
    gh = int(_os.environ.get("DSTPU_FLASH_GH_FWD", 0)) or \
        _pick_gh(bh, t, d, block_q, block_k, q.dtype.itemsize)
    grid = (bh // gh, t // block_q)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k, t_k=t, gh=gh,
                               window=window)
    flops = 4 * bh * t * t * d // (2 if causal else 1)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((gh, block_q, d), lambda n, i: (n, i, 0)),
            pl.BlockSpec((gh, t, d), lambda n, i: (n, 0, 0)),
            pl.BlockSpec((gh, t, d), lambda n, i: (n, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((gh, block_q, d), lambda n, i: (n, i, 0)),
            pl.BlockSpec((gh, block_q, 1), lambda n, i: (n, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(flops),
            bytes_accessed=4 * bh * t * d * q.dtype.itemsize,
            transcendentals=bh * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d), lse.reshape(b, h, t, 1)


# -------------------------------------------------------------------- backward

def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dq_acc_ref, *,
                      causal, scale, block_q, block_k, t_q, gh, window):
    """One-pass backward: grid over k blocks (sequential), inner loop over
    q blocks. Computes s/p ONCE per (q, k) block pair and derives dv, dk
    (local accumulators) AND dq (f32 scratch [GH, T, D] persisting across
    the k-block grid dim — initialized at j==0, flushed at j==last).
    Versus the classic two-kernel (dq + dkv) split this saves two of seven
    dots, one of two exp sweeps, and a full re-fetch of q/do/lse/delta."""
    j = pl.program_id(1)
    nk = t_q // block_k
    k_off = j * block_k
    k_blk = k_ref[...]                           # [GH, BK, D]
    v_blk = v_ref[...]

    @pl.when(j == 0)
    def init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    nq = t_q // block_q
    start = k_off // block_q if causal else 0
    if causal and window is not None:
        nq = jnp.minimum(nq, pl.cdiv(k_off + block_k + window - 1, block_q))

    def body(i, carry):
        dk, dv = carry
        q_i = q_ref[:, pl.ds(i * block_q, block_q), :]
        do_i = do_ref[:, pl.ds(i * block_q, block_q), :]
        lse_i = lse_ref[:, pl.ds(i * block_q, block_q), :]
        delta_i = delta_ref[:, pl.ds(i * block_q, block_q), :]
        s = lax.dot_general(q_i, k_blk, _BNT,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _mask(s, i * block_q, k_off, gh, block_q, block_k, window)
        p = jnp.exp(s - lse_i)                   # [GH, BQ, BK]
        dv_new = dv + lax.dot_general(
            p.astype(do_i.dtype), do_i, _BTN,
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do_i, v_blk, _BNT,
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_i) * scale          # [GH, BQ, BK]
        ds_lp = ds.astype(q_i.dtype)
        dk_new = dk + lax.dot_general(
            ds_lp, q_i, _BTN, preferred_element_type=jnp.float32)
        dq_acc_ref[:, pl.ds(i * block_q, block_q), :] += lax.dot_general(
            ds_lp, k_blk, _BNN, preferred_element_type=jnp.float32)
        return dk_new, dv_new

    d = k_blk.shape[-1]
    dk0 = jnp.zeros((gh, block_k, d), jnp.float32)
    dv0 = jnp.zeros((gh, block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(start, nq, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)

    @pl.when(j == nk - 1)
    def flush():
        dq_ref[...] = dq_acc_ref[...].astype(dq_ref.dtype)


def _pick_gh_fused_bwd(bh: int, t: int, d: int, bq: int, bk: int,
                       itemsize: int = 2) -> int:
    """Head fold for the fused backward: q/do resident [GH,T,D] plus the
    f32 dq scratch dominate. Budget is 2x the fwd budget — calibrated on
    the real chip: gh=2 at (bh96, t1024, d64, bq512, bk256, bf16)
    compiles inside the fused train step (estimate 5.2M), gh=4 blows the
    16M scoped-vmem limit by 1.8M (estimate 12.6M)."""
    for gh in (8, 4, 2, 1):
        if bh % gh:
            continue
        resident = 2 * gh * t * d * itemsize * 2  # q, do (double-buffered)
        dq_bytes = gh * t * d * (4 + itemsize)    # f32 scratch + lp out
        kv_bytes = 2 * gh * bk * d * itemsize * 2
        tmp = gh * bq * bk * (4 + 4 + 2 * itemsize)  # s/p, dp/ds, p_lp+ds_lp
        if resident + dq_bytes + kv_bytes + tmp <= 2 * _VMEM_BUDGET:
            return gh
    return 1


def _bwd_fused(qf, kf, vf, dof, lsef, deltaf, causal, scale, block_q,
               block_k, interpret, window, gh):
    bh, t, d = qf.shape
    flops = 4 * bh * t * t * d // (2 if causal else 1)
    q_full = pl.BlockSpec((gh, t, d), lambda n, j: (n, 0, 0))
    kv_blk = pl.BlockSpec((gh, block_k, d), lambda n, j: (n, j, 0))
    vec_full = pl.BlockSpec((gh, t, 1), lambda n, j: (n, 0, 0))
    return pl.pallas_call(
        functools.partial(_bwd_fused_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, t_q=t, gh=gh,
                          window=window),
        grid=(bh // gh, t // block_k),
        in_specs=[q_full, kv_blk, kv_blk, q_full, vec_full, vec_full],
        out_specs=[q_full, kv_blk, kv_blk],
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), qf.dtype),
                   jax.ShapeDtypeStruct((bh, t, d), kf.dtype),
                   jax.ShapeDtypeStruct((bh, t, d), vf.dtype)],
        scratch_shapes=[pltpu.VMEM((gh, t, d), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=int(flops * 2.5),
            bytes_accessed=7 * bh * t * d * qf.dtype.itemsize,
            transcendentals=bh * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   causal, scale, block_q, block_k, t_k, gh, window):
    q = q_ref[...]                               # [GH, BQ, D]
    do = do_ref[...]
    lse = lse_ref[...]                           # [GH, BQ, 1]
    delta = delta_ref[...]
    q_off = pl.program_id(1) * block_q
    nk = pl.cdiv(q_off + block_q, block_k) if causal else t_k // block_k
    j0 = (jnp.maximum(q_off - window + 1, 0) // block_k
          if causal and window is not None else 0)

    def body(j, dq):
        k_j = k_ref[:, pl.ds(j * block_k, block_k), :]
        v_j = v_ref[:, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k_j, _BNT,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _mask(s, q_off, j * block_k, gh, block_q, block_k, window)
        p = jnp.exp(s - lse)                     # [GH, BQ, BK]
        dp = lax.dot_general(do, v_j, _BNT, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + lax.dot_general(ds.astype(k_j.dtype), k_j, _BNN,
                                    preferred_element_type=jnp.float32)

    dq = lax.fori_loop(j0, nk, body,
                       jnp.zeros((gh, q.shape[1], q.shape[-1]), jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, causal, scale, block_q, block_k, t_q,
                    gh, window):
    k_blk = k_ref[...]                           # [GH, BK, D]
    v_blk = v_ref[...]
    k_off = pl.program_id(1) * block_k
    nq = t_q // block_q
    start = k_off // block_q if causal else 0
    # sliding window: queries at or beyond k_off + bk + window - 1 are dead
    if causal and window is not None:
        nq = jnp.minimum(nq, pl.cdiv(k_off + block_k + window - 1, block_q))

    def body(i, carry):
        dk, dv = carry
        q_i = q_ref[:, pl.ds(i * block_q, block_q), :]
        do_i = do_ref[:, pl.ds(i * block_q, block_q), :]
        lse_i = lse_ref[:, pl.ds(i * block_q, block_q), :]
        delta_i = delta_ref[:, pl.ds(i * block_q, block_q), :]
        s = lax.dot_general(q_i, k_blk, _BNT,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _mask(s, i * block_q, k_off, gh, block_q, block_k, window)
        p = jnp.exp(s - lse_i)                   # [GH, BQ, BK]
        dv_new = dv + lax.dot_general(
            p.astype(do_i.dtype), do_i, _BTN,
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do_i, v_blk, _BNT,
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_i) * scale          # [GH, BQ, BK]
        dk_new = dk + lax.dot_general(
            ds.astype(q_i.dtype), q_i, _BTN,
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    d = k_blk.shape[-1]
    dk0 = jnp.zeros((gh, block_k, d), jnp.float32)
    dv0 = jnp.zeros((gh, block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(start, nq, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, causal, scale, block_q, block_k, interpret,
         window=None):
    b, h, t, d = q.shape
    bh = b * h
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)      # [B, H, T, 1]
    qf, kf, vf, dof = (x.reshape(bh, t, d) for x in (q, k, v, do))
    lsef = lse.reshape(bh, t, 1)
    deltaf = delta.reshape(bh, t, 1)
    if _streamed(t, d, q.dtype.itemsize):
        gh = _pick_gh_streamed(bh, d, block_q, block_k,
                               q.dtype.itemsize)
        dq, dk, dv = _bwd_streamed(qf, kf, vf, dof, lsef, deltaf, causal,
                                   scale, block_q, block_k, interpret,
                                   window, gh)
        return (dq.reshape(b, h, t, d), dk.reshape(b, h, t, d),
                dv.reshape(b, h, t, d))
    if _os.environ.get("DSTPU_FLASH_BWD", "fused") == "fused":
        gh_fused = int(_os.environ.get("DSTPU_FLASH_GH_BWD", 0)) or \
            _pick_gh_fused_bwd(bh, t, d, block_q, block_k,
                               q.dtype.itemsize)
        dq, dk, dv = _bwd_fused(qf, kf, vf, dof, lsef, deltaf, causal,
                                scale, block_q, block_k, interpret, window,
                                gh_fused)
        return (dq.reshape(b, h, t, d), dk.reshape(b, h, t, d),
                dv.reshape(b, h, t, d))
    gh = _pick_gh(bh, t, d, block_q, block_k, q.dtype.itemsize)

    blk_spec = pl.BlockSpec((gh, block_q, d), lambda n, i: (n, i, 0))
    full_spec = pl.BlockSpec((gh, t, d), lambda n, i: (n, 0, 0))
    vec_blk = pl.BlockSpec((gh, block_q, 1), lambda n, i: (n, i, 0))
    vec_full = pl.BlockSpec((gh, t, 1), lambda n, i: (n, 0, 0))
    flops = 4 * bh * t * t * d // (2 if causal else 1)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, t_k=t, gh=gh,
                          window=window),
        grid=(bh // gh, t // block_q),
        in_specs=[blk_spec, full_spec, full_spec, blk_spec,
                  vec_blk, vec_blk],
        out_specs=blk_spec,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=int(flops * 1.5),
            bytes_accessed=5 * bh * t * d * q.dtype.itemsize,
            transcendentals=bh * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    kv_blk = pl.BlockSpec((gh, block_k, d), lambda n, j: (n, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, t_q=t, gh=gh,
                          window=window),
        grid=(bh // gh, t // block_k),
        in_specs=[full_spec, kv_blk, kv_blk, full_spec,
                  vec_full, vec_full],
        out_specs=[kv_blk, kv_blk],
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, t, d), v.dtype)],
        cost_estimate=pl.CostEstimate(
            flops=int(flops * 2.5),
            bytes_accessed=6 * bh * t * d * q.dtype.itemsize,
            transcendentals=bh * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)
    return (dq.reshape(b, h, t, d), dk.reshape(b, h, t, d),
            dv.reshape(b, h, t, d))




# ------------------------------------------------- streamed (long-T) kernels
# K/V blocks arrive via a THIRD grid dimension instead of residing whole in
# VMEM; online-softmax accumulators live in VMEM scratch that persists
# across the innermost grid dim. Dead blocks (causal/window) are skipped
# with pl.when — compute-free, though their DMA still runs.

def _fwd_kernel_streamed(q_ref, k_ref, v_ref, o_ref, lse_ref,
                         acc_ref, m_ref, l_ref, *, causal, scale,
                         block_q, block_k, t_k, gh, window):
    j = pl.program_id(2)
    nkj = t_k // block_k
    q_off = pl.program_id(1) * block_q

    @pl.when(j == 0)
    def init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    k_off = j * block_k
    live = True
    if causal:
        live = k_off <= q_off + block_q - 1
    if causal and window is not None:
        live = live & (k_off + block_k - 1 >= q_off - window + 1)

    def compute():
        q = q_ref[...]
        k_j = k_ref[...]
        v_j = v_ref[...]
        s = lax.dot_general(q, k_j, _BNT,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _mask(s, q_off, k_off, gh, block_q, block_k, window)
        m, l, acc = m_ref[...], l_ref[...], acc_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc * alpha + lax.dot_general(
            p.astype(v_j.dtype), v_j, _BNN, preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    if live is True:
        compute()
    else:
        pl.when(live)(compute)

    @pl.when(j == nkj - 1)
    def finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[...] = m_ref[...] + jnp.log(l)


def _fwd_streamed(qf, kf, vf, causal, scale, block_q, block_k, interpret,
                  window, gh):
    bh, t, d = qf.shape
    grid = (bh // gh, t // block_q, t // block_k)
    kernel = functools.partial(_fwd_kernel_streamed, causal=causal,
                               scale=scale, block_q=block_q, block_k=block_k,
                               t_k=t, gh=gh, window=window)
    flops = 4 * bh * t * t * d // (2 if causal else 1)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((gh, block_q, d), lambda n, i, j: (n, i, 0)),
            pl.BlockSpec((gh, block_k, d), lambda n, i, j: (n, j, 0)),
            pl.BlockSpec((gh, block_k, d), lambda n, i, j: (n, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((gh, block_q, d), lambda n, i, j: (n, i, 0)),
            pl.BlockSpec((gh, block_q, 1), lambda n, i, j: (n, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), qf.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((gh, block_q, d), jnp.float32),
            pltpu.VMEM((gh, block_q, 1), jnp.float32),
            pltpu.VMEM((gh, block_q, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(flops),
            bytes_accessed=(2 * bh * t * d + 2 * bh * t * t // block_q * d)
            * qf.dtype.itemsize,
            transcendentals=bh * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)


def _bwd_dq_kernel_streamed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            dq_ref, dq_acc_ref, *, causal, scale, block_q,
                            block_k, t_k, gh, window):
    j = pl.program_id(2)
    nkj = t_k // block_k
    q_off = pl.program_id(1) * block_q
    k_off = j * block_k

    @pl.when(j == 0)
    def init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    live = True
    if causal:
        live = k_off <= q_off + block_q - 1
    if causal and window is not None:
        live = live & (k_off + block_k - 1 >= q_off - window + 1)

    def compute():
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[...]
        delta = delta_ref[...]
        k_j = k_ref[...]
        v_j = v_ref[...]
        s = lax.dot_general(q, k_j, _BNT,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _mask(s, q_off, k_off, gh, block_q, block_k, window)
        p = jnp.exp(s - lse)
        dp = lax.dot_general(do, v_j, _BNT, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc_ref[...] = dq_acc_ref[...] + lax.dot_general(
            ds.astype(k_j.dtype), k_j, _BNN,
            preferred_element_type=jnp.float32)

    if live is True:
        compute()
    else:
        pl.when(live)(compute)

    @pl.when(j == nkj - 1)
    def finalize():
        dq_ref[...] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_streamed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                             dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *,
                             causal, scale, block_q, block_k, t_q, gh,
                             window):
    i = pl.program_id(2)
    nqi = t_q // block_q
    k_off = pl.program_id(1) * block_k
    q_off = i * block_q

    @pl.when(i == 0)
    def init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    live = True
    if causal:
        live = q_off + block_q - 1 >= k_off
    if causal and window is not None:
        live = live & (q_off <= k_off + block_k - 1 + window - 1)

    def compute():
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        q_i = q_ref[...]
        do_i = do_ref[...]
        lse_i = lse_ref[...]
        delta_i = delta_ref[...]
        s = lax.dot_general(q_i, k_blk, _BNT,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _mask(s, q_off, k_off, gh, block_q, block_k, window)
        p = jnp.exp(s - lse_i)
        dv_acc_ref[...] = dv_acc_ref[...] + lax.dot_general(
            p.astype(do_i.dtype), do_i, _BTN,
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do_i, v_blk, _BNT,
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_i) * scale
        dk_acc_ref[...] = dk_acc_ref[...] + lax.dot_general(
            ds.astype(q_i.dtype), q_i, _BTN,
            preferred_element_type=jnp.float32)

    if live is True:
        compute()
    else:
        pl.when(live)(compute)

    @pl.when(i == nqi - 1)
    def finalize():
        dk_ref[...] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def _bwd_streamed(qf, kf, vf, dof, lsef, deltaf, causal, scale, block_q,
                  block_k, interpret, window, gh):
    bh, t, d = qf.shape
    flops = 4 * bh * t * t * d // (2 if causal else 1)
    q_blk = pl.BlockSpec((gh, block_q, d), lambda n, i, j: (n, i, 0))
    kv_blk = pl.BlockSpec((gh, block_k, d), lambda n, i, j: (n, j, 0))
    vec_q = pl.BlockSpec((gh, block_q, 1), lambda n, i, j: (n, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_streamed, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, t_k=t, gh=gh,
                          window=window),
        grid=(bh // gh, t // block_q, t // block_k),
        in_specs=[q_blk, kv_blk, kv_blk, q_blk, vec_q, vec_q],
        out_specs=q_blk,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), qf.dtype),
        scratch_shapes=[pltpu.VMEM((gh, block_q, d), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=int(flops * 1.5),
            # K/V refetched once per q block
            bytes_accessed=(3 * bh * t * d +
                            2 * bh * t * (t // block_q) * d)
            * qf.dtype.itemsize,
            transcendentals=bh * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    # dkv: middle grid dim over k blocks, innermost over q blocks
    q_blk2 = pl.BlockSpec((gh, block_q, d), lambda n, j, i: (n, i, 0))
    kv_blk2 = pl.BlockSpec((gh, block_k, d), lambda n, j, i: (n, j, 0))
    vec_q2 = pl.BlockSpec((gh, block_q, 1), lambda n, j, i: (n, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_streamed, causal=causal,
                          scale=scale, block_q=block_q, block_k=block_k,
                          t_q=t, gh=gh, window=window),
        grid=(bh // gh, t // block_k, t // block_q),
        in_specs=[q_blk2, kv_blk2, kv_blk2, q_blk2, vec_q2, vec_q2],
        out_specs=[kv_blk2, kv_blk2],
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), kf.dtype),
                   jax.ShapeDtypeStruct((bh, t, d), vf.dtype)],
        scratch_shapes=[pltpu.VMEM((gh, block_k, d), jnp.float32),
                        pltpu.VMEM((gh, block_k, d), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=int(flops * 2.5),
            # Q/dO/lse/delta refetched once per k block
            bytes_accessed=(4 * bh * t * d +
                            2 * bh * t * (t // block_k) * d)
            * qf.dtype.itemsize,
            transcendentals=bh * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)
    return dq, dk, dv


# ------------------------------------------------------------------ public op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal=True, softmax_scale=None,
                    block_q=None, block_k=None, interpret=False,
                    window=None):
    """Blocked flash attention. q,k,v: [B, H, T, D]; returns [B, H, T, D].
    ``window`` enables Mistral-style sliding-window causal attention."""
    out, _ = _flash_fwd(q, k, v, causal, softmax_scale, block_q, block_k,
                        interpret, window)
    return out


def _resolve(q, softmax_scale, block_q, block_k, causal=True, window=None):
    t, d = q.shape[-2], q.shape[-1]
    if window is not None and not causal:
        raise ValueError("sliding window requires causal=True")
    if t % 128 != 0:
        raise ValueError(
            f"pallas flash attention requires seq length divisible by 128, "
            f"got {t}; use the XLA backend for this shape")
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    dq, dk = _pick_blocks(t)
    block_q, block_k = block_q or dq, block_k or dk
    if t % block_q or t % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"sequence length {t}")
    return scale, block_q, block_k


def _flash_fwd(q, k, v, causal, softmax_scale, block_q, block_k, interpret,
               window=None):
    scale, bq, bk = _resolve(q, softmax_scale, block_q, block_k, causal,
                             window)
    out, lse = _fwd(q, k, v, causal, scale, bq, bk, interpret, window)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, softmax_scale, block_q, block_k, interpret, window,
               residuals, g):
    q, k, v, out, lse = residuals
    scale, bq, bk = _resolve(q, softmax_scale, block_q, block_k, causal,
                             window)
    dq, dk, dv = _bwd(q, k, v, out, lse, g, causal, scale, bq, bk, interpret,
                      window)
    return dq, dk, dv


flash_attention.defvjp(lambda q, k, v, c, s, bq, bk, it, w:
                       _flash_fwd(q, k, v, c, s, bq, bk, it, w),
                       _flash_bwd)
