"""Packed-layout Pallas flash attention: q/k/v/o in [B, T, H*D].

Round-3 profiling showed ~5 ms/micro of pure relayout copies in the 125M
step: the model computes qkv as [B, T, 3HD] (lane-aligned, matmul-native)
but the [B, H, T, D] kernel layout forces six head transposes (q/k/v fwd
+ mirrored bwd) and a duplicate save of the attention output. This kernel
keeps the tensors in the layout the surrounding matmuls already produce:

- arrays [B, T, H*D]; a grid step owns GH heads as a LANE SLICE of the
  feature dim (GH*D = 128 lanes for D=64) — blocks stay (sublane, 128·k)
  tiled, no relayout anywhere.
- per-head dots are unrolled over the GH static lane slices ([BQ, D] 2D
  matmuls — what Mosaic lowers batched dots to anyway).
- lse is emitted [B, T, 128] f32 (head h in lane h; lanes >= H padded) so
  its blocks satisfy the (8, 128) tiling floor.
- backward fuses dq+dk+dv in one kernel (dq in f32 VMEM scratch across
  the sequential k-tile grid dim), same structure as the [B,H,T,D]
  fused backward in flash_attention.py.

Reference counterpart: csrc/transformer softmax/attention kernels — but
the DESIGN here is driven by Mosaic tiling (8, 128) rules, not the CUDA
original. Parity oracle: ops/flash_attention.reference_attention
(tests/unit/test_pallas_flash_packed.py, interpret mode).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128


def supported(t: int, d: int, n_head: int, causal: bool, window) -> bool:
    if d > _LANES or _LANES % d or t % 128:
        return False
    gh = _LANES // d
    if n_head % gh:
        return False
    if window is not None and (not causal or window <= 0):
        return False
    # the fused BACKWARD keeps q/k/v/do (bf16, 4*2t*128 B) + lse/delta
    # (f32, 2*4t*128) + the f32 dq scratch (4t*128) + three output blocks
    # resident per grid step — ~3.3 KB/token, double-buffered inputs on
    # top. Cap t so the whole set stays well inside the 16 MB VMEM (long
    # T uses the streamed [B,H,T,D] kernels instead).
    return t <= 4096


def _mask(s, q_off, k_off, bq, bk, window):
    q_pos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    keep = q_pos >= k_pos
    if window is not None:
        keep &= (q_pos - k_pos) < window
    return jnp.where(keep, s, NEG_INF)


# --------------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale,
                bq, bk, t, gh, d, window):
    q_off = pl.program_id(1) * bq
    nk = pl.cdiv(q_off + bq, bk) if causal else t // bk
    j0 = (jnp.maximum(q_off - window + 1, 0) // bk
          if causal and window is not None else 0)
    q = q_ref[0]                                   # [BQ, GH*D]

    accs, ms, ls = [], [], []
    for h in range(gh):
        accs.append(jnp.zeros((bq, d), jnp.float32))
        ms.append(jnp.full((bq, 1), NEG_INF, jnp.float32))
        ls.append(jnp.zeros((bq, 1), jnp.float32))

    def body(j, carry):
        accs, ms, ls = carry
        k_j = k_ref[0, pl.ds(j * bk, bk), :]       # [BK, GH*D]
        v_j = v_ref[0, pl.ds(j * bk, bk), :]
        new_accs, new_ms, new_ls = [], [], []
        for h in range(gh):
            qh = q[:, h * d:(h + 1) * d]
            kh = k_j[:, h * d:(h + 1) * d]
            vh = v_j[:, h * d:(h + 1) * d]
            s = jnp.dot(qh, kh.T, preferred_element_type=jnp.float32) * scale
            if causal:
                s = _mask(s, q_off, j * bk, bq, bk, window)
            m_new = jnp.maximum(ms[h], jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(ms[h] - m_new)
            p = jnp.exp(s - m_new)
            new_ls.append(ls[h] * alpha + jnp.sum(p, axis=-1, keepdims=True))
            new_accs.append(accs[h] * alpha + jnp.dot(
                p.astype(vh.dtype), vh, preferred_element_type=jnp.float32))
            new_ms.append(m_new)
        return new_accs, new_ms, new_ls

    accs, ms, ls = lax.fori_loop(j0, nk, body, (accs, ms, ls))
    outs = []
    lse_out = jnp.zeros((bq, _LANES), jnp.float32)
    lane = lax.broadcasted_iota(jnp.int32, (bq, _LANES), 1)
    for h in range(gh):
        l = jnp.maximum(ls[h], 1e-30)
        outs.append((accs[h] / l).astype(o_ref.dtype))
        # lane-broadcast write of head h's lse (1-lane concats don't
        # lower on Mosaic; a where over a full [BQ, 128] tile does)
        lse_out = jnp.where(lane == h, ms[h] + jnp.log(l), lse_out)
    o_ref[0] = jnp.concatenate(outs, axis=-1)
    lse_ref[0] = lse_out


def _fwd(q, k, v, n_head, causal, scale, bq, bk, interpret, window):
    b, t, hd_total = q.shape
    d = hd_total // n_head
    gh = _LANES // d
    ng = n_head // gh
    grid = (b * ng, t // bq)

    feat = pl.BlockSpec((1, bq, _LANES),
                        lambda n, i, ng=ng: (n // ng, i, n % ng))
    full = pl.BlockSpec((1, t, _LANES),
                        lambda n, i, ng=ng: (n // ng, 0, n % ng))
    lse_spec = pl.BlockSpec((1, bq, _LANES),
                            lambda n, i, ng=ng: (n // ng, i, n % ng))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, scale=scale, bq=bq,
                          bk=bk, t=t, gh=gh, d=d, window=window),
        grid=grid,
        in_specs=[feat, full, full],
        out_specs=[feat, lse_spec],
        out_shape=[jax.ShapeDtypeStruct((b, t, hd_total), q.dtype),
                   jax.ShapeDtypeStruct((b, t, ng * _LANES), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=int(4 * b * n_head * t * t * d // (2 if causal else 1)),
            bytes_accessed=4 * b * t * hd_total * q.dtype.itemsize,
            transcendentals=b * n_head * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# -------------------------------------------------------------------- backward

def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_ref, dk_ref, dv_ref, dq_acc_ref, *, causal, scale,
                bq, bk, t, gh, d, window):
    j = pl.program_id(1)
    nk = t // bk
    k_off = j * bk

    @pl.when(j == 0)
    def init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    nq = t // bq
    start = k_off // bq if causal else 0
    if causal and window is not None:
        nq = jnp.minimum(nq, pl.cdiv(k_off + bk + window - 1, bq))
    k_blk = k_ref[0, pl.ds(k_off, bk), :]          # [BK, GH*D]
    v_blk = v_ref[0, pl.ds(k_off, bk), :]

    def body(i, carry):
        dks, dvs = carry
        q_i = q_ref[0, pl.ds(i * bq, bq), :]
        do_i = do_ref[0, pl.ds(i * bq, bq), :]
        lse_i = lse_ref[0, pl.ds(i * bq, bq), :]
        delta_i = delta_ref[0, pl.ds(i * bq, bq), :]
        lane = lax.broadcasted_iota(jnp.int32, (bq, _LANES), 1)
        new_dks, new_dvs = [], []
        dq_upds = []
        for h in range(gh):
            qh = q_i[:, h * d:(h + 1) * d]
            kh = k_blk[:, h * d:(h + 1) * d]
            vh = v_blk[:, h * d:(h + 1) * d]
            doh = do_i[:, h * d:(h + 1) * d]
            # extract head h's lane as [BQ, 1] via masked lane-reduce
            # (1-lane slices at arbitrary offsets don't lower on Mosaic)
            lse_h = jnp.max(jnp.where(lane == h, lse_i, -jnp.inf), axis=-1,
                            keepdims=True)
            delta_h = jnp.max(jnp.where(lane == h, delta_i, -jnp.inf),
                              axis=-1, keepdims=True)
            s = jnp.dot(qh, kh.T, preferred_element_type=jnp.float32) * scale
            if causal:
                s = _mask(s, i * bq, k_off, bq, bk, window)
            p = jnp.exp(s - lse_h)
            new_dvs.append(dvs[h] + jnp.dot(
                p.astype(doh.dtype).T, doh,
                preferred_element_type=jnp.float32))
            dp = jnp.dot(doh, vh.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta_h) * scale
            ds_lp = ds.astype(qh.dtype)
            new_dks.append(dks[h] + jnp.dot(
                ds_lp.T, qh, preferred_element_type=jnp.float32))
            dq_upds.append(jnp.dot(ds_lp, kh,
                                   preferred_element_type=jnp.float32))
        dq_acc_ref[pl.ds(i * bq, bq), :] += jnp.concatenate(dq_upds, -1)
        return new_dks, new_dvs

    dk0 = [jnp.zeros((bk, d), jnp.float32) for _ in range(gh)]
    dv0 = [jnp.zeros((bk, d), jnp.float32) for _ in range(gh)]
    dks, dvs = lax.fori_loop(start, nq, body, (dk0, dv0))
    dk_ref[0, pl.ds(k_off, bk), :] = jnp.concatenate(
        dks, -1).astype(dk_ref.dtype)
    dv_ref[0, pl.ds(k_off, bk), :] = jnp.concatenate(
        dvs, -1).astype(dv_ref.dtype)

    @pl.when(j == nk - 1)
    def flush():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd(q, k, v, o, lse, do, n_head, causal, scale, bq, bk, interpret,
         window):
    b, t, hd_total = q.shape
    d = hd_total // n_head
    gh = _LANES // d
    ng = n_head // gh
    # delta per head: rowsum over that head's lanes of do*o, packed like lse
    prod = (do.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
        b, t, n_head, d)
    delta = prod.sum(-1)                              # [B, T, H]
    # interleave per group: group g's lanes [g*128 : g*128+gh] hold its heads
    delta_groups = [jnp.concatenate(
        [delta[:, :, g * gh:(g + 1) * gh],
         jnp.zeros((b, t, _LANES - gh), jnp.float32)], -1)
        for g in range(ng)]
    delta_packed = jnp.concatenate(delta_groups, -1)  # [B, T, ng*128]

    full = pl.BlockSpec((1, t, _LANES),
                        lambda n, j, ng=ng: (n // ng, 0, n % ng))
    out_full = pl.BlockSpec((1, t, _LANES),
                            lambda n, j, ng=ng: (n // ng, 0, n % ng))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, causal=causal, scale=scale, bq=bq,
                          bk=bk, t=t, gh=gh, d=d, window=window),
        grid=(b * ng, t // bk),
        in_specs=[full, full, full, full, full, full],
        out_specs=[out_full, out_full, out_full],
        out_shape=[jax.ShapeDtypeStruct((b, t, hd_total), q.dtype),
                   jax.ShapeDtypeStruct((b, t, hd_total), k.dtype),
                   jax.ShapeDtypeStruct((b, t, hd_total), v.dtype)],
        scratch_shapes=[pltpu.VMEM((t, _LANES), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=int(10 * b * n_head * t * t * d // (2 if causal else 1)),
            bytes_accessed=7 * b * t * hd_total * q.dtype.itemsize,
            transcendentals=2 * b * n_head * t * t // (2 if causal else 1)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta_packed)
    return dq, dk, dv


# ------------------------------------------------------------------ public op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def packed_flash_attention(q, k, v, n_head, causal=True, softmax_scale=None,
                           window=None, interpret=False, block=(512, 512)):
    """Flash attention over packed [B, T, H*D] tensors. Returns the
    attention output in the SAME packed layout."""
    out, _ = _pf_fwd(q, k, v, n_head, causal, softmax_scale, window,
                     interpret, block)
    return out


def _resolve(q, n_head, softmax_scale, block):
    t, hd_total = q.shape[-2], q.shape[-1]
    d = hd_total // n_head
    if t % 128:
        raise ValueError(
            f"packed flash attention requires seq length divisible by 128, "
            f"got {t} (check supported() before calling)")
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    bq = next(bb for bb in (block[0], 256, 128) if t % bb == 0)
    bk = next(bb for bb in (block[1], 256, 128) if t % bb == 0)
    return scale, min(t, bq), min(t, bk)


def _pf_fwd(q, k, v, n_head, causal, softmax_scale, window, interpret,
            block):
    scale, bq, bk = _resolve(q, n_head, softmax_scale, block)
    out, lse = _fwd(q, k, v, n_head, causal, scale, bq, bk, interpret,
                    window)
    return out, (q, k, v, out, lse)


def _pf_bwd(n_head, causal, softmax_scale, window, interpret, block,
            res, g):
    q, k, v, out, lse = res
    # smaller blocks than forward: the per-head unrolled temporaries
    # (s/p/dp/ds in f32) dominate the backward's VMEM stack
    scale, bq, bk = _resolve(q, n_head, softmax_scale, (256, 256))
    dq, dk, dv = _bwd(q, k, v, out, lse, g, n_head, causal, scale, bq, bk,
                      interpret, window)
    return dq, dk, dv


packed_flash_attention.defvjp(_pf_fwd, _pf_bwd)
