"""Async NVMe/disk I/O handle (the aio op).

Capability match for the reference's AsyncIOBuilder surface
(csrc/aio/py_lib/py_ds_aio.cpp:16-20 ``aio_read/aio_write/aio_handle``):
`AsyncIOHandle` wraps the C++ thread-pool library (ops/csrc/aio.cpp) and
moves numpy buffers to/from swap files without blocking the caller; tickets
order completion. Used by runtime/swap_tensor for ZeRO-Infinity-style
optimizer-state paging.
"""

import ctypes
import os
from types import SimpleNamespace

import numpy as np

from .native_build import NativeBuildError, load_library
from ..utils.logging import logger


class _NativeAio:
    def __init__(self, n_threads: int):
        self.lib = load_library("aio", openmp=False)
        self.lib.aio_handle_create.restype = ctypes.c_void_p
        self.lib.aio_handle_create.argtypes = [ctypes.c_int]
        self.lib.aio_handle_destroy.argtypes = [ctypes.c_void_p]
        for fn in (self.lib.aio_submit_read, self.lib.aio_submit_write):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64]
        self.lib.aio_wait.restype = ctypes.c_int64
        self.lib.aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        self.lib.aio_wait_all.restype = ctypes.c_int64
        self.lib.aio_wait_all.argtypes = [ctypes.c_void_p]
        self._h = self.lib.aio_handle_create(n_threads)

    def close(self):
        if self._h:
            self.lib.aio_handle_destroy(self._h)
            self._h = None

    def submit_read(self, path, buf, offset=0):
        return self.lib.aio_submit_read(
            self._h, os.fsencode(path), buf.ctypes.data_as(ctypes.c_void_p),
            buf.nbytes, offset)

    def submit_write(self, path, buf, offset=0):
        return self.lib.aio_submit_write(
            self._h, os.fsencode(path), buf.ctypes.data_as(ctypes.c_void_p),
            buf.nbytes, offset)

    def wait(self, ticket):
        return int(self.lib.aio_wait(self._h, ticket))

    def wait_all(self):
        return int(self.lib.aio_wait_all(self._h))


class _SyncFallbackAio:
    """Synchronous fallback when the native lib can't build: submits execute
    inline; wait() is a lookup. Semantics preserved, no overlap."""

    def __init__(self, n_threads: int):
        self._results = {}
        self._next = 1

    def close(self):
        pass

    def _run(self, write, path, buf, offset):
        t = self._next
        self._next += 1
        try:
            mode = "r+b" if (write and os.path.exists(path)) else \
                ("wb" if write else "rb")
            with open(path, mode) as f:
                f.seek(offset)
                if write:
                    f.write(buf.tobytes())
                    rc = buf.nbytes
                else:
                    data = f.read(buf.nbytes)
                    flat = buf.reshape(-1).view(np.uint8)
                    flat[:len(data)] = np.frombuffer(data, dtype=np.uint8)
                    rc = len(data)
        except OSError as e:
            rc = -(e.errno or 1)
        self._results[t] = rc
        return t

    def submit_read(self, path, buf, offset=0):
        return self._run(False, path, buf, offset)

    def submit_write(self, path, buf, offset=0):
        return self._run(True, path, buf, offset)

    def wait(self, ticket):
        return self._results.pop(ticket)

    def wait_all(self):
        bad = [r for r in self._results.values() if r < 0]
        self._results.clear()
        return bad[0] if bad else 0


class AsyncIOHandle:
    """Public handle: submit reads/writes of numpy buffers against files.

    Buffers MUST stay alive (and unmodified, for writes) until their ticket
    completes — the C++ side holds raw pointers.
    """

    def __init__(self, n_threads: int = 4):
        try:
            self._impl = _NativeAio(n_threads)
            self.native = True
        except (NativeBuildError, OSError) as e:
            logger.warning(f"aio native build unavailable ({e}); "
                           f"synchronous fallback in use")
            self._impl = _SyncFallbackAio(n_threads)
            self.native = False

    def __del__(self):
        try:
            self._impl.close()
        except Exception:
            pass

    def submit_read(self, path, buf: np.ndarray, offset: int = 0) -> int:
        assert buf.flags["C_CONTIGUOUS"]
        return self._impl.submit_read(path, buf, offset)

    def submit_write(self, path, buf: np.ndarray, offset: int = 0) -> int:
        assert buf.flags["C_CONTIGUOUS"]
        return self._impl.submit_write(path, buf, offset)

    def wait(self, ticket: int) -> int:
        """Block until `ticket` completes; returns bytes moved (<0 = -errno)."""
        return self._impl.wait(ticket)

    def wait_all(self) -> int:
        return self._impl.wait_all()

    def read(self, path, buf, offset=0) -> int:
        return self.wait(self.submit_read(path, buf, offset))

    def write(self, path, buf, offset=0) -> int:
        return self.wait(self.submit_write(path, buf, offset))


def get_ops(backend: str = "cpu"):
    return SimpleNamespace(AsyncIOHandle=AsyncIOHandle)
