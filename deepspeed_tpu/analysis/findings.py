"""ds_tpu_lint finding/waiver core — the one report format both planes share.

Every rule in the suite (AST lints in ``pylint_rules.py``, HLO auditors
in ``hlo_audit_rules.py``) emits :class:`Finding` records: rule id,
location (``path:line`` for source findings, ``hlo:<artifact>`` for
compiled-program findings), severity, message, and a stable
``waiver_key``. The key deliberately omits line numbers — a waiver
granted for "raw lax.psum in utils/bench_cli.py" keeps working when the
file shifts — and waiver entries in ``lint_waivers.json`` match keys by
``fnmatch`` pattern, so one entry can cover a family of findings when a
whole file or artifact is exempt.

Waiver file format (checked in at the repo root)::

    {"version": 1,
     "waivers": [{"key": "AST001:deepspeed_tpu/utils/bench_cli.py:*",
                  "reason": "raw-bandwidth probe times the raw lax op"}]}

Every waiver MUST carry a non-empty reason string; ``load_waivers``
rejects entries without one, so "waive it silently" is not expressible.

This module is deliberately standalone — stdlib-only, no package
imports — so ``bin/ds_tpu_lint`` can load it by file path and run the
AST plane without importing jax or the deepspeed_tpu backend chain (the
same bootstrap trick ``benchmarks/hlo_audit.py`` uses for hlo_cost).
"""

import fnmatch
import json
import os
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["RULES", "RULES_VERSION", "Finding", "load_waivers",
           "apply_waivers", "unused_waivers", "render_text", "render_json",
           "lint_fingerprint", "default_waivers_path"]

#: bump when a rule is added/removed or its detection semantics change —
#: the /statusz fingerprint records which vintage of the suite a
#: postmortem bundle was checked against
RULES_VERSION = 1

#: rule registry: id -> {plane, name, doc}. The CLI's --list-rules and
#: docs/lint.md are generated views of this table.
RULES: Dict[str, Dict[str, str]] = {
    "AST001": {
        "plane": "ast", "name": "raw-collective",
        "doc": "raw lax collective (psum/pmean/pmax/pmin/psum_scatter/"
               "all_gather/all_to_all/ppermute/pshuffle) outside comm/ and "
               "ops/ — bypasses the compression-aware dispatch and its "
               "wire accounting"},
    "AST002": {
        "plane": "ast", "name": "host-sync-in-traced",
        "doc": "host synchronization (float(arg)/.item()/np.asarray/"
               "np.array/time.time/time.perf_counter/jax.device_get) "
               "inside a function jitted or shard_mapped — traces a "
               "constant or raises under jit, and blocks async dispatch"},
    "AST003": {
        "plane": "ast", "name": "ownerless-gauge",
        "doc": "tracer.set_counter(...) without owner= — leaks the gauge "
               "past its producer's shutdown (the static form of the "
               "test_metrics_lifecycle runtime lint)"},
    "AST004": {
        "plane": "ast", "name": "unknown-config-key",
        "doc": "top-level config key (dict literal passed to initialize, "
               "or examples/configs/*.json) not present in any registered "
               "config block — silently ignored at runtime"},
    "HLO001": {
        "plane": "hlo", "name": "orphaned-async",
        "doc": "async collective start without a matching done (or vice "
               "versa) in the compiled module — a deadlock or a leaked "
               "in-flight buffer"},
    "HLO002": {
        "plane": "hlo", "name": "replica-groups-partition",
        "doc": "a collective's replica_groups do not exactly partition "
               "the participating devices (overlap, gap, or unequal group "
               "sizes) — undefined routing, hangs on real chips"},
    "HLO003": {
        "plane": "hlo", "name": "subaxis-inconsistency",
        "doc": "two collectives in one module use the same group shape "
               "with DIFFERENT partitions — inconsistent (host, local) "
               "subaxis decomposition across hierarchical legs"},
    "HLO004": {
        "plane": "hlo", "name": "issue-order-divergence",
        "doc": "collective issue order differs across per-device "
               "programs — the static shard_map deadlock: two devices "
               "enter different collectives first and both wait forever"},
    "HLO005": {
        "plane": "hlo", "name": "undonated-buffer",
        "doc": "large state argument (grads/optimizer state/KV lanes) "
               "not donated to an output — the program holds input and "
               "output copies live at once, doubling that buffer's HBM"},
    "HLO006": {
        "plane": "hlo", "name": "dispatch-conformance",
        "doc": "the compiled module carries a collective kind that the "
               "comm dispatch never traced — wire bytes invisible to "
               "comm_stats(), compression policies never consulted"},
}


@dataclass
class Finding:
    rule: str                      # "AST001" / "HLO005"
    severity: str                  # "error" | "warning"
    path: str                      # repo-relative path or "hlo:<artifact>"
    line: int                      # 1-based source line; 0 for HLO findings
    message: str
    waiver_key: str                # "<rule>:<path-ish>:<symbol>"
    waived: bool = False
    waiver_reason: str = ""
    meta: Dict[str, Any] = field(default_factory=dict)

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else self.path

    def to_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        d["rule_name"] = RULES.get(self.rule, {}).get("name", "")
        return d


def make_key(rule: str, path: str, symbol: str) -> str:
    return f"{rule}:{path}:{symbol}"


def default_waivers_path(root: str) -> str:
    return os.path.join(root, "lint_waivers.json")


def load_waivers(path: Optional[str]) -> List[Dict[str, str]]:
    """Load and validate the waiver file; [] when ``path`` is None or the
    file does not exist. Raises ValueError on entries without a key or a
    non-empty reason (an unreasoned waiver is a finding suppressed in
    silence — exactly what this suite exists to prevent)."""
    if not path or not os.path.exists(path):
        return []
    with open(path) as f:
        doc = json.load(f)
    waivers = doc.get("waivers", [])
    for w in waivers:
        if not w.get("key"):
            raise ValueError(f"waiver entry without a key: {w!r}")
        if not str(w.get("reason", "")).strip():
            raise ValueError(f"waiver {w['key']!r} has no reason string")
        w.setdefault("_hits", 0)
    return waivers


def apply_waivers(findings: Sequence[Finding],
                  waivers: List[Dict[str, str]]) -> List[Finding]:
    """Mark findings whose waiver_key matches a waiver pattern; mutates
    and returns ``findings``. Waiver ``_hits`` counters are updated so
    :func:`unused_waivers` can name stale entries."""
    for f in findings:
        for w in waivers:
            if fnmatch.fnmatchcase(f.waiver_key, w["key"]):
                f.waived = True
                f.waiver_reason = w["reason"]
                w["_hits"] = w.get("_hits", 0) + 1
                break
    return list(findings)


def unused_waivers(waivers: List[Dict[str, str]]) -> List[str]:
    """Keys of waivers that matched nothing in the last apply_waivers
    pass — stale entries worth deleting (reported, never fatal: a waiver
    for an HLO artifact is legitimately idle during an ast-only run)."""
    return [w["key"] for w in waivers if not w.get("_hits")]


def render_text(findings: Sequence[Finding], show_waived: bool = True) -> str:
    lines = []
    for f in sorted(findings, key=lambda x: (x.waived, x.rule, x.path,
                                             x.line)):
        if f.waived and not show_waived:
            continue
        tag = "waived" if f.waived else f.severity
        name = RULES.get(f.rule, {}).get("name", "")
        lines.append(f"[{tag:7s}] {f.rule} ({name}) {f.location}: "
                     f"{f.message}")
        if f.waived:
            lines.append(f"          waiver: {f.waiver_reason}")
    active = [f for f in findings if not f.waived]
    lines.append(f"{len(list(findings))} finding(s), "
                 f"{len(active)} non-waived")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding],
                extra: Optional[Dict[str, Any]] = None) -> str:
    doc = {
        "rules_version": RULES_VERSION,
        "findings": [f.to_dict() for f in findings],
        "non_waived": sum(1 for f in findings if not f.waived),
    }
    if extra:
        doc.update(extra)
    return json.dumps(doc, indent=1, sort_keys=True)


def lint_fingerprint(root: Optional[str] = None) -> str:
    """One-line suite fingerprint for /statusz and postmortem bundles:
    rules version + rule count + the checked-in waiver count, so a
    bundle records exactly which lint vintage the build was clean
    against. Never raises (statusz must render with a broken or absent
    waiver file)."""
    n_waivers = 0
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    try:
        n_waivers = len(load_waivers(default_waivers_path(root)))
    except Exception:
        n_waivers = -1          # unreadable waiver file: worth noticing
    return (f"ds_tpu_lint v{RULES_VERSION}: {len(RULES)} rules, "
            f"{n_waivers} waivers")
