"""deepspeed_tpu.analysis — framework-aware static analysis (ds_tpu_lint).

Two planes, one finding/waiver format (docs/lint.md):

- **Plane A** (:mod:`hlo_audit_rules`, fed by :mod:`artifacts`):
  auditors over the repo's real lowered programs — async start/done
  matching, replica-group partition/consistency, per-device issue
  order, donation/aliasing vs HBM roles, comm dispatch conformance.
- **Plane B** (:mod:`pylint_rules`): stdlib-``ast`` lints — raw lax
  collectives outside comm/ and ops/, host sync inside traced code,
  ownerless gauges, unknown config keys.

``bin/ds_tpu_lint`` is the CLI; ``lint_waivers.json`` at the repo root
keeps the tree lint-clean with reasoned waivers; the tier-1 gate is
``tests/unit/test_lint.py``.
"""

from .findings import (Finding, RULES, RULES_VERSION,  # noqa: F401
                       apply_waivers, default_waivers_path,
                       lint_fingerprint, load_waivers, render_json,
                       render_text, unused_waivers)
from .hlo_audit_rules import (DISPATCH_ACCEPTS, HloArtifact,  # noqa: F401
                              collect_donation, run_hlo_audit)
from .pylint_rules import (harvest_config_keys,  # noqa: F401
                           lint_source, run_ast_lint)

__all__ = ["Finding", "RULES", "RULES_VERSION", "apply_waivers",
           "default_waivers_path", "lint_fingerprint", "load_waivers",
           "render_json", "render_text", "unused_waivers", "HloArtifact",
           "DISPATCH_ACCEPTS", "collect_donation", "run_hlo_audit",
           "harvest_config_keys", "lint_source", "run_ast_lint"]
