"""Real lowered artifacts for the HLO auditors (ds_tpu_lint Plane A).

Each ``lower_*`` function builds the ACTUAL program the repo ships —
the ZeRO-3 train step with the bucketed overlap schedule and quantized
hierarchical collectives, the fused ``decode_with_slots`` serving step,
the compiled 1F1B pipe step, and the expert-parallel MoE step — lowers
it under the ambient backend (CPU-runnable: ``JAX_PLATFORMS=cpu`` with
8 virtual devices, exactly like benchmarks/overlap.py), and packages
compiled HLO + lowered StableHLO + argument roles + the comm dispatch's
per-op trace delta into an :class:`HloArtifact`.

Sizes: ``tiny`` keeps the tier-1 gate fast (the audited PROGRAM
STRUCTURE — bucket legs, replica groups, donation map — is identical
to the bench shape; only dims shrink); ``bench`` matches
benchmarks/overlap.py for the CLI / postmortem runs.

jax and deepspeed_tpu are imported inside the functions so the AST
plane (and audits of saved ``.hlo`` files) never pays the backend
import.
"""

from typing import Dict, List, Optional, Sequence

from .hlo_audit_rules import HloArtifact

__all__ = ["lower_train_step", "lower_decode_step", "lower_pipe_step",
           "lower_moe_step", "lower_spec_verify_step", "lower_spec_draft_step",
           "default_artifacts", "ARTIFACT_NAMES"]

ARTIFACT_NAMES = ("train_step_zero3", "decode_with_slots", "pipe_step",
                  "moe_step", "spec_verify", "spec_draft")

#: model dims per size knob: (n_layer, n_embd, n_head, seq)
_SIZES = {"tiny": (4, 64, 4, 32), "bench": (8, 512, 8, 128)}


def _leaf_counts(*trees) -> List[int]:
    import jax
    return [len(jax.tree_util.tree_leaves(t)) for t in trees]


def _reset_mesh():
    from ..parallel import topology
    topology.reset_mesh()


def _train_engine(config_extra: Dict, size: str, model=None):
    import deepspeed_tpu
    from ..models.gpt2 import GPT2Config, GPT2Model
    n_layer, n_embd, n_head, seq = _SIZES[size]
    _reset_mesh()
    if model is None:
        model = GPT2Model(GPT2Config(
            vocab_size=256, n_positions=seq + 1, n_embd=n_embd,
            n_layer=n_layer, n_head=n_head, pad_vocab_to_multiple=8,
            scan_unroll=n_layer))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "gradient_clipping": 1.0, "steps_per_print": 0,
    }
    config.update(config_extra)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine, seq


def _lower_engine_step(engine, seq: int, name: str,
                       donatable, donation_min_bytes: int) -> HloArtifact:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from .. import comm

    rng = np.random.default_rng(0)
    gbs = engine.train_micro_batch_size_per_gpu * engine.dp_world_size
    gas = engine.gradient_accumulation_steps
    batch = engine._to_device_batch({"input_ids": rng.integers(
        0, 250, (gas, gbs, seq), dtype=np.int32)})
    args = (engine.params, engine.opt_state, engine.scaler_state, batch,
            jnp.float32(1e-3), jax.random.PRNGKey(0), None,
            jnp.float32(1.0))
    per_before = comm.comm_per_op_stats()
    before = comm.comm_stats()
    with engine.mesh:
        lowered = engine._train_step_fn.lower(*args)
        stablehlo = lowered.as_text()
        hlo = lowered.compile().as_text()
    after = comm.comm_stats()
    per_after = comm.comm_per_op_stats()
    counts = _leaf_counts(*args)
    roles = ["params", "optimizer_state", "scaler", "batch"] + \
        ["scalar"] * (len(counts) - 4)
    return HloArtifact(
        name=name,
        hlo_texts=[hlo],
        stablehlo=stablehlo,
        arg_roles=list(zip(roles, counts)),
        donatable_roles=set(donatable),
        traced_per_op={k: per_after.get(k, 0) - per_before.get(k, 0)
                       for k in per_after},
        comm_delta={k: after[k] - before[k] for k in after},
        donation_min_bytes=donation_min_bytes,
        meta={"dp": engine.dp_world_size, "gas": gas},
    )


def lower_train_step(size: str = "tiny",
                     donation_min_bytes: Optional[int] = None,
                     overlap: bool = True) -> HloArtifact:
    """The bucketed + compressed ZeRO-3 bench train step — the PR-10
    schedule under the PR-6 wire (overlap_schedule on, int8
    hierarchical reduce-scatter): the artifact with the richest
    collective structure the repo emits. ``overlap=False`` compiles the
    same step with the overlap schedule disabled — the rigged
    regression benchmarks/anatomy.py uses to prove ds_tpu_perfdiff
    fails a de-overlapped program by collective bucket name."""
    if donation_min_bytes is None:
        donation_min_bytes = (16 << 10) if size == "tiny" else (1 << 20)
    engine, seq = _train_engine({
        "zero_optimization": {"stage": 3,
                              "stage3_param_persistence_threshold": 0},
        "overlap_schedule": {"enabled": overlap,
                             "bucket_bytes": (64 << 10) if size == "tiny"
                             else (4 << 20)},
        "comm_compression": {"all_gather": "int8", "reduce_scatter": "int8",
                             "hierarchical": True, "devices_per_host": 4},
    }, size)
    try:
        return _lower_engine_step(engine, seq, "train_step_zero3",
                                  ("params", "optimizer_state", "scaler"),
                                  donation_min_bytes)
    finally:
        engine.close()


def lower_pipe_step(size: str = "tiny", pp: int = 8,
                    donation_min_bytes: Optional[int] = None
                    ) -> HloArtifact:
    """The compiled 1F1B pipeline step (shard_map over 'pipe', ppermute
    stage hops through the comm dispatch). pp spans the whole mesh
    (dp=1): the jax pin's pre-0.5 shard_map crashes XLA's partitioner
    on partial-manual regions with a non-trivial auto axis, so the
    pp-only layout is the one this backend can lower — the collective
    structure under audit (per-tick ppermute chain + aux psum) is
    identical."""
    from ..models.gpt2 import GPT2Config, GPT2Model
    _, n_embd, n_head, seq = _SIZES[size]
    if donation_min_bytes is None:
        donation_min_bytes = (16 << 10) if size == "tiny" else (1 << 20)
    model = GPT2Model(GPT2Config(
        vocab_size=256, n_positions=seq + 1, n_embd=n_embd,
        n_layer=pp, n_head=n_head, pad_vocab_to_multiple=8))
    engine, seq = _train_engine({
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 2,
        "pipeline_parallel_size": pp,
        "zero_optimization": {"stage": 0},
    }, size, model=model)
    try:
        return _lower_engine_step(engine, seq, "pipe_step",
                                  ("params", "optimizer_state", "scaler"),
                                  donation_min_bytes)
    finally:
        engine.close()


def lower_moe_step(size: str = "tiny", ep: int = 4,
                   donation_min_bytes: Optional[int] = None
                   ) -> HloArtifact:
    """The expert-parallel MoE train step. Its dispatch/combine einsums
    reshard tokens data-axes ↔ expert-axis, which GSPMD lowers to an
    all-to-all that never passes through comm/comm.py — the HLO006
    finding this artifact exists to keep visible (waived with a
    tracking note: ROADMAP item 3)."""
    from ..models.gpt2_moe import GPT2MoEConfig, GPT2MoEModel
    if donation_min_bytes is None:
        donation_min_bytes = (16 << 10) if size == "tiny" else (1 << 20)
    n_layer, n_embd, n_head, seq = _SIZES["tiny"]   # MoE audit: structure,
    model = GPT2MoEModel(GPT2MoEConfig(             # not scale

        vocab_size=128, n_positions=seq + 1, n_embd=n_embd,
        n_layer=2, n_head=n_head, num_experts=ep, top_k=1,
        pad_vocab_to_multiple=8))
    engine, seq = _train_engine({
        "train_micro_batch_size_per_gpu": 4,
        "zero_optimization": {"stage": 2},
        "expert_parallel_size": ep,
    }, "tiny", model=model)
    try:
        return _lower_engine_step(engine, seq, "moe_step",
                                  ("params", "optimizer_state", "scaler"),
                                  donation_min_bytes)
    finally:
        engine.close()


def lower_decode_step(num_slots: int = 4, max_len: int = 32,
                      donation_min_bytes: int = 1 << 10) -> HloArtifact:
    """The fused all-slot decode step (``GPT2Model.decode_with_slots``
    under the slot pool) — the serving fleet's steady-state program.
    KV lanes are the donatable role here: an undonated pool doubles
    kv_slots HBM per tick."""
    import deepspeed_tpu
    import jax
    import jax.numpy as jnp
    import numpy as np
    from .. import comm
    from ..models.gpt2 import GPT2Config, GPT2Model

    _reset_mesh()
    model = GPT2Model(GPT2Config(vocab_size=128, n_positions=max_len * 2,
                                 n_embd=64, n_layer=2, n_head=4,
                                 pad_vocab_to_multiple=1, dtype="float32"))
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    pool = engine.init_slot_pool(num_slots, max_len)
    toks = np.zeros((num_slots,), np.int32)
    positions = np.ones((num_slots,), np.int32)
    temps = np.zeros((num_slots,), np.float32)
    top_ks = np.zeros((num_slots,), np.int32)
    top_ps = np.ones((num_slots,), np.float32)
    seeds = np.zeros((num_slots,), np.int32)
    per_before = comm.comm_per_op_stats()
    # one call builds (and caches) the compiled step; then lower the same
    # function for the audit text
    pool, _ = engine.slot_decode_step(pool, toks, positions, temps)
    fn = engine._slot_fns[("slot_decode", num_slots, max_len)]
    args = (engine.params, pool, jnp.asarray(toks), jnp.asarray(positions),
            jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps),
            jnp.asarray(seeds))
    with engine.mesh:
        lowered = fn.lower(*args)
        stablehlo = lowered.as_text()
        hlo = lowered.compile().as_text()
    per_after = comm.comm_per_op_stats()
    counts = _leaf_counts(*args)
    roles = ["weights", "kv_slots"] + ["io"] * (len(counts) - 2)
    return HloArtifact(
        name="decode_with_slots",
        hlo_texts=[hlo],
        stablehlo=stablehlo,
        arg_roles=list(zip(roles, counts)),
        donatable_roles={"kv_slots"},
        traced_per_op={k: per_after.get(k, 0) - per_before.get(k, 0)
                       for k in per_after},
        donation_min_bytes=donation_min_bytes,
        meta={"num_slots": num_slots, "max_len": max_len},
    )


def _spec_engine(num_slots: int, max_len: int):
    import deepspeed_tpu
    from ..models.gpt2 import GPT2Config, GPT2Model

    _reset_mesh()
    model = GPT2Model(GPT2Config(vocab_size=128, n_positions=max_len * 2,
                                 n_embd=64, n_layer=2, n_head=4,
                                 pad_vocab_to_multiple=1, dtype="float32"))
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32"})
    from ..serving.config import DraftConfig
    draft = engine.init_draft(DraftConfig(mode="self", layers=1))
    return engine, draft


def lower_spec_verify_step(num_slots: int = 4, max_len: int = 32,
                           k: int = 2,
                           donation_min_bytes: int = 1 << 10) -> HloArtifact:
    """The speculative verify step (``GPT2Model.verify_with_slots`` +
    in-step accept/rollback under the slot pool) — one batched forward
    verifying k draft tokens per slot. The TARGET KV pool is the
    donatable role: verify is state-in/state-out per tick exactly like
    decode, so an undonated pool doubles kv_slots HBM."""
    import jax.numpy as jnp
    import numpy as np
    from .. import comm

    engine, draft = _spec_engine(num_slots, max_len)
    pool = engine.init_slot_pool(num_slots, max_len)
    toks = np.zeros((num_slots,), np.int32)
    drafts = np.zeros((num_slots, k), np.int32)
    positions = np.ones((num_slots,), np.int32)
    temps = np.zeros((num_slots,), np.float32)
    top_ks = np.zeros((num_slots,), np.int32)
    top_ps = np.ones((num_slots,), np.float32)
    seeds = np.zeros((num_slots,), np.int32)
    per_before = comm.comm_per_op_stats()
    pool, _tgt, _acc = engine.slot_verify_step(pool, toks, drafts, positions,
                                               temps, top_ks, top_ps, seeds)
    fn = engine._slot_fns[("slot_verify", num_slots, max_len, k)]
    args = (engine.params, pool, jnp.asarray(toks), jnp.asarray(drafts),
            jnp.asarray(positions), jnp.asarray(temps), jnp.asarray(top_ks),
            jnp.asarray(top_ps), jnp.asarray(seeds))
    with engine.mesh:
        lowered = fn.lower(*args)
        stablehlo = lowered.as_text()
        hlo = lowered.compile().as_text()
    per_after = comm.comm_per_op_stats()
    counts = _leaf_counts(*args)
    roles = ["weights", "kv_slots"] + ["io"] * (len(counts) - 2)
    return HloArtifact(
        name="spec_verify",
        hlo_texts=[hlo],
        stablehlo=stablehlo,
        arg_roles=list(zip(roles, counts)),
        donatable_roles={"kv_slots"},
        traced_per_op={k2: per_after.get(k2, 0) - per_before.get(k2, 0)
                       for k2 in per_after},
        donation_min_bytes=donation_min_bytes,
        meta={"num_slots": num_slots, "max_len": max_len, "k": k},
    )


def lower_spec_draft_step(num_slots: int = 4, max_len: int = 32,
                          k: int = 2,
                          donation_min_bytes: int = 1 << 10) -> HloArtifact:
    """The speculative draft-propose step (k+1 draft decode steps in one
    compiled ``lax.scan``). The DRAFT KV pool is the donatable role —
    the draft pool rides the same state-in/state-out contract as the
    target pool, and HLO005 holds both sides to it."""
    import jax.numpy as jnp
    import numpy as np
    from .. import comm

    engine, draft = _spec_engine(num_slots, max_len)
    dpool = engine.init_draft_pool(draft, num_slots, max_len)
    toks = np.zeros((num_slots,), np.int32)
    positions = np.ones((num_slots,), np.int32)
    temps = np.zeros((num_slots,), np.float32)
    top_ks = np.zeros((num_slots,), np.int32)
    top_ps = np.ones((num_slots,), np.float32)
    seeds = np.zeros((num_slots,), np.int32)
    per_before = comm.comm_per_op_stats()
    dpool, _drafts = engine.slot_draft_propose(draft, dpool, toks, positions,
                                               temps, top_ks, top_ps, seeds,
                                               k)
    fn = engine._slot_fns[("slot_draft", num_slots, max_len, k, draft.key)]
    args = (draft.params, dpool, jnp.asarray(toks), jnp.asarray(positions),
            jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps),
            jnp.asarray(seeds))
    with engine.mesh:
        lowered = fn.lower(*args)
        stablehlo = lowered.as_text()
        hlo = lowered.compile().as_text()
    per_after = comm.comm_per_op_stats()
    counts = _leaf_counts(*args)
    roles = ["weights", "kv_slots"] + ["io"] * (len(counts) - 2)
    return HloArtifact(
        name="spec_draft",
        hlo_texts=[hlo],
        stablehlo=stablehlo,
        arg_roles=list(zip(roles, counts)),
        donatable_roles={"kv_slots"},
        traced_per_op={k2: per_after.get(k2, 0) - per_before.get(k2, 0)
                       for k2 in per_after},
        donation_min_bytes=donation_min_bytes,
        meta={"num_slots": num_slots, "max_len": max_len, "k": k,
              "draft": "self(layers=1)"},
    )


def default_artifacts(size: str = "tiny",
                      include: Optional[Sequence[str]] = None
                      ) -> List[HloArtifact]:
    """The audited artifact set, in the ISSUE/tier-1 order. ``include``
    filters by artifact name."""
    builders = {
        "train_step_zero3": lambda: lower_train_step(size),
        "decode_with_slots": lambda: lower_decode_step(),
        "pipe_step": lambda: lower_pipe_step(size),
        "moe_step": lambda: lower_moe_step(size),
        "spec_verify": lambda: lower_spec_verify_step(),
        "spec_draft": lambda: lower_spec_draft_step(),
    }
    names = include or ARTIFACT_NAMES
    return [builders[n]() for n in names]
