"""ds_tpu_lint Plane A — auditors over REAL lowered/compiled artifacts.

The runtime discipline (one comm dispatch, explicit shard_map exchange
legs, optimization_barrier pin chains) is only as good as the programs
XLA actually emits. These rules read the artifacts themselves — the
compiled HLO text for collective structure, the lowered StableHLO for
argument donation — so a deadlock-shaped or HBM-doubling-shaped bug is
caught on the CPU lowering *before* it becomes a hang on real chips
(DeepCompile's premise: the compiled schedule is an analyzable
artifact; EQuARX's warning: quantized collective legs are where silent
group mismatches hide).

Rules (registry + docs in findings.py):

- HLO001 orphaned-async       — every ``*-start`` pairs with a done
- HLO002 replica-groups-partition — groups exactly partition devices
- HLO003 subaxis-inconsistency — same group shape ⇒ same partition
- HLO004 issue-order-divergence — identical collective issue order
  across per-device programs (static shard_map deadlock check)
- HLO005 undonated-buffer     — large state args must be donated
- HLO006 dispatch-conformance — every HLO collective kind reconciles
  with the comm dispatch's traced accounting

Inputs arrive as :class:`HloArtifact` records —
``analysis/artifacts.py`` lowers the repo's real programs (ZeRO-3
bucketed train step, ``decode_with_slots``, pipe step, MoE step) into
them, and tests feed synthetic seeded-violation fixtures.

Standalone-loadable like findings.py: ``bin/ds_tpu_lint`` file-path-
loads it (with hlo_cost registered under ``_dstpu_hlo_cost``) so saved
``.hlo`` files can be audited without jax.
"""

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

try:
    from .findings import Finding, make_key
    from ..telemetry.hlo_cost import (collect_async, collect_collectives,
                                      collect_replica_groups,
                                      module_num_partitions)
except ImportError:                    # loaded by file path (bin/ds_tpu_lint)
    from _dstpu_lint_findings import Finding, make_key  # type: ignore
    from _dstpu_hlo_cost import (collect_async,  # type: ignore
                                 collect_collectives,
                                 collect_replica_groups,
                                 module_num_partitions)

__all__ = ["HloArtifact", "run_hlo_audit", "collect_donation",
           "DISPATCH_ACCEPTS"]

#: HLO collective kind -> comm-dispatch op names whose traced presence
#: legitimizes it. Many-to-many because quantized/hierarchical dispatch
#: paths lower one logical op into several HLO kinds: a quantized
#: all_reduce is an RS+AG pair, the hierarchical reduce_scatter is a
#: chunk-permute + intra psum_scatter + inter all_to_all, and GSPMD
#: inserts its own all-reduces (loss/grad-norm) and collective-permutes
#: (resharding) alongside any explicitly dispatched exchange.
DISPATCH_ACCEPTS: Dict[str, Tuple[str, ...]] = {
    "all-reduce": ("all_reduce", "broadcast", "scatter", "reduce_scatter",
                   "all_gather"),
    "all-gather": ("all_gather", "all_reduce"),
    "reduce-scatter": ("reduce_scatter", "all_reduce"),
    "all-to-all": ("all_to_all", "reduce_scatter"),
    "collective-permute": ("ppermute", "reduce_scatter", "all_to_all"),
}

_ASYNC_KINDS = ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
                "collective-permute")


@dataclass
class HloArtifact:
    """One lowered program under audit.

    ``hlo_texts``: compiled HLO module text(s) — one entry per device
    program (SPMD emits one; a list exercises the HLO004 cross-program
    order check). ``stablehlo``: the pre-compile lowering, whose
    ``func.func @main`` argument list carries donation attributes in
    flatten order — that is what lets HLO005 name the ROLE of an
    undonated buffer. ``arg_roles``: ``[(role, leaf_count), ...]`` in
    argument flatten order (role names follow the HBMLedger vocabulary:
    params / optimizer_state / kv_slots / batch / …). ``donatable_roles``:
    roles that are state-in/state-out for this program and therefore
    SHOULD be donated (a serving program's weights are read-only and
    exempt). ``traced_per_op``: comm dispatch per-op trace counts
    captured while this artifact was lowered (comm.comm_per_op_stats
    delta); None disables HLO006."""
    name: str
    hlo_texts: List[str] = field(default_factory=list)
    stablehlo: Optional[str] = None
    arg_roles: Optional[List[Tuple[str, int]]] = None
    donatable_roles: Set[str] = field(default_factory=set)
    traced_per_op: Optional[Dict[str, int]] = None
    comm_delta: Optional[Dict[str, int]] = None
    donation_min_bytes: int = 1 << 20
    meta: Dict[str, Any] = field(default_factory=dict)

    @property
    def path(self) -> str:
        return f"hlo:{self.name}"


# ------------------------------------------------------------- donation

_MAIN_RE = re.compile(r"func\.func\s+(?:public\s+)?@main\((.*?)\)\s*->",
                      re.DOTALL)
# the attr dict may nest braces inside quoted strings ('mhlo.sharding =
# "{devices=[8,1]<=[8]}"') — consume quoted runs atomically so the
# closing brace found is the attr dict's own
_ARG_RE = re.compile(
    r"%arg(\d+):\s*tensor<([^>]*)>\s*(\{(?:[^{}\"]+|\"[^\"]*\")*\})?")
_MLIR_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "i64": 8,
                     "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
                     "i8": 1, "ui8": 1, "i1": 1, "f8E4M3FN": 1,
                     "f8E5M2": 1}
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\((\d+),")


def _alias_header_body(hlo_text: str) -> Optional[str]:
    """The balanced-brace body of the module header's
    ``input_output_alias={...}`` (entries nest ``{}`` twice, which a
    regex can't scan)."""
    key = "input_output_alias={"
    i = hlo_text.find(key)
    if i < 0:
        return None
    j = i + len(key)
    depth = 1
    while j < len(hlo_text) and depth:
        c = hlo_text[j]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
        j += 1
    return hlo_text[i + len(key):j - 1]


def _mlir_tensor_bytes(ty: str) -> int:
    parts = ty.split("x")
    dtype = parts[-1]
    dims = parts[:-1]
    n = 1
    for d in dims:
        if not d.isdigit():
            return 0                  # dynamic dim: size unknowable
        n *= int(d)
    return n * _MLIR_DTYPE_BYTES.get(dtype, 4)


def collect_donation(stablehlo: str) -> List[Dict[str, Any]]:
    """Per-argument donation records from a lowered StableHLO module:
    ``{"index", "type", "bytes", "donated"}`` in flatten order.
    Donation is the ``tf.aliasing_output`` / ``jax.buffer_donor``
    attribute jax stamps on donated arguments."""
    m = _MAIN_RE.search(stablehlo)
    sig = m.group(1) if m else stablehlo
    out = []
    for am in _ARG_RE.finditer(sig):
        attrs = am.group(3) or ""
        out.append({
            "index": int(am.group(1)),
            "type": am.group(2),
            "bytes": _mlir_tensor_bytes(am.group(2)),
            "donated": ("tf.aliasing_output" in attrs or
                        "jax.buffer_donor" in attrs),
        })
    return out


def donated_params_from_hlo(hlo_text: str) -> Set[int]:
    """Parameter numbers aliased to an output in a compiled module's
    ``input_output_alias`` header — the post-compile cross-check for
    the StableHLO donation attributes."""
    body = _alias_header_body(hlo_text)
    if body is None:
        return set()
    return {int(x) for x in _ALIAS_ENTRY_RE.findall(body)}


def _role_of(index: int, arg_roles) -> str:
    if not arg_roles:
        return "unknown"
    off = 0
    for role, count in arg_roles:
        if index < off + count:
            return role
        off += count
    return "unknown"


# ------------------------------------------------------------- the rules

def _audit_async(art: HloArtifact, findings: List[Finding]):
    for mi, hlo in enumerate(art.hlo_texts):
        for kind in _ASYNC_KINDS:
            starts = len(re.findall(rf"\b{kind}-start\(", hlo))
            dones = len(re.findall(rf"\b{kind}-done\(", hlo))
            if starts != dones:
                findings.append(Finding(
                    rule="HLO001", severity="error", path=art.path, line=0,
                    message=f"{kind}: {starts} start vs {dones} done in "
                            f"program {mi} — an in-flight collective is "
                            f"never completed (deadlock/leak shape)",
                    waiver_key=make_key("HLO001", art.name, kind)))
        g_start = len(re.findall(r"\basync-start\b", hlo))
        g_done = len(re.findall(r"\basync-done\b", hlo))
        if g_start != g_done:
            findings.append(Finding(
                rule="HLO001", severity="error", path=art.path, line=0,
                message=f"generic async-start/done mismatch "
                        f"({g_start} vs {g_done}) in program {mi}",
                waiver_key=make_key("HLO001", art.name, "async")))


def _check_partition(groups: List[List[int]], n_devices: int) -> str:
    """'' when ``groups`` exactly partition the device set, else the
    violation description."""
    sizes = {len(g) for g in groups}
    if len(sizes) > 1:
        return f"unequal group sizes {sorted(sizes)}"
    flat: List[int] = [d for g in groups for d in g]
    if len(set(flat)) != len(flat):
        dupes = sorted({d for d in flat if flat.count(d) > 1})
        return f"device(s) {dupes} appear in more than one group"
    expect = set(range(n_devices)) if n_devices else \
        set(range(max(flat) + 1)) if flat else set()
    missing = expect - set(flat)
    if missing:
        return (f"devices {sorted(missing)[:8]} participate in no group "
                f"(union must cover all {len(expect)} devices)")
    extra = set(flat) - expect
    if extra:
        return f"group members {sorted(extra)[:8]} exceed the device count"
    return ""


def _audit_replica_groups(art: HloArtifact, findings: List[Finding]):
    for mi, hlo in enumerate(art.hlo_texts):
        n_dev = module_num_partitions(hlo)
        recs = collect_replica_groups(hlo)
        # HLO002: each collective's groups partition the device set
        for rec in recs:
            if rec["groups"] is None:
                continue                 # empty form: all devices, trivially ok
            err = _check_partition(rec["groups"], n_dev)
            if err:
                findings.append(Finding(
                    rule="HLO002", severity="error", path=art.path,
                    line=rec["line"],
                    message=f"{rec['op']} %{rec['name']} replica_groups "
                            f"{rec['groups']}: {err}",
                    waiver_key=make_key("HLO002", art.name, rec["op"])))
        # HLO003: same group shape -> same partition everywhere
        by_shape: Dict[Tuple[int, int], Dict[str, Any]] = {}
        for rec in recs:
            if not rec["groups"]:
                continue
            shape = (len(rec["groups"]), len(rec["groups"][0]))
            canon = tuple(sorted(tuple(sorted(g)) for g in rec["groups"]))
            prev = by_shape.setdefault(shape, {"canon": canon, "rec": rec})
            if prev["canon"] != canon:
                findings.append(Finding(
                    rule="HLO003", severity="error", path=art.path,
                    line=rec["line"],
                    message=f"inconsistent {shape[0]}x{shape[1]} subaxis "
                            f"partition: %{prev['rec']['name']} uses "
                            f"{list(prev['canon'])[:4]} but %{rec['name']} "
                            f"uses {list(canon)[:4]} — hierarchical legs "
                            f"disagree on the (host, local) split",
                    waiver_key=make_key("HLO003", art.name,
                                        f"{shape[0]}x{shape[1]}")))


def _issue_order(hlo: str) -> List[Tuple[str, Any]]:
    """Ordered (op kind, canonical groups) sequence over the module —
    the thing every device must agree on for SPMD progress."""
    seq = []
    for rec in collect_replica_groups(hlo):
        base = re.sub(r"-start$|-done$", "", rec["op"])
        canon = None if rec["groups"] is None else \
            tuple(sorted(tuple(sorted(g)) for g in rec["groups"]))
        if rec["op"].endswith("-done"):
            continue
        seq.append((base, canon))
    return seq


def _audit_issue_order(art: HloArtifact, findings: List[Finding]):
    if len(art.hlo_texts) < 2:
        return
    ref = _issue_order(art.hlo_texts[0])
    for mi, hlo in enumerate(art.hlo_texts[1:], start=1):
        seq = _issue_order(hlo)
        if seq != ref:
            diverge = next((i for i, (a, b) in enumerate(zip(ref, seq))
                            if a != b), min(len(ref), len(seq)))
            a = ref[diverge][0] if diverge < len(ref) else "<end>"
            b = seq[diverge][0] if diverge < len(seq) else "<end>"
            findings.append(Finding(
                rule="HLO004", severity="error", path=art.path, line=0,
                message=f"collective issue order diverges between program "
                        f"0 and program {mi} at position {diverge}: "
                        f"{a} vs {b} — devices would enter different "
                        f"collectives first and deadlock",
                waiver_key=make_key("HLO004", art.name, f"program{mi}")))


def _audit_donation(art: HloArtifact, findings: List[Finding]):
    if not art.stablehlo:
        return
    args = collect_donation(art.stablehlo)
    # cross-check: the compiled module's input_output_alias should donate
    # at least the args StableHLO marked (XLA may add may-alias entries,
    # never drop requested ones silently — if it did, flag it)
    hlo_donated = donated_params_from_hlo(art.hlo_texts[0]) \
        if art.hlo_texts else None
    for a in args:
        role = _role_of(a["index"], art.arg_roles)
        if a["donated"] or a["bytes"] < art.donation_min_bytes:
            continue
        if art.donatable_roles and role not in art.donatable_roles:
            continue
        mib = a["bytes"] / 2**20
        findings.append(Finding(
            rule="HLO005", severity="error", path=art.path, line=0,
            message=f"arg {a['index']} ({role}, tensor<{a['type']}>, "
                    f"{mib:.1f} MiB) is not donated — input and output "
                    f"copies of this {role} buffer are live at once "
                    f"(HBMLedger would double-count the role)",
            waiver_key=make_key("HLO005", art.name,
                                f"{role}:{a['index']}")))
    if hlo_donated is not None and hlo_donated == set() and \
            any(a["donated"] for a in args):
        findings.append(Finding(
            rule="HLO005", severity="warning", path=art.path, line=0,
            message="StableHLO marks donated args but the compiled "
                    "module's input_output_alias is empty — XLA dropped "
                    "every donation (shape/sharding mismatch?)",
            waiver_key=make_key("HLO005", art.name, "alias-dropped")))


def _audit_dispatch(art: HloArtifact, findings: List[Finding]):
    if art.traced_per_op is None:
        return
    traced = {k: v for k, v in art.traced_per_op.items() if v}
    for mi, hlo in enumerate(art.hlo_texts):
        sync = collect_collectives(hlo)
        async_ = collect_async(hlo)
        kinds = set(sync) | set(async_)
        for kind in sorted(kinds):
            accepts = DISPATCH_ACCEPTS.get(kind, ())
            if any(traced.get(op) for op in accepts):
                continue
            count = sync.get(kind, {}).get("count", 0) + async_.get(kind, 0)
            findings.append(Finding(
                rule="HLO006", severity="error", path=art.path, line=0,
                message=f"{count} {kind} op(s) in the compiled module but "
                        f"the comm dispatch traced none of "
                        f"{list(accepts) or '(any)'} — these bytes bypass "
                        f"comm_stats() and every compression policy",
                waiver_key=make_key("HLO006", art.name, kind),
                meta={"hlo_count": count, "traced": traced}))


def run_hlo_audit(artifacts: Sequence[HloArtifact],
                  rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Plane A over a set of artifacts. Returns raw findings — the
    caller applies waivers."""
    active = set(rules) if rules else {"HLO001", "HLO002", "HLO003",
                                       "HLO004", "HLO005", "HLO006"}
    findings: List[Finding] = []
    for art in artifacts:
        if "HLO001" in active:
            _audit_async(art, findings)
        if {"HLO002", "HLO003"} & active:
            _audit_replica_groups(art, findings)
        if "HLO004" in active:
            _audit_issue_order(art, findings)
        if "HLO005" in active:
            _audit_donation(art, findings)
        if "HLO006" in active:
            _audit_dispatch(art, findings)
    return findings
