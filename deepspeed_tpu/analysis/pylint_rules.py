"""ds_tpu_lint Plane B — framework-aware AST lints (stdlib ``ast`` only).

Four rules over the repo's python source (``deepspeed_tpu/``,
``benchmarks/``, ``bin/``, ``examples/``; tests are exempt — they seed
violations on purpose):

AST001 raw-collective
    ``lax.psum``/``all_gather``/``ppermute``/… called outside
    ``deepspeed_tpu/comm/`` and ``deepspeed_tpu/ops/``. Everything else
    must go through the compression-aware dispatch in ``comm/comm.py``
    so int8/fp8 policies and wire accounting apply (this rule is how
    the MoE GSPMD bypass stays *named* rather than forgotten — see the
    HLO006 waiver in lint_waivers.json and ROADMAP item 3).

AST002 host-sync-in-traced
    ``float(arg)``, ``.item()``, ``np.asarray``/``np.array``,
    ``time.time``/``perf_counter``, ``jax.device_get`` inside a function
    that is jitted or shard_mapped (decorator, ``partial(jax.jit,…)``,
    or passed by name/lambda to ``jit``/``shard_map``). Under trace
    these either raise (concretization) or silently bake a constant,
    and on device they force a host round-trip per step.

AST003 ownerless-gauge
    ``*.set_counter(...)`` without ``owner=`` — the static form of the
    tests/unit/test_metrics_lifecycle.py runtime check: ownerless
    gauges survive their producer's shutdown and leak across
    co-resident engines.

AST004 unknown-config-key
    Top-level keys of config dict literals handed to
    ``deepspeed_tpu.initialize(...)`` (and of ``examples/configs/*.json``)
    must exist in the registered config blocks, harvested statically
    from ``runtime/constants.py`` and the ``.get("…")`` reads in
    ``runtime/config.py``, ``serving/config.py`` and
    ``serving/fleet/config.py``. Unknown keys are silently ignored at
    runtime — the classic "my setting did nothing" bug.

Standalone-loadable: ``bin/ds_tpu_lint`` file-path-loads this module so
the AST plane runs without importing jax or the package __init__ chain.
"""

import ast
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set

try:
    from .findings import Finding, make_key
except ImportError:                    # loaded by file path (bin/ds_tpu_lint)
    from _dstpu_lint_findings import Finding, make_key  # type: ignore

__all__ = ["run_ast_lint", "lint_source", "harvest_config_keys",
           "check_config_doc", "DEFAULT_SCAN_DIRS", "COLLECTIVE_FNS"]

#: directories scanned by default, relative to the repo root
DEFAULT_SCAN_DIRS = ("deepspeed_tpu", "benchmarks", "bin", "examples")

#: path prefixes where raw lax collectives are the implementation layer
RAW_COLLECTIVE_OK = ("deepspeed_tpu/comm/", "deepspeed_tpu/ops/")

#: jax.lax collective callables AST001 polices
COLLECTIVE_FNS = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle"})

_HOST_SYNC_MODS = {"np", "numpy", "onp"}
_HOST_SYNC_NP = {"asarray", "array"}
_TIME_FNS = {"time", "perf_counter", "perf_counter_ns", "monotonic"}


def _dotted(node) -> str:
    """'jax.lax.psum' for an Attribute/Name chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_or_shardmap(func) -> bool:
    d = _dotted(func)
    return (d in ("jit", "shard_map") or d.endswith(".jit") or
            d.endswith(".shard_map"))


def _partial_of_jit(call: ast.Call) -> bool:
    """partial(jax.jit, ...) / functools.partial(shard_map, ...)"""
    d = _dotted(call.func)
    if not (d == "partial" or d.endswith(".partial")):
        return False
    return bool(call.args) and _is_jit_or_shardmap(call.args[0])


class _TracedFns(ast.NodeVisitor):
    """Names of functions wrapped for jit/shard_map anywhere in the
    module: decorated defs, and defs/lambdas passed as the first
    positional argument of a jit/shard_map call."""

    def __init__(self):
        self.names: Set[str] = set()
        self.lambda_nodes: List[ast.Lambda] = []

    def visit_FunctionDef(self, node):
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _is_jit_or_shardmap(target) or (
                    isinstance(dec, ast.Call) and _partial_of_jit(dec)):
                self.names.add(node.name)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        if _is_jit_or_shardmap(node.func) or _partial_of_jit(node):
            wrapped = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg in ("fun", "f", "func"):
                    wrapped = kw.value
            if isinstance(wrapped, ast.Name):
                self.names.add(wrapped.id)
            elif isinstance(wrapped, ast.Lambda):
                self.lambda_nodes.append(wrapped)
        self.generic_visit(node)


def _func_params(fn) -> Set[str]:
    a = fn.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


class _Linter(ast.NodeVisitor):
    def __init__(self, rel: str, findings: List[Finding],
                 rules: Set[str], traced: _TracedFns):
        self.rel = rel
        self.findings = findings
        self.rules = rules
        self.traced = traced
        #: stack of param-name sets while inside traced function bodies
        self._traced_stack: List[Set[str]] = []
        self._raw_ok = any(self.rel.startswith(p)
                           for p in RAW_COLLECTIVE_OK)

    # ------------------------------------------------------------ helpers
    def _emit(self, rule, node, symbol, message, severity="error"):
        self.findings.append(Finding(
            rule=rule, severity=severity, path=self.rel,
            line=getattr(node, "lineno", 0), message=message,
            waiver_key=make_key(rule, self.rel, symbol)))

    def _in_traced(self) -> bool:
        return bool(self._traced_stack)

    # ------------------------------------------------------- fn scoping
    def visit_FunctionDef(self, node):
        is_traced = self._in_traced() or node.name in self.traced.names \
            or any(_is_jit_or_shardmap(d.func if isinstance(d, ast.Call)
                                       else d) or
                   (isinstance(d, ast.Call) and _partial_of_jit(d))
                   for d in node.decorator_list)
        if is_traced:
            params = _func_params(node)
            if self._traced_stack:
                params = params | self._traced_stack[-1]
            self._traced_stack.append(params)
        self.generic_visit(node)
        if is_traced:
            self._traced_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        is_traced = self._in_traced() or node in self.traced.lambda_nodes
        if is_traced:
            params = _func_params(node)
            if self._traced_stack:
                params = params | self._traced_stack[-1]
            self._traced_stack.append(params)
        self.generic_visit(node)
        if is_traced:
            self._traced_stack.pop()

    # ------------------------------------------------------------- rules
    def visit_Call(self, node):
        d = _dotted(node.func)

        # AST001: raw lax collective outside comm/ and ops/
        if "AST001" in self.rules and not self._raw_ok:
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in COLLECTIVE_FNS and \
                    (d.split(".")[-2:-1] == ["lax"] or d.startswith("lax.")):
                self._emit(
                    "AST001", node, d.split(".", 1)[-1]
                    if d.startswith("jax.") else d,
                    f"raw {d}() bypasses the comm dispatch — route through "
                    f"deepspeed_tpu.comm (compression policy + wire "
                    f"accounting) or add a reasoned waiver")

        # AST002: host sync inside traced code
        if "AST002" in self.rules and self._in_traced():
            sym = None
            why = None
            if isinstance(node.func, ast.Name) and node.func.id == "float" \
                    and node.args and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in self._traced_stack[-1]:
                sym, why = "float", (f"float({node.args[0].id}) forces a "
                                     f"host sync on a traced value")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                sym, why = ".item", ".item() forces a host sync under trace"
            elif isinstance(node.func, ast.Attribute):
                base = _dotted(node.func.value)
                if base in _HOST_SYNC_MODS and \
                        node.func.attr in _HOST_SYNC_NP:
                    sym = f"{base}.{node.func.attr}"
                    why = (f"{sym}() materializes a traced value on host "
                           f"(use jnp inside jitted code)")
                elif base == "time" and node.func.attr in _TIME_FNS:
                    sym = f"time.{node.func.attr}"
                    why = (f"{sym}() inside a traced function is evaluated "
                           f"ONCE at trace time — it cannot time steps")
                elif d == "jax.device_get":
                    sym, why = "jax.device_get", \
                        "device_get blocks dispatch inside traced code"
            if sym:
                self._emit("AST002", node, sym,
                           f"host sync in jitted/shard_mapped code: {why}")

        # AST003: ownerless gauge
        if "AST003" in self.rules and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "set_counter":
            if not any(kw.arg == "owner" for kw in node.keywords):
                tag = "?"
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    tag = node.args[0].value
                self._emit("AST003", node, tag,
                           f"set_counter({tag!r}) without owner= — the "
                           f"gauge outlives its producer (see "
                           f"test_metrics_lifecycle)")

        self.generic_visit(node)


# --------------------------------------------------------------- AST004

#: files whose string keys define the registered config surface
_CONFIG_SOURCES = ("deepspeed_tpu/runtime/constants.py",
                   "deepspeed_tpu/runtime/config.py",
                   "deepspeed_tpu/serving/config.py",
                   "deepspeed_tpu/serving/fleet/config.py",
                   "deepspeed_tpu/inference/config.py",
                   # the elasticity block parses itself (ElasticityConfig
                   # reads param_dict.get(...)); its keys and the fleet
                   # AutoscaleConfig dataclass fields are the PR-14
                   # config surface
                   "deepspeed_tpu/elasticity/elasticity.py",
                   # the measured-trials sweep: AutotuneConfig dataclass
                   # fields are the `autotune` block's key surface
                   "deepspeed_tpu/autotuning/measure.py")

#: keys read through non-static paths (getattr loops, env, kwargs)
_EXTRA_KNOWN = {"seed"}


def harvest_config_keys(root: str) -> Set[str]:
    """The statically-registered config key surface: every string
    constant in runtime/constants.py plus every string literal read via
    ``.get("…")`` or ``d["…"]`` in the config parsers. A superset of
    the top-level keys (nested keys like "enabled" ride along), which
    is exactly the safe direction for a not-registered check. Dataclass
    config models (ServingConfig and friends) register keys as FIELD
    names, so class-level annotated assignments count too."""
    known: Set[str] = set(_EXTRA_KNOWN)
    for rel in _CONFIG_SOURCES:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue
        with open(path) as f:
            try:
                tree = ast.parse(f.read())
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str) and \
                    all(isinstance(t, ast.Name) for t in node.targets):
                known.add(node.value.value)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                known.add(node.args[0].value)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                known.add(node.slice.value)
            elif isinstance(node, ast.ClassDef):
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and \
                            isinstance(stmt.target, ast.Name):
                        known.add(stmt.target.id)
                    elif isinstance(stmt, ast.Assign):
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                known.add(t.id)
    return known


def check_config_doc(doc: dict, known: Set[str], rel: str,
                     findings: List[Finding], line: int = 0):
    """Flag top-level keys of a parsed config document not in the
    registered surface."""
    for key in doc:
        if isinstance(key, str) and key not in known:
            findings.append(Finding(
                rule="AST004", severity="error", path=rel, line=line,
                message=f"config key {key!r} is not in any registered "
                        f"config block — it will be silently ignored",
                waiver_key=make_key("AST004", rel, key)))


def _config_dicts_passed_to_initialize(tree: ast.Module):
    """(dict node, lineno) for every dict literal handed to an
    ``initialize``/``init_inference`` call as ``config=`` (or the 2nd
    positional arg), following one level of Name indirection."""
    assigns: Dict[str, ast.Dict] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigns[t.id] = node.value
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if not (d == "initialize" or d.endswith(".initialize")):
            continue
        cfg = None
        for kw in node.keywords:
            if kw.arg == "config":
                cfg = kw.value
        if cfg is None and len(node.args) >= 2:
            cfg = node.args[1]
        if isinstance(cfg, ast.Name):
            cfg = assigns.get(cfg.id)
        if isinstance(cfg, ast.Dict):
            out.append((cfg, node.lineno))
    return out


def _check_config_literals(tree, known, rel, findings):
    for dct, line in _config_dicts_passed_to_initialize(tree):
        for k in dct.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                    and k.value not in known:
                findings.append(Finding(
                    rule="AST004", severity="error", path=rel,
                    line=getattr(k, "lineno", line),
                    message=f"config key {k.value!r} passed to initialize() "
                            f"is not in any registered config block",
                    waiver_key=make_key("AST004", rel, k.value)))


# ----------------------------------------------------------------- entry

def lint_source(source: str, rel: str,
                rules: Optional[Iterable[str]] = None,
                known_config_keys: Optional[Set[str]] = None
                ) -> List[Finding]:
    """Run the AST rules over one python source string (``rel`` is the
    repo-relative path used for locations and waiver keys)."""
    active = set(rules) if rules else {"AST001", "AST002", "AST003",
                                       "AST004"}
    findings: List[Finding] = []
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return findings                  # not python (data file in bin/)
    traced = _TracedFns()
    traced.visit(tree)
    _Linter(rel, findings, active, traced).visit(tree)
    if "AST004" in active and known_config_keys:
        _check_config_literals(tree, known_config_keys, rel, findings)
    return findings


def _iter_py_files(root: str, dirs: Sequence[str]):
    for d in dirs:
        base = os.path.join(root, d)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [x for x in dirnames
                           if x not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                path = os.path.join(dirpath, fn)
                if fn.endswith(".py"):
                    yield path
                elif d == "bin" and not fn.endswith((".json", ".md")):
                    # bin/ scripts have no extension; sniff the shebang
                    try:
                        with open(path) as f:
                            if "python" in f.readline():
                                yield path
                    except OSError:
                        pass


def run_ast_lint(root: str, files: Optional[Sequence[str]] = None,
                 rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Plane B over the repo (or an explicit file list). Returns raw
    findings — the caller applies waivers."""
    root = os.path.abspath(root)
    known = harvest_config_keys(root)
    findings: List[Finding] = []
    paths = [os.path.abspath(p) for p in files] if files else \
        list(_iter_py_files(root, DEFAULT_SCAN_DIRS))
    for path in paths:
        rel = os.path.relpath(path, root)
        if rel.split(os.sep)[0] == "tests":
            continue                     # fixtures seed violations
        try:
            with open(path) as f:
                src = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        if path.endswith(".json"):
            if (not rules) or "AST004" in set(rules):
                try:
                    doc = json.loads(src)
                except ValueError:
                    continue
                if isinstance(doc, dict):
                    check_config_doc(doc, known, rel, findings)
            continue
        findings.extend(lint_source(src, rel, rules=rules,
                                    known_config_keys=known))
    if files is None and ((not rules) or "AST004" in set(rules)):
        cfg_dir = os.path.join(root, "examples", "configs")
        if os.path.isdir(cfg_dir):
            for fn in sorted(os.listdir(cfg_dir)):
                if not fn.endswith(".json"):
                    continue
                rel = os.path.join("examples", "configs", fn)
                try:
                    with open(os.path.join(cfg_dir, fn)) as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    continue
                if isinstance(doc, dict):
                    check_config_doc(doc, known, rel, findings)
    return findings
