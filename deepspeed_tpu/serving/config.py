"""Serving config.

``ServingConfig`` follows the ``DeepSpeedConfigModel`` pattern of
deepspeed_tpu/inference/config.py: a dataclass with ``from_dict`` JSON
mapping, alias warnings, strict unknown-key rejection, and ``validate()``.
The monitor sink sub-blocks reuse ``MonitorSinkConfig`` from the training
config so a serving JSON can carry the same ``csv_monitor`` /
``tensorboard`` / ``wandb`` sections as a training JSON.
"""

import dataclasses
from typing import Any, Optional

from ..runtime.config import MonitorSinkConfig
from ..runtime.config_utils import ConfigError, DeepSpeedConfigModel


@dataclasses.dataclass
class SLOConfig(DeepSpeedConfigModel):
    """The serving ``"slo"`` block (serving/metrics.py): sliding-window
    latency percentiles + error-budget burn rate against configurable
    targets. ``window`` bounds the percentile sources (a long-running
    replica's memory stays O(window)); each ``*_ms`` target is optional —
    unset targets track percentiles but contribute no violations. The
    burn-rate gauge is observed violation rate ÷ allowed violation rate
    (``1 - target``): 1.0 = burning budget exactly as fast as allowed,
    >1 = out of SLO."""
    #: sliding-window size (latency samples kept per metric)
    window: int = 1024
    #: time-to-first-token target, ms (p{quantile} must stay under it)
    ttft_ms: Optional[float] = None
    #: time-per-output-token target, ms (fused decode-step wall time)
    tpot_ms: Optional[float] = None
    #: end-to-end request latency target, ms
    e2e_ms: Optional[float] = None
    #: fraction of samples that must meet each target (0.99 = "p99 SLO")
    target: float = 0.99
    #: age samples out of the sliding windows by WALL CLOCK after this
    #: many seconds (None = count-bounded only). Without it an idle
    #: replica's windows are frozen history — its last burn rate reads
    #: as live forever, which starves it in the router's burn-penalty
    #: score and can pin autoscaling; with it, ``last_burn_rate`` and
    #: the dstpu_tenant_* burn gauges relax to 0 once the replica has
    #: been idle for ``decay_s``.
    decay_s: Optional[float] = None

    def validate(self):
        if self.window < 8:
            raise ConfigError("slo.window must be >= 8")
        if not (0.0 < self.target < 1.0):
            raise ConfigError("slo.target must be in (0, 1)")
        for name in ("ttft_ms", "tpot_ms", "e2e_ms"):
            val = getattr(self, name)
            if val is not None and val <= 0:
                raise ConfigError(f"slo.{name} must be > 0 when set")
        if self.decay_s is not None and self.decay_s <= 0:
            raise ConfigError("slo.decay_s must be > 0 when set")


@dataclasses.dataclass
class PrefixCacheConfig(DeepSpeedConfigModel):
    """The ``"prefix_cache"`` block (serving/fleet/prefix_cache.py):
    radix-tree reuse of retired slots' KV lanes. A request whose prompt
    shares >= ``min_prefix_len`` tokens with a cached sequence admits via
    lane-copy + suffix prefill instead of a full prefill."""
    enabled: bool = False
    #: shortest shared prefix worth a lane copy (shorter prompts also
    #: never donate their slot)
    min_prefix_len: int = 8
    #: cap on slots parked in the cache (0 = bounded only by the pool;
    #: eviction is on-demand LRU either way)
    max_cached_slots: int = 0

    def validate(self):
        if self.min_prefix_len < 1:
            raise ConfigError("prefix_cache.min_prefix_len must be >= 1")
        if self.max_cached_slots < 0:
            raise ConfigError("prefix_cache.max_cached_slots must be >= 0")


@dataclasses.dataclass
class KVQuantConfig(DeepSpeedConfigModel):
    """The ``"kv_quant"`` block: store the slot pool int8 with per-column
    f32 scales (inference/kv_quant.py) — ~4x the concurrent slots per HBM
    byte, greedy-decode parity bounded by the per-column quantization
    error (tests/unit/test_fleet.py pins the bound)."""
    enabled: bool = False

    def validate(self):
        pass


@dataclasses.dataclass
class ChunkedPrefillConfig(DeepSpeedConfigModel):
    """The ``"chunked_prefill"`` block (serving/scheduler.py): Sarathi-
    style stall-free batching on static shapes. A prompt whose unshared
    suffix exceeds ``chunk_tokens`` is admitted as a PREFILLING request
    that holds its slot across ticks and writes one ``chunk_tokens``-sized
    K/V chunk per tick (``InferenceEngine.slot_chunk_prefill`` — logits
    head DCE'd, one compiled program per pow2 chunk flavor), with the
    final sub-chunk going through the existing pow2 suffix-prefill
    machinery so the first token still derives from ``(seed, position)``
    only. Each tick's work is bounded by ``decode + at most chunk_tokens
    of prefill``, so in-flight TPOT stays bounded regardless of prompt
    length."""
    enabled: bool = False
    #: prefill tokens per tick. Must be a power of two: the chunk program
    #: compiles exactly once per (chunk_tokens, pool) flavor, like the
    #: suffix-prefill buckets it is built from.
    chunk_tokens: int = 256

    def validate(self):
        if self.chunk_tokens < 16 or \
                (self.chunk_tokens & (self.chunk_tokens - 1)):
            raise ConfigError(
                f"chunked_prefill.chunk_tokens must be a power of two "
                f">= 16 (one compiled chunk flavor), got {self.chunk_tokens}")


@dataclasses.dataclass
class TenantConfig(DeepSpeedConfigModel):
    """The ``"tenants"`` block: the tenant dimension of the serving
    plane. With ``enabled``, the scheduler's single FIFO becomes
    per-tenant queues served by deficit round-robin — admission work
    (prefill tokens) is granted proportionally to ``weights`` among
    backlogged tenants, so one whale tenant cannot head-of-line-block
    everyone else's TTFT. The FleetRouter additionally enforces
    per-tenant token-bucket rate limits (``rate_tokens_per_s`` /
    ``burst_tokens``, cost = prompt + requested new tokens), rejecting
    over-limit submits with a 429-style ``RateLimited`` QueueFull.
    Per-tenant SLO windows (serving/metrics.py) export
    ``dstpu_tenant_*`` gauges either way."""
    enabled: bool = False
    #: DRR weight for tenants not named in ``weights``
    default_weight: float = 1.0
    #: {tenant: weight} — a weight-2 tenant gets twice the admission
    #: tokens of a weight-1 tenant while both are backlogged
    weights: Any = None
    #: DRR quantum per weight unit, in prompt tokens per round
    quantum_tokens: int = 256
    #: router token-bucket refill for tenants not named in ``rates``
    #: (tokens/second; 0 = unlimited)
    rate_tokens_per_s: float = 0.0
    #: {tenant: tokens_per_s} per-tenant refill overrides
    rates: Any = None
    #: token-bucket capacity (burst allowance), tokens
    burst_tokens: int = 8192
    #: cap on distinct tenants with live metric windows; excess tenants
    #: fold into ``__other__`` (gauge cardinality stays bounded even if
    #: a client sprays random tenant strings)
    max_tracked: int = 64

    def validate(self):
        if self.default_weight <= 0:
            raise ConfigError("tenants.default_weight must be > 0")
        if self.weights is None:
            self.weights = {}
        if not isinstance(self.weights, dict) or not all(
                isinstance(k, str) and isinstance(v, (int, float)) and v > 0
                for k, v in self.weights.items()):
            raise ConfigError(
                "tenants.weights must be a {tenant: positive weight} dict")
        if self.quantum_tokens < 1:
            raise ConfigError("tenants.quantum_tokens must be >= 1")
        if self.rate_tokens_per_s < 0:
            raise ConfigError("tenants.rate_tokens_per_s must be >= 0")
        if self.rates is None:
            self.rates = {}
        if not isinstance(self.rates, dict) or not all(
                isinstance(k, str) and isinstance(v, (int, float)) and v >= 0
                for k, v in self.rates.items()):
            raise ConfigError(
                "tenants.rates must be a {tenant: tokens_per_s} dict")
        if self.burst_tokens < 1:
            raise ConfigError("tenants.burst_tokens must be >= 1")
        if self.max_tracked < 1:
            raise ConfigError("tenants.max_tracked must be >= 1")

    def weight_of(self, tenant: str) -> float:
        return float(self.weights.get(tenant, self.default_weight))

    def rate_of(self, tenant: str) -> float:
        return float(self.rates.get(tenant, self.rate_tokens_per_s))


@dataclasses.dataclass
class DraftConfig(DeepSpeedConfigModel):
    """The draft flavor inside the ``"speculative"`` block
    (inference/speculative.py). ``mode="self"`` — the self-speculative
    fallback — slices the target's own first ``layers`` blocks as the
    draft (no second model has to fit HBM); ``mode="model"`` builds a
    separate small config of the same family (``n_layer``/``n_embd``/
    ``n_head`` override the target's dims; vocab and positions are
    inherited so token ids line up)."""
    mode: str = "self"      # self | model
    #: self mode: early-exit depth (0 = target n_layer // 2)
    layers: int = 0
    #: model mode: draft dims (0 = inherit the target's)
    n_layer: int = 2
    n_embd: int = 0
    n_head: int = 0
    #: model mode: draft param init seed (until a trained draft loads)
    seed: int = 0

    def validate(self):
        if self.mode not in ("self", "model"):
            raise ConfigError(
                f"speculative.draft.mode must be self|model, "
                f"got {self.mode!r}")
        if self.layers < 0:
            raise ConfigError("speculative.draft.layers must be >= 0")
        if self.mode == "model" and self.n_layer < 1:
            raise ConfigError("speculative.draft.n_layer must be >= 1")


@dataclasses.dataclass
class SpeculativeConfig(DeepSpeedConfigModel):
    """The ``"speculative"`` block: draft-model speculative decoding
    over the slot pool. Each tick the draft proposes ``k`` tokens per
    slot (one compiled scan), the target verifies all of them in ONE
    batched forward (``verify_with_slots``) and every slot advances by
    its accepted prefix plus one target token — between 1 and k+1
    tokens per tick instead of exactly 1. The emitted stream is bitwise
    identical to non-speculative serving (exact-match verification
    against the target's deterministic per-position sample)."""
    enabled: bool = False
    #: draft tokens proposed per slot per tick. Must be a power of two:
    #: each (num_slots, max_model_len, k) flavor of the verify program
    #: compiles exactly once, and pow2 buckets keep the flavor count
    #: logarithmic if an adaptive policy later varies k.
    k: int = 4
    #: draft flavor (dict -> DraftConfig)
    draft: Any = None
    #: acceptance-rate EMA floor: crossing BELOW it (edge-triggered,
    #: after warmup_ticks) fires the flight recorder with kind
    #: "acceptance_drop" — speculation that stopped paying for itself
    #: is an incident worth a postmortem bundle. 0 disables.
    acceptance_floor: float = 0.0
    #: speculative ticks before the floor rule arms
    warmup_ticks: int = 8
    #: EMA smoothing for the acceptance gauge
    ema_alpha: float = 0.2

    def validate(self):
        if self.k < 1 or (self.k & (self.k - 1)):
            raise ConfigError(
                f"speculative.k must be a power of two >= 1 (one compiled "
                f"verify flavor per k bucket), got {self.k}")
        if not (0.0 <= self.acceptance_floor <= 1.0):
            raise ConfigError(
                "speculative.acceptance_floor must be in [0, 1]")
        if self.warmup_ticks < 1:
            raise ConfigError("speculative.warmup_ticks must be >= 1")
        if not (0.0 < self.ema_alpha <= 1.0):
            raise ConfigError("speculative.ema_alpha must be in (0, 1]")
        if isinstance(self.draft, dict):
            self.draft = DraftConfig.from_dict(self.draft)
        elif self.draft is None:
            self.draft = DraftConfig()
        self.draft.validate()


@dataclasses.dataclass
class LoadgenConfig(DeepSpeedConfigModel):
    """The ``"loadgen"`` block: the seeded trace-driven load generator
    (serving/loadgen.py). Every knob feeds one ``numpy`` Generator, so a
    given seed always produces the identical arrival/tenant/length
    schedule — the property the soak-diff regression gate rests on.
    Arrivals are an inhomogeneous Poisson process shaped by a diurnal
    sinusoid; tenants are drawn zipf (a few whales, a long tail);
    prompt/output lengths are lognormal (heavy tail); a fraction of
    prompts share cohort prefixes (what the radix cache exists for);
    abuse spikes slam many requests from one tenant into one instant
    (what router rate limits exist for)."""
    seed: int = 0
    #: trace horizon, seconds of simulated wall-clock
    duration_s: float = 10.0
    #: mean request rate at the diurnal midline, requests/second
    base_rate: float = 6.0
    #: peak-to-midline rate swing, fraction of base_rate in [0, 1)
    diurnal_amplitude: float = 0.5
    #: sinusoid period; 0 = one full cycle over duration_s
    diurnal_period_s: float = 0.0
    #: distinct steady tenants (t0..tN-1); abuse spikes add "abuser"
    tenants: int = 4
    #: zipf skew over the steady tenants (larger = whalier)
    zipf_alpha: float = 1.2
    #: lognormal prompt-length median (tokens) / sigma / hard cap
    prompt_len_median: int = 12
    prompt_len_sigma: float = 0.6
    prompt_len_max: int = 96
    #: lognormal output-length median (tokens) / sigma / hard cap
    output_len_median: int = 8
    output_len_sigma: float = 0.5
    output_len_max: int = 32
    #: fraction of requests whose prompt starts with a cohort prefix
    shared_prefix_fraction: float = 0.35
    #: distinct shared-prefix cohorts and the prefix length (tokens)
    prefix_cohorts: int = 3
    prefix_len: int = 16
    #: abuse spikes: count, requests per spike, tenant they bill to
    abuse_spikes: int = 1
    abuse_spike_requests: int = 12
    abuse_tenant: str = "abuser"
    #: token-id vocabulary for generated prompts
    vocab: int = 256

    def validate(self):
        if self.duration_s <= 0:
            raise ConfigError("loadgen.duration_s must be > 0")
        if self.base_rate <= 0:
            raise ConfigError("loadgen.base_rate must be > 0")
        if not (0.0 <= self.diurnal_amplitude < 1.0):
            raise ConfigError(
                "loadgen.diurnal_amplitude must be in [0, 1)")
        if self.tenants < 1:
            raise ConfigError("loadgen.tenants must be >= 1")
        if self.zipf_alpha <= 1.0:
            raise ConfigError(
                "loadgen.zipf_alpha must be > 1 (zipf divergence)")
        for name in ("prompt_len_median", "prompt_len_max",
                     "output_len_median", "output_len_max",
                     "prefix_len", "vocab"):
            if getattr(self, name) < 1:
                raise ConfigError(f"loadgen.{name} must be >= 1")
        if not (0.0 <= self.shared_prefix_fraction <= 1.0):
            raise ConfigError(
                "loadgen.shared_prefix_fraction must be in [0, 1]")
        if self.prefix_cohorts < 1:
            raise ConfigError("loadgen.prefix_cohorts must be >= 1")
        if self.abuse_spikes < 0 or self.abuse_spike_requests < 1:
            raise ConfigError(
                "loadgen.abuse_spikes must be >= 0 and "
                "abuse_spike_requests >= 1")
        if "/" in self.abuse_tenant:
            raise ConfigError("loadgen.abuse_tenant must not contain '/'")


@dataclasses.dataclass
class SoakConfig(DeepSpeedConfigModel):
    """The ``"soak"`` block: chaos schedule + invariant tolerances for
    the fleet soak harness (benchmarks/soak.py + telemetry/scorecard.py).
    Chaos times are fractions of the loadgen trace horizon so the same
    config scales from the tier-1 fast smoke to a minutes-long full
    soak."""
    #: when to kill a live replica, as a fraction of duration_s (<0 off)
    kill_replica_at_frac: float = 0.3
    #: when the autoscale-forcing burst starts, fraction of duration_s
    #: (<0 off), how long it lasts (fraction), and the rate multiplier
    #: stacked on top of the diurnal rate while it runs
    burst_at_frac: float = 0.55
    burst_duration_frac: float = 0.15
    burst_rate_mult: float = 4.0
    #: when to start a rolling weight update mid-soak, as a fraction of
    #: duration_s (<0 off) — a same-version rollout through the full
    #: plane (canary replay in shadow, SLO-gated shift, one-at-a-time
    #: replace), so the bitwise verify has a ground truth
    rollout_at_frac: float = -1.0
    #: invariant (c): SLO burn must fall back to <= 1.0 within this many
    #: seconds after each chaos event
    recovery_window_s: float = 20.0
    #: invariant (a): |sum(goodput buckets) - wall| tolerance, relative
    goodput_tolerance: float = 0.02
    #: invariant (e): critical-path decomposition slack (relative to e2e
    #: mean, with an absolute floor in ms)
    critical_path_tolerance: float = 0.05
    critical_path_floor_ms: float = 0.5
    #: burn/live-replica sampling cadence during the drive loop
    sample_interval_s: float = 0.1
    #: wall-clock grace after the trace drains: lets scale-down + drains
    #: complete and burn samples decay before the scorecard folds
    tail_s: float = 2.0

    def validate(self):
        if self.burst_rate_mult < 1.0:
            raise ConfigError("soak.burst_rate_mult must be >= 1")
        if self.burst_duration_frac < 0 or self.burst_duration_frac > 1:
            raise ConfigError(
                "soak.burst_duration_frac must be in [0, 1]")
        if self.recovery_window_s <= 0:
            raise ConfigError("soak.recovery_window_s must be > 0")
        if not (0.0 < self.goodput_tolerance < 1.0):
            raise ConfigError("soak.goodput_tolerance must be in (0, 1)")
        if not (0.0 < self.critical_path_tolerance < 1.0):
            raise ConfigError(
                "soak.critical_path_tolerance must be in (0, 1)")
        if self.sample_interval_s <= 0:
            raise ConfigError("soak.sample_interval_s must be > 0")
        if self.tail_s < 0:
            raise ConfigError("soak.tail_s must be >= 0")


@dataclasses.dataclass
class CostConfig(DeepSpeedConfigModel):
    """The ``"cost"`` block (telemetry/costplane.py): per-request /
    per-tenant chip-second and HBM attribution. Every serving tick's
    wall-clock is split across the requests occupying it (decode by
    tokens emitted, prefill to its owner, the rest an explicit overhead
    residual, so costs sum to serving wall by construction), HBM
    byte-seconds accrue from slot footprint x residency, and radix-cache
    hits record avoided prefill cost as savings. Folded per-tenant at
    the FleetRouter into the ``dstpu_cost_*`` family, the ``/statusz``
    costs table, and the soak scorecard's cost invariant. Off by
    default: nothing is allocated and every scheduler hook is one
    ``is None`` test."""
    enabled: bool = False
    #: EMA smoothing for the observed per-token prefill cost — the rate
    #: radix-cache savings are priced at
    ema_alpha: float = 0.25
    #: accrue HBM-byte-seconds per occupied slot (footprint x residency)
    hbm: bool = True
    #: cap on distinct tenants with live cost totals; excess folds into
    #: ``__other__`` (same bounded-cardinality rule as tenants.max_tracked)
    max_tracked: int = 64

    def validate(self):
        if not (0.0 < self.ema_alpha <= 1.0):
            raise ConfigError("cost.ema_alpha must be in (0, 1]")
        if self.max_tracked < 1:
            raise ConfigError("cost.max_tracked must be >= 1")


@dataclasses.dataclass
class ServingConfig(DeepSpeedConfigModel):
    """Continuous-batching serving knobs (deepspeed_tpu/serving/)."""

    # slot pool: one statically-shaped KV cache [L, num_slots, H,
    # max_model_len, hd], allocated once — admission never reshapes it
    num_slots: int = 8
    max_model_len: int = 512          # KV-cache columns per slot

    # admission control / robustness
    max_queue: int = 64               # bounded queue; submit() past this
                                      # raises QueueFull (backpressure)
    max_prefills_per_tick: int = 1    # prefill admission budget per tick
                                      # (bounds tail latency of decode ticks)
    default_max_new_tokens: int = 64
    request_timeout_s: Optional[float] = None  # default per-request deadline

    # metrics fan-out through MonitorMaster (serving/metrics.py)
    monitor: bool = False
    monitor_interval: int = 16        # ticks between gauge emissions
    tensorboard: Any = None           # dict -> MonitorSinkConfig
    wandb: Any = None
    csv_monitor: Any = None
    prometheus: Any = None            # dict -> MonitorSinkConfig (telemetry
                                      # sink: {job}.prom text dump)

    # telemetry (dict -> runtime.config.TelemetryConfig): per-request
    # queue→prefill→decode→complete spans + decode-tick spans; shutdown()
    # writes trace_output/snapshot_output when set
    telemetry: Any = None

    # statusz (dict -> runtime.config.StatuszConfig): live introspection
    # server — /healthz goes 503 while this replica drains, so a balancer
    # stops routing before the process exits
    statusz: Any = None

    # slo (dict -> SLOConfig): sliding-window TTFT/TPOT/e2e percentiles
    # and error-budget burn rate (serving/metrics.py)
    slo: Any = None

    # flight_recorder (dict -> runtime.config.FlightRecorderConfig):
    # per-tick step records (queue depth, SLO burn) + postmortem bundles
    # on SLO burn-rate spikes, preemption, and /debug/capture
    flight_recorder: Any = None

    # compile_plane (dict -> runtime.config.CompilePlaneConfig): compile
    # ledger over the serving programs (prefill buckets, fused decode,
    # pool init) with fingerprint diffs + cost/memory analysis, and the
    # HBM role ledger (params / kv_slots -> dstpu_mem_* gauges)
    compile_plane: Any = None

    # perf_plane (dict -> runtime.config.PerfPlaneConfig): per-program
    # anatomy over the serving ticks (decode/verify/chunked-prefill
    # bucket decomposition, dstpu_anat_* gauges, perf_regression
    # trigger); requires compile_plane.enabled
    perf_plane: Any = None

    # resilience (dict -> resilience.config.ResilienceConfig): with
    # handle_signals, SIGTERM/SIGINT stops admissions and drains in-flight
    # requests at the next tick (running slots complete, queued requests
    # are cancelled) — the serving half of preemption handling
    resilience: Any = None

    # replica role in a disaggregated fleet: "unified" serves end-to-end;
    # "prefill" runs prompt passes and hands KV off (handoff_sink);
    # "decode" admits KVHandoffs into its pool and runs the token loop
    role: str = "unified"

    # prefix_cache (dict -> PrefixCacheConfig): radix reuse of retired
    # slots — shared system-prompt prefixes skip recomputation
    prefix_cache: Any = None

    # kv_quant (dict -> KVQuantConfig): int8 slot pool, ~4x slots/HBM byte
    kv_quant: Any = None

    # speculative (dict -> SpeculativeConfig): draft-model speculative
    # decoding — 1..k+1 tokens per tick at bitwise-identical output
    speculative: Any = None

    # chunked_prefill (dict -> ChunkedPrefillConfig): interleave long
    # prompts' prefill with decode ticks in chunk_tokens-sized chunks —
    # bounded in-flight TPOT regardless of prompt length
    chunked_prefill: Any = None

    # tenants (dict -> TenantConfig): per-tenant weighted-fair admission
    # (DRR), router rate limits, and dstpu_tenant_* SLO gauges
    tenants: Any = None

    # fleet (dict -> fleet.config.FleetConfig): router + replica-set
    # block read by ds_tpu_serve --fleet / benchmarks; inert (and
    # allocating nothing) on a single replica
    fleet: Any = None

    # loadgen (dict -> LoadgenConfig): seeded trace-driven load shape
    # for the soak harness (serving/loadgen.py); inert at serve time
    loadgen: Any = None

    # soak (dict -> SoakConfig): chaos schedule + invariant tolerances
    # for benchmarks/soak.py and telemetry/scorecard.py; inert at serve
    # time
    soak: Any = None

    # cost (dict -> CostConfig): per-request / per-tenant chip-second +
    # HBM attribution (telemetry/costplane.py) — the dstpu_cost_* family
    cost: Any = None

    ALIASES = {"max_seq_len": "max_model_len"}

    def validate(self):
        if self.num_slots < 1:
            raise ConfigError(f"num_slots must be >= 1, got {self.num_slots}")
        if self.max_model_len < 2:
            raise ConfigError(
                f"max_model_len must be >= 2, got {self.max_model_len}")
        if self.max_queue < 1:
            raise ConfigError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.max_prefills_per_tick < 1:
            raise ConfigError("max_prefills_per_tick must be >= 1")
        if self.default_max_new_tokens < 1:
            raise ConfigError("default_max_new_tokens must be >= 1")
        if self.request_timeout_s is not None and self.request_timeout_s <= 0:
            raise ConfigError("request_timeout_s must be > 0 when set")
        if self.monitor_interval < 1:
            raise ConfigError("monitor_interval must be >= 1")
        for name in ("tensorboard", "wandb", "csv_monitor", "prometheus"):
            val = getattr(self, name)
            if val is None:
                val = MonitorSinkConfig()
            elif isinstance(val, dict):
                val = MonitorSinkConfig.from_dict(val)
            setattr(self, name, val)
        if isinstance(self.telemetry, dict):
            from ..runtime.config import TelemetryConfig
            self.telemetry = TelemetryConfig.from_dict(self.telemetry)
        from ..runtime.config import StatuszConfig
        if isinstance(self.statusz, dict):
            self.statusz = StatuszConfig.from_dict(self.statusz)
        elif self.statusz is None:
            self.statusz = StatuszConfig()
        if isinstance(self.slo, dict):
            self.slo = SLOConfig.from_dict(self.slo)
        elif self.slo is None:
            self.slo = SLOConfig()
        from ..runtime.config import FlightRecorderConfig
        if isinstance(self.flight_recorder, dict):
            self.flight_recorder = FlightRecorderConfig.from_dict(
                self.flight_recorder)
        elif self.flight_recorder is None:
            self.flight_recorder = FlightRecorderConfig()
        from ..runtime.config import CompilePlaneConfig
        if isinstance(self.compile_plane, dict):
            self.compile_plane = CompilePlaneConfig.from_dict(
                self.compile_plane)
        elif self.compile_plane is None:
            self.compile_plane = CompilePlaneConfig()
        from ..runtime.config import PerfPlaneConfig
        if isinstance(self.perf_plane, dict):
            self.perf_plane = PerfPlaneConfig.from_dict(self.perf_plane)
        elif self.perf_plane is None:
            self.perf_plane = PerfPlaneConfig()
        if self.perf_plane.enabled and not (
                self.compile_plane.enabled and
                self.compile_plane.memory_analysis):
            raise ConfigError(
                "serving.perf_plane requires compile_plane.enabled with "
                "memory_analysis: the anatomy is computed from the "
                "optimized HLO the compile ledger captures per event")
        from ..resilience.config import ResilienceConfig
        if isinstance(self.resilience, dict):
            self.resilience = ResilienceConfig.from_dict(self.resilience)
        elif self.resilience is None:
            self.resilience = ResilienceConfig()
        if self.role not in ("unified", "prefill", "decode"):
            raise ConfigError(
                f"serving.role must be unified|prefill|decode, "
                f"got {self.role!r}")
        if isinstance(self.prefix_cache, dict):
            self.prefix_cache = PrefixCacheConfig.from_dict(
                self.prefix_cache)
        elif self.prefix_cache is None:
            self.prefix_cache = PrefixCacheConfig()
        if isinstance(self.kv_quant, dict):
            self.kv_quant = KVQuantConfig.from_dict(self.kv_quant)
        elif self.kv_quant is None:
            self.kv_quant = KVQuantConfig()
        if isinstance(self.speculative, dict):
            self.speculative = SpeculativeConfig.from_dict(self.speculative)
        elif self.speculative is None:
            self.speculative = SpeculativeConfig()
        self.speculative.validate()
        if isinstance(self.chunked_prefill, dict):
            self.chunked_prefill = ChunkedPrefillConfig.from_dict(
                self.chunked_prefill)
        elif self.chunked_prefill is None:
            self.chunked_prefill = ChunkedPrefillConfig()
        self.chunked_prefill.validate()
        if self.chunked_prefill.enabled and \
                self.chunked_prefill.chunk_tokens > self.max_model_len:
            raise ConfigError(
                f"chunked_prefill.chunk_tokens="
                f"{self.chunked_prefill.chunk_tokens} exceeds "
                f"max_model_len={self.max_model_len}")
        if isinstance(self.tenants, dict):
            self.tenants = TenantConfig.from_dict(self.tenants)
        elif self.tenants is None:
            self.tenants = TenantConfig()
        self.tenants.validate()
        from .fleet.config import FleetConfig
        if isinstance(self.fleet, dict):
            self.fleet = FleetConfig.from_dict(self.fleet)
        elif self.fleet is None:
            self.fleet = FleetConfig()
        if isinstance(self.loadgen, dict):
            self.loadgen = LoadgenConfig.from_dict(self.loadgen)
        elif self.loadgen is None:
            self.loadgen = LoadgenConfig()
        self.loadgen.validate()
        if isinstance(self.soak, dict):
            self.soak = SoakConfig.from_dict(self.soak)
        elif self.soak is None:
            self.soak = SoakConfig()
        self.soak.validate()
        if isinstance(self.cost, dict):
            self.cost = CostConfig.from_dict(self.cost)
        elif self.cost is None:
            self.cost = CostConfig()
        self.cost.validate()
