"""ServingEngine — continuous-batching facade over InferenceEngine.

The online counterpart of ``InferenceEngine.generate()`` (one compiled
program per static batch): requests arrive one at a time via
``submit(prompt, ...) -> request_id``, are admitted into a fixed pool of
decode slots, and every ``step()`` advances ALL in-flight requests by one
token through a single compiled decode program. Per-token streaming runs
through ``on_token`` callbacks; robustness controls — bounded admission
queue with backpressure, per-request deadlines, graceful drain — are
first-class.

    engine = deepspeed_tpu.init_inference(model, config={...})
    srv = ServingEngine(engine, {"num_slots": 8, "max_model_len": 512})
    rid = srv.submit(prompt_ids, SamplingParams(max_new_tokens=32),
                     on_token=lambda req, tok: print(tok))
    srv.run_until_idle()
    print(srv.result(rid).output_ids)
    srv.shutdown()
"""

import time
from typing import Callable, Dict, Optional, Union

import numpy as np

from ..utils.logging import log_dist
from .config import ServingConfig
from .metrics import ServingMetrics
from .scheduler import (ContinuousBatchingScheduler, QueueFull, Request,
                        RequestState, SamplingParams)

__all__ = ["ServingEngine", "SamplingParams", "QueueFull", "RequestState"]


class ServingEngine:
    """Slot-based continuous-batching serving on top of InferenceEngine."""

    def __init__(self, engine, config: Union[ServingConfig, dict, None] = None,
                 clock: Callable[[], float] = time.monotonic, seed: int = 0,
                 handoff_sink: Optional[Callable] = None,
                 id_start: int = 0, id_stride: int = 1,
                 replica_name: Optional[str] = None):
        if config is None:
            config = ServingConfig()
        elif isinstance(config, dict):
            config = ServingConfig.from_dict(config)
        else:
            config.validate()
        self.config = config
        self.engine = engine
        # fleet lane identity: the name build_fleet gave this replica (or
        # "serving" standalone) — stamped on every span so the fleet
        # aggregator can split the shared span ring into per-replica lanes
        self.replica = replica_name or "serving"
        # fleet id spacing: replica i of N uses ids i, i+N, i+2N, ... so a
        # request's async trace spans stay unique when it migrates between
        # co-resident replicas (handoff, failover)
        self._id_start = int(id_start)
        self._id_stride = max(1, int(id_stride))
        self.monitor = None
        if config.monitor:
            from ..monitor.monitor import MonitorMaster
            self.monitor = MonitorMaster(config)
        from ..telemetry.trace import configure_tracer
        self.tracer = configure_tracer(config.telemetry) \
            if config.telemetry is not None else configure_tracer()
        from ..telemetry.goodput import configure_ledger, get_ledger
        tcfg = config.telemetry
        if tcfg is not None:
            # config wins, same contract as configure_tracer; without a
            # telemetry block the process-global ledger state stands (a
            # co-resident training engine may have enabled it)
            configure_ledger(enabled=bool(
                getattr(tcfg, "enabled", False) and
                getattr(tcfg, "goodput", True)))
        self._ledger = get_ledger()
        self.metrics = ServingMetrics(monitor=self.monitor,
                                      monitor_interval=config.monitor_interval,
                                      tracer=self.tracer, slo=config.slo,
                                      tenants=getattr(config, "tenants",
                                                      None))
        # flight recorder: per-tick records (queue depth, SLO burn) +
        # postmortem bundles on burn-rate spikes / preemption / explicit
        # /debug/capture; off by default = nothing allocated
        self._recorder = None
        self._last_burn = 0.0
        self._last_spec_ema = None
        if getattr(config.flight_recorder, "enabled", False):
            from ..telemetry.flight_recorder import FlightRecorder
            self._recorder = FlightRecorder(config.flight_recorder,
                                            tracer=self.tracer)
            self._recorder.add_provider("serving", self._statusz_section)
            # bundles embed the trace ids in flight on THIS replica, so
            # the router can correlate same-trace bundles across members
            self._recorder.set_trace_provider(self._traces_in_flight)
        # compile/memory plane (telemetry/compileplane.py): compile ledger
        # over the serving programs — each prefill bucket, the fused
        # decode step, pool init — plus the HBM role ledger attributing
        # per-device bytes to params vs the KV slot pool. Off by default
        # = nothing allocated, no per-call fingerprints.
        self._compile_plane = None
        self._hbm = None
        self._hbm_interval = 8
        cpcfg = getattr(config, "compile_plane", None)
        if getattr(cpcfg, "enabled", False):
            from ..telemetry.compileplane import CompileLedger, HBMLedger
            self._compile_plane = CompileLedger(cpcfg, tracer=self.tracer,
                                                owner=self)
            engine.compile_plane = self._compile_plane
            if cpcfg.hbm:
                self._hbm = HBMLedger(tracer=self.tracer, owner=self)
                self._hbm_interval = int(cpcfg.hbm_interval_steps)
            if self._recorder is not None:
                self._recorder.attach_compile_plane(self._compile_plane)
        # perf plane: tick anatomy per compile event (decode/verify/
        # chunked-prefill buckets), anat/* gauges, perf_regression
        # trigger — rides the compile ledger's HLO capture
        self._perf_plane = None
        ppcfg = getattr(config, "perf_plane", None)
        if getattr(ppcfg, "enabled", False) and \
                self._compile_plane is not None:
            from ..telemetry.perfplane import PerfPlane
            self._perf_plane = PerfPlane(ppcfg, tracer=self.tracer,
                                         owner=self,
                                         recorder=self._recorder)
            self._compile_plane.attach_perf_plane(self._perf_plane)
            if self._recorder is not None:
                self._recorder.add_provider(
                    "anatomy", self._perf_plane.bundle_section)
        self.statusz = None
        if getattr(config.statusz, "enabled", False):
            from ..telemetry.statusz import StatuszServer
            self.statusz = StatuszServer(config.statusz, tracer=self.tracer)
            self.statusz.register("serving", self._statusz_section)
            self.statusz.register_health("serving", self._health_check)
            if self._recorder is not None:
                self.statusz.attach_recorder(self._recorder)
            if self._compile_plane is not None:
                self.statusz.register("compile_plane",
                                      self._compile_plane.summary)
            if self._perf_plane is not None:
                self.statusz.register("anatomy", self._perf_plane.summary)
            if self._hbm is not None:
                self.statusz.register("memory", self._hbm.summary)
        self.scheduler = ContinuousBatchingScheduler(
            engine, config, metrics=self.metrics, clock=clock, seed=seed,
            handoff_sink=handoff_sink, replica_name=self.replica)
        if self.statusz is not None and self.scheduler.cost is not None:
            # standalone engines surface their own ledger; in a fleet the
            # router's fold is the authoritative per-tenant total
            self.statusz.register("costs", self._cost_section)
        self._requests: Dict[int, Request] = {}
        self._next_id = self._id_start
        self._draining = False
        self._preempt_drained = False
        self._preemption = None
        if config.resilience is not None and config.resilience.handle_signals:
            from ..resilience.preemption import PreemptionHandler
            self._preemption = PreemptionHandler.install()
        n_pos = getattr(getattr(engine.module, "config", None),
                        "n_positions", None)
        if n_pos is not None and config.max_model_len > n_pos:
            raise ValueError(
                f"serving.max_model_len={config.max_model_len} exceeds the "
                f"model's context length n_positions={n_pos}")
        log_dist(
            f"ServingEngine initialized: slots={config.num_slots} "
            f"max_model_len={config.max_model_len} "
            f"max_queue={config.max_queue}", ranks=[0])

    # ---------------------------------------------------------------- submit
    def submit(self, prompt, sampling: Optional[SamplingParams] = None,
               on_token: Optional[Callable] = None, trace=None) -> int:
        """Enqueue one request. Returns its request_id; raises ``QueueFull``
        when the bounded admission queue is at capacity (backpressure — the
        caller sheds load or retries with backoff) and ``RuntimeError``
        after shutdown/drain began. ``trace`` carries an existing
        distributed TraceContext (the fleet router's) — without one the
        scheduler mints a fresh per-request context at enqueue."""
        if self._draining:
            raise RuntimeError("ServingEngine is draining; submit rejected")
        sampling = sampling or SamplingParams()
        sampling.validate()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        max_new = (sampling.max_new_tokens
                   if sampling.max_new_tokens is not None
                   else self.config.default_max_new_tokens)
        if prompt.size + max_new > self.config.max_model_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) "
                f"exceeds serving.max_model_len={self.config.max_model_len}")
        req = Request(request_id=self._next_id, prompt=prompt,
                      sampling=sampling, max_new_tokens=max_new,
                      on_token=on_token, trace=trace)
        self.scheduler.enqueue(req)     # raises QueueFull on backpressure
        self._requests[req.request_id] = req
        self._next_id += self._id_stride
        return req.request_id

    def submit_handoff(self, handoff, request: Optional[Request] = None,
                       on_token: Optional[Callable] = None) -> int:
        """Enqueue a completed prefill (serving/fleet/handoff.py) for
        decode in THIS replica's pool. With ``request`` (the router path)
        the same Request object continues — its token list, callbacks,
        and deadline travel with the KV state; without one (direct API
        use) a Request is reconstructed from the handoff's metadata and
        the already-sampled first token is delivered here. Raises
        ``QueueFull`` past ``max_queue`` (shared with the prompt queue)
        and ``ValueError`` when the handoff cannot fit this replica's
        pool."""
        if self._draining:
            raise RuntimeError("ServingEngine is draining; handoff rejected")
        kv_len = int(handoff.kv_len)
        max_new = (request.max_new_tokens if request is not None
                   else int(handoff.max_new_tokens))
        if kv_len + max_new > self.config.max_model_len:
            raise ValueError(
                f"handoff kv_len ({kv_len}) + max_new_tokens ({max_new}) "
                f"exceeds serving.max_model_len={self.config.max_model_len}")
        # version boundary check (rollout plane): a KV lane computed by a
        # different weights_version must never seed this replica's decode
        # — refuse it and re-prefill locally instead. None = pre-rollout
        # producer, accepted for compatibility.
        incoming = getattr(handoff, "weights_version", None)
        refused = incoming is not None and \
            int(incoming) != self.weights_version
        deliver_first = request is None
        if request is None:
            sampling = SamplingParams(
                temperature=handoff.temperature,
                top_k=int(getattr(handoff, "top_k", 0)),
                top_p=float(getattr(handoff, "top_p", 1.0)),
                seed=int(getattr(handoff, "seed", 0)),
                max_new_tokens=handoff.max_new_tokens,
                eos_token_id=handoff.eos_token_id,
                tenant=getattr(handoff, "tenant", None) or "default")
            trace = None
            if handoff.trace is not None:
                # a deserialized frame carries the producing side's trace
                # identity: decode continues the SAME trace (marks restart
                # in this process's clock domain)
                from ..telemetry.disttrace import TraceContext
                trace = TraceContext.from_header(handoff.trace)
            request = Request(
                request_id=self._next_id,
                prompt=np.asarray(handoff.prompt, np.int32).reshape(-1),
                sampling=sampling, max_new_tokens=handoff.max_new_tokens,
                on_token=on_token, trace=trace)
            self._next_id += self._id_stride
            request.submit_time = self.scheduler.clock()
            if not refused:
                self.tracer.async_begin(
                    "request", request.request_id, cat="serving",
                    args={"prompt_len": int(request.prompt.size),
                          "max_new_tokens": request.max_new_tokens,
                          "handoff": True, "replica": self.replica,
                          **(trace.span_args() if trace is not None
                             else {})})
        if refused:
            return self._refuse_handoff(handoff, request,
                                        fresh=deliver_first)
        self.scheduler.enqueue_handoff(handoff, request)   # QueueFull here
        self._requests[request.request_id] = request
        if deliver_first:
            request.state = RequestState.RUNNING
            request.first_token_time = self.scheduler.clock()
            request.tokens.append(int(handoff.first_token))
            if on_token is not None:
                try:
                    on_token(request, int(handoff.first_token))
                except Exception:
                    pass
        return request.request_id

    def _refuse_handoff(self, handoff, request: Request,
                        fresh: bool) -> int:
        """Refuse a KV lane from a different ``weights_version`` and
        re-prefill the request in THIS replica's pool instead. KV state
        computed by one model and read by another is silent corruption,
        and a mid-rollout fleet is exactly when producer and consumer
        versions differ. The (seed, cache position) sampling contract
        regenerates the SAME token stream from the local prefill, and
        the router's delivered-position dedup keeps client delivery
        exactly-once — the refusal costs one extra prompt pass, never
        correctness. ``fresh`` marks the direct-API path (the Request
        was just reconstructed here and has no open lifecycle span)."""
        if len(self.scheduler.queue) >= self.config.max_queue:
            # reject BEFORE mutating the request so the router can retry
            # the untouched handoff on another decode replica
            self.metrics.record_reject()
            raise QueueFull(
                f"serving queue at capacity ({self.config.max_queue}); "
                f"handoff refusal cannot re-prefill")
        producer = getattr(handoff, "weights_version", None)
        ctx = getattr(request, "trace", None)
        if ctx is not None:
            ctx.mark("handoff_refused")
        self.metrics.record_handoff_refused()
        with self.tracer.span(
                "handoff_refused", cat="serving",
                args={"request_id": request.request_id,
                      "producer_version": producer,
                      "local_version": self.weights_version,
                      "source": getattr(handoff, "source", None),
                      "replica": self.replica,
                      **(ctx.span_args() if ctx is not None else {})}):
            pass
        if not fresh:
            # the request already lived a prefill on the producing side:
            # close its open lifecycle span and reset to pre-admission
            # state — enqueue() below re-opens the span for the local
            # re-prefill, keeping the trace balanced
            self.tracer.async_end(
                "request", request.request_id, cat="serving",
                args={"handoff_refused": True,
                      "replica": self.replica})
            request.state = RequestState.QUEUED
            request.tokens.clear()
            request.prefill_pos = 0
            request.prefill_started = False
            request.first_token_time = None
        self.scheduler.enqueue(request)
        self._requests[request.request_id] = request
        log_dist(
            f"serving: KV handoff for request {request.request_id} "
            f"REFUSED (producer weights_version {producer} != local "
            f"{self.weights_version}); re-prefilling locally", ranks=[0])
        return request.request_id

    # ------------------------------------------------------------------ step
    def step(self) -> int:
        """One scheduler tick: expire deadlines, admit into free slots
        (prefill), one fused decode step over all active slots. Returns
        requests still in flight. On a preemption signal (SIGTERM/SIGINT
        or the ``preempt_signal`` fault) the tick becomes a clean drain:
        admissions stop, running slots complete, queued requests cancel."""
        if self._check_preemption():
            return 0
        rec = self._recorder
        t0 = time.perf_counter() if rec is not None else 0.0
        bucket = "serving_drain" if self._draining else "serving_step"
        with self._ledger.track(bucket):
            in_flight = self.scheduler.tick()
        self.metrics.flush()
        if self._hbm is not None and \
                self.metrics.ticks % self._hbm_interval == 0:
            self._update_hbm()
        if rec is not None:
            self._flight_record((time.perf_counter() - t0) * 1e3)
        return in_flight

    def _update_hbm(self):
        """HBM role ledger update: the serving replica's per-device bytes
        are the weights plus the slot-pool KV cache — the
        ``dstpu_mem_params_gib`` / ``dstpu_mem_kv_slots_gib`` gauges."""
        try:
            kv_bytes = self._hbm.device_bytes(self.scheduler.pool.cache)
            if self.scheduler.draft_cache is not None:
                # the draft pool is KV state too — it rides the same role
                kv_bytes += self._hbm.device_bytes(
                    self.scheduler.draft_cache)
            roles = {"params": self._hbm.device_bytes(self.engine.params),
                     "kv_slots": kv_bytes}
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
            self._hbm.update(roles,
                             peak_bytes=stats.get("peak_bytes_in_use"))
        except Exception as e:
            log_dist(f"compile plane: HBM ledger update failed: {e}",
                     ranks=[0])

    def _flight_record(self, dur_ms: float):
        """One scheduler tick into the flight recorder. Tick times swing
        legitimately (prefill vs decode), so the slow-step rule stays off;
        the serving trigger is the SLO error-budget burn rate crossing
        ``flight_recorder.slo_burn_threshold`` (edge-triggered — a burn
        that stays high fires once, not every tick)."""
        rec = self._recorder
        burn = self.metrics.last_burn_rate
        rec.record_step(self.metrics.ticks, dur_ms, slow_check=False,
                        extra={"queue_depth": self.queue_depth,
                               "active_requests": self.active_requests,
                               "draining": self._draining,
                               "slo_burn_rate": burn})
        if burn is not None:
            thresh = rec.slo_burn_threshold
            if burn > thresh and self._last_burn <= thresh:
                rec.trigger(
                    "slo_burn",
                    f"tick {self.metrics.ticks}: burn rate {burn:.2f} "
                    f"crossed {thresh:g} (queue {self.queue_depth}, "
                    f"{self.active_requests} active)")
            self._last_burn = burn
        spec = self.scheduler.spec
        ema = self.metrics.spec_acceptance_ema
        if spec is not None and ema is not None and \
                spec.acceptance_floor > 0 and \
                self.metrics.spec_ticks >= spec.warmup_ticks:
            # edge-triggered on the EMA dropping BELOW the floor:
            # speculation that stopped paying for itself (draft drift,
            # workload change) is an incident, not a steady alarm
            floor = spec.acceptance_floor
            prev = self._last_spec_ema
            if ema < floor and (prev is None or prev >= floor):
                tpt = self.metrics.spec_tokens_per_tick_ema or 0.0
                rec.trigger(
                    "acceptance_drop",
                    f"tick {self.metrics.ticks}: speculative acceptance "
                    f"EMA {ema:.3f} fell below floor {floor:g} "
                    f"(k={self.metrics.spec_k}, tokens/tick {tpt:.2f})")
            self._last_spec_ema = ema

    def _check_preemption(self) -> bool:
        if self._preemption is None or self._draining:
            return False
        from ..resilience.faults import fault
        if fault("preempt_signal"):
            self._preemption.signal()
        if not self._preemption.preempted:
            return False
        self._preempt_drained = True
        self.tracer.set_counter("resilience/preemptions", 1.0, owner=self)
        if self._recorder is not None:
            # capture before the drain rewrites queue/slot state; bypasses
            # debounce — there is no second chance after a preemption
            self._recorder.trigger(
                "preemption",
                f"serving drain on preemption signal "
                f"({self.active_requests} running, {self.queue_depth} "
                f"queued)", force=True)
        log_dist("serving: preemption signal received; draining "
                 f"({self.active_requests} running, {self.queue_depth} "
                 f"queued)", ranks=[0])
        with self.tracer.span("preempt_drain", cat="resilience"):
            with self._ledger.track("preemption"):
                self.drain(serve_queued=False)
        return True

    def run_until_idle(self, max_ticks: int = 100_000) -> int:
        """Tick until no request is queued or running. Returns ticks run."""
        for i in range(max_ticks):
            if self.step() == 0:
                return i + 1
        return max_ticks

    # --------------------------------------------------------------- results
    def result(self, request_id: int) -> Request:
        return self._requests[request_id]

    def cancel(self, request_id: int) -> bool:
        """Cancel a queued request (running requests finish their course)."""
        req = self._requests.get(request_id)
        if req is None or req.state is not RequestState.QUEUED:
            return False
        try:
            self.scheduler.queue.remove(req)
        except ValueError:
            return False
        req.state = RequestState.CANCELLED
        req.finish_time = self.scheduler.clock()
        self._close_request_spans(req)
        return True

    # ------------------------------------------------------------- lifecycle
    def drain(self, serve_queued: bool = True, max_ticks: int = 100_000):
        """Graceful shutdown: stop admissions, finish in-flight work.
        ``serve_queued=False`` additionally cancels everything still
        queued (only running slots complete)."""
        self._draining = True
        if not serve_queued:
            while self.scheduler.queue:
                req = self.scheduler.queue.popleft()
                req.state = RequestState.CANCELLED
                req.finish_time = self.scheduler.clock()
                self._close_request_spans(req)
        ticks = self.run_until_idle(max_ticks=max_ticks)
        self.metrics.flush()
        return ticks

    def _close_request_spans(self, req):
        """Cancellation bypasses the scheduler's _finish: close the
        request's open async spans so the trace stays balanced."""
        self.tracer.async_end("request/queued", req.request_id,
                              cat="serving")
        self.tracer.async_end("request", req.request_id, cat="serving",
                              args={"state": req.state.value,
                                    "tokens": len(req.tokens)})

    def shutdown(self, serve_queued: bool = True):
        """Drain, flush metrics, close monitor sinks (releases the CSV
        file handles MonitorMaster holds), write the configured telemetry
        exports (telemetry.trace_output / snapshot_output), stop the
        statusz server, and retract this engine's gauges from the shared
        telemetry counter space."""
        self.drain(serve_queued=serve_queued)
        if self.monitor is not None:
            self.monitor.close()
        tcfg = self.config.telemetry
        if tcfg is not None and getattr(tcfg, "enabled", False):
            from ..telemetry.export import (write_chrome_trace,
                                            write_snapshot)
            try:
                if tcfg.trace_output:
                    write_chrome_trace(tcfg.trace_output, self.tracer)
                if tcfg.snapshot_output:
                    write_snapshot(tcfg.snapshot_output, self.tracer,
                                   extra={"serving": self.metrics.summary()})
            except OSError as e:
                log_dist(f"serving telemetry export failed: {e}", ranks=[0])
        if self.statusz is not None:
            self.statusz.close()
        if self._recorder is not None:
            self._recorder.close()
        # gauge lifecycle: a closed engine's queue depth / TTFT must not
        # survive in prometheus_dump() or /metrics as if it were live
        self.metrics.close()
        if self._compile_plane is not None and \
                getattr(self.engine, "compile_plane", None) \
                is self._compile_plane:
            self.engine.compile_plane = None   # detach from the shared
                                               # InferenceEngine
        self.tracer.release_counters(self)

    def _traces_in_flight(self):
        """Trace ids of every request still moving through THIS replica
        (queued, awaiting handoff insert, or decoding) — embedded in this
        replica's flight-recorder bundles for cross-replica correlation."""
        sched = self.scheduler
        reqs = list(sched.queue)
        reqs += [req for _h, req in list(sched.handoff_queue)]
        reqs += [sched.pool.requests[s] for s in sched.pool.active_slots]
        reqs += list(sched.prefilling.values())
        return sorted({req.trace.trace_id for req in reqs
                       if req is not None and req.trace is not None})

    # ------------------------------------------------------------- statusz
    def _health_check(self):
        """Load-balancer liveness: unhealthy the moment drain starts (or
        a preemption landed), so routing stops BEFORE in-flight work
        finishes — the window where new submits would be rejected."""
        if self._preempt_drained:
            return False, "preempted (drained)"
        if self._draining:
            return False, "draining"
        return True, "serving"

    def _statusz_section(self) -> dict:
        out = {
            "queue_depth": self.queue_depth,
            "active_requests": self.active_requests,
            "num_slots": self.config.num_slots,
            "slot_occupancy": round(
                self.active_requests / self.config.num_slots, 3),
            "submitted": self.metrics.submitted,
            "completed": self.metrics.completed,
            "rejected": self.metrics.rejected,
            "timeouts": self.metrics.timeouts,
            "tokens_out": self.metrics.tokens_out,
            "draining": self._draining,
            "weights_version": self.weights_version,
        }
        if self.config.role != "unified":
            out["role"] = self.config.role
        if self.metrics.handoffs_in or self.metrics.handoffs_out:
            out["kv_handoffs_in"] = self.metrics.handoffs_in
            out["kv_handoffs_out"] = self.metrics.handoffs_out
        if self.metrics.handoffs_refused:
            out["kv_handoffs_refused"] = self.metrics.handoffs_refused
        sched = self.scheduler
        if sched.chunked is not None:
            out["chunked_prefill"] = (
                f"chunk_tokens={sched.chunked.chunk_tokens} "
                f"prefilling={len(sched.prefilling)}")
        if sched.queue.enabled:
            depths = sched.queue.depths()
            if depths:
                out["tenant_queues"] = " ".join(
                    f"{t}={n}" for t, n in sorted(depths.items()))
        tstatus = self.metrics.tenant_status()
        if len(tstatus) > 1 or (tstatus and "default" not in tstatus):
            for tenant, row in sorted(tstatus.items()):
                out[f"tenant_{tenant}"] = (
                    f"share={row['token_share']} "
                    f"ttft_p99={row['ttft_ms_p99']}ms "
                    f"burn={row['burn_rate']} done={row['completed']}")
        pc = self.scheduler.prefix_cache
        if pc is not None:
            for k, v in pc.stats().items():
                out[f"prefix_{k}"] = v
        if sched.spec is not None:
            out["speculative"] = (f"k={sched.spec.k} "
                                  f"draft={sched.draft.describe}")
            m = self.metrics
            if m.spec_ticks:
                out["spec_acceptance_ema"] = round(
                    m.spec_acceptance_ema or 0.0, 4)
                out["spec_tokens_per_tick"] = round(
                    m.spec_tokens_per_tick_ema or 0.0, 3)
                out["spec_draft/verify_ms"] = \
                    f"{m.spec_draft_ms:.2f} / {m.spec_verify_ms:.2f}"
        for name, ps in self.metrics.percentiles().items():
            if ps["n"]:
                out[f"{name}_p50/p95/p99"] = \
                    f'{ps["p50"]} / {ps["p95"]} / {ps["p99"]}'
        slo = self.metrics.slo_status()
        if any(m.get("target_ms") is not None
               for m in slo["metrics"].values()):
            out["slo_burn_rate"] = slo["burn_rate"]
        return out

    def _cost_section(self) -> dict:
        """The standalone engine's /statusz ``costs`` section: this
        replica's cost-ledger snapshot (a fleet's router folds these
        instead). Empty when the cost plane is off."""
        cost = self.scheduler.cost
        return cost.snapshot() if cost is not None else {}

    # ------------------------------------------------------------- inspection
    @property
    def weights_version(self) -> int:
        """The checkpoint ``weights_version`` this replica serves (0 =
        unversioned: fresh init or a pre-rollout checkpoint). Reported
        on /statusz and compared across replicas — and across KV handoff
        frames — by the rollout plane."""
        return int(getattr(self.engine, "weights_version", 0) or 0)

    @property
    def preempted(self) -> bool:
        """True once a preemption signal triggered the clean drain."""
        return self._preempt_drained

    @property
    def queue_depth(self) -> int:
        return len(self.scheduler.queue)

    @property
    def active_requests(self) -> int:
        """Requests holding a slot: decoding OR mid-chunked-prefill (a
        PREFILLING request is active work, not queue depth)."""
        return (len(self.scheduler.pool.active_slots) +
                len(self.scheduler.prefilling))

    def decode_executables(self) -> int:
        """Compiled-executable count of the fused decode step (the
        compile-once contract: stays 1 across differing prompt lengths),
        for THIS engine's pool flavor (fp vs quantized)."""
        return self.engine.slot_decode_executables(
            self.config.num_slots, self.config.max_model_len,
            quantized=self.scheduler.pool.quantized)
