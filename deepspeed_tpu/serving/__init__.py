"""Continuous-batching serving subsystem (docs/serving.md).

The layer above InferenceEngine that the static-batch reference
(DeepSpeed v0.9.1) does not have: slot-based KV cache (kv_slots),
iteration-level scheduler (scheduler), the ServingEngine facade (engine),
serving config (config), and TTFT/latency/utilization metrics (metrics).
"""

from .config import ServingConfig
from .engine import ServingEngine
from .kv_slots import SlotPool
from .metrics import ServingMetrics
from .scheduler import (ContinuousBatchingScheduler, QueueFull, Request,
                        RequestState, SamplingParams)

__all__ = [
    "ServingConfig", "ServingEngine", "SlotPool", "ServingMetrics",
    "ContinuousBatchingScheduler", "QueueFull", "Request", "RequestState",
    "SamplingParams",
]
