"""Continuous-batching serving subsystem (docs/serving.md).

The layer above InferenceEngine that the static-batch reference
(DeepSpeed v0.9.1) does not have: slot-based KV cache (kv_slots),
iteration-level scheduler (scheduler), the ServingEngine facade (engine),
serving config (config), TTFT/latency/utilization metrics (metrics), and
the fleet layer (fleet/): SLO-aware router, prefill/decode
disaggregation over KV handoffs, and radix prefix reuse of the slot
pool.
"""

from .config import (ChunkedPrefillConfig, DraftConfig, KVQuantConfig,
                     LoadgenConfig, PrefixCacheConfig, ServingConfig,
                     SLOConfig, SoakConfig, SpeculativeConfig,
                     TenantConfig)
from .engine import ServingEngine
from .fleet import (AutoscaleConfig, FleetConfig, FleetRequest,
                    FleetRouter, KVHandoff, RadixPrefixCache,
                    ReplicaHandle, RolloutConfig, RolloutController,
                    build_fleet)
from .kv_slots import SlotPool
from .loadgen import ChaosEvent, LoadEvent, SoakTrace, generate_trace
from .metrics import FleetMetrics, ServingMetrics
from .scheduler import (ContinuousBatchingScheduler, QueueFull,
                        RateLimited, Request, RequestState, SamplingParams,
                        TenantQueues)

__all__ = [
    "ServingConfig", "SLOConfig", "PrefixCacheConfig", "KVQuantConfig",
    "SpeculativeConfig", "DraftConfig", "ChunkedPrefillConfig",
    "TenantConfig", "LoadgenConfig", "SoakConfig",
    "ServingEngine", "SlotPool", "ServingMetrics", "FleetMetrics",
    "ContinuousBatchingScheduler", "QueueFull", "RateLimited", "Request",
    "RequestState", "SamplingParams", "TenantQueues",
    "AutoscaleConfig", "FleetConfig", "FleetRouter", "FleetRequest", "KVHandoff",
    "RadixPrefixCache", "ReplicaHandle", "build_fleet",
    "RolloutConfig", "RolloutController",
    "ChaosEvent", "LoadEvent", "SoakTrace", "generate_trace",
]
