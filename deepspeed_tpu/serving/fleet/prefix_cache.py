"""Radix prefix cache over the slot KV pool.

Cross-request KV reuse, the XLA-static analogue of paged-attention prefix
sharing: instead of remapping cache *blocks* (pointer indirection XLA
cannot compile), whole retired **slots** become the cache. When a request
finishes, its slot — whose lane already holds the K/V of every token it
processed — is *donated* to this cache instead of returning to the free
list. A later request whose prompt shares a prefix with a cached
sequence is admitted by `slot_copy_lane` (device-side lane copy) +
`slot_suffix_prefill` (only the unshared tail runs through the stack):
the dominant serving pattern — a long shared system prompt with a short
user turn — skips almost all of its prefill compute.

The index is a radix tree (edge-compressed trie) over token sequences.
Lookup walks the query as deep as tokens match and returns the
most-recently-used entry under the divergence point; the match length —
not the entry's full length — is what the admission reuses, so a cached
``ABCDEF`` still serves an ``ABCXYZ`` query up to ``ABC``.

Entries are **ref-count pinned** while an admission copies from them
(and by anything else that calls ``pin``); eviction is LRU over unpinned
entries and happens on demand — when the scheduler needs a slot and the
free list is empty, the LRU cached slot is released back to the pool.
The cache never allocates device memory of its own: it only defers the
recycling of lanes the pool already paid for.
"""

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["RadixPrefixCache", "PrefixHit", "reuse_plan"]


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


def reuse_plan(prompt_len: int, matched_len: int,
               max_len: int) -> Tuple[int, int]:
    """(offset, suffix_len) for a prefix-reuse admission.

    The suffix is prefilled at a pow2 bucket starting at ``offset``;
    both constraints are folded in here: at least one suffix token must
    run (the sampled next token needs a query position — a fully-cached
    prompt still prefills its last token), and the bucket must fit below
    ``max_len`` (when it would not, the offset backs off so reuse never
    corrupts the lane tail — ``offset = max_len - bucket`` always fits
    because ``prompt_len <= max_len``). ``offset == 0`` means reuse is
    not worth it: fall back to a full prefill."""
    matched = min(matched_len, prompt_len - 1)
    if matched <= 0:
        return 0, prompt_len
    suffix = prompt_len - matched
    bucket = min(_next_pow2(suffix), max_len)
    offset = min(matched, max_len - bucket)
    return max(0, offset), prompt_len - max(0, offset)


class _Node:
    """Radix node: compressed edges keyed by first token; at most one
    cache entry terminates at a node (duplicate keys are rejected at
    donation)."""
    __slots__ = ("edges", "entry", "parent", "pkey")

    def __init__(self, parent=None, pkey=None):
        self.edges: Dict[int, Tuple[tuple, "_Node"]] = {}
        self.entry: Optional["_Entry"] = None
        self.parent = parent
        self.pkey = pkey          # first token of the edge from parent


@dataclasses.dataclass
class _Entry:
    slot: int
    key: tuple                    # the cached token sequence
    kv_len: int                   # valid cache columns in the lane
    node: _Node
    refs: int = 0
    last_use: int = 0


@dataclasses.dataclass
class PrefixHit:
    """One pinned lookup result: copy ``slot``'s lane and suffix-prefill
    from column ``matched``. Call ``cache.release(hit, used)`` when the
    copy is done (or abandoned) — the pin blocks eviction meanwhile."""
    slot: int
    matched: int
    entry: _Entry


class RadixPrefixCache:
    """Trie of donated slots + ref-counts + LRU eviction."""

    def __init__(self, config=None, tracer=None):
        self.min_prefix_len = int(getattr(config, "min_prefix_len", 8)
                                  if config is not None else 8)
        self.max_entries = int(getattr(config, "max_cached_slots", 0)
                               if config is not None else 0)
        self.root = _Node()
        self.entries: Dict[int, _Entry] = {}       # slot -> entry
        self._by_key: Dict[tuple, _Entry] = {}
        self._stamp = 0
        # counters surfaced as serving/prefix_* gauges and in /statusz
        self.lookups = 0
        self.hits = 0
        self.tokens_saved = 0
        self.donations = 0
        self.evictions = 0

    # -------------------------------------------------------------- lookup
    def lookup(self, tokens) -> Optional[PrefixHit]:
        """Longest-shared-prefix probe. Returns a PINNED hit when at least
        ``min_prefix_len`` tokens match (and at least one suffix token
        remains to prefill), else None."""
        self.lookups += 1
        tokens = tuple(int(t) for t in np.asarray(tokens).reshape(-1))
        node, depth = self._walk(tokens)
        matched = min(depth, len(tokens) - 1)
        if matched < self.min_prefix_len:
            return None
        entry = self._best_entry(node)
        if entry is None:
            return None
        matched = min(matched, entry.kv_len)
        if matched < self.min_prefix_len:
            return None
        self.hits += 1
        entry.refs += 1
        self._stamp += 1
        entry.last_use = self._stamp
        return PrefixHit(slot=entry.slot, matched=matched, entry=entry)

    def release(self, hit: PrefixHit, used_tokens: int = 0):
        """Unpin a lookup; ``used_tokens`` is the prefix length actually
        reused (post ``reuse_plan``), fed to the tokens-saved counter."""
        hit.entry.refs = max(0, hit.entry.refs - 1)
        self.tokens_saved += max(0, int(used_tokens))

    def pin(self, slot: int) -> bool:
        """Explicit pin of a cached slot (blocks eviction until unpin)."""
        e = self.entries.get(slot)
        if e is None:
            return False
        e.refs += 1
        return True

    def unpin(self, slot: int) -> bool:
        e = self.entries.get(slot)
        if e is None:
            return False
        e.refs = max(0, e.refs - 1)
        return True

    def _walk(self, tokens: tuple) -> Tuple[_Node, int]:
        """Deepest (node, depth) whose subtree shares ``depth`` leading
        tokens with the query."""
        node, depth = self.root, 0
        while depth < len(tokens):
            edge = node.edges.get(tokens[depth])
            if edge is None:
                break
            label, child = edge
            j = 0
            while (j < len(label) and depth + j < len(tokens)
                   and label[j] == tokens[depth + j]):
                j += 1
            depth += j
            node = child          # full or partial edge match: entries
            if j < len(label):    # under `child` share exactly `depth`
                break
        return node, depth

    def _best_entry(self, node: _Node) -> Optional[_Entry]:
        """Most-recently-used entry in ``node``'s subtree (small pools:
        a DFS is cheaper than maintaining per-node aggregates)."""
        best = None
        stack = [node]
        while stack:
            n = stack.pop()
            if n.entry is not None and \
                    (best is None or n.entry.last_use > best.last_use):
                best = n.entry
            for _label, child in n.edges.values():
                stack.append(child)
        return best

    # ------------------------------------------------------------ donation
    def donate(self, slot: int, tokens, kv_len: int
               ) -> Tuple[bool, Optional[int]]:
        """Offer a retiring slot's lane to the cache. Returns
        ``(accepted, evicted_slot)``: when accepted the caller must NOT
        free the slot (the lane stays resident as the cache entry);
        ``evicted_slot`` — an LRU entry displaced by the ``max_cached_slots``
        cap — must be freed by the caller. Rejected donations (too short,
        exact key already cached, slot already donated) leave the slot to
        the normal free path."""
        key = tuple(int(t) for t in np.asarray(tokens).reshape(-1))[:kv_len]
        if len(key) < self.min_prefix_len or slot in self.entries:
            return False, None
        if key in self._by_key:
            # the resident entry is at least as useful; refresh its LRU
            self._stamp += 1
            self._by_key[key].last_use = self._stamp
            return False, None
        node = self._insert(key)
        if node.entry is not None:   # same terminal node, different kv_len
            return False, None
        self._stamp += 1
        entry = _Entry(slot=slot, key=key, kv_len=min(kv_len, len(key)),
                       node=node, last_use=self._stamp)
        node.entry = entry
        self.entries[slot] = entry
        self._by_key[key] = entry
        self.donations += 1
        evicted = None
        if self.max_entries and len(self.entries) > self.max_entries:
            evicted = self.evict_lru(exclude=slot)
        return True, evicted

    def _insert(self, key: tuple) -> _Node:
        node, i = self.root, 0
        while i < len(key):
            first = key[i]
            edge = node.edges.get(first)
            if edge is None:
                child = _Node(parent=node, pkey=first)
                node.edges[first] = (key[i:], child)
                return child
            label, child = edge
            j = 0
            while (j < len(label) and i + j < len(key)
                   and label[j] == key[i + j]):
                j += 1
            if j == len(label):
                node, i = child, i + j
                continue
            # split the edge at the divergence point
            mid = _Node(parent=node, pkey=first)
            node.edges[first] = (label[:j], mid)
            mid.edges[label[j]] = (label[j:], child)
            child.parent, child.pkey = mid, label[j]
            node, i = mid, i + j
        return node

    # ------------------------------------------------------------ eviction
    def evict_lru(self, exclude: Optional[int] = None) -> Optional[int]:
        """Drop the least-recently-used UNPINNED entry; returns its slot
        (for the caller to free) or None when everything is pinned."""
        victim = None
        for e in self.entries.values():
            if e.refs > 0 or e.slot == exclude:
                continue
            if victim is None or e.last_use < victim.last_use:
                victim = e
        if victim is None:
            return None
        self._remove(victim)
        self.evictions += 1
        return victim.slot

    def remove_slot(self, slot: int) -> bool:
        """Forcibly drop a slot's entry (pool teardown), pinned or not."""
        e = self.entries.get(slot)
        if e is None:
            return False
        self._remove(e)
        return True

    def _remove(self, entry: _Entry):
        self.entries.pop(entry.slot, None)
        self._by_key.pop(entry.key, None)
        node = entry.node
        node.entry = None
        # prune now-empty leaf chains (no merge: single-edge pass-through
        # nodes are harmless and the next donation may re-split anyway)
        while (node is not None and node.parent is not None
               and not node.edges and node.entry is None):
            parent = node.parent
            parent.edges.pop(node.pkey, None)
            node = parent

    # ------------------------------------------------------------- queries
    @property
    def cached_slots(self) -> int:
        return len(self.entries)

    @property
    def evictable(self) -> int:
        return sum(1 for e in self.entries.values() if e.refs == 0)

    @property
    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    def stats(self) -> dict:
        return {"cached_slots": self.cached_slots,
                "pinned": self.cached_slots - self.evictable,
                "lookups": self.lookups, "hits": self.hits,
                "hit_rate": round(self.hit_rate, 4),
                "tokens_saved": self.tokens_saved,
                "donations": self.donations, "evictions": self.evictions}
