"""Replica handle — one serving replica as the router sees it.

A replica is an in-process ``ServingEngine`` (``ds_tpu_serve --fleet``),
a remote ``/healthz``+``/statusz`` endpoint, or both. The router never
touches engine internals for *liveness*: readiness is the same signal a
cloud load balancer uses — the ``/healthz`` probe PR 4 built, which goes
503 the moment the replica drains or its preemption latch fires.

Probe discipline (the PR-8 stale-readiness fix): a probe that **times
out** marks the replica NOT-ready exactly like a 503 — a hung replica
must not keep receiving traffic just because it never answered. NOT-ready
replicas are re-probed on a jittered exponential backoff
(``resilience/retry.backoff_delays``) instead of every router tick, so a
dead endpoint costs one socket timeout per backoff step, not per tick.
A replica whose last *successful* probe is older than
``heartbeat_timeout_s`` is reported stale: the router evicts it and
re-enqueues its in-flight requests onto survivors.
"""

import time
import urllib.error
import urllib.request
from typing import Callable, Optional

from ...resilience.retry import backoff_delays
from ...utils.logging import logger

__all__ = ["ReplicaHandle"]


class ReplicaHandle:
    """Probe schedule + load signals for one replica."""

    def __init__(self, name: str, engine=None, url: Optional[str] = None,
                 role: str = "unified", config=None,
                 clock: Callable[[], float] = time.monotonic, rng=None):
        if engine is None and url is None:
            raise ValueError(f"replica {name!r} needs an engine or a url")
        self.name = name
        self.engine = engine
        # in-process replicas with a live statusz server are probed over
        # real HTTP — the same path a remote replica takes
        if url is None and getattr(engine, "statusz", None) is not None:
            url = engine.statusz.url
        self.url = url.rstrip("/") if url else None
        self.role = role
        self._cfg = config
        self._clock = clock
        self._rng = rng
        self.ready = False
        self.failed = False           # hard eviction (router decision)
        self.last_ready_at: Optional[float] = None
        self.last_detail = "unprobed"
        self.probes = 0
        self.probe_failures = 0
        self._next_probe = float("-inf")
        self._backoff = None

    def _p(self, key, default):
        return getattr(self._cfg, key, default) if self._cfg is not None \
            else default

    # -------------------------------------------------------------- probing
    def probe(self, now: Optional[float] = None) -> bool:
        """Readiness, refreshing on schedule: ready replicas re-probe
        every ``probe_interval_s``; NOT-ready replicas on the jittered
        backoff. Between due times the cached verdict stands."""
        if self.failed:
            return False
        now = self._clock() if now is None else now
        if now < self._next_probe:
            return self.ready
        self.probes += 1
        ok, detail = self._probe_once()
        self.last_detail = detail
        if ok:
            self.ready = True
            self.last_ready_at = now
            self._backoff = None
            self._next_probe = now + float(self._p("probe_interval_s", 0.5))
        else:
            if self.ready or self._backoff is None:
                self._backoff = backoff_delays(
                    float(self._p("probe_backoff_s", 0.25)),
                    float(self._p("probe_backoff_max_s", 4.0)), self._rng)
            self.ready = False
            self.probe_failures += 1
            self._next_probe = now + next(self._backoff)
        return self.ready

    def _probe_once(self):
        if self.url is not None:
            try:
                with urllib.request.urlopen(
                        self.url + "/healthz",
                        timeout=float(self._p("probe_timeout_s", 1.0))) as r:
                    return r.status == 200, "ok"
            except urllib.error.HTTPError as e:
                # 503 = the replica SAYS it is not ready (drain/preempt)
                return False, f"healthz {e.code}"
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                # timeout / refused / unreachable: NOT ready — same verdict
                # as a 503, different root cause (the stale-readiness fix)
                return False, f"probe failed: {getattr(e, 'reason', e)}"
        ok, detail = self.engine._health_check()
        return ok, detail

    def stale(self, now: Optional[float] = None) -> bool:
        """True when the last successful probe is too old to trust: the
        replica is presumed dead (vs merely not-ready) and the router
        fails its requests over. A replica that was never ready goes
        stale ``heartbeat_timeout_s`` after construction."""
        now = self._clock() if now is None else now
        timeout = float(self._p("heartbeat_timeout_s", 10.0))
        anchor = self.last_ready_at
        if anchor is None:
            anchor = getattr(self, "_born", None)
            if anchor is None:
                self._born = now
                return False
        return now - anchor > timeout

    def preempted(self) -> bool:
        return self.engine is not None and \
            bool(getattr(self.engine, "preempted", False))

    # ---------------------------------------------------------------- load
    def load(self) -> dict:
        """Queue/occupancy/burn signals for routing. In-process replicas
        read the engine directly (always fresh, no socket); url-only
        replicas poll ``/statusz?format=json``."""
        if self.engine is not None:
            m = self.engine.metrics
            burn = m.last_burn_rate
            return {"queue_depth": self.engine.queue_depth,
                    "active_requests": self.engine.active_requests,
                    "slot_occupancy": round(
                        self.engine.active_requests /
                        self.engine.config.num_slots, 3),
                    "slo_burn_rate": burn,
                    "weights_version": int(
                        getattr(self.engine, "weights_version", 0) or 0)}
        try:
            import json
            with urllib.request.urlopen(
                    self.url + "/statusz?format=json",
                    timeout=float(self._p("probe_timeout_s", 1.0))) as r:
                doc = json.load(r)
            srv = (doc.get("sections") or {}).get("serving") or {}
            return {"queue_depth": srv.get("queue_depth", 0),
                    "active_requests": srv.get("active_requests", 0),
                    "slot_occupancy": srv.get("slot_occupancy", 0.0),
                    "slo_burn_rate": srv.get("slo_burn_rate"),
                    "weights_version": int(
                        srv.get("weights_version", 0) or 0)}
        except (urllib.error.URLError, OSError, ValueError) as e:
            logger.warning(f"fleet: statusz poll of {self.name} failed: {e}")
            return {"queue_depth": 0, "active_requests": 0,
                    "slot_occupancy": 0.0, "slo_burn_rate": None,
                    "weights_version": 0}

    def score(self) -> float:
        """Routing score — lower is better."""
        sig = self.load()
        burn = sig.get("slo_burn_rate") or 0.0
        return (sig["queue_depth"] + sig["active_requests"] +
                float(self._p("slo_burn_penalty", 4.0)) * float(burn))

    def summary(self) -> dict:
        """One /statusz fleet-table row."""
        out = {"role": self.role, "ready": self.ready,
               "failed": self.failed, "detail": self.last_detail,
               "probes": self.probes, "probe_failures": self.probe_failures}
        if self.url:
            out["url"] = self.url
        if self.engine is not None:
            out.update(self.load())
        return out
