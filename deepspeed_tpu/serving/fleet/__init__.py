"""Fleet-scale serving (docs/serving.md, "From one replica to a fleet").

The layer above ServingEngine that turns one excellent replica into a
fleet: SLO-aware routing over live /healthz+/statusz signals with
drain-aware failover (router), prefill/decode role disaggregation over a
serializable KV handoff (handoff), and cross-request radix prefix reuse
of the slot KV pool (prefix_cache) — plus the fleet config block
(config) and per-replica probe/backoff handles (replica).
"""

from .config import AutoscaleConfig, FleetConfig, RolloutConfig
from .handoff import InProcessTransport, KVHandoff
from .prefix_cache import PrefixHit, RadixPrefixCache, reuse_plan
from .replica import ReplicaHandle
from .rollout import RolloutController
from .router import FleetRequest, FleetRouter, build_fleet

__all__ = [
    "AutoscaleConfig", "FleetConfig", "RolloutConfig", "KVHandoff",
    "InProcessTransport",
    "RadixPrefixCache", "PrefixHit", "reuse_plan",
    "ReplicaHandle", "FleetRouter", "FleetRequest", "build_fleet",
    "RolloutController",
]
