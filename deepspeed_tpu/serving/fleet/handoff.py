"""KV handoff — serialized prefill state crossing replica boundaries.

Disaggregated serving splits the two phases of a request across replica
roles: *prefill* replicas run the compute-bound prompt pass, *decode*
replicas run the bandwidth-bound token loop. The boundary object is
``KVHandoff``: one slot lane (the prompt's K/V), the sampled first
token, and enough request metadata for the decode side to continue
byte-for-byte where prefill stopped.

Transport is pluggable. In-process fleets (``ds_tpu_serve --fleet``)
pass the lane as host numpy arrays — ``slot_extract_lane`` on the
prefill pool, ``slot_insert_lane`` into the decode pool. The
``to_bytes``/``from_bytes`` codec frames the same payload for a real
interconnect later (ICI/RDMA or TCP between hosts): a JSON header (shapes,
dtypes, metadata) plus raw little-endian buffers in header order, so a
receiver can post fixed-size receives without parsing numpy containers.
Quantized pools hand off their int8 q + f32 scale slices directly — the
wire cost of a disaggregated transfer is the *quantized* lane, ~4x
smaller, with zero extra quantization error (the decode pool inserts the
slices verbatim).
"""

import dataclasses
import json
import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["KVHandoff", "InProcessTransport"]

_MAGIC = b"DSKV1\n"


def _flatten_lane(lane) -> Tuple[List[Tuple[str, np.ndarray]], bool]:
    """(ordered (path, array) pairs, quantized?) for any lane flavor."""
    from ...inference.kv_quant import QuantizedSlotPool
    if isinstance(lane, QuantizedSlotPool):
        pairs = [(f"q/{k}", np.asarray(v))
                 for k, v in sorted(lane.q.items())]
        pairs += [(f"scales/{k}", np.asarray(v))
                  for k, v in sorted(lane.scales.items())]
        return pairs, True
    return [(k, np.asarray(v)) for k, v in sorted(lane.items())], False


def _unflatten_lane(pairs: Dict[str, np.ndarray], quantized: bool):
    if not quantized:
        return dict(pairs)
    from ...inference.kv_quant import QuantizedSlotPool
    q = {k[len("q/"):]: v for k, v in pairs.items() if k.startswith("q/")}
    s = {k[len("scales/"):]: v for k, v in pairs.items()
         if k.startswith("scales/")}
    return QuantizedSlotPool(q=q, scales=s)


@dataclasses.dataclass
class KVHandoff:
    """One completed prefill, ready for a decode pool.

    ``lane`` is a host pytree shaped like one pool slot (``[L, 1, H,
    max_len, hd]`` leaves, or the q/scales pair for quantized pools);
    ``kv_len`` says how many columns are valid — the insert copies the
    whole lane and the decode mask never reads past ``kv_len`` until the
    columns are rewritten. ``first_token`` was already sampled (and
    delivered — TTFT happens on the prefill side); decode feeds it at
    column ``kv_len``."""
    prompt: np.ndarray              # int32 [T] — the prefilled tokens
    first_token: int
    kv_len: int                     # valid cache columns (== len(prompt))
    lane: Any                       # host lane pytree (fp or quantized)
    temperature: float = 0.0
    #: sampling law (with temperature + seed): the decode side must
    #: reproduce the prefill side's stream bit-for-bit, so the full
    #: replay law crosses the wire in the frame header
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    max_new_tokens: int = 64
    eos_token_id: Optional[int] = None
    request_id: Optional[int] = None
    source: Optional[str] = None    # producing replica name
    #: the tenant this request bills to — survives disaggregation so the
    #: decode side's per-tenant SLO windows and DRR admission see the
    #: same tenant the prefill side admitted under
    tenant: Optional[str] = None
    #: distributed trace context header (TraceContext.to_header()) — the
    #: request's fleet-wide identity rides the frame so the decode side
    #: continues the SAME trace, not a fresh one
    trace: Optional[Dict[str, Any]] = None
    #: the producing replica's weights_version: a decode replica whose
    #: own version differs REFUSES the lane (re-prefills locally) — KV
    #: from one model fed through another is silent corruption, and a
    #: mid-rollout fleet is exactly when versions differ. ``None`` means
    #: a pre-rollout producer (accepted for compatibility).
    weights_version: Optional[int] = None

    # ------------------------------------------------------------- framing
    def to_bytes(self) -> bytes:
        """RDMA-shaped framing: magic, u32 header length, JSON header,
        then raw buffers in header order."""
        pairs, quantized = _flatten_lane(self.lane)
        header = {
            "prompt": [int(t) for t in np.asarray(self.prompt).reshape(-1)],
            "first_token": int(self.first_token),
            "kv_len": int(self.kv_len),
            "temperature": float(self.temperature),
            "top_k": int(self.top_k),
            "top_p": float(self.top_p),
            "seed": int(self.seed),
            "max_new_tokens": int(self.max_new_tokens),
            "eos_token_id": self.eos_token_id,
            "request_id": self.request_id,
            "source": self.source,
            "tenant": self.tenant,
            "trace": self.trace,
            "weights_version": self.weights_version,
            "quantized": quantized,
            "buffers": [{"path": p, "dtype": a.dtype.str,
                         "shape": list(a.shape)} for p, a in pairs],
        }
        hdr = json.dumps(header).encode("utf-8")
        out = [_MAGIC, struct.pack("<I", len(hdr)), hdr]
        out += [np.ascontiguousarray(a).tobytes() for _p, a in pairs]
        return b"".join(out)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "KVHandoff":
        if blob[:len(_MAGIC)] != _MAGIC:
            raise ValueError("not a KVHandoff frame (bad magic)")
        off = len(_MAGIC)
        (hlen,) = struct.unpack_from("<I", blob, off)
        off += 4
        header = json.loads(blob[off:off + hlen].decode("utf-8"))
        off += hlen
        pairs = {}
        for buf in header["buffers"]:
            dt = np.dtype(buf["dtype"])
            n = int(np.prod(buf["shape"])) if buf["shape"] else 1
            arr = np.frombuffer(blob, dtype=dt, count=n, offset=off)
            pairs[buf["path"]] = arr.reshape(buf["shape"])
            off += n * dt.itemsize
        return cls(
            prompt=np.asarray(header["prompt"], np.int32),
            first_token=header["first_token"],
            kv_len=header["kv_len"],
            lane=_unflatten_lane(pairs, header["quantized"]),
            temperature=header["temperature"],
            top_k=header.get("top_k", 0),
            top_p=header.get("top_p", 1.0),
            seed=header.get("seed", 0),
            max_new_tokens=header["max_new_tokens"],
            eos_token_id=header["eos_token_id"],
            request_id=header["request_id"],
            source=header["source"],
            tenant=header.get("tenant"),
            trace=header.get("trace"),
            weights_version=header.get("weights_version"))

    def nbytes(self) -> int:
        """Payload bytes a transport would move (lane buffers only)."""
        pairs, _q = _flatten_lane(self.lane)
        return sum(a.nbytes for _p, a in pairs)


class InProcessTransport:
    """The trivial transport: deliver the handoff object to a sink
    callable in the same process. Exists so the router is written against
    ``transport.send(handoff, request)`` — an RDMA/TCP transport swaps in
    behind the same call, shipping ``handoff.to_bytes()``."""

    def __init__(self, sink: Callable[[KVHandoff, Any], None]):
        self._sink = sink
        self.sent = 0
        self.bytes_moved = 0

    def send(self, handoff: KVHandoff, request: Any = None):
        self.sent += 1
        self.bytes_moved += handoff.nbytes()
        self._sink(handoff, request)
