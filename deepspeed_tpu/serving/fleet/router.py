"""SLO-aware fleet router — load balancing, failover, disaggregation.

The front-end of an N-replica serving fleet. Requests enter here, not at
a replica: ``submit()`` picks the lowest-loaded READY replica (readiness
= the live ``/healthz`` probe, load = queue depth + active slots +
``slo_burn_penalty`` x the replica's SLO burn rate), and ``step()``
drives the whole fleet — probing on schedule, ticking in-process
replicas, and handling the three failure signals:

- **preemption latch** — the replica's SIGTERM handler fired; its drain
  completes running work, the router re-enqueues what was still queued;
- **stale heartbeat** — no successful probe within
  ``heartbeat_timeout_s``: the replica is presumed dead mid-stream, its
  in-flight requests resubmit to survivors (greedy decode makes the
  replay deterministic; the delivery adapter deduplicates streamed
  tokens, so a client sees each position exactly once);
- **explicit kill** — tests and ops mark a replica failed directly.

Every failover bumps ``fleet/failovers``, emits a ``failover`` span, and
fires the flight recorder (kind ``failover``) when one is attached.

With role disaggregation (``fleet.prefill_replicas``), new requests
route to *prefill* replicas; each completed prompt pass comes back
through the handoff sink and is forwarded — KV lane and Request object
together — to the least-loaded *decode* replica, which continues the
token loop in its own slot pool.

With the ``autoscale`` block (docs/elasticity.md), the replica count
stops being a launch-time constant: sustained SLO burn spawns a replica
through ``build_fleet``'s factory; sustained quiet drains the
least-loaded one — new traffic stops immediately, running requests
finish in place (streamed tokens stay exactly-once), then it is removed.
"""

import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from ...telemetry.trace import get_tracer
from ...utils.logging import log_dist, logger
from ..metrics import FleetMetrics
from ..scheduler import (QueueFull, RateLimited, RequestState,
                         SamplingParams)
from .config import FleetConfig
from .replica import ReplicaHandle

__all__ = ["FleetRouter", "FleetRequest", "TenantRateLimiter",
           "build_fleet"]


class TenantRateLimiter:
    """Per-tenant token buckets at the fleet front door. Cost of one
    submit = prompt tokens + requested new tokens (the work the fleet is
    being asked to buy); refill ``rate_of(tenant)`` tokens/second up to
    ``burst_tokens``. A tenant whose bucket cannot cover the cost is
    rejected with a 429-style ``RateLimited`` BEFORE touching any
    replica queue — rate abuse is shed at the cheapest possible point,
    and the DRR queues behind it only ever see conforming traffic."""

    def __init__(self, config, clock=time.monotonic):
        self.config = config
        self.clock = clock
        #: tenant -> [tokens, last_refill_t]
        self._buckets: Dict[str, list] = {}

    def _bucket(self, tenant: str, now: float) -> list:
        b = self._buckets.get(tenant)
        if b is None:
            # a fresh tenant starts with a full burst allowance
            b = self._buckets[tenant] = [float(self.config.burst_tokens),
                                         now]
        return b

    def try_admit(self, tenant: str, cost: float) -> Optional[float]:
        """Take ``cost`` tokens from the tenant's bucket. Returns None
        on success, else the seconds until the bucket could cover the
        cost (the Retry-After hint; inf for a cost above burst at rate
        0)."""
        rate = self.config.rate_of(tenant)
        if rate <= 0:
            return None                       # unlimited tenant
        now = self.clock()
        b = self._bucket(tenant, now)
        b[0] = min(float(self.config.burst_tokens),
                   b[0] + (now - b[1]) * rate)
        b[1] = now
        if b[0] >= cost:
            b[0] -= cost
            return None
        return (cost - b[0]) / rate

    def snapshot(self) -> Dict[str, float]:
        """tenant -> tokens currently in the bucket (statusz)."""
        return {t: round(b[0], 1) for t, b in self._buckets.items()}

_DONE_STATES = (RequestState.FINISHED, RequestState.TIMEOUT)


class FleetRequest:
    """Router-side view of one request across replica assignments."""

    def __init__(self, fleet_id: int, prompt, sampling, on_token,
                 trace=None):
        self.fleet_id = fleet_id
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.sampling = sampling
        self.on_token = on_token
        self.replica: Optional[str] = None
        self.request = None        # live serving.Request on that replica
        self.attempts = 0
        self.delivered = 0         # token positions streamed to the user
        self.failed_reason: Optional[str] = None
        #: distributed trace context minted at router admission — every
        #: replica assignment (including failover replays) continues it
        self.trace = trace
        self._path_observed = False   # critical path folded into the
                                      # aggregator exactly once

    # The delivery adapter: replays after failover re-generate tokens the
    # user already saw (greedy decode is deterministic), so only positions
    # past the high-water mark are forwarded.
    def _adapter(self, req, tok):
        pos = len(req.tokens)
        if pos <= self.delivered:
            return
        self.delivered = pos
        if self.on_token is not None:
            self.on_token(req, tok)

    @property
    def done(self) -> bool:
        if self.failed_reason is not None:
            return True
        return self.request is not None and self.request.state in _DONE_STATES

    @property
    def state(self) -> str:
        if self.failed_reason is not None:
            return "failed"
        if self.request is None:
            return "pending"
        return self.request.state.value

    @property
    def output_ids(self):
        if self.request is not None:
            return self.request.output_ids
        return self.prompt

    @property
    def tokens(self) -> list:
        return self.request.tokens if self.request is not None else []


class FleetRouter:
    """Front-end over N ReplicaHandles."""

    def __init__(self, replicas: List[ReplicaHandle],
                 config: Optional[FleetConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 tracer=None, recorder=None, replica_factory=None):
        self.config = config or FleetConfig(enabled=True)
        #: () -> ReplicaHandle with a fleet-unique name; what scale_up
        #: spawns. build_fleet provides one closing over the shared
        #: weights + serving JSON; a router without a factory can still
        #: scale DOWN (give capacity back) but never up
        self.replica_factory = replica_factory
        #: replica name -> drain start time: scale-down and rollout share
        #: this ONE drain path — a draining replica keeps ticking until
        #: its running requests finish, routing nothing new to it
        self._draining: Dict[str, float] = {}
        #: per-drain force-evict timeout (begin_drain resolves it at
        #: drain start: rollout drains may carry their own window)
        self._drain_timeout_of: Dict[str, float] = {}
        #: replica names standing in SHADOW — a rollout canary under
        #: verify: probed and ticked like any member, never routed new
        #: traffic until the controller promotes it
        self._shadow: set = set()
        #: the live RolloutController (serving/fleet/rollout.py); stays
        #: attached after a rollout resolves so gauges/statusz keep the
        #: last verdict visible until the next rollout replaces it
        self.rollout = None
        self._as_high_since: Optional[float] = None
        self._as_low_since: Optional[float] = None
        self._as_last_action: float = float("-inf")
        self.last_scale: Optional[dict] = None
        self.replicas: Dict[str, ReplicaHandle] = {
            r.name: r for r in replicas}
        if len(self.replicas) != len(replicas):
            raise ValueError("replica names must be unique")
        self.clock = clock
        self.tracer = tracer or get_tracer()
        self.recorder = recorder
        self.metrics = FleetMetrics(tracer=self.tracer)
        # per-tenant token-bucket rate limits (fleet.tenants block, or
        # the serving tenants block build_fleet copied down); no tenants
        # config (or no rates configured) allocates no limiter state
        self.limiter = None
        tcfg = getattr(self.config, "tenants", None)
        if tcfg is not None and (tcfg.rate_tokens_per_s > 0 or tcfg.rates):
            self.limiter = TenantRateLimiter(tcfg, clock=clock)
        self._fleet_requests: Dict[int, FleetRequest] = {}
        #: cost fold retained from replicas that left the fleet (failed,
        #: drained, rolled out) — a replica's chip-seconds were spent
        #: whether or not it survived, so the fleet fold must keep them
        #: after the ledger's owner is disposed
        self._cost_retired: dict = {}
        self._next_fid = 0
        self._pending: "deque[FleetRequest]" = deque()
        self._pending_handoffs: "deque" = deque()
        self._shutdown = False
        # fleet-wide distributed tracing (telemetry/disttrace.py): trace
        # contexts minted per request, merged per-replica Perfetto lanes,
        # per-stage critical-path gauges. fleet.disttrace=False builds
        # none of it (requests still carry per-replica contexts).
        self.aggregator = None
        if getattr(self.config, "disttrace", True):
            from ...telemetry.disttrace import FleetAggregator
            self.aggregator = FleetAggregator(self, tracer=self.tracer)
        if self.recorder is not None and self.aggregator is not None:
            self.recorder.set_trace_provider(
                self.aggregator.in_flight_trace_ids)
        self.statusz = None
        sz = getattr(self.config, "statusz", None)
        if getattr(sz, "enabled", False):
            from ...telemetry.statusz import StatuszServer
            self.statusz = StatuszServer(sz, tracer=self.tracer)
            self.statusz.register("fleet", self._statusz_section)
            self.statusz.register("tenants", self._tenant_section)
            self.statusz.register("autoscale", self.autoscale_summary)
            self.statusz.register("rollout", self.rollout_summary)
            self.statusz.register("costs", self._cost_section)
            self.statusz.register_health("fleet", self._health_check)
            if self.aggregator is not None:
                self.statusz.register("critical_path",
                                      self.aggregator.statusz_section)
                self.statusz.attach_aggregator(self.aggregator)
            if self.recorder is not None:
                self.statusz.attach_recorder(self.recorder)
        # wire prefill replicas' handoff sinks to this router
        for r in replicas:
            if r.engine is not None and r.role == "prefill":
                sched = r.engine.scheduler
                if sched.handoff_sink is None:
                    sched.handoff_sink = self._make_sink(r.name)
        now = self.clock()
        for r in replicas:
            r.probe(now)
        self._refresh_gauges()
        log_dist(
            f"FleetRouter initialized: {len(replicas)} replica(s) "
            f"({', '.join(f'{r.name}:{r.role}' for r in replicas)})",
            ranks=[0])

    # ---------------------------------------------------------------- roles
    def _entry_replicas(self) -> List[ReplicaHandle]:
        """Where NEW requests go: prefill replicas when disaggregated,
        else unified."""
        pre = [r for r in self.replicas.values()
               if r.role == "prefill" and not r.failed
               and r.name not in self._shadow]
        if pre:
            return pre
        return [r for r in self.replicas.values()
                if r.role == "unified" and not r.failed
                and r.name not in self._draining
                and r.name not in self._shadow]

    def _decode_replicas(self) -> List[ReplicaHandle]:
        return [r for r in self.replicas.values()
                if r.role == "decode" and not r.failed]

    @staticmethod
    def _pick(cands: List[ReplicaHandle]) -> List[ReplicaHandle]:
        ready = [r for r in cands if r.ready]
        return sorted(ready, key=lambda r: r.score())

    # --------------------------------------------------------------- submit
    def submit(self, prompt, sampling: Optional[SamplingParams] = None,
               on_token: Optional[Callable] = None) -> int:
        """Route one request into the fleet; returns its fleet id.
        Raises QueueFull when no replica can take it AND the router's
        own pending queue is at ``max_pending`` (fleet-wide
        backpressure)."""
        if self._shutdown:
            raise RuntimeError("FleetRouter is shut down; submit rejected")
        sampling = sampling or SamplingParams()
        tenant = getattr(sampling, "tenant", None) or "default"
        if self.limiter is not None:
            # cost = the work this submit asks the fleet to buy
            prompt_arr = np.asarray(prompt).reshape(-1)
            cost = float(prompt_arr.size +
                         (sampling.max_new_tokens or 0))
            retry = self.limiter.try_admit(tenant, cost)
            if retry is not None:
                self.metrics.record_throttle(tenant)
                raise RateLimited(
                    f"tenant {tenant!r} rate-limited "
                    f"({cost:g} tokens over budget); retry in "
                    f"{retry:.2f}s", tenant=tenant,
                    retry_after_s=round(retry, 3))
        from ...telemetry.disttrace import TraceContext
        ctx = TraceContext.mint(origin="router", tenant=tenant)
        # seed + sampling params ride the trace from the first hop: every
        # replica assignment (and failover replay) reproduces the same law
        ctx.sampling = sampling.to_dict()
        ctx.mark("submit")
        freq = FleetRequest(self._next_fid, prompt, sampling, on_token,
                            trace=ctx)
        self._next_fid += 1
        self.metrics.submitted += 1
        if not self._try_assign(freq):
            if len(self._pending) >= self.config.max_pending:
                self.metrics.submitted -= 1
                raise QueueFull(
                    f"fleet pending queue at capacity "
                    f"({self.config.max_pending}) and no replica ready")
            self._pending.append(freq)
        self._fleet_requests[freq.fleet_id] = freq
        return freq.fleet_id

    def _try_assign(self, freq: FleetRequest) -> bool:
        cands = self._pick(self._entry_replicas())
        if self.rollout is not None:
            # mid-shift the controller reorders candidates (error
            # diffusion over step_fraction) — never filters them, so a
            # full preferred group falls through to the other and no
            # request is ever dropped by the shift itself
            cands = self.rollout.order_candidates(cands)
        for r in cands:
            try:
                rid = r.engine.submit(freq.prompt, freq.sampling,
                                      on_token=freq._adapter,
                                      trace=freq.trace)
            except QueueFull:
                continue
            freq.replica, freq.request = r.name, r.engine.result(rid)
            freq.attempts += 1
            # "to", not "replica": router spans stay on the router's lane
            # in the merged timeline (the aggregator partitions by the
            # "replica" arg). "assignments", not "attempt": the latter is
            # the trace context's replay counter (span_args).
            with self.tracer.span(
                    "route", cat="fleet",
                    args={"fleet_id": freq.fleet_id, "to": r.name,
                          "assignments": freq.attempts,
                          **(freq.trace.span_args()
                             if freq.trace is not None else {})}):
                pass
            return True
        return False

    # -------------------------------------------------------------- handoff
    def _make_sink(self, source: str):
        def sink(handoff, request):
            handoff.source = source
            self._route_handoff(handoff, request)
        return sink

    def _route_handoff(self, handoff, request) -> bool:
        for r in self._pick(self._decode_replicas()):
            try:
                r.engine.submit_handoff(handoff, request=request)
            except QueueFull:
                continue
            freq = self._freq_of(request)
            if freq is not None:
                freq.replica = r.name
            self.metrics.handoffs += 1
            trace = getattr(request, "trace", None)
            with self.tracer.span(
                    "kv_handoff", cat="fleet",
                    args={"from": handoff.source, "to": r.name,
                          "kv_len": int(handoff.kv_len),
                          "bytes": handoff.nbytes(),
                          **(trace.span_args() if trace is not None
                             else {})}):
                pass
            return True
        self._pending_handoffs.append((handoff, request))
        return False

    def _freq_of(self, request) -> Optional[FleetRequest]:
        for freq in self._fleet_requests.values():
            if freq.request is request:
                return freq
        return None

    # ----------------------------------------------------------------- step
    def step(self) -> int:
        """One fleet tick: probe on schedule, evict dead replicas
        (failing their work over), retry pending assignments, tick every
        live in-process replica. Returns requests still in flight."""
        now = self.clock()
        for r in self.replicas.values():
            r.probe(now)
        self._detect_failures(now)
        # gate blown drain timeouts the same tick they are detectable —
        # BEFORE routing and replica ticks — so a wedged drain's requests
        # fail over now, not one sweep later
        self._finalize_drains(now)
        self._retry_pending()
        in_flight = 0
        for r in list(self.replicas.values()):
            if r.failed or r.engine is None:
                continue
            in_flight += r.engine.step()
        self._finalize_drains(now)
        if self.rollout is not None:
            self.rollout.tick(now)
        self._autoscale_tick(now)
        self._harvest_completions()
        self._refresh_gauges()
        return in_flight + len(self._pending) + len(self._pending_handoffs)

    def _retry_pending(self):
        for _ in range(len(self._pending_handoffs)):
            handoff, request = self._pending_handoffs.popleft()
            self._route_handoff(handoff, request)   # re-queues on failure
            if self._pending_handoffs and \
                    self._pending_handoffs[-1][0] is handoff:
                break                               # still nowhere to go
        for _ in range(len(self._pending)):
            freq = self._pending.popleft()
            if freq.attempts > self.config.max_retries:
                freq.failed_reason = (
                    f"gave up after {freq.attempts} attempts "
                    f"(max_retries={self.config.max_retries})")
                logger.warning(f"fleet: request {freq.fleet_id} "
                               f"{freq.failed_reason}")
                continue
            if not self._try_assign(freq):
                self._pending.append(freq)
                break                               # no replica ready now

    def _harvest_completions(self):
        done = 0
        for f in self._fleet_requests.values():
            if f.request is None or f.request.state not in _DONE_STATES:
                continue
            done += 1
            if self.aggregator is not None and not f._path_observed:
                f._path_observed = True
                self.aggregator.observe(f)
        newly = done != self.metrics.completed
        self.metrics.completed = done
        if newly and self.aggregator is not None:
            self.aggregator.export_gauges()

    # ------------------------------------------------------------- failover
    def _detect_failures(self, now: float):
        for r in list(self.replicas.values()):
            if r.failed:
                continue
            if r.preempted():
                self._evict(r, "preemption latch fired")
            elif r.stale(now):
                self._evict(r, f"heartbeat stale ({r.last_detail})")

    def kill(self, name: str, reason: str = "killed"):
        """Mark a replica dead NOW (tests, ops). Its in-flight requests
        fail over on the spot."""
        self._evict(self.replicas[name], reason)

    def _evict(self, replica: ReplicaHandle, reason: str):
        replica.failed = True
        replica.ready = False
        # the dead replica's chip-seconds were spent: fold its ledger
        # into the retired accumulator NOW, while the in-process object
        # is still reachable (cost_summary skips failed replicas)
        self._fold_replica_costs(replica)
        self._draining.pop(replica.name, None)
        self._drain_timeout_of.pop(replica.name, None)
        self._shadow.discard(replica.name)
        victims = [f for f in self._fleet_requests.values()
                   if f.replica == replica.name and not f.done]
        trace_ids = []
        for freq in victims:
            if freq.trace is not None:
                # the replayed attempt is a CHILD span of the one that
                # just died — same trace_id, linked parent, attempt+1
                freq.trace.replay()
                trace_ids.append(freq.trace.trace_id)
            freq.replica, freq.request = None, None
            self._pending.append(freq)
        self.metrics.failovers += 1
        self.metrics.requeued += len(victims)
        with self.tracer.span("failover", cat="fleet",
                              args={"member": replica.name,
                                    "reason": reason,
                                    "requeued": len(victims),
                                    "trace_ids": trace_ids[:16]}):
            pass
        if self.recorder is not None:
            self.recorder.trigger(
                "failover",
                f"replica {replica.name} evicted ({reason}); "
                f"{len(victims)} request(s) re-enqueued onto survivors",
                force=True)
            if self.aggregator is not None:
                # stitch same-trace bundles across the router's and the
                # replicas' bundle dirs into one cross-replica postmortem
                try:
                    self.aggregator.cross_replica_postmortem()
                except Exception as e:
                    logger.warning(
                        f"fleet: cross-replica postmortem failed: {e}")
        log_dist(
            f"fleet: FAILOVER — replica {replica.name} evicted ({reason}); "
            f"re-enqueued {len(victims)} in-flight request(s)", ranks=[0])

    # ------------------------------------------------------------ autoscale
    def _live_unified(self) -> List[ReplicaHandle]:
        """Replicas the controller counts and may shrink: live, unified,
        not already draining."""
        return [r for r in self.replicas.values()
                if r.role == "unified" and not r.failed
                and r.name not in self._draining
                and r.name not in self._shadow]

    def _load_signals(self) -> tuple:
        """(fleet burn, total queue depth) in one sweep. Burn is the
        WORST live replica's burn rate (a fleet is out of SLO if any
        replica serves out of SLO — the same worst-of rule the tenant
        table uses) — but only replicas with CURRENT work count: the
        burn window is a rate with no clock, so an idle replica's window
        is history, not pressure. Without this, the routing score's burn
        penalty starves a burnt replica of traffic, its window never
        refreshes, and the frozen burn pins the fleet at max forever."""
        burn, queue = 0.0, len(self._pending)
        for r in self.replicas.values():
            if r.failed or r.name in self._draining:
                continue
            sig = r.load()
            depth = int(sig.get("queue_depth") or 0)
            active = int(sig.get("active_requests") or 0)
            queue += depth
            if depth + active > 0:
                burn = max(burn, float(sig.get("slo_burn_rate") or 0.0))
        return burn, queue

    def _fleet_burn(self) -> float:
        return self._load_signals()[0]

    def _queue_total(self) -> int:
        return self._load_signals()[1]

    def _in_flight_on(self, name: str) -> List[FleetRequest]:
        return [f for f in self._fleet_requests.values()
                if f.replica == name and not f.done]

    def _autoscale_tick(self, now: float):
        """The controller: sustained burn above threshold grows the
        fleet; sustained quiet (low burn AND empty queues) shrinks it.
        Each condition must hold ``sustain_s`` continuously, and actions
        are ``cooldown_s`` apart — a windowed burn gauge flaps, a fleet
        must not."""
        ac = getattr(self.config, "autoscale", None)
        if ac is None or not ac.enabled or self._shutdown:
            return
        if self.rollout is not None and self.rollout.active:
            # a rollout owns the replica set while it runs: scaling
            # mid-shift would fight the traffic shift (and a scale-down
            # could drain the very replica the canary is verifying)
            self._as_high_since = self._as_low_since = None
            return
        burn, queue = self._load_signals()
        live = len(self._live_unified())
        if burn >= ac.scale_up_burn:
            self._as_low_since = None
            if self._as_high_since is None:
                self._as_high_since = now
        elif burn <= ac.scale_down_burn and queue <= ac.scale_down_queue:
            self._as_high_since = None
            if self._as_low_since is None:
                self._as_low_since = now
        else:
            self._as_high_since = self._as_low_since = None
        if now - self._as_last_action < ac.cooldown_s:
            return
        if self._as_high_since is not None and \
                now - self._as_high_since >= ac.sustain_s and \
                live < ac.max_replicas and self.replica_factory is not None:
            self._as_last_action = now
            self._as_high_since = None
            self.scale_up(f"slo burn {burn:.2f} >= {ac.scale_up_burn:g} "
                          f"sustained {ac.sustain_s:g}s")
        elif self._as_low_since is not None and \
                now - self._as_low_since >= ac.sustain_s and \
                live > ac.min_replicas:
            self._as_last_action = now
            self._as_low_since = None
            self.scale_down(f"slo burn {burn:.2f} <= "
                            f"{ac.scale_down_burn:g} and queue {queue} <= "
                            f"{ac.scale_down_queue} sustained "
                            f"{ac.sustain_s:g}s")

    def scale_up(self, reason: str = "manual") -> Optional[str]:
        """Spawn one replica through the factory and start routing to it
        the moment its probe passes. Returns the new replica's name."""
        if self.replica_factory is None:
            logger.warning("fleet: scale_up requested but no "
                           "replica_factory; ignoring")
            return None
        replica = self.replica_factory()
        if replica.name in self.replicas:
            raise ValueError(
                f"replica_factory returned duplicate name {replica.name!r}")
        self.replicas[replica.name] = replica
        replica.probe(self.clock())
        self._note_scale("up", replica.name, reason)
        log_dist(f"fleet: SCALE-UP -> {replica.name} ({reason}); "
                 f"{len(self._live_unified())} live replica(s)", ranks=[0])
        return replica.name

    def scale_down(self, reason: str = "manual",
                   name: Optional[str] = None) -> Optional[str]:
        """Start draining the least-loaded live replica (or ``name``).
        New traffic stops routing to it immediately; its running
        requests finish in place (the PR-8 drain contract — streamed
        tokens keep their exactly-once delivery because nothing is
        interrupted); once idle it is shut down and removed. Returns the
        draining replica's name. Refuses to go below
        ``autoscale.min_replicas`` (1 without the block) — ``kill()`` is
        the operator's escape hatch, not this."""
        ac = getattr(self.config, "autoscale", None)
        floor = ac.min_replicas if (ac is not None and ac.enabled) else 1
        if len(self._live_unified()) <= floor:
            logger.warning(
                f"fleet: scale_down refused — at the min_replicas floor "
                f"({floor})")
            return None
        if name is None:
            cands = sorted(self._live_unified(), key=lambda r: r.score())
            if not cands:
                return None
            name = cands[0].name
        elif name not in self.replicas or name in self._draining:
            return None
        self.begin_drain(name)
        self._note_scale("down", name, reason)
        log_dist(f"fleet: SCALE-DOWN draining {name} ({reason}); "
                 f"{len(self._live_unified())} live replica(s) remain",
                 ranks=[0])
        return name

    def begin_drain(self, name: str, timeout_s=None) -> bool:
        """The ONE drain entry scale-down AND rollout share: new traffic
        stops routing to ``name`` immediately; ``_finalize_drains``
        completes the removal once its running requests finish, or
        force-evicts it past the drain timeout (the failover path
        re-enqueues its requests onto survivors — delivery stays
        exactly-once via the delivered-position dedup)."""
        if name not in self.replicas or name in self._draining:
            return False
        ac = getattr(self.config, "autoscale", None)
        default = getattr(ac, "drain_timeout_s", 30.0) if ac else 30.0
        self._draining[name] = self.clock()
        self._drain_timeout_of[name] = float(
            timeout_s if timeout_s is not None else default)
        self._shadow.discard(name)
        return True

    def _finalize_drains(self, now: float):
        """Resolve every draining replica that can be resolved NOW:
        finished ones are removed cleanly, ones past their drain timeout
        are force-evicted in this same sweep."""
        for name in list(self._draining):
            self._finalize_drain_one(name, now)

    def _finalize_drain_one(self, name: str, now: float) -> bool:
        """Finish or force-evict ONE draining replica. Returns True when
        the drain resolved (clean completion, force-evict, or a stale
        entry); False while the replica is still legitimately busy
        inside its timeout window."""
        since = self._draining.get(name)
        if since is None:
            return True
        timeout = self._drain_timeout_of.get(name, 30.0)
        r = self.replicas.get(name)
        if r is None or r.failed:
            self._draining.pop(name, None)
            self._drain_timeout_of.pop(name, None)
            return True
        busy = self._in_flight_on(name) or (
            r.engine is not None and
            (r.engine.active_requests or r.engine.queue_depth))
        if not busy:
            self._draining.pop(name, None)
            self._drain_timeout_of.pop(name, None)
            self._fold_replica_costs(r)
            del self.replicas[name]
            if r.engine is not None:
                r.engine.shutdown()
            log_dist(f"fleet: drain of {name} complete", ranks=[0])
            return True
        if now - since > timeout:
            self._draining.pop(name, None)
            self._drain_timeout_of.pop(name, None)
            self._evict(r, f"drain timeout after {timeout:g}s")
            del self.replicas[name]
            if r.engine is not None:
                self._dispose_failed(r.engine)
            return True
        return False

    def _note_scale(self, kind: str, name: str, reason: str):
        if kind == "up":
            self.metrics.scale_ups += 1
        else:
            self.metrics.scale_downs += 1
        self.last_scale = {"kind": kind, "replica": name,
                           "reason": reason, "time": time.time(),
                           "live": len(self._live_unified()),
                           "draining": sorted(self._draining)}
        with self.tracer.span(f"scale_{kind}", cat="fleet",
                              args={"replica": name, "reason": reason}):
            pass
        if self.recorder is not None:
            # scale events are rare and each one is evidence — bypass the
            # per-kind debounce so an up immediately followed by a down
            # (a flapping policy) still bundles both
            self.recorder.trigger(
                "resize", f"scale_{kind} {name}: {reason}", force=True)

    def autoscale_summary(self) -> dict:
        """The /statusz ``autoscale`` section (and ds_tpu_top panel):
        target vs live count, bounds, last action."""
        ac = getattr(self.config, "autoscale", None)
        live = len(self._live_unified())
        out = {
            "enabled": bool(ac is not None and ac.enabled),
            "live_replicas": live,
            "draining": sorted(self._draining),
            "scale_ups": self.metrics.scale_ups,
            "scale_downs": self.metrics.scale_downs,
        }
        if ac is not None and ac.enabled:
            out["min_replicas"] = ac.min_replicas
            out["max_replicas"] = ac.max_replicas
            out["can_grow"] = self.replica_factory is not None
        if self.last_scale is not None:
            last = dict(self.last_scale)
            last["age_s"] = round(max(0.0, time.time() - last["time"]), 1)
            out["last_scale"] = last
        return out

    # -------------------------------------------------------------- rollout
    def start_rollout(self, engine_view, config=None):
        """Begin a zero-downtime rolling weight update to ``engine_view``
        (an InferenceEngine — typically ``engine.load_version(dir, tag)``,
        a shallow view sharing compiled programs but serving the new
        checkpoint's params). Returns the live RolloutController; the
        rollout advances inside ``step()`` — canary verify in shadow,
        SLO-guarded traffic shift, vPrev drain — and rolls back
        automatically on any gate breach."""
        from .config import RolloutConfig
        from .rollout import RolloutController
        if self._shutdown:
            raise RuntimeError("FleetRouter is shut down")
        ro = config if config is not None else \
            (getattr(self.config, "rollout", None) or RolloutConfig())
        if not getattr(ro, "enabled", True):
            raise RuntimeError(
                "fleet.rollout.enabled is False; rollout refused")
        if self.rollout is not None and self.rollout.active:
            raise RuntimeError("a rollout is already in progress")
        ctl = RolloutController(self, engine_view, ro)
        self.rollout = ctl
        ctl.start()
        return ctl

    def version_skew(self) -> dict:
        """Live replicas' weights_version spread. ``skew`` is the number
        of distinct versions beyond one — 0 means the whole fleet serves
        the same weights (the steady state every rollout must return
        to). A shadow canary counts: it IS skew until promoted or
        drained."""
        versions = {}
        for name, r in self.replicas.items():
            if r.failed or r.engine is None:
                continue
            versions[name] = int(
                getattr(r.engine, "weights_version", 0) or 0)
        distinct = len(set(versions.values())) if versions else 0
        return {"versions": versions, "skew": max(0, distinct - 1)}

    def rollout_summary(self) -> dict:
        """The /statusz ``rollout`` section (and ds_tpu_top panel)."""
        if self.rollout is None:
            return {}
        return self.rollout.summary()

    # -------------------------------------------------------------- results
    def result(self, fleet_id: int) -> FleetRequest:
        return self._fleet_requests[fleet_id]

    def run_until_idle(self, max_ticks: int = 100_000) -> int:
        """Tick until every accepted request reached a terminal state (or
        nothing can make progress). Returns ticks run."""
        for i in range(max_ticks):
            in_flight = self.step()
            open_reqs = [f for f in self._fleet_requests.values()
                         if not f.done]
            if not open_reqs:
                return i + 1
            if in_flight == 0 and not any(
                    r.ready for r in self._entry_replicas()):
                logger.warning(
                    f"fleet: {len(open_reqs)} request(s) stranded with no "
                    f"ready replica; giving up run_until_idle")
                return i + 1
        return max_ticks

    # ------------------------------------------------------------ lifecycle
    def drain(self, max_ticks: int = 100_000):
        """Finish in-flight fleet work, then drain every live replica."""
        self.run_until_idle(max_ticks=max_ticks)
        for r in self.replicas.values():
            if not r.failed and r.engine is not None:
                r.engine.drain()

    def shutdown(self):
        """Drain, shut every live replica down, release the fleet gauges
        and dead replicas' lingering gauges, stop the router statusz."""
        if self._shutdown:
            return
        self.drain()
        self._shutdown = True
        for r in self.replicas.values():
            if r.engine is None:
                continue
            if r.failed:
                self._dispose_failed(r.engine)
            else:
                r.engine.shutdown()
        if self.statusz is not None:
            self.statusz.close()
        if self.recorder is not None:
            self.recorder.close()
        self.metrics.close()
        self.tracer.release_counters(self)

    @staticmethod
    def _dispose_failed(engine):
        """Best-effort gauge/server cleanup of a replica that was marked
        dead without a drain (a real dead process takes its /metrics with
        it; an in-process 'corpse' must not leave gauges looking live)."""
        try:
            engine.metrics.close()
            if engine.statusz is not None:
                engine.statusz.close()
            if engine._recorder is not None:
                engine._recorder.close()
            engine.tracer.release_counters(engine)
        except Exception as e:
            logger.warning(f"fleet: disposing failed replica: {e}")

    # ----------------------------------------------------------------- costs
    def _fold_replica_costs(self, replica: ReplicaHandle):
        """Fold a departing replica's cost ledger into ``_cost_retired``
        exactly once — the ledger is reset after the fold, so a second
        fold of the same object (kill of an already-failed replica, a
        drain timeout's evict + dispose) adds zero."""
        engine = replica.engine
        cost = getattr(getattr(engine, "scheduler", None), "cost", None) \
            if engine is not None else None
        if cost is None:
            return
        from ...telemetry.costplane import merge_cost_totals
        merge_cost_totals(self._cost_retired, cost.snapshot())
        cost.reset()

    def cost_summary(self) -> dict:
        """Fleet-wide cost fold: every live replica's ``CostLedger``
        snapshot plus the retired accumulator (failed/drained replicas
        folded at departure). Per-tenant chip-ms / HBM-GiB-s / token
        totals and the fleet serving-wall + overhead residual — by
        construction tenant costs + overhead sum to the fleet's serving
        wall-clock."""
        from ...telemetry.costplane import merge_cost_totals
        out: dict = {"enabled": False}
        if self._cost_retired:
            out["enabled"] = True
            merge_cost_totals(out, self._cost_retired)
        for r in self.replicas.values():
            if r.failed or r.engine is None:
                continue
            cost = getattr(r.engine.scheduler, "cost", None)
            if cost is None:
                continue
            out["enabled"] = True
            merge_cost_totals(out, cost.snapshot())
        return out

    def reset_costs(self):
        """Zero the fleet cost fold — live ledgers AND the retired
        accumulator. Benchmarks call this after warmup so the cost
        window matches the measured goodput window."""
        self._cost_retired = {}
        for r in self.replicas.values():
            if r.engine is None:
                continue
            cost = getattr(r.engine.scheduler, "cost", None)
            if cost is not None:
                cost.reset()

    def _cost_section(self) -> dict:
        """The /statusz ``costs`` section (and ds_tpu_top panel): the
        fleet cost fold plus the derived capacity view. Empty when no
        replica runs a cost ledger — the panel degrades away."""
        costs = self.cost_summary()
        if not costs.get("enabled"):
            return {}
        from ...telemetry.costplane import capacity_report
        costs["capacity"] = capacity_report(costs,
                                            replicas=len(self.replicas))
        return costs

    # -------------------------------------------------------------- statusz
    def _prefix_totals(self):
        hits = lookups = 0
        for r in self.replicas.values():
            if r.engine is None:
                continue
            pc = r.engine.scheduler.prefix_cache
            if pc is not None:
                hits += pc.hits
                lookups += pc.lookups
        return hits, lookups

    def _refresh_gauges(self):
        hits, lookups = self._prefix_totals()
        self.metrics.update(
            replicas=len(self.replicas),
            ready=sum(1 for r in self.replicas.values()
                      if r.ready and not r.failed),
            pending=len(self._pending) + len(self._pending_handoffs),
            prefix_hits=hits, prefix_lookups=lookups)
        ac = getattr(self.config, "autoscale", None)
        if ac is not None and ac.enabled:
            self.metrics.update_autoscale(
                live=len(self._live_unified()),
                draining=len(self._draining),
                min_replicas=ac.min_replicas,
                max_replicas=ac.max_replicas)
        if self.rollout is not None:
            self.metrics.update_rollout(
                skew=self.version_skew()["skew"],
                **self.rollout.gauge_row())
        costs = self.cost_summary()
        if costs.get("enabled"):
            # the dstpu_cost_* family is emitted ONLY here: replicas
            # share one in-process tracer, so per-replica emission would
            # last-writer-win; the router fold is the one total
            self.metrics.update_cost(costs)

    def tenant_summary(self) -> dict:
        """Fleet-wide per-tenant view: each live replica's tenant SLO
        windows aggregated (counts summed, percentile/burn worst-of —
        a tenant is out of SLO if ANY replica serves it out of SLO),
        plus the router-side throttle counts and bucket levels. The
        table ds_tpu_top renders to name the tenant eating the
        budget."""
        agg: Dict[str, dict] = {}

        def row_of(tenant):
            return agg.setdefault(tenant, {
                "submitted": 0, "completed": 0, "timeouts": 0,
                "tokens_out": 0, "prompt_tokens": 0, "ttft_ms_p99": 0.0,
                "burn_rate": 0.0, "throttled": 0})

        for r in self.replicas.values():
            if r.engine is None or r.failed:
                continue
            for tenant, rep in r.engine.metrics.tenant_status().items():
                a = row_of(tenant)
                for key in ("submitted", "completed", "timeouts",
                            "tokens_out", "prompt_tokens"):
                    a[key] += rep.get(key, 0)
                a["ttft_ms_p99"] = max(a["ttft_ms_p99"],
                                       rep["ttft_ms_p99"])
                a["burn_rate"] = max(a["burn_rate"], rep["burn_rate"])
        for tenant, n in self.metrics.tenant_throttled.items():
            row_of(tenant)["throttled"] = n
        total = max(1, sum(a["tokens_out"] for a in agg.values()))
        buckets = self.limiter.snapshot() if self.limiter is not None \
            else {}
        for tenant, a in agg.items():
            a["token_share"] = round(a["tokens_out"] / total, 4)
            if tenant in buckets:
                a["bucket_tokens"] = buckets[tenant]
        return agg

    def _tenant_section(self) -> dict:
        table = self.tenant_summary()
        if not table:
            return {}
        return {"throttled_total": self.metrics.throttled,
                "rate_limited": self.limiter is not None,
                "table": table}

    def _health_check(self):
        if self._shutdown:
            return False, "shut down"
        entry = [r for r in self._entry_replicas() if r.ready]
        if not entry:
            return False, "no ready entry replica"
        if self.config.prefill_replicas and not any(
                r.ready for r in self._decode_replicas()):
            return False, "no ready decode replica"
        return True, f"{len(entry)} ready"

    def _statusz_section(self) -> dict:
        hits, lookups = self._prefix_totals()
        out = {
            "replicas": len(self.replicas),
            "ready": sum(1 for r in self.replicas.values()
                         if r.ready and not r.failed),
            "failed": sum(1 for r in self.replicas.values() if r.failed),
            "pending_requests": len(self._pending),
            "pending_handoffs": len(self._pending_handoffs),
            "submitted": self.metrics.submitted,
            "completed": self.metrics.completed,
            "failovers": self.metrics.failovers,
            "requeued": self.metrics.requeued,
            "kv_handoffs": self.metrics.handoffs,
            "prefix_hit_rate": round(hits / lookups, 4) if lookups else 0.0,
        }
        # nested per-replica rows: ds_tpu_top's fleet view renders these
        # and polls each replica's url for its own /statusz detail
        out["replica_table"] = {name: r.summary()
                                for name, r in self.replicas.items()}
        return out


def build_fleet(engine, serving_config, clock=time.monotonic,
                seed: int = 0) -> FleetRouter:
    """One InferenceEngine (weights are shared — replicas differ only in
    their slot pools) + one serving JSON -> a running in-process fleet.
    Per-replica ServingConfigs are derived from the base config: role
    from ``fleet.roles()``, a fresh ephemeral statusz port per replica
    (a fixed port cannot be bound N times), and id spacing so request
    ids stay fleet-unique."""
    from ..config import ServingConfig
    from ..engine import ServingEngine
    if isinstance(serving_config, dict):
        serving_config = ServingConfig.from_dict(serving_config)
    else:
        serving_config.validate()
    import os
    fleet_cfg = serving_config.fleet
    if fleet_cfg.tenants is None:
        # one JSON defines the tenant policy once: the serving-level
        # tenants block is also the router's rate-limit + table source
        fleet_cfg.tenants = getattr(serving_config, "tenants", None)
    roles = fleet_cfg.roles()
    n = len(roles)
    replicas = []
    recorder = None
    rec_cfg = serving_config.flight_recorder
    if getattr(rec_cfg, "enabled", False):
        # router and replicas each get their own bundle subdirectory —
        # recorders number bundles independently and must not collide
        from ...telemetry.flight_recorder import FlightRecorder
        from ...runtime.config import FlightRecorderConfig
        router_rec = FlightRecorderConfig.from_dict(rec_cfg.to_dict())
        router_rec.dir = os.path.join(str(rec_cfg.dir), "router")
        recorder = FlightRecorder(router_rec)
    # id_stride spaces request-id streams so they stay fleet-unique over
    # the fleet's LIFETIME replica bound, not its launch size — replicas
    # come and go (autoscale spawns, rollout stands up vNext members),
    # and a new replica reusing a dead one's id lane would collide with
    # requests the dead one minted
    stride = 1024

    def _make_replica(i: int, role: str,
                      engine_override=None) -> ReplicaHandle:
        cfg = ServingConfig.from_dict(serving_config.to_dict())
        cfg.role = role
        if getattr(cfg.statusz, "enabled", False):
            cfg.statusz.port = 0          # ephemeral per replica
        if getattr(cfg.flight_recorder, "enabled", False):
            cfg.flight_recorder.dir = os.path.join(
                str(rec_cfg.dir), f"r{i}")
        srv = ServingEngine(engine_override if engine_override is not None
                            else engine,
                            cfg, clock=clock, seed=seed + i,
                            id_start=i, id_stride=stride,
                            replica_name=f"r{i}")
        return ReplicaHandle(
            f"r{i}", engine=srv, role=role, config=fleet_cfg, clock=clock)

    for i, role in enumerate(roles):
        replicas.append(_make_replica(i, role))
    serial = [n]

    def factory(engine_override=None):
        i = serial[0]
        serial[0] += 1
        if i >= stride:
            raise RuntimeError(
                f"fleet exhausted its lifetime replica-id space "
                f"({stride}); restart the router")
        return _make_replica(i, "unified", engine_override=engine_override)

    router = FleetRouter(replicas, fleet_cfg, clock=clock,
                         recorder=recorder, replica_factory=factory)
    return router
